"""LocalSGD / DiLoCo integration: threads-as-replicas with the real stack.

Mirrors reference torchft/local_sgd_integ_test.py: LocalSGD recovery,
DiLoCo recovery, and a third replica joining mid-run (upscale).
"""

import time
from concurrent.futures import ThreadPoolExecutor
from typing import List

import numpy as np
import optax
import pytest

from torchft_tpu.coordination import LighthouseClient, LighthouseServer
from torchft_tpu.local_sgd import DiLoCo, LocalSGD
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroupTCP

from torchft_tpu.utils import faults
from torchft_tpu.utils.faults import FaultRule, InjectedFault


def fail_at(replica: int, step: int) -> FaultRule:
    """Replica-crash rule for the DiLoCo runners (train.step site)."""
    return FaultRule(site="train.step", replica=f"diloco_{replica}", step=step)


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


@pytest.fixture
def lighthouse():
    server = LighthouseServer(
        min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=1000
    )
    yield server
    server.shutdown()


class DiLoCoRunner:
    """Replica running DiLoCo: deterministic inner updates so outer syncs
    are exactly comparable across replicas."""

    def __init__(
        self,
        replica_id: int,
        lighthouse_addr: str,
        outer_syncs: int = 4,
        sync_every: int = 4,
        n_fragments: int = 2,
        algo: str = "diloco",
        inner_sleep: float = 0.0,
        quantize: bool = False,
        device_quantize=None,
        param_elems: int = 4,
    ) -> None:
        self.replica_id = replica_id
        self.lighthouse_addr = lighthouse_addr
        self.outer_syncs = outer_syncs
        self.sync_every = sync_every
        self.n_fragments = n_fragments
        self.algo = algo
        self.inner_sleep = inner_sleep
        self.quantize = quantize
        self.device_quantize = device_quantize
        self.param_elems = param_elems

    def run(self) -> dict:
        for attempt in range(3):
            try:
                return self._train()
            except InjectedFault:
                continue
        raise RuntimeError("exhausted attempts")

    def _train(self) -> dict:
        params = {
            "layer0": np.zeros(self.param_elems, dtype=np.float32),
            "layer1": np.zeros(self.param_elems, dtype=np.float32),
        }
        holder = {"p": params}

        def get_params():
            return dict(holder["p"])

        def set_params(p):
            holder["p"] = dict(p)

        manager = Manager(
            pg=ProcessGroupTCP(timeout=10.0),
            min_replica_size=1,
            lighthouse_addr=self.lighthouse_addr,
            replica_id=f"diloco_{self.replica_id}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=False,
            timeout=20.0,
            quorum_timeout=20.0,
            load_state_dict=lambda sd: holder.__setitem__(
                "p", {k: np.array(v) for k, v in sd.items()}
            ),
            state_dict=lambda: {k: np.array(v) for k, v in holder["p"].items()},
        )
        try:
            if self.algo == "diloco":
                algo = DiLoCo(
                    manager,
                    [["layer0"], ["layer1"]][: self.n_fragments]
                    if self.n_fragments > 1
                    else [["layer0", "layer1"]],
                    get_params,
                    set_params,
                    optax.sgd(0.5, momentum=0.9, nesterov=True),
                    sync_every=self.sync_every,
                    should_quantize=self.quantize,
                    device_quantize=self.device_quantize,
                )
            else:
                algo = LocalSGD(manager, get_params, set_params, self.sync_every)
            target_steps = self.outer_syncs * (
                self.n_fragments if self.algo == "diloco" else 1
            )
            while manager.current_step() < target_steps:
                faults.check(
                    "train.step",
                    replica=f"diloco_{self.replica_id}",
                    step=manager.current_step(),
                )
                if self.inner_sleep:
                    time.sleep(self.inner_sleep)
                # deterministic inner update (same on all replicas)
                p = get_params()
                set_params(
                    {k: v - 0.01 * (1.0 + i) for i, (k, v) in enumerate(sorted(p.items()))}
                )
                algo.step()
            return {
                "params": get_params(),
                "manager_state": manager.state_dict(),
            }
        finally:
            manager.shutdown()


def run_replicas(runners) -> "List[dict]":
    with ThreadPoolExecutor(max_workers=len(runners)) as ex:
        futures = [ex.submit(r.run) for r in runners]
        return [f.result(timeout=180) for f in futures]


def assert_params_equal(results):
    base = results[0]["params"]
    for other in results[1:]:
        for k in base:
            np.testing.assert_array_equal(base[k], other["params"][k])


class TestLocalSGDInteg:
    def test_local_sgd_healthy(self, lighthouse):
        runners = [
            DiLoCoRunner(
                i, lighthouse.address(), algo="local_sgd", outer_syncs=3)
            for i in range(2)
        ]
        results = run_replicas(runners)
        assert all(r["manager_state"]["step"] == 3 for r in results)
        assert_params_equal(results)

    def test_local_sgd_recovery(self, lighthouse):
        faults.FAULTS.configure([fail_at(replica=1, step=1)])
        runners = [
            DiLoCoRunner(
                i, lighthouse.address(), algo="local_sgd", outer_syncs=4)
            for i in range(2)
        ]
        results = run_replicas(runners)
        assert faults.FAULTS.injected() == 1
        assert all(r["manager_state"]["step"] == 4 for r in results)
        assert_params_equal(results)


class TestDiLoCoInteg:
    def test_diloco_healthy_two_fragments(self, lighthouse):
        runners = [
            DiLoCoRunner(
                i, lighthouse.address(), outer_syncs=3)
            for i in range(2)
        ]
        results = run_replicas(runners)
        # step counts fragment syncs: 3 rounds x 2 fragments
        assert all(r["manager_state"]["step"] == 6 for r in results)
        assert_params_equal(results)

    def test_diloco_quantized_allreduce(self, lighthouse):
        # int8-quantized pseudogradient exchange: lossy vs f32, but the
        # dequantized result is identical bytes on every replica, so
        # cross-replica bitwise equality still holds
        runners = [
            DiLoCoRunner(
                i, lighthouse.address(), outer_syncs=3, quantize=True
            )
            for i in range(2)
        ]
        results = run_replicas(runners)
        assert all(r["manager_state"]["step"] == 6 for r in results)
        assert_params_equal(results)

    def test_diloco_device_quantized_pipeline(self, lighthouse, monkeypatch):
        """DiLoCo's quantized leg routed through ``device_quantize=True``
        (ROADMAP item 1 / ISSUE 8 satellite): the pseudogradients stay
        jax arrays, the Pallas int8 kernel (interpret mode on CPU)
        quantizes before the D2H copy, and the per-chunk payload copies
        ride the chunked wire pipeline.  Parity: bitwise-equal across
        replicas (same reduced bytes), and close to the host-codec run
        (paths differ only by own-slice quantization error)."""
        from torchft_tpu.ops import pallas_quant

        launches = []
        real = pallas_quant.fused_quantize_into_int8

        def counted(mat):
            launches.append(mat.shape)
            return real(mat)

        monkeypatch.setattr(
            pallas_quant, "fused_quantize_into_int8", counted
        )
        # fragments big enough that the (rows, 2048) matrix splits into
        # several pipeline chunks at CHUNK_ROWS=2 — the "full chunked
        # pipeline" part of the satellite
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "2")
        dev = run_replicas(
            [
                DiLoCoRunner(
                    i, lighthouse.address(), outer_syncs=2, quantize=True,
                    device_quantize=True, param_elems=12_000,
                )
                for i in range(2)
            ]
        )
        assert launches, "device path never hit the Pallas quantizer"
        assert_params_equal(dev)
        host = run_replicas(
            [
                DiLoCoRunner(
                    i, lighthouse.address(), outer_syncs=2, quantize=True,
                    device_quantize=False, param_elems=12_000,
                )
                for i in range(2)
            ]
        )
        assert_params_equal(host)
        for k, v in dev[0]["params"].items():
            hv = host[0]["params"][k]
            denom = np.abs(hv).max() + 1e-9
            assert np.abs(np.asarray(v) - hv).max() / denom < 0.05, k

    def test_diloco_recovery(self, lighthouse):
        faults.FAULTS.configure([fail_at(replica=1, step=2)])
        runners = [
            DiLoCoRunner(
                i, lighthouse.address(), outer_syncs=4)
            for i in range(2)
        ]
        results = run_replicas(runners)
        assert faults.FAULTS.injected() == 1
        assert all(r["manager_state"]["step"] == 8 for r in results)
        assert_params_equal(results)

    def test_diloco_upscale_mid_run(self, lighthouse):
        # Third replica joins after the first two have synced a couple of
        # times.  The join is gated on OBSERVED fleet progress (lighthouse
        # ``max_step``), not a wall-clock delay: a fixed sleep assumes the
        # first two replicas are mid-run when it expires, which a loaded
        # host breaks in both directions (the load-flake CHANGES PR 3
        # recorded).  inner_sleep paces every remaining step at >= 0.2 s,
        # so triggering at max_step >= 2 leaves ~1.6 s of join headroom
        # regardless of how slowly this test got scheduled.
        runners = [
            DiLoCoRunner(
                i, lighthouse.address(), outer_syncs=5, inner_sleep=0.05
            )
            for i in range(3)
        ]
        status = LighthouseClient(lighthouse.address())
        join_seen = {}

        def run_third():
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                doc = status.status(timeout=5.0)
                if doc.get("max_step", 0) >= 2:
                    break
                time.sleep(0.02)
            join_seen["max_step"] = doc.get("max_step", 0)
            return runners[2].run()

        with ThreadPoolExecutor(max_workers=3) as ex:
            futures = [ex.submit(runners[0].run), ex.submit(runners[1].run)]
            futures.append(ex.submit(run_third))
            # one shared deadline: sequential per-future waits would stack
            # to 3x on a wedge and hold CI for ~11 minutes before failing
            deadline = time.monotonic() + 180.0
            ordered = [
                f.result(timeout=max(0.0, deadline - time.monotonic()))
                for f in futures
            ]
        status.close()
        # the join landed mid-run: progress had started but not finished
        assert 2 <= join_seen["max_step"] < 10, join_seen
        assert all(r["manager_state"]["step"] == 10 for r in ordered)
        assert_params_equal(ordered)
