"""Checkpoint transport round-trip tests.

Mirrors reference torchft/checkpointing/{http_transport_test,
pg_transport_test, transport_test}.py: full + chunked HTTP fetch, RWLock
serving guarantees, PG transport incl. in-place receive.
"""

import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from torchft_tpu.checkpointing import HTTPTransport, PGTransport
from torchft_tpu.checkpointing import serialization as ser
from torchft_tpu.coordination import StoreServer
from torchft_tpu.parallel.process_group import ProcessGroupTCP


def sample_state_dict():
    return {
        "user": {
            "params": {
                "w": np.arange(12, dtype=np.float32).reshape(3, 4),
                "b": np.zeros(4, dtype=np.float32),
            },
            "opt": [np.ones(3, dtype=np.float64), 7],
            "label": "hello",
        },
        "torchft": {"step": 5, "batches_committed": 10},
    }


def assert_state_dicts_equal(a, b):
    import jax

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        else:
            assert x == y


class TestSerialization:
    def test_round_trip(self):
        sd = sample_state_dict()
        assert_state_dicts_equal(ser.deserialize(ser.serialize(sd)), sd)

    def test_chunked_round_trip(self):
        sd = sample_state_dict()
        import jax

        n = len(jax.tree_util.tree_flatten(sd)[0])
        chunks = ser.split_chunks(n, 3)
        assert sorted(sum(chunks, [])) == list(range(n))
        merged = {}
        skeleton = None
        for idx in chunks:
            s, leaves, total = ser.deserialize_from(
                __import__("io").BytesIO(ser.serialize(sd, chunk_indices=idx))
            )
            skeleton = s
            merged.update(leaves)
        assert_state_dicts_equal(ser.reassemble(skeleton, merged, n), sd)

    def test_missing_chunk_detected(self):
        sd = sample_state_dict()
        import io

        s, leaves, n = ser.deserialize_from(
            io.BytesIO(ser.serialize(sd, chunk_indices=[0]))
        )
        with pytest.raises(ValueError, match="missing leaf"):
            ser.reassemble(s, leaves, n)

    def test_jax_arrays(self):
        import jax.numpy as jnp

        sd = {"w": jnp.arange(6.0).reshape(2, 3)}
        out = ser.deserialize(ser.serialize(sd))
        np.testing.assert_array_equal(out["w"], np.arange(6.0).reshape(2, 3))


class TestHTTPTransport:
    def test_full_round_trip(self):
        sender = HTTPTransport(timeout=10.0)
        receiver = HTTPTransport(timeout=10.0)
        try:
            sd = sample_state_dict()
            sender.send_checkpoint([1], step=5, state_dict=sd, timeout=10.0)
            out = receiver.recv_checkpoint(
                src_rank=0, metadata=sender.metadata(), step=5, timeout=10.0
            )
            assert_state_dicts_equal(out, sd)
        finally:
            sender.shutdown()
            receiver.shutdown()

    def test_chunked_round_trip(self):
        sender = HTTPTransport(timeout=10.0, num_chunks=3)
        receiver = HTTPTransport(timeout=10.0, num_chunks=3)
        try:
            sd = sample_state_dict()
            sender.send_checkpoint([1], step=2, state_dict=sd, timeout=10.0)
            out = receiver.recv_checkpoint(
                src_rank=0, metadata=sender.metadata(), step=2, timeout=10.0
            )
            assert_state_dicts_equal(out, sd)
        finally:
            sender.shutdown()
            receiver.shutdown()

    def test_inplace_recv_into_live_state(self):
        sd = sample_state_dict()
        import jax

        live = jax.tree_util.tree_map(
            lambda x: np.zeros_like(x) if isinstance(x, np.ndarray) else x, sd
        )
        sender = HTTPTransport(timeout=10.0)
        receiver = HTTPTransport(timeout=10.0, state_dict_fn=lambda: live)
        try:
            sender.send_checkpoint([1], step=7, state_dict=sd, timeout=10.0)
            out = receiver.recv_checkpoint(
                src_rank=0, metadata=sender.metadata(), step=7, timeout=10.0
            )
            assert_state_dicts_equal(out, sd)
            # numpy leaves were filled in place: same buffers as `live`
            out_leaves = jax.tree_util.tree_flatten(out)[0]
            live_leaves = jax.tree_util.tree_flatten(live)[0]
            for o, l in zip(out_leaves, live_leaves):
                if isinstance(l, np.ndarray):
                    assert o is l
        finally:
            sender.shutdown()
            receiver.shutdown()

    def test_inplace_mismatch_falls_back(self):
        sd = sample_state_dict()
        receiver = HTTPTransport(
            timeout=10.0, state_dict_fn=lambda: {"wrong": np.zeros(1)}
        )
        sender = HTTPTransport(timeout=10.0)
        try:
            sender.send_checkpoint([1], step=8, state_dict=sd, timeout=10.0)
            out = receiver.recv_checkpoint(
                src_rank=0, metadata=sender.metadata(), step=8, timeout=10.0
            )
            assert_state_dicts_equal(out, sd)
        finally:
            sender.shutdown()
            receiver.shutdown()

    def test_wrong_step_404(self):
        sender = HTTPTransport(timeout=5.0)
        try:
            sender.send_checkpoint([1], step=5, state_dict={"x": 1}, timeout=5.0)
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"{sender.metadata()}/checkpoint/99/full", timeout=5
                )
        finally:
            sender.shutdown()

    def test_disallow_checkpoint(self):
        sender = HTTPTransport(timeout=5.0)
        try:
            sender.send_checkpoint([1], step=1, state_dict={"x": 1}, timeout=5.0)
            sender.disallow_checkpoint()
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"{sender.metadata()}/checkpoint/1/full", timeout=5
                )
        finally:
            sender.shutdown()


class TestPGTransport:
    def _pair(self, store, state_dict_fn=None):
        pgs = [ProcessGroupTCP(timeout=10.0) for _ in range(2)]
        threads = [
            threading.Thread(
                target=pgs[r].configure,
                args=(f"{store.address()}/pgt", f"r{r}", r, 2),
            )
            for r in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        return (
            PGTransport(pgs[0], timeout=10.0),
            PGTransport(pgs[1], timeout=10.0, state_dict_fn=state_dict_fn),
            pgs,
        )

    def test_round_trip(self):
        with StoreServer() as store:
            sender, receiver, pgs = self._pair(store)
            sd = sample_state_dict()
            out = {}

            def send():
                sender.send_checkpoint([1], step=5, state_dict=sd, timeout=10.0)

            def recv():
                out["sd"] = receiver.recv_checkpoint(
                    src_rank=0, metadata="<n/a>", step=5, timeout=10.0
                )

            ts = [threading.Thread(target=send), threading.Thread(target=recv)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(20)
            assert_state_dicts_equal(out["sd"], sd)
            for pg in pgs:
                pg.shutdown()

    def test_in_place_receive(self):
        with StoreServer() as store:
            target = {
                "user": {
                    "params": {
                        "w": np.zeros((3, 4), dtype=np.float32),
                        "b": np.zeros(4, dtype=np.float32),
                    },
                    "opt": [np.zeros(3, dtype=np.float64), 0],
                    "label": "",
                },
                "torchft": {"step": 0, "batches_committed": 0},
            }
            sender, receiver, pgs = self._pair(store, state_dict_fn=lambda: target)
            sd = sample_state_dict()
            out = {}

            def send():
                sender.send_checkpoint([1], step=5, state_dict=sd, timeout=10.0)

            def recv():
                out["sd"] = receiver.recv_checkpoint(
                    src_rank=0, metadata="<n/a>", step=5, timeout=10.0
                )

            ts = [threading.Thread(target=send), threading.Thread(target=recv)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(20)
            assert_state_dicts_equal(out["sd"], sd)
            # fast path: the result's array leaves ARE the target's buffers
            assert out["sd"]["user"]["params"]["w"] is target["user"]["params"]["w"]
            np.testing.assert_array_equal(
                target["user"]["params"]["w"], sd["user"]["params"]["w"]
            )
            for pg in pgs:
                pg.shutdown()

    def test_step_mismatch(self):
        with StoreServer() as store:
            sender, receiver, pgs = self._pair(store)
            errs = {}

            def send():
                try:
                    sender.send_checkpoint([1], step=5, state_dict={"x": np.ones(2)}, timeout=5.0)
                except Exception as e:  # noqa: BLE001
                    errs["send"] = e

            def recv():
                try:
                    receiver.recv_checkpoint(src_rank=0, metadata="", step=7, timeout=5.0)
                except Exception as e:  # noqa: BLE001
                    errs["recv"] = e

            ts = [threading.Thread(target=send), threading.Thread(target=recv)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(15)
            assert "step mismatch" in str(errs["recv"])
            for pg in pgs:
                pg.shutdown()


class TestBf16AndZeroDim:
    def test_bf16_round_trip(self):
        # TPU's default training dtype must survive serialization (ml_dtypes
        # have no buffer-protocol format char — regression for memoryview.cast)
        import jax.numpy as jnp
        import ml_dtypes

        sd = {
            "w": np.full((4, 3), 1.5, dtype=np.float32).astype(ml_dtypes.bfloat16),
            "step": np.asarray(7, dtype=np.int32),
            "j": jnp.ones((2,), dtype=jnp.bfloat16),
        }
        out = ser.deserialize(ser.serialize(sd))
        assert out["w"].dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(
            out["w"].astype(np.float32), np.full((4, 3), 1.5, np.float32)
        )
        assert out["step"].shape == () and out["step"] == 7
        assert out["j"].dtype == ml_dtypes.bfloat16

    def test_bf16_http_transport(self):
        import ml_dtypes

        sender = HTTPTransport(timeout=10.0)
        receiver = HTTPTransport(timeout=10.0)
        try:
            sd = {"w": np.arange(6, dtype=np.float32).astype(ml_dtypes.bfloat16)}
            sender.send_checkpoint([1], step=3, state_dict=sd, timeout=10.0)
            out = receiver.recv_checkpoint(
                src_rank=0, metadata=sender.metadata(), step=3, timeout=10.0
            )
            assert out["w"].dtype == ml_dtypes.bfloat16
        finally:
            sender.shutdown()
            receiver.shutdown()

    def test_version_keyed_staging_retention(self):
        """Serving-tier contract (ISSUE 12): concurrently publishing
        version V+1 while clients still fetch V must not retire V early
        — V survives until it ages out of the staging window."""
        import threading

        tr = HTTPTransport(timeout=10.0, max_staged=3)
        try:
            docs = {
                v: {"w": np.full(2048, float(v), np.float32)}
                for v in range(1, 6)
            }
            tr.send_checkpoint([], step=1, state_dict=docs[1], timeout=5.0)
            tr.send_checkpoint([], step=2, state_dict=docs[2], timeout=5.0)
            # fetch V=1 from many threads WHILE V=3 (and then V=4) stage
            results = {}

            def _fetch(i):
                try:
                    results[i] = tr.recv_checkpoint(
                        src_rank=0, metadata=tr.metadata(), step=1,
                        timeout=10.0,
                    )
                except Exception as e:  # noqa: BLE001 - asserted below
                    results[i] = e

            threads = [
                threading.Thread(target=_fetch, args=(i,)) for i in range(4)
            ]
            for t in threads:
                t.start()
            tr.send_checkpoint([], step=3, state_dict=docs[3], timeout=5.0)
            for t in threads:
                t.join(timeout=20)
                assert not t.is_alive()
            # every concurrent fetch of V=1 completed with V=1's bytes
            for i, out in results.items():
                assert not isinstance(out, Exception), f"fetch {i}: {out}"
                np.testing.assert_array_equal(out["w"], docs[1]["w"])
            # window is 3: V=1 still staged after the concurrent publish
            assert tr.staged_steps() == [1, 2, 3]
            # a FOURTH version finally ages V=1 out (oldest first)
            tr.send_checkpoint([], step=4, state_dict=docs[4], timeout=5.0)
            assert tr.staged_steps() == [2, 3, 4]
        finally:
            tr.shutdown()

    def test_staging_writer_never_starved_by_fetch_storm(self):
        """The writer-priority lock: a continuous 503-poll storm on the
        read side must not starve send_checkpoint (the serving soak's
        failure mode before the turnstile)."""
        import threading
        import time as _time
        import urllib.error
        import urllib.request

        tr = HTTPTransport(timeout=10.0, max_staged=4)
        stop = threading.Event()

        def _poll():
            # hammer an unstaged step: each request takes the read lock
            while not stop.is_set():
                try:
                    urllib.request.urlopen(
                        f"{tr.metadata()}/checkpoint/999/full", timeout=1.0
                    )
                except (urllib.error.HTTPError, OSError):
                    pass

        threads = [
            threading.Thread(target=_poll, daemon=True) for _ in range(8)
        ]
        try:
            for t in threads:
                t.start()
            _time.sleep(0.2)  # let the storm densify
            t0 = _time.monotonic()
            tr.send_checkpoint(
                [], step=1, state_dict={"w": np.ones(4)}, timeout=5.0
            )
            staged_in = _time.monotonic() - t0
            assert staged_in < 5.0, f"staging starved for {staged_in:.1f}s"
            assert 1 in tr.staged_steps()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
            tr.shutdown()

    def test_fragment_resource(self):
        """frag_<name> serves exactly one staged fragment; an unknown
        fragment is a permanent 404, distinct from the unstaged 503."""
        import urllib.error
        import urllib.request

        from torchft_tpu.checkpointing import serialization as ser

        tr = HTTPTransport(timeout=10.0)
        try:
            doc = {
                "frag:manifest": {"version": 3, "fragments": ["0"]},
                "frag:0": {"w": np.arange(4, dtype=np.float32)},
            }
            tr.send_checkpoint([], step=3, state_dict=doc, timeout=5.0)
            with urllib.request.urlopen(
                f"{tr.metadata()}/checkpoint/3/frag_0", timeout=5.0
            ) as resp:
                skeleton, leaves, n = ser.deserialize_from(resp)
            frag = ser.reassemble(skeleton, leaves, n)
            np.testing.assert_array_equal(frag["w"], doc["frag:0"]["w"])
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{tr.metadata()}/checkpoint/3/frag_nope", timeout=5.0
                )
            assert ei.value.code == 404
            # unstaged version stays the retryable 503
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{tr.metadata()}/checkpoint/99/frag_0", timeout=5.0
                )
            assert ei.value.code == 503
        finally:
            tr.shutdown()

    def test_recv_retries_until_staged(self):
        # healer fetches BEFORE the sender stages: must poll, not fail
        import threading
        import time as _time

        sender = HTTPTransport(timeout=10.0)
        receiver = HTTPTransport(timeout=10.0)
        try:
            sd = {"w": np.ones(3)}

            def stage_late():
                _time.sleep(0.5)
                sender.send_checkpoint([1], step=9, state_dict=sd, timeout=5.0)

            t = threading.Thread(target=stage_late)
            t.start()
            out = receiver.recv_checkpoint(
                src_rank=0, metadata=sender.metadata(), step=9, timeout=10.0
            )
            t.join()
            np.testing.assert_array_equal(out["w"], np.ones(3))
        finally:
            sender.shutdown()
            receiver.shutdown()
