"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path); env must be set before jax initializes its backends.
"""

import os
import sys

# Force (not setdefault): the machine environment pre-sets JAX_PLATFORMS to
# the TPU platform and a sitecustomize registers its PJRT plugin; the env
# var alone does not win, so also override via jax.config before any backend
# initialization.
os.environ["JAX_PLATFORMS"] = "cpu"

# Tier-1 runs with the runtime lock-order detector armed (must be set
# before the first torchft_tpu import, which creates the instrumented
# locks).  Export TORCHFT_LOCKCHECK=0 to opt out locally.
os.environ.setdefault("TORCHFT_LOCKCHECK", "1")

# ...and with live topology-plan verification armed (ISSUE 19): every
# reduction plan build, serving tree_commit, and stripe resolution the
# suite exercises is validated against the tft-plan invariant catalog.
# Observe-only (a rejection is metrics + flight record + ERROR log, never
# a raise); tests/test_plan_verify.py gates on zero rejections.  Export
# TORCHFT_PLAN_VERIFY=0 to opt out locally.
os.environ.setdefault("TORCHFT_PLAN_VERIFY", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices; the XLA_FLAGS
    # --xla_force_host_platform_device_count=8 above covers it there
    pass
