"""utils/lockcheck.py: order-graph construction, cycle detection on an
intentionally-cyclic pair (the acceptance bar), reentrancy, hold-time
outliers, Condition compatibility, and the disabled fast path."""

import threading
import time

import pytest

from torchft_tpu.utils import lockcheck


@pytest.fixture(autouse=True)
def _fresh_graph():
    lockcheck.reset()
    was = lockcheck.enabled()
    lockcheck.set_enabled(True)
    yield
    lockcheck.set_enabled(was)
    lockcheck.reset()


class TestOrderGraph:
    def test_nested_acquire_records_edge(self):
        a, b = lockcheck.lock("g.A"), lockcheck.lock("g.B")
        with a:
            with b:
                pass
        assert "g.B" in lockcheck.edges().get("g.A", set())

    def test_consistent_order_is_not_a_cycle(self):
        a, b = lockcheck.lock("c.A"), lockcheck.lock("c.B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert lockcheck.cycles() == []

    def test_intentional_cycle_pair_is_flagged(self):
        """The acceptance scenario: thread 1 takes A then B, thread 2
        takes B then A — a real deadlock (both inner acquires time out),
        and the detector must name the cycle even though neither inner
        acquisition ever succeeds."""
        a, b = lockcheck.lock("dl.A"), lockcheck.lock("dl.B")
        barrier = threading.Barrier(2)

        def t1():
            with a:
                barrier.wait(timeout=5)
                if b.acquire(timeout=0.3):
                    b.release()

        def t2():
            with b:
                barrier.wait(timeout=5)
                if a.acquire(timeout=0.3):
                    a.release()

        th1, th2 = threading.Thread(target=t1), threading.Thread(target=t2)
        th1.start(), th2.start()
        th1.join(timeout=10), th2.join(timeout=10)
        cycles = lockcheck.cycles()
        assert any({"dl.A", "dl.B"} <= set(c) for c in cycles), cycles

    def test_cycle_reported_once_and_counted(self):
        from torchft_tpu.utils import metrics

        a, b = lockcheck.lock("m.A"), lockcheck.lock("m.B")
        with a:
            with b:
                pass
        # reversed order on the same thread is sequentially fine but
        # closes the order-graph cycle
        with b:
            with a:
                pass
        with b:
            with a:  # same cycle again: deduplicated
                pass
        assert len([c for c in lockcheck.cycles() if {"m.A", "m.B"} <= set(c)]) == 1
        rendered = metrics.REGISTRY.render()
        assert "torchft_lock_cycles_total{" in rendered

    def test_three_lock_transitive_cycle(self):
        a, b, c = (lockcheck.lock(f"t3.{n}") for n in "ABC")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:
                pass
        assert any({"t3.A", "t3.B", "t3.C"} <= set(cy) for cy in lockcheck.cycles())


class TestSemantics:
    def test_rlock_reentrancy(self):
        r = lockcheck.rlock("sem.R")
        with r:
            with r:
                assert r.locked()
        assert not r.locked()

    def test_rlock_reentry_adds_no_self_edge(self):
        r = lockcheck.rlock("sem.R2")
        with r:
            with r:
                pass
        assert lockcheck.cycles() == []

    def test_timeout_acquire_failure_returns_false(self):
        l = lockcheck.lock("sem.T")
        l.acquire()
        try:
            got = []
            t = threading.Thread(target=lambda: got.append(l.acquire(timeout=0.05)))
            t.start()
            t.join()
            assert got == [False]
        finally:
            l.release()

    def test_cross_thread_release_is_tolerated(self):
        """threading.Lock allows release from another thread; rwlock's
        last-reader-releases-writer-gate depends on it."""
        l = lockcheck.lock("sem.X")
        l.acquire()
        t = threading.Thread(target=l.release)
        t.start()
        t.join()
        assert not l.locked()

    def test_condition_wait_notify_reports_no_false_cycle(self):
        """threading.Condition adopts CheckedLock._is_owned; without it
        the stdlib fallback probes acquire(False) while holding, which
        attempt-time edge recording would misread as a same-name
        self-acquisition — a false deadlock alarm on every wait/notify
        (the ProcessGroupBaby cond pattern)."""
        inner = lockcheck.lock("sem.cond_probe")
        cond = threading.Condition(inner)
        with cond:
            cond.notify_all()
            cond.wait(timeout=0.01)
        with cond:
            cond.notify_all()
        assert lockcheck.cycles() == [], lockcheck.cycles()

    def test_condition_over_checked_lock(self):
        inner = lockcheck.lock("sem.cond_lock")
        cond = threading.Condition(inner)
        hits = []

        def waiter():
            with cond:
                cond.wait(timeout=5)
                hits.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        with cond:
            cond.notify()
        t.join(timeout=5)
        assert hits == [1]

    def test_hold_time_outlier_counted(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_LOCKCHECK_HOLD_MS", "10")
        l = lockcheck.lock("sem.slow")
        with l:
            time.sleep(0.05)
        assert lockcheck.hold_outliers().get("sem.slow", 0) >= 1


class TestDisabled:
    def test_disabled_returns_plain_primitives(self):
        lockcheck.set_enabled(False)
        l = lockcheck.lock("off.A")
        r = lockcheck.rlock("off.B")
        assert not isinstance(l, lockcheck.CheckedLock)
        assert not isinstance(r, lockcheck.CheckedLock)
        with l:
            pass
        with r:
            pass

    def test_enabled_reflects_setter(self):
        lockcheck.set_enabled(False)
        assert not lockcheck.enabled()
        lockcheck.set_enabled(True)
        assert lockcheck.enabled()


class TestWiredModules:
    """The instrumented production modules really produce checked locks
    when the detector is on (the tier-1 conftest arms it, so the whole
    suite doubles as a soak)."""

    def test_flightrecorder_ring_lock_instrumented(self):
        from torchft_tpu.utils import flightrecorder as fr

        rec = fr.FlightRecorder(capacity=4)
        assert isinstance(rec._lock, lockcheck.CheckedLock)
        rec.record("op")
        assert rec.total_recorded() == 1

    def test_rwlock_gates_instrumented_and_functional(self):
        from torchft_tpu.utils.rwlock import RWLock

        rw = RWLock(timeout=2)
        assert isinstance(rw._reader_lock, lockcheck.CheckedLock)
        assert isinstance(rw._writer_lock, lockcheck.CheckedLock)
        with rw.r_lock():
            pass
        with rw.w_lock():
            pass
        # the writer side is a community *gate* (released cross-thread):
        # hold-time instrumented but excluded from the order graph, so the
        # rwlock's two-mutex dance cannot report a false cycle
        edges = lockcheck.edges()
        assert "rwlock.writer_gate" not in edges.get("rwlock.reader_gate", set())
        assert "rwlock.writer_gate" not in edges
        assert not any("rwlock" in n for c in lockcheck.cycles() for n in c)

    def test_faults_registry_instrumented(self):
        from torchft_tpu.utils.faults import FaultRegistry

        reg = FaultRegistry(seed=1)
        assert isinstance(reg._lock, lockcheck.CheckedLock)
        reg.check("nope.site")  # no rules: must be a cheap no-op
