"""Ulysses all-to-all sequence parallelism: correctness vs dense reference,
agreement with ring attention, and model integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from torchft_tpu.ops.ring_attention import dense_attention, ring_attention
from torchft_tpu.ops.ulysses import ulysses_attention


def _qkv(b=2, t=16, h=4, d=8, dtype=jnp.float32):
    key = jax.random.PRNGKey(7)
    return [
        jax.random.normal(jax.random.fold_in(key, i), (b, t, h, d), dtype)
        for i in range(3)
    ]


def _cp_mesh(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("cp",))


@pytest.mark.parametrize("sp_size", [1, 2, 4])
@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense(sp_size, causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, _cp_mesh(sp_size), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_matches_ring():
    q, k, v = _qkv(t=32)
    mesh = _cp_mesh(4)
    ring = ring_attention(q, k, v, mesh, causal=True)
    uly = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ring), atol=2e-5)


def test_batch_sharded_alongside():
    q, k, v = _qkv(b=4, t=16, h=4, d=8)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "cp"))
    out = ulysses_attention(q, k, v, mesh, axis_name="cp", batch_axes=("dp",))
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_heads_not_divisible_raises():
    q, k, v = _qkv(h=3)
    with pytest.raises(Exception, match="divisible"):
        ulysses_attention(q, k, v, _cp_mesh(2))


def _gqa_qkv(b=2, t=16, h=8, hkv=2, d=8):
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, t, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, t, hkv, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, t, hkv, d))
    return q, k, v


def test_gqa_unexpanded_kv_matches_expanded():
    # kv heads cross the all-to-all unexpanded and broadcast up locally;
    # result must equal attention over pre-expanded kv
    q, k, v = _gqa_qkv()
    mesh = _cp_mesh(2)
    out = ulysses_attention(q, k, v, mesh, causal=True)
    rep = q.shape[2] // k.shape[2]
    ref = dense_attention(
        q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2), causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_gqa_ring_unexpanded_kv():
    q, k, v = _gqa_qkv()
    mesh = _cp_mesh(4)
    out = ring_attention(q, k, v, mesh, causal=True)
    rep = q.shape[2] // k.shape[2]
    ref = dense_attention(
        q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2), causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_transformer_tp_not_dividing_kv_heads_falls_back():
    # tp=4 does not divide n_kv_heads=2: the model must pre-expand K/V to a
    # tp-shardable head count instead of failing in shard_map
    from torchft_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=8, n_kv_heads=2, d_ff=64,
        n_layers=1, max_seq_len=16, dtype=jnp.float32, attn_impl="ring",
    )
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    mesh = Mesh(np.array(jax.devices()).reshape(1, 1, 2, 4),
                ("dp", "fsdp", "cp", "tp"))
    out = tfm.forward(params, tokens, cfg, mesh=mesh)
    ref = tfm.forward(
        params, tokens,
        tfm.TransformerConfig(**{**cfg.__dict__, "attn_impl": "dense"}),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_cp_less_mesh_raises_clearly():
    from torchft_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=64,
        n_layers=1, max_seq_len=16, dtype=jnp.float32, attn_impl="ring",
    )
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((4, 8), jnp.int32)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("fsdp", "tp"))
    with pytest.raises(ValueError, match="requires a 'cp' mesh axis"):
        tfm.forward(params, tokens, cfg, mesh=mesh)


def test_unknown_attn_impl_raises():
    from torchft_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=64,
        n_layers=1, max_seq_len=16, dtype=jnp.float32, attn_impl="ulyses",
    )
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="unknown attn_impl"):
        tfm.forward(params, tokens, cfg)


def test_bf16_inputs():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = ulysses_attention(q, k, v, _cp_mesh(4))
    ref = dense_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def test_grad_flows():
    q, k, v = _qkv()
    mesh = _cp_mesh(4)

    def loss(q, k, v):
        return (ulysses_attention(q, k, v, mesh) ** 2).sum()

    def ref_loss(q, k, v):
        return (dense_attention(q, k, v) ** 2).sum()

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), atol=1e-4)


def test_transformer_ulysses_matches_dense():
    from torchft_tpu.models import transformer as tfm

    # n_kv_heads == n_heads here: with tp=2, cp=2 each shard holds 2 query
    # and 2 kv heads (GQA-with-cp coverage lives in the op-level tests)
    cfg_dense = tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=64,
        n_layers=2, max_seq_len=32, dtype=jnp.float32, attn_impl="dense",
    )
    cfg_uly = tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=64,
        n_layers=2, max_seq_len=32, dtype=jnp.float32, attn_impl="ulysses",
    )
    params = tfm.init_params(jax.random.PRNGKey(0), cfg_dense)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)

    # cp must divide the per-device head count after tp sharding:
    # 4 heads / tp=2 -> 2 local heads, cp=2
    mesh = Mesh(np.array(jax.devices()).reshape(2, 1, 2, 2),
                ("dp", "fsdp", "cp", "tp"))
    ref = tfm.forward(params, tokens, cfg_dense)
    out = tfm.forward(params, tokens, cfg_uly, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=5e-5, rtol=1e-4,
    )


def test_gqa_kv_replication_when_not_divisible():
    """hkv % cp != 0 no longer asserts: kv heads replicate minimally
    (lcm path) and the result still matches the expanded dense reference."""
    q, k, v = _gqa_qkv(h=8, hkv=2)  # hkv=2 not divisible by cp=4
    mesh = _cp_mesh(4)
    out = ulysses_attention(q, k, v, mesh, causal=True)
    rep = q.shape[2] // k.shape[2]
    ref = dense_attention(
        q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2), causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_gqa_kv_replication_lcm_always_divides():
    """The minimal replication target is lcm(hkv, cp): given h % hkv == 0
    and h % cp == 0, h is divisible by both and hence by their lcm, so no
    further fallback exists.  Full-MHA expansion is the lcm itself when
    lcm == h — drive that end to end (h=12, hkv=4, cp=3 -> lcm 12 = h)."""
    from torchft_tpu.ops.ulysses import _replicated_kv_heads

    assert _replicated_kv_heads(8, 2, 4) == 4    # partial replication
    assert _replicated_kv_heads(12, 4, 3) == 12  # lcm == h: full MHA
    q, k, v = _gqa_qkv(h=12, hkv=4, t=12)
    mesh = _cp_mesh(3)  # hkv=4 not divisible by 3 -> replication engages
    out = ulysses_attention(q, k, v, mesh, causal=True)
    rep = q.shape[2] // k.shape[2]
    ref = dense_attention(
        q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2), causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_flash_tile_local_attention():
    """Lane-aligned global T engages the fused Pallas flash kernel inside
    the all-to-all layout (interpret mode off-TPU); numerics must match
    the dense path (the ring composition has the same flash-tile check)."""
    q, k, v = _qkv(b=1, t=256, h=4, d=8)
    mesh = _cp_mesh(2)  # t_full = 256 -> flash path
    out = ulysses_attention(q, k, v, mesh, causal=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-6)


def test_flash_tile_grad_flows():
    q, k, v = _qkv(b=1, t=128, h=2, d=8)
    mesh = _cp_mesh(2)

    def loss(q_):
        return jnp.sum(ulysses_attention(q_, k, v, mesh, causal=True) ** 2)

    def loss_dense(q_):
        return jnp.sum(dense_attention(q_, k, v, causal=True) ** 2)

    g = jax.grad(loss)(q)
    g_ref = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-5)
