"""ParameterServer prototype: session mint + 2-rank PG serving.

Mirrors the reference's parameter-server semantics
(reference: torchft/parameter_server.py): GET /new_session returns a
store prefix, server thread serves rank 0, client configures rank 1.
"""

import threading

import numpy as np
import pytest

from torchft_tpu.parallel.process_group import ProcessGroup, ProcessGroupTCP
from torchft_tpu.parameter_server import ParameterServer


class _EchoPS(ParameterServer):
    """Serves one allreduce then one broadcast of stored params per session."""

    params = np.arange(8, dtype=np.float32)
    sessions_served = 0
    session_error = None

    @classmethod
    def new_process_group(cls) -> ProcessGroup:
        return ProcessGroupTCP(timeout=20.0)

    def forward(self, session_id: str, pg: ProcessGroup) -> None:
        try:
            got = pg.allreduce([np.ones(4, np.float32)]).wait(timeout=20)
            np.testing.assert_array_equal(got[0], np.full(4, 3.0, np.float32))
            pg.broadcast(self.params, root=0).wait(timeout=20)
            type(self).sessions_served += 1
        except Exception as e:  # noqa: BLE001 - surfaced by the test body
            type(self).session_error = e
            raise


@pytest.fixture
def ps():
    server = _EchoPS(port=0)
    _EchoPS.sessions_served = 0
    _EchoPS.session_error = None
    yield server
    server.shutdown()


class TestParameterServer:
    def test_session_roundtrip(self, ps):
        pg = _EchoPS.new_session(ps.address())
        try:
            got = pg.allreduce([np.full(4, 2.0, np.float32)]).wait(timeout=20)
            np.testing.assert_array_equal(got[0], np.full(4, 3.0, np.float32))
            params = pg.broadcast(np.zeros(8, np.float32), root=0).wait(timeout=20)
            np.testing.assert_array_equal(params, _EchoPS.params)
        finally:
            pg.shutdown()
        assert _EchoPS.session_error is None

    def test_multiple_sequential_sessions(self, ps):
        for _ in range(2):
            pg = _EchoPS.new_session(ps.address())
            try:
                pg.allreduce([np.full(4, 2.0, np.float32)]).wait(timeout=20)
                pg.broadcast(np.zeros(8, np.float32), root=0).wait(timeout=20)
            finally:
                pg.shutdown()
        # server threads finish asynchronously after the client's last op
        done = threading.Event()

        def _poll():
            while _EchoPS.sessions_served < 2:
                if done.wait(0.05):
                    return
            done.set()

        t = threading.Thread(target=_poll, daemon=True)
        t.start()
        assert done.wait(10), "server sessions did not complete"

    def test_bad_path_rejected(self, ps):
        import urllib.error
        import urllib.request

        bad = ps.address().replace("/new_session", "/nope")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bad)

    def test_session_mint_document(self, ps):
        """GET /new_session mints a fresh uuid session whose store prefix
        is namespaced under the server's rendezvous store."""
        import urllib.request

        with urllib.request.urlopen(ps.address()) as f:
            import json as _json

            data = _json.load(f)
        assert set(data) == {"session_id", "store_addr"}
        assert f"/session/{data['session_id']}" in data["store_addr"]
        store_base = ps._store.address()
        assert data["store_addr"].startswith(store_base)
        # distinct mints -> distinct sessions (each gets its own PG pair)
        with urllib.request.urlopen(ps.address()) as f:
            data2 = _json.load(f)
        assert data2["session_id"] != data["session_id"]

    def test_rank_assignment(self, ps):
        """Server serves rank 0, the minted client configures rank 1 of a
        2-rank session PG (the reference's fixed convention)."""
        pg = _EchoPS.new_session(ps.address())
        try:
            assert pg.rank() == 1
            assert pg.size() == 2
            pg.allreduce([np.full(4, 2.0, np.float32)]).wait(timeout=20)
            pg.broadcast(np.zeros(8, np.float32), root=0).wait(timeout=20)
        finally:
            pg.shutdown()

    def test_failed_collective_tears_down_session(self):
        """A client that dies mid-session fails the server's collective;
        the session thread raises, frees its PG, and the server keeps
        minting fresh sessions."""
        server = _EchoPS(port=0)
        _EchoPS.sessions_served = 0
        _EchoPS.session_error = None
        try:
            pg = _EchoPS.new_session(server.address())
            # abandon the session mid-protocol: the server's allreduce is
            # waiting on rank 1's contribution that never comes
            pg.shutdown()
            deadline = threading.Event()
            assert not deadline.wait(0.2)
            # the server must still serve a FRESH session end-to-end
            pg2 = _EchoPS.new_session(server.address())
            try:
                got = pg2.allreduce([np.full(4, 2.0, np.float32)]).wait(
                    timeout=20
                )
                np.testing.assert_array_equal(
                    got[0], np.full(4, 3.0, np.float32)
                )
                pg2.broadcast(np.zeros(8, np.float32), root=0).wait(timeout=20)
            finally:
                pg2.shutdown()
        finally:
            server.shutdown()

    def test_new_session_retries_until_server_up(self):
        """new_session goes through the unified retry layer: a server
        that binds after the first attempts is polled, not failed."""
        import socket

        # reserve a port, delay-bind the real server onto it
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        results = {}

        def _mint():
            try:
                results["pg"] = _EchoPS.new_session(
                    f"http://127.0.0.1:{port}/new_session", timeout=20.0
                )
            except Exception as e:  # noqa: BLE001 - surfaced below
                results["error"] = e

        t = threading.Thread(target=_mint, daemon=True)
        t.start()
        # let a few connection-refused attempts happen first
        t.join(timeout=0.5)
        server = _EchoPS(port=port)
        try:
            t.join(timeout=20)
            assert not t.is_alive(), "new_session never completed"
            assert "error" not in results, results.get("error")
            pg = results["pg"]
            try:
                pg.allreduce([np.full(4, 2.0, np.float32)]).wait(timeout=20)
                pg.broadcast(np.zeros(8, np.float32), root=0).wait(timeout=20)
            finally:
                pg.shutdown()
        finally:
            server.shutdown()

    def test_new_session_deadline_bounded(self):
        """With nothing listening, new_session fails within its deadline
        with TimeoutError (the retry budget), not an unbounded hang."""
        import socket
        import time

        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        t0 = time.monotonic()
        with pytest.raises((TimeoutError, ConnectionError)):
            _EchoPS.new_session(
                f"http://127.0.0.1:{port}/new_session", timeout=1.5
            )
        assert time.monotonic() - t0 < 10
