"""ParameterServer prototype: session mint + 2-rank PG serving.

Mirrors the reference's parameter-server semantics
(reference: torchft/parameter_server.py): GET /new_session returns a
store prefix, server thread serves rank 0, client configures rank 1.
"""

import threading

import numpy as np
import pytest

from torchft_tpu.parallel.process_group import ProcessGroup, ProcessGroupTCP
from torchft_tpu.parameter_server import ParameterServer


class _EchoPS(ParameterServer):
    """Serves one allreduce then one broadcast of stored params per session."""

    params = np.arange(8, dtype=np.float32)
    sessions_served = 0
    session_error = None

    @classmethod
    def new_process_group(cls) -> ProcessGroup:
        return ProcessGroupTCP(timeout=20.0)

    def forward(self, session_id: str, pg: ProcessGroup) -> None:
        try:
            got = pg.allreduce([np.ones(4, np.float32)]).wait(timeout=20)
            np.testing.assert_array_equal(got[0], np.full(4, 3.0, np.float32))
            pg.broadcast(self.params, root=0).wait(timeout=20)
            type(self).sessions_served += 1
        except Exception as e:  # noqa: BLE001 - surfaced by the test body
            type(self).session_error = e
            raise


@pytest.fixture
def ps():
    server = _EchoPS(port=0)
    _EchoPS.sessions_served = 0
    _EchoPS.session_error = None
    yield server
    server.shutdown()


class TestParameterServer:
    def test_session_roundtrip(self, ps):
        pg = _EchoPS.new_session(ps.address())
        try:
            got = pg.allreduce([np.full(4, 2.0, np.float32)]).wait(timeout=20)
            np.testing.assert_array_equal(got[0], np.full(4, 3.0, np.float32))
            params = pg.broadcast(np.zeros(8, np.float32), root=0).wait(timeout=20)
            np.testing.assert_array_equal(params, _EchoPS.params)
        finally:
            pg.shutdown()
        assert _EchoPS.session_error is None

    def test_multiple_sequential_sessions(self, ps):
        for _ in range(2):
            pg = _EchoPS.new_session(ps.address())
            try:
                pg.allreduce([np.full(4, 2.0, np.float32)]).wait(timeout=20)
                pg.broadcast(np.zeros(8, np.float32), root=0).wait(timeout=20)
            finally:
                pg.shutdown()
        # server threads finish asynchronously after the client's last op
        done = threading.Event()

        def _poll():
            while _EchoPS.sessions_served < 2:
                if done.wait(0.05):
                    return
            done.set()

        t = threading.Thread(target=_poll, daemon=True)
        t.start()
        assert done.wait(10), "server sessions did not complete"

    def test_bad_path_rejected(self, ps):
        import urllib.error
        import urllib.request

        bad = ps.address().replace("/new_session", "/nope")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bad)
