"""tft-plan tests (ISSUE 19): the unified topology-plan IR + invariant
verifier.

Covers the tentpole surface end to end:

- IR adapter units: reduction (synthesize_plan union), serving (native
  BFS doc), stripe (first-K roster + round-robin leaf layout), plus the
  malformed-IR guard rails;
- the seeded plan-mutation catalog — every mutation caught by its NAMED
  invariant as the first ordered violation, and every invariant
  exercised by at least one mutation;
- exhaustive small-world enumeration clean on all three planes
  (worlds x topologies x churn x failover);
- the stripe property tests (satellite: disjoint exhaustive ranges over
  any roster/TORCHFT_HEAL_SOURCES/fragment-count, survives per-fragment
  failover requeue) and the one-copy-of-math pin against manager.py;
- cross-language serving-tree parity: the native lighthouse BFS and the
  pure-Python reference produce the SAME tree (fanout, capacity
  override, expiry) and the same IR;
- the TORCHFT_PLAN_VERIFY runtime hook: accept/reject/error verdicts in
  torchft_plan_verify_total, the plan.verify flight record,
  torchft-diagnose naming a bad plan (signal ``bad_plan``), and the
  observe-only guarantee (never raises into a committing path);
- live integration: a real 2-group hierarchical allreduce and a real
  publish->relay->fetch serving round under TORCHFT_PLAN_VERIFY=1 with
  ZERO rejections (the suite-wide arming in conftest.py makes every
  other integration test an implicit instance of this gate).
"""

import dataclasses
import json
import logging
import random
import time

import numpy as np
import pytest

from tests.test_process_group import make_group, run_parallel, store  # noqa: F401
from torchft_tpu import diagnose
from torchft_tpu.analysis import plan_ir as pir
from torchft_tpu.analysis import plan_verify as pv
from torchft_tpu.coordination import LighthouseClient, LighthouseServer
from torchft_tpu.ops import topology as T
from torchft_tpu.ops.collectives import allreduce_quantized
from torchft_tpu.parallel.process_group import REDUCE_SUM
from torchft_tpu.serving import ServingClient, ServingReplica, WeightPublisher
from torchft_tpu.utils import flightrecorder as fr
from torchft_tpu.utils import metrics as _metrics


def _count(plane, verdict):
    return _metrics.PLAN_VERIFY_TOTAL.labels(plane=plane, verdict=verdict).get()


def _wait_until(cond, timeout=15.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# IR adapters
# ---------------------------------------------------------------------------


class TestReductionIR:
    def test_hosts2_world6_shape(self):
        topo = T.parse_topology("hosts:2", 6)
        ir = pir.reduction_ir(topo, wire="int8", slice_nbytes=64)
        assert ir.plane == "reduction" and ir.unit == "slice"
        assert ir.units == 3  # three groups -> three row-slices
        assert {n.id for n in ir.nodes} == {f"r{i}" for i in range(6)}
        assert ir.node("r0").role == "leader" and ir.node("r1").role == "member"
        assert ir.node("r3").host == "g1"
        # leaders are the requant boundaries; every rank is a consumer
        assert ir.boundaries == ("r0", "r2", "r4")
        assert ir.roots == ("r0",)
        assert set(ir.consumers) == {n.id for n in ir.nodes}
        hops = {e.hop for e in ir.edges}
        assert hops == {
            "intra.reduce", "inter.exchange", "inter.gather", "intra.bcast",
        }
        # only the broadcast leg is a distribution-tree edge
        assert all(
            e.tree == (e.hop == "intra.bcast") for e in ir.edges
        )
        # inter-leader legs move one slice; intra legs the whole bundle
        for e in ir.edges:
            if e.hop.startswith("inter."):
                assert e.nbytes == 64
            else:
                assert e.nbytes == 64 * 3

    def test_coverage_tiles_for_every_rank(self):
        topo = T.parse_topology("hosts:2", 6)
        ir = pir.reduction_ir(topo, slice_nbytes=64)
        for rank in range(6):
            spans = sorted(
                (o.lo, o.hi) for o in ir.coverage if o.consumer == f"r{rank}"
            )
            covered = set()
            for lo, hi in spans:
                covered.update(range(lo, hi))
            assert covered == set(range(ir.units)), f"r{rank}"

    def test_verifies_clean_including_single_host(self):
        for spec, world in (("hosts:2", 6), ("hosts:1", 5), ("hosts:4", 4),
                            ("0,1;2,3,4", 5)):
            topo = T.parse_topology(spec, world)
            ir = pir.reduction_ir(topo, slice_nbytes=64)
            assert pv.verify_plan(ir) == [], (spec, world)


class TestServingIR:
    def test_reference_doc_round_trips_to_ir(self):
        ir = pv.base_serving_ir()
        assert ir.plane == "serving" and ir.units == 1
        assert ir.roots == ("pub:p0",)
        assert ir.fanout == 2 and ir.epoch == 3
        # s0 carries its capacity override into the node
        assert ir.node("s0").capacity == 3
        relays = [e for e in ir.edges if e.hop == "serving.relay"]
        sources = [e for e in ir.edges if e.hop == "serving.source"]
        assert len(relays) == 6 and len(sources) == 1
        assert sources[0].src == "pub:p0" and sources[0].dst == "s0"
        # capacity-3 root takes three children under fanout 2
        assert sorted(e.dst for e in relays if e.src == "s0") == [
            "s1", "s2", "s3",
        ]
        assert pv.verify_plan(ir) == []

    def test_no_publisher_root_holds_local(self):
        members = [
            {"replica_id": f"s{i}", "address": f"http://s{i}:1",
             "role": "server"}
            for i in range(3)
        ]
        doc = pir.reference_serving_plan(members, fanout=2)
        ir = pir.serving_ir(doc)
        assert ir.roots == ("s0",)
        (own,) = [o for o in ir.coverage if o.consumer == "s0"]
        assert own.via == ""  # root serves whatever it already holds
        assert pv.verify_plan(ir) == []

    def test_empty_membership_is_a_valid_plan(self):
        ir = pir.serving_ir(pir.reference_serving_plan([], fanout=2))
        assert ir.nodes == () and pv.verify_plan(ir) == []


class TestStripeIR:
    def test_nominal_assignment_round_robin(self):
        ir = pv.base_stripe_ir(num_fragments=6, num_leaves=17)
        assert ir.plane == "stripe" and ir.unit == "leaf" and ir.units == 17
        assert ir.node("http://src0:1").role == "primary"
        assert ir.consumers == ("healer",)
        # exactly one tree edge: the primary's (manifest-defining) leg
        tree = [e for e in ir.edges if e.tree]
        assert [e.src for e in tree] == ["http://src0:1"]
        assert tree[0].hop == "heal.primary"
        # fragment f's slots ride via sources[f % len(sources)]
        for o in ir.coverage:
            frag = o.lo % 6
            assert o.via == f"http://src{frag % 4}:1"
        assert pv.verify_plan(ir) == []

    def test_empty_sources_rejected(self):
        with pytest.raises(ValueError, match="no sources"):
            pir.stripe_ir([], 2, 8)

    def test_primary_cannot_fail_over(self):
        ir = pv.base_stripe_ir()
        with pytest.raises(ValueError, match="primary"):
            pir.stripe_reassign(ir, "http://src0:1")


class TestMalformedIR:
    def test_dangling_edge_raises(self):
        ir = pv.base_serving_ir()
        bad = dataclasses.replace(ir, edges=ir.edges + (
            pir.PlanEdge("s0", "ghost", "serving.relay"),
        ))
        with pytest.raises(ValueError, match="unknown node"):
            pv.verify_plan(bad)

    def test_out_of_range_ownership_raises(self):
        ir = pv.base_stripe_ir()
        bad = dataclasses.replace(ir, coverage=ir.coverage + (
            pir.Ownership("healer", 0, ir.units + 1),
        ))
        with pytest.raises(ValueError, match="out of"):
            pv.verify_plan(bad)

    def test_node_lookup(self):
        ir = pv.base_serving_ir()
        assert ir.node("s3").id == "s3"
        with pytest.raises(KeyError):
            ir.node("nope")


# ---------------------------------------------------------------------------
# Seeded plan mutations: each caught by its NAMED invariant
# ---------------------------------------------------------------------------


class TestPlanMutations:
    @pytest.mark.parametrize(
        "mut", pv.PLAN_MUTATIONS, ids=[m.name for m in pv.PLAN_MUTATIONS]
    )
    def test_mutation_caught_by_named_invariant(self, mut):
        violations = pv.check_plan_mutation(mut.name)
        assert violations, f"{mut.name} slipped past the verifier"
        assert violations[0].invariant == mut.catches, (
            f"{mut.name}: first violation is {violations[0].invariant}, "
            f"expected {mut.catches}"
        )

    def test_every_invariant_exercised(self):
        assert {m.catches for m in pv.PLAN_MUTATIONS} == set(pv.INVARIANTS)

    def test_unknown_mutation_rejected(self):
        with pytest.raises(KeyError):
            pv.check_plan_mutation("no_such_bug")

    def test_base_plans_are_clean(self):
        # the mutation catalog only proves anything if its bases verify
        assert pv.verify_plan(pv.base_serving_ir()) == []
        assert pv.verify_plan(pv.base_reduction_ir()) == []
        assert pv.verify_plan(pv.base_stripe_ir()) == []


# ---------------------------------------------------------------------------
# Exhaustive small-world enumeration + elastic stability
# ---------------------------------------------------------------------------


class TestEnumeration:
    def test_all_small_world_plans_verify_clean(self):
        result = pv.explore_plans()
        assert result["violations"] == []
        assert result["plans"] > 500  # the space must stay meaningfully big

    def test_hosts_k_elastic_stability(self):
        for k in range(1, 6):
            assert pv.elastic_stability(f"hosts:{k}", range(1, 10)) == []

    def test_drifting_assignment_is_flagged(self):
        violations = pv.check_plan_mutation("rerank_drift")
        assert violations and all(
            v.invariant == "elastic-stability" for v in violations
        )


# ---------------------------------------------------------------------------
# Stripe property tests (satellite: disjoint exhaustive ranges under any
# roster x TORCHFT_HEAL_SOURCES x fragment-count, survives failover)
# ---------------------------------------------------------------------------


def _random_participants(rng, n, max_step):
    out = []
    for i in range(n):
        step = max_step if rng.random() < 0.7 else max_step - rng.randint(1, 3)
        p = {
            "replica_id": f"rep{i}",
            "address": f"http://rep{i}:8470" if rng.random() < 0.9 else "",
            "step": step,
        }
        if rng.random() < 0.05:
            p = "corrupt-entry"  # roster math must skip non-dict junk
        out.append(p)
    return out


class TestStripeProperties:
    def test_grid_disjoint_and_exhaustive(self):
        for nsrc in range(1, 6):
            sources = [f"http://s{i}:1" for i in range(nsrc)]
            for nfrag in (1, 2, 3, 5, 8):
                for leaves in (1, 2, 5, 13):
                    ir = pir.stripe_ir(sources, nfrag, leaves)
                    owned = []
                    for o in ir.coverage:
                        owned.extend(range(o.lo, o.hi))
                    # disjoint AND exhaustive over the leaf space
                    assert sorted(owned) == list(range(leaves)), (
                        nsrc, nfrag, leaves,
                    )
                    assert pv.verify_plan(ir) == []

    def test_random_rosters_and_failover_requeue(self):
        rng = random.Random(19)
        for _ in range(60):
            n = rng.randint(1, 10)
            max_step = rng.randint(5, 50)
            parts = _random_participants(rng, n, max_step)
            max_sources = rng.randint(1, 6)
            primary_index = rng.randrange(n)
            roster = pir.stripe_roster(
                parts, max_step, primary_index, max_sources
            )
            # the bound check runs after the append (faithful port of the
            # manager's historical loop), so max_sources=1 still admits
            # one extra candidate
            assert len(roster) <= max(1, max_sources - 1)
            for addr in roster:
                i = next(
                    j for j, p in enumerate(parts)
                    if isinstance(p, dict) and p.get("address") == addr
                )
                assert i != primary_index
                assert parts[i]["step"] == max_step
            primary = "http://primary:1"
            sources = [primary] + roster
            nfrag = rng.randint(1, 9)
            leaves = rng.randint(1, 40)
            ir = pir.stripe_ir(sources, nfrag, leaves, step=max_step)
            assert pv.verify_plan(ir) == []
            # every per-fragment failover requeue must still verify
            for dead in sources[1:]:
                assert pv.verify_plan(pir.stripe_reassign(ir, dead)) == []

    def test_cohort_is_first_k_max_step(self):
        parts = [
            {"replica_id": "a", "step": 9},
            {"replica_id": "b", "step": 8},
            {"replica_id": "c", "step": 9},
            "garbage",
            {"replica_id": "d", "step": 9},
        ]
        assert pir.stripe_source_cohort(parts, 9, 2) == ["a", "c"]
        assert pir.stripe_source_cohort(parts, 9, 10) == ["a", "c", "d"]

    def test_manager_consumes_the_one_copy_of_the_math(self):
        # the healer and the verifier share stripe_roster/_source_cohort;
        # a reintroduced inline copy in manager.py is how they drift
        import inspect

        from torchft_tpu.manager import Manager

        assert "stripe_roster" in inspect.getsource(
            Manager._resolve_stripe_sources
        )
        assert "stripe_source_cohort" in inspect.getsource(
            Manager._in_stripe_source_set
        )


# ---------------------------------------------------------------------------
# Cross-language serving-tree parity (satellite: native BFS == reference)
# ---------------------------------------------------------------------------


def _native_members(plan):
    """Reconstruct the membership the native BFS saw from its output."""
    members = [
        {"replica_id": n["replica_id"], "address": n["address"],
         "role": "server", "capacity": n["capacity"],
         "version": n["version"]}
        for n in plan["nodes"]
    ]
    members.extend(
        {"replica_id": p["replica_id"], "address": p["address"],
         "role": "publisher", "version": p["version"],
         "version_ms": p["version_ms"]}
        for p in plan["publishers"]
    )
    return members


def _assert_parity(plan):
    ref = pir.reference_serving_plan(
        _native_members(plan), plan["fanout"], epoch=plan["epoch"]
    )
    assert ref["root_source"] == plan["root_source"]
    assert ref["depth"] == plan["depth"]
    by_id = {n["replica_id"]: n for n in plan["nodes"]}
    assert len(by_id) == len(ref["nodes"])
    for rn in ref["nodes"]:
        nn = by_id[rn["replica_id"]]
        for key in ("parent", "depth", "children", "capacity"):
            assert rn[key] == nn[key], (rn["replica_id"], key)
    # and the two docs adapt to the SAME IR (edge-for-edge)
    a, b = pir.serving_ir(plan), pir.serving_ir(ref)
    assert set(a.edges) == set(b.edges)
    assert set(a.coverage) == set(b.coverage)
    assert a.roots == b.roots
    assert pv.verify_plan(a) == []


class TestServingTreeParity:
    def test_fanout_tree_parity(self):
        with LighthouseServer(min_replicas=1, serving_fanout=2) as server:
            c = LighthouseClient(server.address())
            c.serving_heartbeat("pub", "http://p:1", role="publisher",
                                version=3)
            for i in range(7):
                c.serving_heartbeat(f"s{i}", f"http://s{i}:1", role="server")
            _assert_parity(c.serving_plan())

    def test_capacity_override_parity(self):
        with LighthouseServer(min_replicas=1, serving_fanout=2) as server:
            c = LighthouseClient(server.address())
            c.serving_heartbeat("s0", "http://s0:1", role="server",
                                capacity=4)
            for i in range(1, 6):
                c.serving_heartbeat(f"s{i}", f"http://s{i}:1", role="server")
            plan = c.serving_plan()
            _assert_parity(plan)
            root = [n for n in plan["nodes"] if n["parent"] == ""][0]
            assert root["children"] == 4  # capacity beat the fanout on BOTH sides

    def test_expiry_parity(self):
        with LighthouseServer(
            min_replicas=1, heartbeat_timeout_ms=300, quorum_tick_ms=50
        ) as server:
            c = LighthouseClient(server.address())
            c.serving_heartbeat("a", "http://a:1", role="server")
            c.serving_heartbeat("b", "http://b:1", role="server")

            def b_expired():
                c.serving_heartbeat("a", "http://a:1", role="server")
                plan = c.serving_plan()
                return [n["replica_id"] for n in plan["nodes"]] == ["a"]

            _wait_until(b_expired, msg="node b to expire from the tree")
            _assert_parity(c.serving_plan())

    def test_version_tie_first_in_order_wins(self):
        # strict > in both implementations: equal versions keep the
        # first publisher in replica_id order as root source
        members = [
            {"replica_id": "pz", "address": "http://z:1",
             "role": "publisher", "version": 7},
            {"replica_id": "pa", "address": "http://a:1",
             "role": "publisher", "version": 7},
        ]
        ref = pir.reference_serving_plan(members, fanout=2)
        assert ref["root_source"] == "http://a:1"


# ---------------------------------------------------------------------------
# Runtime hook: TORCHFT_PLAN_VERIFY
# ---------------------------------------------------------------------------


class TestLiveHook:
    def test_enabled_reads_env(self, monkeypatch):
        monkeypatch.delenv("TORCHFT_PLAN_VERIFY", raising=False)
        assert not pv.enabled()
        monkeypatch.setenv("TORCHFT_PLAN_VERIFY", "1")
        assert pv.enabled()
        monkeypatch.setenv("TORCHFT_PLAN_VERIFY", "0")
        assert not pv.enabled()

    def test_accept_counts_and_flight_record(self):
        before = _count("serving", "accept")
        assert pv.check_live(pv.base_serving_ir()) is None
        assert _count("serving", "accept") == before + 1
        recs = [
            r for r in fr.RECORDER.snapshot()
            if r["op"] == "plan.verify" and r.get("plane") == "serving"
        ]
        assert recs and recs[-1]["verdict"] == "accept"
        assert recs[-1]["status"] == "ok" and recs[-1]["step"] == 3

    def test_reject_counts_records_and_logs(self, caplog):
        ir = pv.base_serving_ir()
        bad = dataclasses.replace(ir, edges=tuple(
            e for e in ir.edges if not (e.src == "s0" and e.dst == "s1")
        ))
        before = _count("serving", "reject")
        with caplog.at_level(logging.ERROR, logger=pv.logger.name):
            first = pv.check_live(bad)
        assert first is not None
        assert first.invariant == "root-reaches-all"
        assert _count("serving", "reject") == before + 1
        assert any(
            "rejected live serving plan" in r.message for r in caplog.records
        )
        recs = [
            r for r in fr.RECORDER.snapshot()
            if r["op"] == "plan.verify" and r.get("verdict") == "reject"
        ]
        assert recs and recs[-1]["status"] == "error"
        assert recs[-1]["invariant"] == "root-reaches-all"

    def test_malformed_ir_never_raises_into_commit_path(self):
        ir = pv.base_serving_ir()
        bad = dataclasses.replace(ir, edges=ir.edges + (
            pir.PlanEdge("s0", "ghost", "serving.relay"),
        ))
        before = _count("serving", "error")
        assert pv.check_live(bad) is None  # observe-only: swallowed, counted
        assert _count("serving", "error") == before + 1


class TestDiagnoseBadPlan:
    def _dump(self, tmp_path, recs):
        path = tmp_path / "flight.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        entries, _ = diagnose.load_records([str(path)])
        return diagnose.analyze(entries)

    def test_rejected_plan_named_as_culprit(self, tmp_path):
        s = 1_000_000_000
        t0 = 1_000 * s
        report = self._dump(tmp_path, [
            {"flight": "rec", "op": "quorum_rpc", "status": "ok",
             "start_ns": t0, "end_ns": t0 + s, "replica_id": "a", "step": 4},
            {"flight": "rec", "op": "plan.verify", "status": "error",
             "start_ns": t0 + 2 * s, "end_ns": t0 + 2 * s,
             "replica_id": "a", "step": 4, "plane": "serving",
             "verdict": "reject", "invariant": "root-reaches-all",
             "detail": "2 node(s) unreachable from roots"},
        ])
        culprit = report["culprit"]
        assert culprit["signal"] == "bad_plan"
        assert culprit["replica_id"] == "a"
        assert "root-reaches-all" in culprit["reason"]
        assert "serving" in culprit["reason"]

    def test_injected_fault_still_outranks_bad_plan(self, tmp_path):
        s = 1_000_000_000
        t0 = 1_000 * s
        report = self._dump(tmp_path, [
            {"flight": "rec", "op": "fault", "status": "fault",
             "start_ns": t0, "end_ns": t0, "replica_id": "b", "step": 2,
             "fault": "train.step:raise", "site": "train.step",
             "action": "raise"},
            {"flight": "rec", "op": "plan.verify", "status": "error",
             "start_ns": t0 + s, "end_ns": t0 + s, "replica_id": "a",
             "step": 2, "plane": "stripe", "verdict": "reject",
             "invariant": "full-coverage", "detail": "gap"},
            {"flight": "rec", "op": "allreduce", "status": "error",
             "start_ns": t0 + 2 * s, "end_ns": t0 + 3 * s,
             "replica_id": "a", "step": 2, "reason": "peer closed"},
        ])
        assert report["culprit"]["signal"] == "injected_fault"

    def test_accepted_plans_never_name_a_culprit(self, tmp_path):
        s = 1_000_000_000
        t0 = 1_000 * s
        report = self._dump(tmp_path, [
            {"flight": "rec", "op": "plan.verify", "status": "ok",
             "start_ns": t0, "end_ns": t0, "replica_id": "a", "step": 1,
             "plane": "reduction", "verdict": "accept", "invariant": "",
             "detail": ""},
        ])
        assert report["culprit"] is None


# ---------------------------------------------------------------------------
# Live integration: real plans, zero rejections (the tier-1 gate; the
# conftest-wide TORCHFT_PLAN_VERIFY=1 arming makes the whole suite an
# extended version of this test)
# ---------------------------------------------------------------------------


class TestLiveZeroRejections:
    def test_hier_allreduce_plans_accepted(self, store, monkeypatch):  # noqa: F811
        monkeypatch.setenv("TORCHFT_PLAN_VERIFY", "1")
        accept0 = _count("reduction", "accept")
        reject0 = _count("reduction", "reject")
        world = 4
        pgs = make_group(store, world, prefix="planverify")
        try:
            data = [
                np.arange(24, dtype=np.float32).reshape(4, 6) + r
                for r in range(world)
            ]

            def run(rank, _):
                w = allreduce_quantized(
                    data[rank], REDUCE_SUM, pgs[rank], topology="hosts:2"
                )
                return w.wait(timeout=60)

            results = run_parallel(world, run)
            assert len(results) == world
        finally:
            for pg in pgs:
                pg.shutdown()
        # every rank validated its live reduction plan; none rejected
        assert _count("reduction", "accept") - accept0 >= world
        assert _count("reduction", "reject") == reject0

    def test_serving_round_plans_accepted(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_PLAN_VERIFY", "1")
        accept0 = _count("serving", "accept")
        reject0 = _count("serving", "reject")
        rng = np.random.RandomState(7)
        sd = {"w": rng.randn(16, 32).astype(np.float32), "step": 1}
        lh = LighthouseServer(
            min_replicas=1, heartbeat_timeout_ms=1000, quorum_tick_ms=50,
            serving_fanout=2,
        )
        pub = WeightPublisher(
            lh.address(), wire="int8", fragments=2, heartbeat_interval=0.1
        )
        reps = [
            ServingReplica(
                lh.address(), replica_id=f"pv{i}", poll_interval=0.05,
                fetch_timeout=10.0,
            )
            for i in range(2)
        ]
        client = ServingClient(lh.address(), plan_ttl=0.1)
        try:
            v = pub.publish(sd)
            _state, got = client.fetch(timeout=20)
            assert got == v
            _wait_until(
                lambda: all(r.version() == v for r in reps),
                msg="relays converged",
            )
        finally:
            client.close()
            for r in reps:
                r.shutdown()
            pub.shutdown()
            lh.shutdown()
        # every tree_commit validated its live serving plan; none rejected
        assert _count("serving", "accept") > accept0
        assert _count("serving", "reject") == reject0

    def test_no_stripe_rejections_so_far(self):
        # heal integration tests run with the hook armed suite-wide;
        # whatever has executed by now must not have rejected a plan
        assert _count("stripe", "reject") == 0
