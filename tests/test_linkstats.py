"""Fleet link-state plane (ISSUE 16): the passive per-link registry, its
hot-path budget, closed-loop estimator accuracy against the declared wire
shaping, the heartbeat-digest -> lighthouse matrix -> /links.json
aggregation round trip, the serving staleness ledger, the
``lighthouse.links`` chaos degradation, and the ``torchft-diagnose
--links`` slow-link analysis.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from tests.test_process_group import make_group, run_parallel, store  # noqa: F401
from torchft_tpu.coordination import LighthouseClient, LighthouseServer
from torchft_tpu.parallel.process_group import ProcessGroupTCP
from torchft_tpu.utils import linkstats
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils.faults import (
    FAULTS,
    FaultRule,
    InjectedConnectionDrop,
)


@pytest.fixture(autouse=True)
def _fresh_registry():
    linkstats.LINKS.reset()
    yield
    linkstats.LINKS.reset()


def _row(peer="h1", plane="reduction", local=False, goodput=1e8,
         rtt_p99=2.0, samples=16, src=None):
    r = {
        "peer": peer, "plane": plane, "local": local,
        "goodput_bps": goodput, "rtt_ms": rtt_p99 / 2,
        "rtt_p99_ms": rtt_p99, "samples": samples, "bytes": 1 << 20,
        "age_s": 0.1,
    }
    if src is not None:
        r["src"] = src
    return r


class TestRegistry:
    def test_record_and_snapshot(self):
        reg = linkstats.LinkRegistry()
        # 10 MB in 0.1 s post-first-byte => 100 MB/s
        for _ in range(4):
            reg.record("h1", "reduction", 10_000_000, 0.105,
                       first_byte_s=0.005)
        m = reg.snapshot()
        assert m.version == 4
        (s,) = m.entries
        assert (s.peer, s.plane, s.local) == ("h1", "reduction", False)
        assert s.samples == 4 and s.bytes_total == 40_000_000
        assert s.goodput_bps == pytest.approx(1e8, rel=0.01)
        assert s.rtt_p50_ms == pytest.approx(5.0, rel=0.01)
        assert s.rtt_p99_ms == pytest.approx(5.0, rel=0.01)

    def test_version_monotone_and_frozen(self):
        reg = linkstats.LinkRegistry()
        reg.record("h1", "rpc", 0, 0.001, first_byte_s=0.001)
        m1 = reg.snapshot()
        m2 = reg.snapshot()
        # equal versions name an identical matrix
        assert m1.version == m2.version
        assert [e.peer for e in m1.entries] == [e.peer for e in m2.entries]
        reg.record("h2", "rpc", 0, 0.001, first_byte_s=0.001)
        assert reg.snapshot().version > m1.version

    def test_rpc_plane_is_rtt_only(self):
        reg = linkstats.LinkRegistry()
        # whole wall == first byte: zero transfer leg, no goodput claim
        reg.record("h1", "rpc", 0, 0.002, first_byte_s=0.002)
        s = reg.snapshot().get("h1", "rpc")
        assert s.goodput_bps == 0.0
        assert s.rtt_p50_ms == pytest.approx(2.0, rel=0.01)

    def test_wan_pseudo_host_never_merges_with_local(self):
        reg = linkstats.LinkRegistry()
        # the same physical host measured as local fabric AND as a
        # shaped (WAN-modeled) boundary link: distinct keys, distinct
        # estimates — the two can never average together
        reg.record("hostA", "reduction", 1 << 20, 0.001, local=True)
        reg.record("hostA#g1", "reduction", 1 << 20, 0.1,
                   first_byte_s=0.05, local=False)
        m = reg.snapshot()
        loc = m.get("hostA", "reduction")
        wan = m.get("hostA#g1", "reduction")
        assert loc.local and not wan.local
        assert loc.goodput_bps > wan.goodput_bps * 10

    def test_decay_tracks_regime_change(self):
        reg = linkstats.LinkRegistry()
        for _ in range(32):  # old regime: 100 MB/s
            reg.record("h1", "fragments", 1_000_000, 0.01)
        for _ in range(200):  # new regime: 10 MB/s
            reg.record("h1", "fragments", 1_000_000, 0.1)
        g = reg.snapshot().get("h1", "fragments").goodput_bps
        assert g == pytest.approx(1e7, rel=0.3)

    def test_reset_rereads_env(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_LINK_WINDOW", "4")
        reg = linkstats.LinkRegistry()
        reg.reset()
        for ms in (1, 2, 3, 4, 5, 6, 7, 8):
            reg.record("h1", "rpc", 0, ms / 1e3, first_byte_s=ms / 1e3)
        # window 4: only the last 4 first-byte samples survive
        s = reg.snapshot().get("h1", "rpc")
        assert s.rtt_p50_ms >= 6.0


class TestTopkLabel:
    def test_first_k_keep_name_then_fold(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_LINK_TOPK", "3")
        reg = linkstats.LinkRegistry()
        reg.reset()
        labels = [reg.peer_topk_label(f"h{i}") for i in range(8)]
        assert labels[:3] == ["h0", "h1", "h2"]
        assert set(labels[3:]) == {"other"}
        # stable on re-ask: at most K+1 distinct label values ever
        assert reg.peer_topk_label("h0") == "h0"
        assert reg.peer_topk_label("h7") == "other"
        assert len(set(labels)) == 4


class TestDigest:
    def test_empty_registry_yields_none(self):
        assert linkstats.LinkRegistry().maybe_digest("me") is None

    def test_digest_shape_and_rate_limit(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_LINK_REPORT_S", "60")
        reg = linkstats.LinkRegistry()
        reg.reset()
        reg.record("h1", "reduction", 1 << 20, 0.01, first_byte_s=0.001)
        d = reg.maybe_digest("me")
        assert d["host"] == "me"
        (row,) = d["rows"]
        assert row["peer"] == "h1" and row["plane"] == "reduction"
        assert not row["local"] and row["samples"] == 1
        # rate-limited: not due again for 60 s
        assert reg.maybe_digest("me") is None

    def test_rows_bounded_to_worst_k_per_plane(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_LINK_TOPK", "4")
        monkeypatch.setenv("TORCHFT_LINK_REPORT_S", "0")
        reg = linkstats.LinkRegistry()
        reg.reset()
        for i in range(12):  # goodput ascending with i
            reg.record(f"h{i}", "reduction", 1 << 20, 0.1 / (i + 1))
        d = reg.maybe_digest("me")
        assert len(d["rows"]) == 4
        # worst (lowest goodput) first — the links worth shipping
        assert [r["peer"] for r in d["rows"]] == ["h0", "h1", "h2", "h3"]

    def test_digest_refreshes_bounded_gauges(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_LINK_REPORT_S", "0")
        reg = linkstats.LinkRegistry()
        reg.reset()
        reg.record("h1", "reduction", 1 << 20, 0.1, first_byte_s=0.01)
        reg.record("loc", "reduction", 1 << 20, 0.001, local=True)
        assert reg.maybe_digest("me") is not None
        assert _metrics.LINK_PAIRS.get() == 2
        # the min-goodput aggregate is WAN-only: the local row's memory-
        # speed estimate must not mask a slow wire
        wan_g = reg.snapshot().get("h1", "reduction").goodput_bps
        assert _metrics.LINK_GOODPUT_MIN.get() == pytest.approx(
            wan_g, rel=0.01
        )
        assert _metrics.LINK_GOODPUT.labels(
            peer="h1", plane="reduction"
        ).get() == pytest.approx(wan_g, rel=0.01)


class TestHotPathBudget:
    def test_record_overhead_under_budget(self):
        """Acceptance bar: <= ~2.5 us per record() — it sits inside the
        collective send path.  Best of several batches so a loaded CI
        host doesn't flake the measurement (the flight-recorder budget
        test's protocol); the implementation is one plain lock + a few
        float ops + one deque append."""
        reg = linkstats.LinkRegistry()
        n = 20_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _i in range(n):
                reg.record("h1", "reduction", 1024, 1e-3,
                           first_byte_s=1e-4)
            best = min(best, (time.perf_counter() - t0) / n)
        assert best <= 2.5e-6, f"record() hot path {best * 1e6:.2f} us"


class TestClosedLoopAccuracy:
    @staticmethod
    def _drive(store, prefix, payload_words, sends, **pg_kw):  # noqa: F811
        world = 2
        pgs = [ProcessGroupTCP(timeout=30.0, **pg_kw) for _ in range(world)]

        def cfg(rank, _):
            pgs[rank].configure(
                f"{store.address()}/{prefix}", f"r{rank}", rank, world
            )

        run_parallel(world, cfg)
        payload = np.ones(payload_words, dtype=np.float32)

        def run(rank, _):
            for i in range(sends):
                if rank == 0:
                    pgs[0].send(payload, 1, tag=i).wait(timeout=30)
                else:
                    pgs[1].recv(0, tag=i).wait(timeout=30)

        run_parallel(world, run)
        for pg in pgs:
            pg.shutdown()
        wan = [
            s for s in linkstats.LINKS.snapshot().entries
            if s.plane == "reduction" and not s.local
            and s.samples >= sends
        ]
        assert wan, "shaped sends never reached the registry"
        (s,) = wan
        return s

    def test_goodput_matches_declared_bandwidth(self, store):  # noqa: F811
        """The acceptance loop, bandwidth leg: pace a PG wire at a
        declared rate, drive real sends through it, and require the
        passive goodput estimate to land within +/-30% of the declared
        value.  RTT stays off here so the token bucket cannot refill
        during first-byte sleeps (that credit is real bandwidth-delay
        headroom, not pacing error — the RTT leg is measured below)."""
        linkstats.LINKS.reset()
        gbps = 0.25
        # ~63 MB >> the 4 MB bucket burst, 2 MiB per message
        s = self._drive(store, "lclpb", 1 << 19, 30, bandwidth_gbps=gbps)
        declared = gbps * 1e9
        assert declared * 0.7 <= s.goodput_bps <= declared * 1.3, (
            f"goodput {s.goodput_bps / 1e6:.1f} MB/s vs declared "
            f"{declared / 1e6:.1f} MB/s"
        )

    def test_rtt_matches_declared_latency(self, store):  # noqa: F811
        """...and the RTT leg: small messages on a latency-shaped wire;
        the first-byte p50 must land within +/-30% of the declared RTT."""
        linkstats.LINKS.reset()
        rtt_ms = 20.0
        s = self._drive(store, "lclpr", 256, 6, rtt_ms=rtt_ms)
        assert rtt_ms * 0.7 <= s.rtt_p50_ms <= rtt_ms * 1.3
        assert rtt_ms * 0.7 <= s.rtt_p99_ms <= rtt_ms * 1.3

    def test_boundary_pairs_key_separately_from_local(
        self, store, monkeypatch  # noqa: F811
    ):
        """A same-host peer across the declared topology boundary keys
        under the ``host#gN`` pseudo-host (WAN row); an intra-group peer
        keys under the plain host (local row)."""
        linkstats.LINKS.reset()
        monkeypatch.setenv("TORCHFT_TOPOLOGY", "0;1")
        pgs = make_group(store, 2, prefix="lsep1")
        payload = np.ones(256, dtype=np.float32)

        def run(rank, _):
            if rank == 0:
                pgs[0].send(payload, 1, tag=1).wait(timeout=20)
            else:
                pgs[1].recv(0, tag=1).wait(timeout=20)

        run_parallel(2, run)
        for pg in pgs:
            pg.shutdown()
        wan = [
            s for s in linkstats.LINKS.snapshot().entries
            if s.plane == "reduction" and not s.local
        ]
        assert wan and all("#g" in s.peer for s in wan)

        linkstats.LINKS.reset()
        monkeypatch.setenv("TORCHFT_TOPOLOGY", "0,1")
        pgs = make_group(store, 2, prefix="lsep2")
        run_parallel(2, run)
        for pg in pgs:
            pg.shutdown()
        entries = [
            s for s in linkstats.LINKS.snapshot().entries
            if s.plane == "reduction"
        ]
        assert entries
        assert all(s.local and "#" not in s.peer for s in entries)


class TestEndToEndSlowLink:
    def test_throttled_pair_reaches_diagnose_via_lighthouse(
        self, store, tmp_path  # noqa: F811
    ):
        """The whole plane, closed loop: two wires shaped at declared
        rates -> passive registry -> heartbeat digests -> lighthouse
        matrix (estimates still within +/-30% of declared) -> serialized
        /links.json artifact -> ``torchft-diagnose --links`` names the
        deliberately-throttled pair as the ``slow_link`` culprit."""
        from torchft_tpu.diagnose import analyze_links, load_links

        fast_gbps, slow_gbps = 0.25, 0.02
        linkstats.LINKS.reset()
        TestClosedLoopAccuracy._drive(
            store, "e2ef", 1 << 19, 30, bandwidth_gbps=fast_gbps
        )
        d_fast = linkstats.LINKS.maybe_digest("hfast")
        linkstats.LINKS.reset()
        TestClosedLoopAccuracy._drive(
            store, "e2es", 1 << 18, 24, bandwidth_gbps=slow_gbps
        )
        d_slow = linkstats.LINKS.maybe_digest("hslow")
        assert d_fast and d_slow
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                # two healthy reporters of the fast wire + the throttled
                # one: the fleet median is the fast rate
                c.heartbeat("rf", links=d_fast)
                c.heartbeat("rf2", links=dict(d_fast, host="hfast2"))
                c.heartbeat("rs", links=d_slow)
                doc = c.links()
            finally:
                c.close()
        by_src = {
            (r["src"], r["plane"]): r["goodput_bps"] for r in doc["rows"]
        }
        for src, declared in (("hfast", fast_gbps * 1e9),
                              ("hslow", slow_gbps * 1e9)):
            g = by_src[(src, "reduction")]
            assert declared * 0.7 <= g <= declared * 1.3, (
                f"{src} matrix goodput {g / 1e6:.1f} MB/s vs declared "
                f"{declared / 1e6:.1f} MB/s"
            )
        # the serialized-artifact path the CLI takes
        artifact = tmp_path / "links.json"
        artifact.write_text(json.dumps(doc))
        rep = analyze_links(load_links(str(artifact)))
        assert rep["culprit"]["signal"] == "slow_link"
        assert rep["culprit"]["replica_id"].startswith("link hslow->")


class TestLighthouseAggregation:
    def test_heartbeat_digest_to_matrix_round_trip(self):
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                c.heartbeat("r0", links={
                    "host": "h0",
                    "rows": [_row(peer="h1", goodput=5e7),
                             _row(peer="h2", plane="rpc", goodput=0.0,
                                  rtt_p99=8.0)],
                })
                doc = c.links()
                assert doc["rows_total"] == 2 and doc["hosts"] == 1
                assert doc["reports_total"] == 1
                v1 = doc["version"]
                assert v1 > 0
                by_peer = {r["peer"]: r for r in doc["rows"]}
                assert by_peer["h1"]["src"] == "h0"
                assert by_peer["h1"]["goodput_bps"] == pytest.approx(5e7)
                assert by_peer["h2"]["rtt_p99_ms"] == pytest.approx(8.0)
                assert by_peer["h1"]["age_ms"] >= 0
                # worst = lowest-goodput WAN row, on every page
                assert doc["worst"]["peer"] == "h1"

                # latest-wins per host: a re-report REPLACES h0's rows
                c.heartbeat("r0", links={
                    "host": "h0", "rows": [_row(peer="h3", goodput=9e7)],
                })
                doc2 = c.links()
                assert doc2["rows_total"] == 1
                assert doc2["rows"][0]["peer"] == "h3"
                # monotone matrix version: the new matrix supersedes
                assert doc2["version"] > v1
            finally:
                c.close()

    def test_http_links_json_matches_rpc_and_stays_bounded(self):
        """64 reporting hosts: GET /links.json (default page) stays under
        the 16 KB acceptance budget while fleet truth (rows_total, hosts,
        version, worst) survives pagination."""
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                for i in range(64):
                    c.heartbeat(f"r{i}", links={
                        "host": f"h{i:02d}",
                        "rows": [
                            _row(peer=f"h{(i + 1) % 64:02d}",
                                 goodput=1e8 + i),
                            _row(peer=f"h{(i + 2) % 64:02d}",
                                 plane="fragments", goodput=2e8 + i),
                            _row(peer=f"h{(i + 3) % 64:02d}",
                                 plane="rpc", goodput=0.0, rtt_p99=3.0),
                        ],
                    })
                raw = urllib.request.urlopen(
                    f"http://{srv.address()}/links.json", timeout=5
                ).read()
                assert len(raw) < 16 * 1024, (
                    f"/links.json default page is {len(raw)} B"
                )
                doc = json.loads(raw.decode())
                assert doc["rows_total"] == 192 and doc["hosts"] == 64
                assert doc["pages"] * doc["per_page"] >= 192
                # RPC serves the same document; explicit paging walks it
                page1 = c.links(page=1, per_page=10)
                assert len(page1["rows"]) == 10
                assert page1["rows_total"] == 192
                assert page1["version"] == doc["version"]
            finally:
                c.close()

    def test_serving_staleness_ledger(self):
        """Publisher stamps publish time; nodes carry their held stamp;
        the lighthouse differences them on the single publish clock."""
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                c.serving_heartbeat("pub", "http://p:1", role="publisher",
                                    version=5, version_ms=10_000)
                c.serving_heartbeat("fresh", "http://a:1", role="server",
                                    version=5, version_ms=10_000)
                c.serving_heartbeat("behind", "http://b:1", role="server",
                                    version=4, version_ms=9_400)
                c.serving_heartbeat("unstamped", "http://c:1",
                                    role="server", version=4)
                nodes = {
                    n["replica_id"]: n for n in c.serving_plan()["nodes"]
                }
                assert nodes["fresh"]["staleness_ms"] == 0
                assert nodes["behind"]["staleness_ms"] == 600
                # no stamp = unknown, not zero — never fake freshness
                assert nodes["unstamped"]["staleness_ms"] == -1
            finally:
                c.close()


class TestChaosLinksDrop:
    def test_dropped_report_degrades_to_stale_rows(self):
        """The ``lighthouse.links`` site: an injected drop loses the
        digest (rows age in place) but the heartbeat plane itself keeps
        working — telemetry loss must never wedge liveness."""
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                c.heartbeat("r0", links={
                    "host": "h0", "rows": [_row(peer="h1", goodput=5e7)],
                })
                v1 = c.links()["version"]
                FAULTS.configure([
                    FaultRule(site="lighthouse.links", action="drop",
                              times=1)
                ])
                with pytest.raises(InjectedConnectionDrop):
                    c.heartbeat("r0", links={
                        "host": "h0",
                        "rows": [_row(peer="h1", goodput=6e7)],
                    })
                # liveness survives: the next plain heartbeat goes through
                assert "error" not in c.heartbeat("r0")
                # the matrix degraded to the STALE previous rows — never
                # emptied, never wedged
                doc = c.links()
                assert doc["version"] == v1
                (row,) = doc["rows"]
                assert row["goodput_bps"] == pytest.approx(5e7)
                assert row["age_ms"] >= 0
            finally:
                FAULTS.configure([])
                c.close()


class TestDiagnoseLinks:
    def _doc(self, rows):
        return {"rows": rows, "rows_total": len(rows), "hosts": 3,
                "version": 7}

    def test_sustained_slow_link_named_as_culprit(self):
        from torchft_tpu.diagnose import analyze_links

        rows = [_row(src="h0", peer=f"h{i}", goodput=1e8, samples=20)
                for i in range(1, 5)]
        rows.append(_row(src="h0", peer="h9", goodput=1e7, samples=20))
        rep = analyze_links(self._doc(rows))
        assert rep["culprit"]["signal"] == "slow_link"
        assert rep["culprit"]["replica_id"] == "link h0->h9"
        assert rep["slow_links"][0]["peer"] == "h9"
        assert rep["rows_wan"] == 5

    def test_thin_evidence_never_names_a_culprit(self):
        from torchft_tpu.diagnose import (
            SLOW_LINK_MIN_SAMPLES,
            analyze_links,
        )

        rows = [_row(src="h0", peer=f"h{i}", goodput=1e8, samples=20)
                for i in range(1, 5)]
        # 10x below median but under the sample floor: one unlucky
        # transfer, not a sustained slow wire
        rows.append(_row(src="h0", peer="h9", goodput=1e7,
                         samples=SLOW_LINK_MIN_SAMPLES - 1))
        assert analyze_links(self._doc(rows))["culprit"] is None

    def test_local_rows_never_skew_the_median(self):
        from torchft_tpu.diagnose import analyze_links

        # memory-speed local rows + uniform WAN rows: nothing is slow
        rows = [_row(src="h0", peer="self", local=True, goodput=1e11,
                     samples=50)]
        rows += [_row(src="h0", peer=f"h{i}", goodput=1e8, samples=20)
                 for i in range(1, 4)]
        rep = analyze_links(self._doc(rows))
        assert rep["culprit"] is None
        assert rep["median_wan_goodput_bps"] == pytest.approx(1e8)

    def test_wire_split_quantifies_the_named_culprit(self):
        from torchft_tpu.diagnose import analyze_links, apply_wire_split

        rows = [_row(src="h0", peer=f"h{i}", goodput=1e8, samples=20)
                for i in range(1, 5)]
        rows.append(_row(src="h0", peer="h9", goodput=2e7, samples=20))
        links_rep = analyze_links(self._doc(rows))
        step = {
            "step": 3, "critical_replica": "r0",
            "replicas": {"r0": {"categories": {"wire": 2.0}}},
        }
        trace_rep = {"steps": [step]}
        apply_wire_split(trace_rep, links_rep)
        # 20 MB/s on a 100 MB/s-median fleet: 1/5 expected, 4/5 excess
        assert step["wire_expected_s"] == pytest.approx(0.4)
        assert step["wire_excess_s"] == pytest.approx(1.6)
        assert step["wire_slow_link"] == "h0->h9"

    def test_wire_split_noop_without_slow_link(self):
        from torchft_tpu.diagnose import analyze_links, apply_wire_split

        rows = [_row(src="h0", peer=f"h{i}", goodput=1e8, samples=20)
                for i in range(1, 5)]
        links_rep = analyze_links(self._doc(rows))
        step = {
            "step": 3, "critical_replica": "r0",
            "replicas": {"r0": {"categories": {"wire": 2.0}}},
        }
        apply_wire_split({"steps": [step]}, links_rep)
        # the split exists to quantify a named culprit, not to invent one
        assert "wire_expected_s" not in step

    def test_render_links_text_calls_out_slow_links(self):
        from torchft_tpu.diagnose import analyze_links, render_links_text

        rows = [_row(src="h0", peer=f"h{i}", goodput=1e8, samples=20)
                for i in range(1, 5)]
        rows.append(_row(src="h0", peer="h9", goodput=1e7, samples=20))
        doc = self._doc(rows)
        text = render_links_text(doc, analyze_links(doc))
        assert "SLOW LINK: h0->h9" in text
        assert "fleet link matrix" in text

    def test_load_links_over_http_and_rejects_garbage(self, tmp_path):
        from torchft_tpu.diagnose import load_links

        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                c.heartbeat("r0", links={
                    "host": "h0", "rows": [_row(peer="h1")],
                })
            finally:
                c.close()
            doc = load_links(f"http://{srv.address()}")
            assert doc["rows_total"] == 1
        p = tmp_path / "not_links.json"
        p.write_text(json.dumps({"steps": []}))
        with pytest.raises(ValueError, match="links.json"):
            load_links(str(p))


# ---------------------------------------------------------------------------
# frozen snapshot contract (ISSUE 19 satellite): LinkMatrix.snapshot()
# is the input surface the future plan synthesizer (ROADMAP item 4)
# consumes, so its row schema is pinned in analysis/plan_ir.py the same
# way the native RPC schemas are pinned in protocol.lock — a rename
# breaks HERE, not in the synthesizer.
# ---------------------------------------------------------------------------


class TestSnapshotFrozenContract:
    def _live_stat(self):
        reg = linkstats.LinkRegistry()
        reg.record("h1", "reduction", 10_000_000, 0.105, first_byte_s=0.005)
        (stat,) = reg.snapshot().entries
        return stat

    def test_linkstat_fields_pinned(self):
        import dataclasses as _dc

        from torchft_tpu.analysis import plan_ir as pir

        got = tuple(f.name for f in _dc.fields(linkstats.LinkStat))
        assert got == pir.LINK_SNAPSHOT_FIELDS, (
            "LinkStat changed shape; update plan_ir.LINK_SNAPSHOT_FIELDS "
            "and the plan synthesizer's consumers TOGETHER"
        )

    def test_wire_row_keys_pinned(self):
        from torchft_tpu.analysis import plan_ir as pir

        row = self._live_stat().to_dict()
        assert tuple(row) == pir.LINK_ROW_KEYS, (
            "LinkStat.to_dict() changed the /links.json row schema; "
            "update plan_ir.LINK_ROW_KEYS and every aggregator TOGETHER"
        )
        # the wire row round-trips through JSON without loss of keys
        assert tuple(json.loads(json.dumps(row))) == pir.LINK_ROW_KEYS

    def test_seeded_rename_is_caught(self):
        """Drift-gate selfcheck, wire-drift style: seed a field rename
        and prove the contract comparison actually fires for EVERY
        pinned key (a vacuous gate is worse than none)."""
        from torchft_tpu.analysis import plan_ir as pir

        row = self._live_stat().to_dict()
        for key in pir.LINK_ROW_KEYS:
            mutated = dict(row)
            mutated[f"{key}_v2"] = mutated.pop(key)
            assert tuple(mutated) != pir.LINK_ROW_KEYS, key

    def test_snapshot_values_survive_the_wire_row(self):
        stat = self._live_stat()
        row = stat.to_dict()
        assert row["peer"] == stat.peer and row["plane"] == stat.plane
        assert row["local"] is stat.local
        assert row["samples"] == stat.samples
        assert row["bytes"] == stat.bytes_total  # deliberate short name
        assert row["rtt_ms"] == pytest.approx(stat.rtt_p50_ms, abs=1e-3)
