"""Serving-tier WAN realism + client version pinning (ISSUE 13
satellites).

Leg 1: the serving fetch/relay paths honor the training-side wire model
(``TORCHFT_WIRE_RTT_MS`` / ``TORCHFT_WIRE_GBPS`` scoped by
``TORCHFT_TOPOLOGY``) via serving/wire.py — including the shaped-link
test pinning that fetch p99 stays bounded at 50 ms RTT.

Leg 2: ``ServingClient(pin_version=..., min_version=...)`` — pin-hit,
pin-miss (evicted version 503s to the deadline instead of silently
substituting), rollback-floor refusal, and unpinned re-resolution
staying intact.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.serving import WeightPublisher, ServingClient, fetch_resource
from torchft_tpu.serving import payload as _payload
from torchft_tpu.serving import wire as _wire


def _state(seed: int):
    rng = np.random.default_rng(seed)
    return {
        "w": rng.standard_normal((64, 32)).astype(np.float32),
        "b": rng.standard_normal((32,)).astype(np.float32),
        "step": seed,
    }


class TestWireShaperUnits:
    def test_flat_topology_shapes_every_source(self):
        s = _wire.WireShaper(10.0, 0.0, "", local_hosts={"me"})
        assert s.crosses_boundary("http://me:1234")
        assert s.crosses_boundary("http://far:1234")

    def test_declared_topology_exempts_local_host(self):
        s = _wire.WireShaper(10.0, 0.0, "hosts:2", local_hosts={"me"})
        assert not s.crosses_boundary("http://me:1234")
        assert not s.crosses_boundary("me:1234")
        assert s.crosses_boundary("http://far:1234")

    def test_charge_sleeps_one_rtt(self):
        s = _wire.WireShaper(40.0, 0.0, "", local_hosts={"me"})
        t0 = time.monotonic()
        slept = s.charge("http://far:1", 1024)
        assert time.monotonic() - t0 >= 0.035
        assert slept >= 0.035

    def test_unshaped_or_local_is_free(self):
        assert _wire.WireShaper(0.0, 0.0, "", None).charge("x:1", 1 << 20) == 0.0
        s = _wire.WireShaper(50.0, 0.5, "hosts:2", local_hosts={"me"})
        assert s.charge("http://me:1", 1 << 20) == 0.0

    def test_bandwidth_debt_beyond_burst(self):
        # 1 GB/s, 4 MiB burst: a 12 MiB message owes ~8 MiB of debt
        s = _wire.WireShaper(0.0, 1.0, "", local_hosts={"me"})
        t0 = time.monotonic()
        s.charge("http://far:1", 12 << 20)
        elapsed = time.monotonic() - t0
        assert elapsed >= (8 << 20) / 1e9 * 0.8

    def test_get_shaper_tracks_env(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_WIRE_RTT_MS", "0")
        monkeypatch.setenv("TORCHFT_WIRE_GBPS", "0")
        assert not _wire.get_shaper().active
        monkeypatch.setenv("TORCHFT_WIRE_RTT_MS", "25")
        assert _wire.get_shaper().active


class TestShapedServingFetch:
    """The satellite's shaped-link test: real staged payload, real HTTP
    fetch path, 50 ms simulated RTT — p50 pays the RTT, p99 stays
    bounded (no retry storm or compounding sleeps)."""

    def test_fetch_p99_bounded_at_50ms_rtt(self, monkeypatch):
        transport = HTTPTransport()
        try:
            doc = _payload.encode_payload(_state(3), 5, fragments=2)
            transport.send_checkpoint([], 5, doc, timeout=10)
            base = transport.metadata()
            # unshaped warm-up proves the path works without the model
            fetch_resource(base, 5, "full", timeout=10)
            monkeypatch.setenv("TORCHFT_WIRE_RTT_MS", "50")
            durations = []
            for _ in range(10):
                t0 = time.monotonic()
                got = fetch_resource(base, 5, "full", timeout=10)
                durations.append(time.monotonic() - t0)
            state = _payload.decode_payload(got)[0]
            np.testing.assert_array_equal(state["w"], _state(3)["w"])
            durations.sort()
            p50 = durations[len(durations) // 2]
            p99 = durations[-1]
            # every fetch pays the 50 ms first-byte latency once ...
            assert p50 >= 0.05, f"p50 {p50:.3f}s below the simulated RTT"
            # ... and only once: the tail stays a small multiple of it
            assert p99 < 0.5, f"p99 {p99:.3f}s unbounded under 50 ms RTT"
        finally:
            transport.shutdown()

    def test_declared_topology_keeps_local_fetch_fast(self, monkeypatch):
        transport = HTTPTransport()
        try:
            doc = _payload.encode_payload(_state(4), 2, fragments=1)
            transport.send_checkpoint([], 2, doc, timeout=10)
            monkeypatch.setenv("TORCHFT_WIRE_RTT_MS", "200")
            monkeypatch.setenv("TORCHFT_TOPOLOGY", "hosts:2")
            t0 = time.monotonic()
            fetch_resource(transport.metadata(), 2, "full", timeout=10)
            # transport metadata advertises this machine's hostname:
            # intra-host rides the local fabric unshaped
            assert time.monotonic() - t0 < 0.15
        finally:
            transport.shutdown()


@pytest.fixture
def pub_tier():
    """lighthouse + publisher with a 2-version staging window."""
    lh = LighthouseServer(
        min_replicas=1, heartbeat_timeout_ms=1000, quorum_tick_ms=50
    )
    pub = WeightPublisher(
        lh.address(), fragments=2, max_versions=2, heartbeat_interval=0.05
    )
    yield lh, pub
    pub.shutdown()
    lh.shutdown()


def _wait_latest(client: ServingClient, v: int, timeout: float = 10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if client.latest_version() >= v:
            return
        time.sleep(0.02)
    raise TimeoutError(f"serving tier never advertised v{v}")


class TestServingClientPinning:
    def test_pin_hit_serves_the_pinned_version(self, pub_tier):
        lh, pub = pub_tier
        v1 = pub.publish(_state(1))
        v2 = pub.publish(_state(2))
        client = ServingClient(lh.address(), plan_ttl=0.05, pin_version=v1)
        try:
            _wait_latest(client, v2)
            state, got = client.fetch(timeout=20)
            assert got == v1  # NOT silently upgraded to v2
            np.testing.assert_array_equal(state["w"], _state(1)["w"])
        finally:
            client.close()

    def test_pin_miss_evicted_version_errors_on_503(self, pub_tier):
        lh, pub = pub_tier
        v1 = pub.publish(_state(1))
        pub.publish(_state(2))
        pub.publish(_state(3))  # window=2: v1 evicted
        client = ServingClient(lh.address(), plan_ttl=0.05, pin_version=v1)
        try:
            _wait_latest(client, v1 + 2)
            with pytest.raises(TimeoutError):
                client.fetch(timeout=2.0)
        finally:
            client.close()

    def test_unpinned_re_resolution_still_works(self, pub_tier):
        lh, pub = pub_tier
        v1 = pub.publish(_state(1))
        client = ServingClient(lh.address(), plan_ttl=0.05)
        try:
            _wait_latest(client, v1)
            _, got1 = client.fetch(timeout=20)
            assert got1 == v1
            v2 = pub.publish(_state(2))
            _wait_latest(client, v2)
            state2, got2 = client.fetch(timeout=20)
            assert got2 == v2
            np.testing.assert_array_equal(state2["w"], _state(2)["w"])
        finally:
            client.close()

    def test_min_version_floor_refuses_rollback(self, pub_tier):
        lh, pub = pub_tier
        v1 = pub.publish(_state(1))
        client = ServingClient(
            lh.address(), plan_ttl=0.05, min_version=v1 + 10
        )
        try:
            _wait_latest_any = client.latest_version()  # plan warm
            assert _wait_latest_any >= 0
            with pytest.raises(RuntimeError, match="rollback floor"):
                client.fetch(timeout=5.0)
        finally:
            client.close()

    def test_floor_ratchets_to_fetched_version(self, pub_tier):
        lh, pub = pub_tier
        v1 = pub.publish(_state(1))
        v2 = pub.publish(_state(2))
        client = ServingClient(lh.address(), plan_ttl=0.05)
        try:
            _wait_latest(client, v2)
            _, got = client.fetch(timeout=20)
            assert got == v2
            # an explicit fetch of the OLDER (still staged) version is
            # now a refused rollback, not a silent downgrade
            with pytest.raises(RuntimeError, match="rollback floor"):
                client.fetch(version=v1, timeout=5.0)
        finally:
            client.close()

    def test_pin_below_floor_rejected_at_construction(self, pub_tier):
        lh, _pub = pub_tier
        with pytest.raises(ValueError):
            ServingClient(lh.address(), pin_version=1, min_version=5)
