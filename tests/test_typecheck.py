"""mypy strict gate over the layers that judge the tree.

The analysis code (tft-lint passes, the tft-verify model checker and
wire-schema extractor) and the utils layer it leans on must themselves
pass a type checker — a lint suite with type holes is a lint suite you
cannot trust.  Slow-marked: mypy is a dev/CI dependency, not a runtime
one, so the gate skips (loudly) where it is not installed instead of
failing the minimal image.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def _mypy_available() -> bool:
    if shutil.which("mypy"):
        return True
    try:
        import mypy  # noqa: F401

        return True
    except ImportError:
        return False


@pytest.mark.skipif(
    not _mypy_available(), reason="mypy not installed in this environment"
)
class TestStrictTyping:
    def test_analysis_and_utils_pass_strict_mypy(self):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "mypy",
                "--config-file",
                os.path.join(REPO, "mypy.ini"),
                os.path.join(REPO, "torchft_tpu", "analysis"),
                os.path.join(REPO, "torchft_tpu", "utils"),
                # the plan layer's inputs are typed end to end: the
                # topology synthesizer feeds analysis/plan_ir.py (which
                # the analysis dir above already covers)
                os.path.join(REPO, "torchft_tpu", "ops", "topology.py"),
            ],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert proc.returncode == 0, (
            f"mypy strict gate failed:\n{proc.stdout}\n{proc.stderr}"
        )


class TestConfigCommitted:
    def test_mypy_config_exists_and_targets_the_judging_layers(self):
        """The config is part of the contract even where mypy itself is
        absent: it must stay committed and keep `strict` on."""
        path = os.path.join(REPO, "mypy.ini")
        assert os.path.isfile(path)
        text = open(path, encoding="utf-8").read()
        assert "strict = True" in text

    def test_makefile_typecheck_target_wired(self):
        text = open(os.path.join(REPO, "Makefile"), encoding="utf-8").read()
        assert "typecheck:" in text and "mypy" in text
