"""Guard bench.py's MFU arithmetic: the published model-FLOPs formula and
peak-TFLOPs lookup are the credibility of the headline MFU number."""

import numpy as np


class TestModelFlops:
    def _cfg(self):
        from torchft_tpu.models.transformer import TransformerConfig

        return TransformerConfig(
            vocab_size=32000, d_model=1536, n_heads=6, n_kv_heads=3,
            d_ff=4096, n_layers=16, max_seq_len=1024,
        )

    def test_param_count_matches_actual_tree(self):
        import jax

        from bench import _model_flops_per_step
        from torchft_tpu.models.transformer import init_params

        cfg = self._cfg()
        fl = _model_flops_per_step(cfg, batch=8, seq=1024)
        params = init_params(jax.random.PRNGKey(0), cfg)
        # matmul params = everything except norms and the (gather-only)
        # embedding; the TIED head reuses embed as a matmul, so add V*E
        leaves = jax.tree_util.tree_leaves_with_path(params)
        total = 0
        for path, leaf in leaves:
            name = str(path)
            if "norm" in name or "embed" in name:
                continue
            total += leaf.size
        total += cfg.vocab_size * cfg.d_model  # tied head
        assert fl["params_matmul"] == total, (fl["params_matmul"], total)

    def test_flops_formula_structure(self):
        from bench import _model_flops_per_step

        cfg = self._cfg()
        b, t = 8, 1024
        fl = _model_flops_per_step(cfg, b, t)
        n = fl["params_matmul"]
        mm = 6 * n * b * t
        attn = 3 * (2 * 2 * b * t * t * cfg.d_model) * cfg.n_layers
        assert fl["flops"] == mm + attn
        assert fl["tokens"] == b * t

    def test_peak_flops_lookup(self):
        from bench import _peak_flops

        assert _peak_flops("TPU v5 lite") == 197e12
        assert _peak_flops("TPU v4") == 275e12
        assert _peak_flops("TPU v6e") == 918e12
        assert _peak_flops("Unknown Chip") is None


class TestCompactTailSummary:
    """The LAST bench stdout line must fit (and survive) the driver's
    2000-byte tail capture with the primary recovery metric intact
    (VERDICT r5 #2 — the r5 number was truncated out of the tail)."""

    def _fake_result(self):
        # representative of a real emission, padded so the FULL line is
        # far larger than the tail window
        return {
            "metric": "recovery_to_healthy_step_latency",
            "unit": "s",
            "value": 0.412,
            "vs_baseline": 0.412,
            "recovery_cycles_s": [0.398, 0.412, 0.455],
            "recovery_phases_ms": {
                "teardown": 12.0, "manager_init": 55.1, "quorum_rpc": 140.2,
                "pg_configure": 61.0, "heal_recv": 90.5, "ring": 33.3,
                "commit": 8.8,
            },
            "overhead_pct": 1.92,
            "crosscheck": {
                "converged_2pts": True, "gap_pts": 0.8,
                "noise_floor_bound": False,
                "pair_ratios": [1.01] * 64,  # bulk the full line
            },
            "model_overhead_pct": 0.12,
            "model": {
                "mfu_pct": 57.1, "step_ms": 225.0,
                "config": "d1536 L16 " * 40,
            },
            "diloco": {
                "shaped": {
                    "1.0": {"winner": "int8", "int8_speedup_x": 1.62,
                            "f32_sync_s": 9.1, "int8_sync_s": 5.6},
                    "0.5": {"winner": "int8", "int8_speedup_x": 2.4},
                    "0.1": {"winner": "int8", "int8_speedup_x": 3.4},
                },
                "wire_reduction_x": 3.99,
                "padding": ["x" * 100] * 40,
            },
            "serving": {
                "servers": 4, "clients": 8, "payload_mb": 2.0,
                "wire": "int8",
                "published_cps": 9.1, "delivered_total": 4000,
                "delivered_cps": 334.0, "fetch_p50_ms": 2.2,
                "fetch_p99_ms": 58.0, "failed_fetches": 0,
                "failovers": 27,
                "kill": {"victim": "bench0", "victim_children": 2,
                         "at_version": 55},
                "bitwise_identical_after_failover": True,
            },
            "ha": {
                "peers": 3, "lease_ms": 500, "trials": 3,
                "kill_to_quorum_p50_s": 0.81, "kill_to_quorum_max_s": 1.4,
                "kill_to_quorum_s": [0.7, 0.81, 1.4],
                "quorum_id_monotone": True, "term_advanced": True,
                "takeover_terms": [2, 2, 2],
            },
            "serving_depth": {
                "payload_mb": 2.0, "fragments": 8, "publishes": 3,
                "d3_rtt50_speedup_x": 2.1,
                "d3_rtt50_flat_p50_ms": 980.0,
                "d3_rtt50_stream_p50_ms": 466.0,
                "d3_rtt50_delta_p50_ms": 120.0,
                "d3_rtt50_staleness_p50_ms": 510.0,
                "d3_rtt50_frag_staleness_p50_ms": 410.0,
                "d3_rtt50_frag_staleness_max_ms": 495.0,
                "winner": "stream",
                "rtt_50ms": {"d3": {"flat_p50_ms": 980.0}},
            },
        }

    def test_summary_under_budget_with_primary_metric(self):
        import json

        from bench import COMPACT_SUMMARY_MAX_BYTES, compact_summary

        line = json.dumps(compact_summary(self._fake_result()))
        assert len(line.encode()) < COMPACT_SUMMARY_MAX_BYTES
        parsed = json.loads(line)
        assert parsed["metric"] == "recovery_to_healthy_step_latency"
        assert parsed["value"] == 0.412
        assert parsed["compact"] is True
        assert parsed["mfu_pct"] == 57.1
        assert parsed["overhead_pct"] == 1.92
        assert parsed["crosscheck"]["converged_2pts"] is True
        assert parsed["diloco_winners"]["0.5"]["winner"] == "int8"
        assert len(parsed["recovery_phases_ms_top"]) == 4
        # the serving headline survives the budget (ISSUE 12): sustained
        # checkpoints/sec, p99 fetch, and the post-failover verdict
        assert parsed["serving"]["published_cps"] == 9.1
        assert parsed["serving"]["fetch_p99_ms"] == 58.0
        assert parsed["serving"]["bitwise_identical_after_failover"] is True
        assert parsed["serving"]["failed_fetches"] == 0
        # the HA failover headline survives the budget (ISSUE 13):
        # leader-kill -> next-quorum latency + the monotonicity verdicts
        assert parsed["ha"]["kill_to_quorum_p50_s"] == 0.81
        assert parsed["ha"]["quorum_id_monotone"] is True
        assert parsed["ha"]["term_advanced"] is True
        # the fragment-provenance headline survives the budget
        # (ISSUE 18): per-fragment staleness spread at depth 3 / 50 ms
        assert parsed["fragments"]["stale_p50_ms"] == 410.0
        assert parsed["fragments"]["stale_max_ms"] == 495.0
        assert parsed["serving_depth"]["d3_rtt50_speedup_x"] == 2.1

    def test_tail_of_captured_emission_parses_to_summary(self):
        """Simulate the driver: capture full-result line + compact line,
        keep only the last 2000 bytes, parse the last complete line."""
        import json

        from bench import compact_summary, last_json_line

        result = self._fake_result()
        emission = (
            "recovery cycle 2: 0.455s phases {...}\n"  # stderr-ish noise
            + json.dumps(result) + "\n"
            + json.dumps(compact_summary(result)) + "\n"
        )
        assert len(json.dumps(result)) > 2000  # the r5 failure mode
        tail = emission[-2000:]
        parsed = last_json_line(tail)
        assert parsed["compact"] is True
        assert parsed["value"] == 0.412
        assert parsed["metric"] == "recovery_to_healthy_step_latency"

    def test_degrades_on_partial_result(self):
        from bench import compact_summary

        out = compact_summary({"error": "boom", "value": None})
        assert out["error"] == "boom"
        assert out["metric"] == "recovery_to_healthy_step_latency"

    def test_budget_enforced_on_pathological_input(self):
        import json

        from bench import COMPACT_SUMMARY_MAX_BYTES, compact_summary

        result = self._fake_result()
        # a phase dict with huge keys cannot push the line past budget
        result["recovery_phases_ms"] = {
            "phase_" + "x" * 300 + str(i): float(i) for i in range(8)
        }
        line = json.dumps(compact_summary(result))
        assert len(line.encode()) <= COMPACT_SUMMARY_MAX_BYTES
