"""Guard bench.py's MFU arithmetic: the published model-FLOPs formula and
peak-TFLOPs lookup are the credibility of the headline MFU number."""

import numpy as np


class TestModelFlops:
    def _cfg(self):
        from torchft_tpu.models.transformer import TransformerConfig

        return TransformerConfig(
            vocab_size=32000, d_model=1536, n_heads=6, n_kv_heads=3,
            d_ff=4096, n_layers=16, max_seq_len=1024,
        )

    def test_param_count_matches_actual_tree(self):
        import jax

        from bench import _model_flops_per_step
        from torchft_tpu.models.transformer import init_params

        cfg = self._cfg()
        fl = _model_flops_per_step(cfg, batch=8, seq=1024)
        params = init_params(jax.random.PRNGKey(0), cfg)
        # matmul params = everything except norms and the (gather-only)
        # embedding; the TIED head reuses embed as a matmul, so add V*E
        leaves = jax.tree_util.tree_leaves_with_path(params)
        total = 0
        for path, leaf in leaves:
            name = str(path)
            if "norm" in name or "embed" in name:
                continue
            total += leaf.size
        total += cfg.vocab_size * cfg.d_model  # tied head
        assert fl["params_matmul"] == total, (fl["params_matmul"], total)

    def test_flops_formula_structure(self):
        from bench import _model_flops_per_step

        cfg = self._cfg()
        b, t = 8, 1024
        fl = _model_flops_per_step(cfg, b, t)
        n = fl["params_matmul"]
        mm = 6 * n * b * t
        attn = 3 * (2 * 2 * b * t * t * cfg.d_model) * cfg.n_layers
        assert fl["flops"] == mm + attn
        assert fl["tokens"] == b * t

    def test_peak_flops_lookup(self):
        from bench import _peak_flops

        assert _peak_flops("TPU v5 lite") == 197e12
        assert _peak_flops("TPU v4") == 275e12
        assert _peak_flops("TPU v6e") == 918e12
        assert _peak_flops("Unknown Chip") is None
