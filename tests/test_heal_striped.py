"""Striped multi-source delta heal (ISSUE 15).

Unit layer: the shared fragment plane's heal encode
(``stage_heal_checkpoint`` — header first, fragments as they encode,
digest manifest last) and the striped receive
(``HTTPTransport.recv_checkpoint_striped`` — disjoint fragment ranges
across every source, per-fragment failover, delta diffs, ``into=``
buffer reuse).

Chaos layer: a stripe source killed MID-heal and a poisoned (bitwise-
corrupted) fragment both fail over per-fragment to surviving sources and
the heal completes bitwise — the acceptance property of the striped
rebuild.  The ``transport.heal.frag`` fault site drives the scheduled
variants.

Integration layer: a 3-replica fleet with a mid-run kill heals over the
striped path (multiple stripe sources) and converges bitwise, exactly
like the legacy path it replaced.
"""

import threading
import time

import numpy as np
import pytest

from torchft_tpu.checkpointing import fragments as frags
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.utils import faults
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils.faults import FaultRule


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


def make_state(leaves: int = 12, seed: int = 7) -> dict:
    rng = np.random.default_rng(seed)
    return {
        "user": {
            f"w{i}": rng.standard_normal(257).astype(np.float32)
            for i in range(leaves)
        },
        "torchft": {"step": 5, "batches_committed": 10},
    }


def clone_state(state: dict) -> dict:
    return {
        "user": {k: v.copy() for k, v in state["user"].items()},
        "torchft": dict(state["torchft"]),
    }


def assert_state_equal(a: dict, b: dict) -> None:
    assert a["torchft"] == b["torchft"]
    assert set(a["user"]) == set(b["user"])
    for k in a["user"]:
        np.testing.assert_array_equal(a["user"][k], b["user"][k])


@pytest.fixture
def sources():
    """Three transports, each stream-staging the SAME state at step 5 —
    bitwise-replicated heal sources."""
    state = make_state()
    transports = [HTTPTransport(timeout=10.0) for _ in range(3)]
    threads = [
        threading.Thread(
            target=t.send_checkpoint_streamed,
            args=([1], 5, state, 10.0, 6),
        )
        for t in transports
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    yield state, transports
    for t in transports:
        t.shutdown()


class TestStripedHeal:
    def test_full_heal_striped_bitwise_and_into_reuse(self, sources):
        state, transports = sources
        local = clone_state(state)
        for v in local["user"].values():
            v[:] = 0.0
        local["torchft"] = {"step": 0, "batches_committed": 0}
        retained = {k: v for k, v in local["user"].items()}
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=20.0,
                local_state_fn=lambda: local, delta=False,
            )
        finally:
            healer.shutdown()
        assert_state_equal(got, state)
        assert info["mode"] == "full"
        assert info["sources"] == 3
        assert info["changed"] == info["fragments"] == 6
        # decode landed IN the retained buffers (zero-alloc heal path)
        for k, buf in retained.items():
            assert got["user"][k] is buf
        # the phase split is the ledger's heal vocabulary
        assert set(info["phases"]) == {
            "heal_manifest", "heal_diff", "heal_wire", "heal_decode"
        }

    def test_delta_heal_wire_scales_with_changed_fragments(self, sources):
        state, transports = sources
        # rejoiner differs in exactly ONE leaf -> one changed fragment
        local = clone_state(state)
        local["user"]["w3"][:] = -1.0
        before = _metrics.HEAL_WIRE_BYTES.labels(mode="delta").get()
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=20.0,
                local_state_fn=lambda: local, delta=True,
            )
        finally:
            healer.shutdown()
        assert_state_equal(got, state)
        assert info["mode"] == "delta"
        # w3's fragment + the torchft scalars' fragment(s) at most; far
        # fewer than all 6 — and the wire carried only those bytes
        assert 1 <= info["changed"] < info["fragments"]
        delta_bytes = (
            _metrics.HEAL_WIRE_BYTES.labels(mode="delta").get() - before
        )
        assert delta_bytes == info["wire_bytes"]
        full_payload = sum(
            v.nbytes for v in state["user"].values()
        )
        assert delta_bytes < full_payload / 2

    def test_delta_identical_state_fetches_nothing(self, sources):
        state, transports = sources
        local = clone_state(state)
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=20.0,
                local_state_fn=lambda: local, delta=True,
            )
        finally:
            healer.shutdown()
        assert_state_equal(got, state)
        assert info["changed"] == 0
        assert info["wire_bytes"] == 0

    def test_kill_stripe_source_mid_heal(self, sources):
        state, transports = sources
        # Stretch every fragment fetch well past the kill delay: the
        # victim's in-flight fragments are guaranteed to still be in
        # flight when it dies, so the per-fragment failover MUST fire.
        faults.FAULTS.configure(
            [FaultRule(site="transport.heal.frag", action="delay",
                       delay=0.15, times=100)],
            seed=0,
        )
        local = clone_state(state)
        for v in local["user"].values():
            v[:] = 0.0
        killer = threading.Timer(0.05, transports[2].shutdown)
        killer.start()
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=30.0,
                local_state_fn=lambda: local, delta=False,
            )
        finally:
            killer.cancel()
            healer.shutdown()
        assert_state_equal(got, state)
        # the dead source's fragments moved to the survivors
        assert info["failovers"] >= 1
        # the delay pacing guarantees every worker held work before any
        # fetch completed, so BOTH survivors delivered fragments
        assert info["sources_used"] >= 2
        assert _metrics.HEAL_STRIPE_SOURCES.get() >= 2
        assert faults.FAULTS.injected("transport.heal.frag") > 0

    def test_dead_source_from_start_fails_over(self, sources):
        state, transports = sources
        dead = HTTPTransport(timeout=5.0)
        dead_addr = dead.metadata()
        dead.shutdown()
        local = clone_state(state)
        for v in local["user"].values():
            v[:] = 0.0
        before = _metrics.HEAL_FRAG_FAILOVERS.get()
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [transports[0].metadata(), dead_addr,
                 transports[1].metadata()],
                5, timeout=30.0,
                local_state_fn=lambda: local, delta=False,
            )
        finally:
            healer.shutdown()
        assert_state_equal(got, state)
        assert info["failovers"] >= 1
        assert _metrics.HEAL_FRAG_FAILOVERS.get() > before

    @pytest.mark.parametrize("delta", [True, False])
    def test_poisoned_fragment_fails_over_and_never_lands(
        self, sources, delta
    ):
        state, transports = sources
        # bitwise-corrupt one fragment's staged bytes on a NON-primary
        # source: its sha256 no longer matches the primary's manifest.
        # Restage through the transport API so the poison lands in BOTH
        # data planes (the Python slot and the native zero-copy mirror).
        victim = transports[1]
        with victim._staged_lock.r_lock():
            raw = bytearray(victim._staged[5].sd["frag:2"])
        raw[len(raw) // 2] ^= 0xFF
        victim.stage_streamed_part(5, "frag:2", bytes(raw))
        local = clone_state(state)
        for v in local["user"].values():
            v[:] = 0.0
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=30.0,
                local_state_fn=lambda: local, delta=delta,
            )
        finally:
            healer.shutdown()
        # the healed state is bitwise the fleet's, never the poison
        assert_state_equal(got, state)

    def test_forged_slot_fragment_cannot_contaminate_other_slots(
        self, sources
    ):
        """A corrupt fragment whose bytes DECODE but claim FOREIGN leaf
        slots must not overwrite other fragments' leaves (full mode
        decodes before the deferred verify): the slot-layout check
        rejects it and the repair pass restores it from the primary."""
        from torchft_tpu.checkpointing import serialization as ser

        state, transports = sources
        victim = transports[1]
        # forge EVERY fragment on the victim as a VALID serialized
        # stream claiming slot 0 (fragment 0's territory) with a
        # poisoned value — whatever the dynamic stripe routes to the
        # victim decodes fine but fails the slot-layout check
        forged = ser.serialize({"0": np.full(3, -777.0, dtype=np.float32)})
        for i in range(6):
            # transport API restage: forges Python slot + native mirror
            victim.stage_streamed_part(5, f"frag:{i}", forged)
        # pace fetches so every worker pops before any completes: the
        # victim's workers are guaranteed to hold (forged) fragments
        faults.FAULTS.configure(
            [FaultRule(site="transport.heal.frag", action="delay",
                       delay=0.02, times=100)],
            seed=0,
        )
        local = clone_state(state)
        for v in local["user"].values():
            v[:] = 0.0
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=30.0,
                local_state_fn=lambda: local, delta=False,
            )
        finally:
            healer.shutdown()
        # every leaf bitwise — the forged slot-0 writes never survive
        # (rejected fragments repaired digest-verified from the primary)
        assert_state_equal(got, state)
        assert info["failovers"] >= 1

    def test_poisoned_primary_fragment_heals_from_peers(self, sources):
        state, transports = sources
        primary = transports[0]
        with primary._staged_lock.r_lock():
            raw = bytearray(primary._staged[5].sd["frag:1"])
        raw[0] ^= 0xFF
        primary.stage_streamed_part(5, "frag:1", bytes(raw))
        local = clone_state(state)
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=30.0,
                local_state_fn=lambda: local, delta=True,
            )
        finally:
            healer.shutdown()
        # delta mode verifies on receipt: the primary's corrupt bytes are
        # rejected against its OWN manifest and the fragment heals from a
        # bitwise-replicated peer
        assert_state_equal(got, state)

    def test_injected_fragment_drop_absorbed_by_retry(self, sources):
        state, transports = sources
        faults.FAULTS.configure(
            [FaultRule(site="transport.heal.frag", action="drop", times=2)],
            seed=0,
        )
        local = clone_state(state)
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=30.0,
                local_state_fn=lambda: local, delta=False,
            )
        finally:
            healer.shutdown()
        assert_state_equal(got, state)
        assert faults.FAULTS.injected("transport.heal.frag") == 2


class TestHealStagingLifecycle:
    def test_streamed_slot_survives_one_commit_round(self):
        """Streamed heal slots hold immutable bytes, so they get ONE
        round of disallow_checkpoint grace — a striped healer's
        multi-request window stays open across the sources' commit —
        and retire on the second round (nothing lingers unbounded).
        Legacy slots still retire immediately."""
        state = make_state(leaves=2)
        t = HTTPTransport(timeout=5.0)
        try:
            t.send_checkpoint_streamed([1], 7, state, timeout=5.0)
            t.send_checkpoint([1], 8, state, timeout=5.0)
            assert set(t.staged_steps()) == {7, 8}
            t.disallow_checkpoint()
            assert t.staged_steps() == [7]  # legacy slot retired at once
            t.disallow_checkpoint()
            assert t.staged_steps() == []
        finally:
            t.shutdown()

    def test_header_serves_before_encode_finishes(self):
        """Cut-through contract: the digest-less header (and every
        already-staged fragment) serves while the source is still
        encoding; whole-document reads 503 until the manifest lands."""
        import urllib.error

        state = make_state(leaves=4)
        t = HTTPTransport(timeout=5.0)
        try:
            header, frag_iter = frags.iter_heal_fragments(state, 4)
            t.begin_streamed_checkpoint(
                9, {"frag:header": dict(header, version=9)}
            )
            name, raw, digest = next(frag_iter)
            t.stage_streamed_part(9, f"frag:{name}", raw)

            hbuf = frags.fetch_raw(t.metadata(), 9, "frag_header", 2.0,
                                   role="heal")
            got_header = frags.decode_manifest(hbuf)
            assert got_header["fragments"] == ["0", "1", "2", "3"]
            assert "digests" not in got_header
            fbuf = frags.fetch_raw(t.metadata(), 9, "frag_0", 2.0,
                                   role="heal")
            assert bytes(memoryview(fbuf)) == raw
            with pytest.raises((urllib.error.HTTPError, TimeoutError)):
                frags.fetch_raw(t.metadata(), 9, "full", 0.3, role="heal")
        finally:
            t.shutdown()

    def test_legacy_source_falls_back_to_whole_document(self):
        """A source that staged the legacy whole-document snapshot
        serves a striped healer via the classic full fetch (mixed-config
        fleet): frag_header 404s and the striped receive falls back."""
        state = make_state(leaves=3)
        t = HTTPTransport(timeout=5.0)
        healer = HTTPTransport(timeout=5.0)
        try:
            t.send_checkpoint([1], 4, state, timeout=5.0)
            got, info = healer.recv_checkpoint_striped(
                [t.metadata()], 4, timeout=10.0,
                local_state_fn=None, delta=False,
            )
            assert info["mode"] == "legacy"
            assert_state_equal(got, state)
        finally:
            healer.shutdown()
            t.shutdown()

    def test_into_fallback_is_counted_not_silent(self):
        """Satellite: a failing state_dict_fn no longer silently
        disables the warm-buffer receive — it logs and counts
        torchft_heal_into_fallbacks_total."""
        state = make_state(leaves=2)
        src = HTTPTransport(timeout=5.0)
        before = _metrics.HEAL_INTO_FALLBACKS.get()

        def broken_state():
            raise RuntimeError("user state fn exploded")

        healer = HTTPTransport(timeout=5.0, state_dict_fn=broken_state)
        try:
            src.send_checkpoint_streamed([1], 3, state, timeout=5.0)
            got, info = healer.recv_checkpoint_striped(
                [src.metadata()], 3, timeout=10.0, delta=False,
            )
            assert_state_equal(got, state)
            assert _metrics.HEAL_INTO_FALLBACKS.get() == before + 1
        finally:
            healer.shutdown()
            src.shutdown()

    def test_local_digest_layout_matches_staged(self):
        """local_fragment_digests must produce EXACTLY the digests a
        source stages for the same state — the delta diff's soundness."""
        state = make_state(leaves=5)
        t = HTTPTransport(timeout=5.0)
        try:
            manifest = t.send_checkpoint_streamed([1], 2, state,
                                                  timeout=5.0, fragments=4)
            _n, mine = frags.local_fragment_digests(state, 4)
            assert mine == manifest["digests"]
        finally:
            t.shutdown()


class TestStripedHealInteg:
    """Fleet-level: a killed replica heals over the striped path and
    the fleet converges bitwise (Runner/lighthouse idiom of
    test_manager_integ)."""

    def test_striped_recovery_bitwise(self):
        from test_manager_integ import (
            Runner,
            assert_bitwise_equal,
            fail_at,
            run_replicas,
        )

        from torchft_tpu.coordination import LighthouseServer

        lighthouse = LighthouseServer(
            min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        wire_before = (
            _metrics.HEAL_WIRE_BYTES.labels(mode="full").get()
            + _metrics.HEAL_WIRE_BYTES.labels(mode="delta").get()
        )
        try:
            faults.FAULTS.configure([fail_at(replica=1, step=2)])
            runners = [
                Runner(i, lighthouse.address(), total_steps=5,
                       min_replica_size=1)
                for i in range(3)
            ]
            results = run_replicas(runners)
        finally:
            lighthouse.shutdown()
        assert all(r["manager_state"]["step"] == 5 for r in results)
        assert_bitwise_equal(results)
        # the heal actually rode the striped fragment plane
        wire_after = (
            _metrics.HEAL_WIRE_BYTES.labels(mode="full").get()
            + _metrics.HEAL_WIRE_BYTES.labels(mode="delta").get()
        )
        assert wire_after > wire_before
        # the heal fetched over the fragment plane (the gauge reports
        # sources that DELIVERED; with a tiny 4-fragment state on
        # loopback one source can win every pop race, so >= 1 — the
        # deterministic >= 2 assertion lives in the delay-paced
        # TestStripedHeal.test_kill_stripe_source_mid_heal)
        assert _metrics.HEAL_STRIPE_SOURCES.get() >= 1
