"""Coordination-plane HA: endpoint parsing, the client failover walk,
leased leadership, and the lease RPC surface.

The chaos leg (SIGKILL the leader subprocess mid-quorum / mid-serving-
fetch) lives in tests/test_ha_integ.py; this file covers the fast units:
comma-list parsing, dead-first-endpoint walks, redirect following,
retry-budget accounting, lease grant semantics, and the
``lighthouse.lease`` fault site.
"""

from __future__ import annotations

import time

import pytest

from torchft_tpu.coordination import (
    LighthouseClient,
    LighthouseServer,
    NotLeaderError,
    _RpcClient,
    parse_endpoints,
)
from torchft_tpu.ha import LighthouseFleet, exclude_self, pick_free_ports
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils.faults import FAULTS, FaultRule, InjectedFault

LEASE_MS = 300


@pytest.fixture
def fleet():
    f = LighthouseFleet(n=3, min_replicas=1, lease_timeout_ms=LEASE_MS)
    try:
        f.wait_for_leader(10)
        yield f
    finally:
        f.shutdown()


class TestEndpointParsing:
    def test_single_address(self):
        assert parse_endpoints("host:1234") == ["host:1234"]

    def test_comma_list(self):
        assert parse_endpoints("a:1,b:2,c:3") == ["a:1", "b:2", "c:3"]

    def test_whitespace_and_empties_tolerated(self):
        assert parse_endpoints(" a:1 , ,b:2,  ") == ["a:1", "b:2"]

    def test_exclude_self_by_port(self):
        full = ["hostA:29510", "hostB:29511", "hostC:29512"]
        assert exclude_self(full, 29511) == ["hostA:29510", "hostC:29512"]

    def test_exclude_self_same_port_everywhere_picks_local_host(self):
        # the standard multi-host deployment: every peer on one port —
        # only the LOCAL host's entry is "me" (port alone is ambiguous
        # and must never guess: a wrong exclusion leaves this peer
        # lease-voting for itself twice)
        full = ["hostA:29510", "hostB:29510", "hostC:29510"]
        assert exclude_self(full, 29510, local_hosts={"hostB"}) == [
            "hostA:29510", "hostC:29510",
        ]

    def test_exclude_self_same_port_real_hostname(self):
        import socket

        me = socket.gethostname()
        full = [f"hostA:29510", f"{me}:29510", "hostC:29512"]
        assert exclude_self(full, 29510) == ["hostA:29510", "hostC:29512"]

    def test_exclude_self_ambiguous_same_port_raises(self):
        with pytest.raises(ValueError, match="ambiguous|match by port"):
            exclude_self(
                ["a:29510", "b:29510"], 29510, local_hosts={"nothing"}
            )

    def test_exclude_self_absent_list_unchanged(self):
        full = ["a:1", "b:2"]
        assert exclude_self(full, 9999) == full

    def test_exclude_self_ephemeral_port_never_matches(self):
        full = ["a:1", "b:2"]
        assert exclude_self(full, 0) == full


class TestFailoverWalk:
    def test_dead_first_endpoint_is_walked(self):
        # a refused port first, the live single-process lighthouse second
        (dead_port,) = pick_free_ports(1)
        with LighthouseServer(bind=":0", min_replicas=1) as server:
            before = _metrics.HA_FAILOVERS.get()
            cli = LighthouseClient(
                f"127.0.0.1:{dead_port},{server.address()}",
                connect_timeout=5.0,
            )
            try:
                t0 = time.monotonic()
                status = cli.status(timeout=10.0)
                walk_s = time.monotonic() - t0
                assert "quorum_id" in status
                # the dead endpoint cost a bounded connect slice, not the
                # caller's deadline
                assert walk_s < 5.0
                assert _metrics.HA_FAILOVERS.get() > before
            finally:
                cli.close()

    def test_redirect_follow_from_follower(self, fleet):
        leader = fleet.wait_for_leader(10)
        followers = [i for i in fleet.alive() if i != leader]
        assert followers
        before = _metrics.HA_REDIRECTS.get()
        # list ONLY follower endpoints: the walk must reach the leader
        # purely by following the NOT_LEADER redirect hint
        cli = LighthouseClient(
            ",".join(fleet.endpoints()[i] for i in followers),
            connect_timeout=5.0,
        )
        try:
            status = cli.status(timeout=10.0)
            assert "quorum_id" in status
            assert _metrics.HA_REDIRECTS.get() > before
        finally:
            cli.close()

    def test_follower_replies_not_leader_with_hint(self, fleet):
        leader = fleet.wait_for_leader(10)
        follower = next(i for i in fleet.alive() if i != leader)
        raw = _RpcClient(fleet.endpoints()[follower], 5.0)
        try:
            with pytest.raises(NotLeaderError) as exc:
                raw.call("status", {}, 5.0)
            assert exc.value.leader == fleet.endpoints()[leader]
        finally:
            raw.close()

    def test_retry_budget_never_exceeded_all_dead(self):
        dead = pick_free_ports(3)
        cli = LighthouseClient(
            ",".join(f"127.0.0.1:{p}" for p in dead), connect_timeout=5.0
        )
        try:
            t0 = time.monotonic()
            with pytest.raises((TimeoutError, ConnectionError)):
                cli.status(timeout=1.0)
            elapsed = time.monotonic() - t0
            # the 1 s call budget bounds the whole walk (+ scheduling
            # slack), regardless of endpoint count or retry passes
            assert elapsed < 2.5
        finally:
            cli.close()

    def test_single_endpoint_error_shape_unchanged(self):
        # pre-HA behavior: one dead endpoint surfaces the plain
        # connection/timeout error, no walk wrapping
        (dead_port,) = pick_free_ports(1)
        cli = LighthouseClient(f"127.0.0.1:{dead_port}", connect_timeout=0.5)
        try:
            with pytest.raises((TimeoutError, ConnectionError)):
                cli.status(timeout=1.0)
        finally:
            cli.close()


class TestLeasedLeadership:
    def test_exactly_one_leader(self, fleet):
        leaders = [
            i for i in fleet.alive() if fleet.ha_info(i)["is_leader"]
        ]
        assert len(leaders) == 1

    def test_takeover_on_leader_kill_bumps_term(self, fleet):
        term0 = fleet.term()
        killed = fleet.kill_leader()
        new_leader = fleet.wait_for_leader(15)
        assert new_leader != killed
        assert fleet.term() > term0

    def test_quorum_id_monotone_across_takeover(self, fleet):
        cli = LighthouseClient(fleet.addresses(), connect_timeout=5.0)
        try:
            q1 = cli.quorum("ha_mono:1", timeout=10.0)
            fleet.kill_leader()
            q2 = cli.quorum("ha_mono:2", timeout=15.0)
            assert q2.quorum_id > q1.quorum_id
            # term-prefixed: the new id carries a strictly higher term word
            assert (q2.quorum_id >> 32) > (q1.quorum_id >> 32)
        finally:
            cli.close()

    def test_serving_epoch_monotone_across_takeover(self, fleet):
        cli = LighthouseClient(fleet.addresses(), connect_timeout=5.0)
        try:
            cli.serving_heartbeat("srv_a", "http://a:1", role="server")
            e1 = int(cli.serving_plan()["epoch"])
            fleet.kill_leader()
            # re-registration on the new leader re-forms the tree under a
            # higher-term epoch
            reply = cli.serving_heartbeat(
                "srv_a", "http://a:1", role="server", timeout=15.0
            )
            assert int(reply["plan_epoch"]) > e1
        finally:
            cli.close()

    def test_single_process_mode_ha_info(self):
        with LighthouseServer(bind=":0", min_replicas=1) as server:
            info = server.ha_info()
            assert info["enabled"] is False
            assert info["is_leader"] is True
            assert info["term"] == 0

    def test_status_carries_ha_block(self, fleet):
        cli = LighthouseClient(fleet.addresses(), connect_timeout=5.0)
        try:
            status = cli.status(timeout=10.0)
            assert status["ha"]["enabled"] is True
            assert status["ha"]["is_leader"] is True  # redirected to leader
            assert status["ha"]["term"] >= 1
        finally:
            cli.close()


class TestHaPeersFederation:
    """Lighthouse-peer observability federation (ISSUE 15): one leader
    scrape covers the whole coordination plane via per-peer
    lease-channel state."""

    def _wait_ha_peers(self, fleet, cli, timeout=10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = cli.status(timeout=10.0)
            rows = status["ha"].get("ha_peers") or []
            if len(rows) == len(fleet.endpoints()) - 1:
                return status, rows
            time.sleep(LEASE_MS / 1000 / 4)
        raise AssertionError(
            f"leader never recorded all peers: {status['ha']}"
        )

    def test_status_ha_peers_schema_roundtrip(self, fleet):
        cli = LighthouseClient(fleet.addresses(), connect_timeout=5.0)
        try:
            status, rows = self._wait_ha_peers(fleet, cli)
            addrs = {r["address"] for r in rows}
            # the leader's rows name exactly its two peers (never itself)
            leader = status["ha"]["leader"]
            assert leader not in addrs
            assert addrs < set(fleet.endpoints())
            for r in rows:
                # schema round-trip: every documented field is present
                # and typed (the one-scrape-covers-the-plane contract)
                assert isinstance(r["term"], int) and r["term"] >= 1
                assert isinstance(r["granted"], bool)
                assert r["granted"] is True  # live fleet: grants flow
                assert 0 <= r["last_ack_age_ms"] < 10_000
                assert 0 <= r["promise_remaining_ms"] <= LEASE_MS
                assert isinstance(r["takeovers_total"], int)
                assert r["holder"] == leader
        finally:
            cli.close()

    def test_dead_peer_ack_age_grows(self, fleet):
        import urllib.request

        cli = LighthouseClient(fleet.addresses(), connect_timeout=5.0)
        try:
            self._wait_ha_peers(fleet, cli)
            leader = fleet.wait_for_leader(10)
            victim = next(i for i in fleet.alive() if i != leader)
            victim_addr = fleet.endpoints()[victim]
            fleet.kill(victim)
            time.sleep(LEASE_MS / 1000 * 2)
            status = cli.status(timeout=10.0)
            row = next(
                r for r in status["ha"]["ha_peers"]
                if r["address"] == victim_addr
            )
            # the corpse's row survives with a growing ack age — the
            # federation signal a dashboard alerts on
            assert row["last_ack_age_ms"] >= LEASE_MS
            # /metrics on the leader carries the per-peer series
            scraped = (
                urllib.request.urlopen(
                    f"http://{fleet.leader_address()}/metrics", timeout=5
                )
                .read()
                .decode()
            )
            assert "torchft_lighthouse_peer_term{peer=" in scraped
            assert (
                "torchft_lighthouse_peer_lease_ack_age_ms{peer=" in scraped
            )
            assert "torchft_lighthouse_peer_takeovers{peer=" in scraped
        finally:
            cli.close()


class TestLeaseRpc:
    def test_grant_refuse_renew_semantics(self, fleet):
        leader = fleet.wait_for_leader(10)
        follower = next(i for i in fleet.alive() if i != leader)
        peer = LighthouseClient(fleet.endpoints()[follower])
        try:
            term = fleet.term() + 100  # far above anything promised
            # the follower's promise from the live leader is fresh: a new
            # candidate is shielded out even with a higher term
            shielded = peer.lease(term, "cand_a:1")
            assert shielded["granted"] is False
            # After the promise lapses the grant path opens.  Kill BOTH
            # other peers: the survivor alone has no majority, so no new
            # leader can re-shield it while we probe the lease rules.
            for i in list(fleet.alive()):
                if i != follower:
                    fleet.kill(i)
            time.sleep(LEASE_MS / 1000 * 1.5)
            first = peer.lease(term + 100, "cand_a:1")
            # the peer may have already promised its own (or the third
            # peer's) candidacy a term; walk above it
            t = max(int(first["term"]), term + 100) + 1
            granted = peer.lease(t, "cand_a:1")
            assert granted["granted"] is True
            assert granted["holder"] == "cand_a:1"
            # same term, different candidate: refused (at most one leader
            # per term)
            rival = peer.lease(t, "cand_b:1")
            assert rival["granted"] is False
            assert rival["holder"] == "cand_a:1"
            # renewal by the holder: granted
            renewed = peer.lease(t, "cand_a:1")
            assert renewed["granted"] is True
        finally:
            peer.close()

    def test_lease_fault_site(self, fleet):
        FAULTS.configure(
            [FaultRule(site="lighthouse.lease", times=1)], seed=0
        )
        try:
            cli = LighthouseClient(fleet.addresses())
            try:
                with pytest.raises(InjectedFault):
                    cli.lease(1, "cand:1")
            finally:
                cli.close()
        finally:
            FAULTS.configure([])
