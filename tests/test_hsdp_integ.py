"""HSDP integration: FT replica dim x inner fsdp/tp pjit sharding.

Analog of the reference's fsdp_test.py (4-GPU FSDP/TP + FT replicate dim):
two thread-replicas each own a disjoint 4-device inner mesh (fsdp=2, tp=2)
on the virtual CPU backend; inner grads are computed sharded under jit, the
elastic replica dimension averages them through the real Manager/Lighthouse
stack on host buffers, and replicas must end bitwise identical.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.models import transformer as tfm
from torchft_tpu.parallel.device_mesh import ft_init_device_mesh
from torchft_tpu.parallel.process_group import ProcessGroupTCP

N_REPLICAS = 2
INNER = {"fsdp": 2, "tp": 2}


def _cfg(**kw):
    # the inner mesh has only fsdp/tp; absent axes (dp, cp) are filtered
    # out of the activation/batch specs automatically
    base = dict(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=64,
        n_layers=2, max_seq_len=16, dtype=jnp.float32, attn_impl="dense",
    )
    base.update(kw)
    return tfm.TransformerConfig(**base)


def _train_replica(replica_id, lighthouse_addr, barrier, steps=3,
                   inner=INNER, cfg=None):
    cfg = cfg or _cfg()
    devices = jax.devices()[replica_id * 4 : (replica_id + 1) * 4]
    state = {}

    manager = Manager(
        pg=ProcessGroupTCP(timeout=20.0),
        min_replica_size=N_REPLICAS,
        lighthouse_addr=lighthouse_addr,
        replica_id=f"hsdp_{replica_id}",
        group_rank=0,
        group_world_size=1,
        use_async_quorum=False,
        timeout=30.0,
        quorum_timeout=30.0,
        load_state_dict=lambda sd: state.update(
            {"params": sd["params"], "opt_state": sd["opt_state"]}
        ),
        state_dict=lambda: {
            "params": jax.tree_util.tree_map(np.asarray, state["params"]),
            "opt_state": jax.tree_util.tree_map(np.asarray, state["opt_state"]),
        },
    )
    try:
        fmesh = ft_init_device_mesh(manager, inner, devices=devices)
        mesh = fmesh.mesh
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        params = tfm.shard_params(params, mesh, cfg)
        tx = optax.sgd(0.1)
        state["params"] = params
        state["opt_state"] = tx.init(params)

        grad_fn = jax.jit(
            lambda p, t: jax.value_and_grad(tfm.loss_fn)(p, t, cfg, mesh=mesh)
        )
        rng = np.random.default_rng(100 + replica_id)  # per-replica data
        barrier.wait(timeout=60)

        while manager.current_step() < steps:
            manager.start_quorum()
            tokens = jnp.asarray(
                rng.integers(0, cfg.vocab_size, (4, cfg.max_seq_len)),
                jnp.int32,
            )
            _, grads = grad_fn(state["params"], tokens)
            host_grads = jax.tree_util.tree_map(np.asarray, grads)
            avg = manager.allreduce(host_grads).wait(timeout=30)
            if manager.should_commit():
                # healed state may arrive as host arrays; re-shard both
                params = jax.tree_util.tree_map(
                    lambda x, s: jax.device_put(
                        jnp.asarray(x), jax.sharding.NamedSharding(mesh, s)
                    ),
                    state["params"],
                    tfm.param_specs(cfg, mesh),
                )
                updates, new_opt = tx.update(
                    jax.tree_util.tree_map(jnp.asarray, avg),
                    jax.tree_util.tree_map(jnp.asarray, state["opt_state"]),
                    params,
                )
                state["params"] = optax.apply_updates(params, updates)
                state["opt_state"] = new_opt

        return {
            "params": jax.tree_util.tree_map(np.asarray, state["params"]),
            "step": manager.current_step(),
        }
    finally:
        manager.shutdown()


def _run_replicas(inner=INNER, cfg=None):
    """Fan out N_REPLICAS thread-replicas and assert the HSDP contract:
    all reach the step target and end bitwise identical."""
    assert len(jax.devices()) >= 8, "needs the 8-device CPU mesh"
    lighthouse = LighthouseServer(min_replicas=N_REPLICAS, join_timeout_ms=30000)
    try:
        barrier = threading.Barrier(N_REPLICAS)
        with ThreadPoolExecutor(max_workers=N_REPLICAS) as ex:
            futs = [
                ex.submit(
                    _train_replica, r, lighthouse.address(), barrier,
                    3, inner, cfg,
                )
                for r in range(N_REPLICAS)
            ]
            results = [f.result(timeout=300) for f in futs]
    finally:
        lighthouse.shutdown()

    assert all(r["step"] == 3 for r in results)
    # despite different per-replica data, averaged grads keep the
    # replicas bitwise identical (the HSDP replicate-dim contract)
    leaves0 = jax.tree_util.tree_leaves(results[0]["params"])
    leaves1 = jax.tree_util.tree_leaves(results[1]["params"])
    for a, b in zip(leaves0, leaves1):
        np.testing.assert_array_equal(a, b)
    return results


class TestHSDPInteg:
    def test_two_replicas_inner_fsdp_tp_converge(self):
        _run_replicas()

    def test_context_parallel_inner_mesh(self):
        """FT replica dim x inner ring-attention cp mesh: sequence
        parallelism composes with the elastic quorum (T=32 over cp=4,
        longer than the dense test so multi-chunk ring steps are real)."""
        _run_replicas(
            inner={"cp": 4}, cfg=_cfg(attn_impl="ring", max_seq_len=32)
        )


def test_train_hsdp_example():
    """End-to-end smoke of the user-facing HSDP example (demo mode)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "examples/train_hsdp.py", "--local-replicas", "2",
         "--steps", "6"],
        capture_output=True, text=True, cwd=repo, timeout=300,
    )
    assert out.returncode == 0, out.stderr + out.stdout
    assert out.stdout.count("done: 6 committed steps") == 2
