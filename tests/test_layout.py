"""Unit + property tests for online parallelism switching (ISSUE 11).

Covers the pure pieces of ``parallel/layout.py`` — planner determinism
and feasibility, interval math and slice-diff exactness, the monotone
layout-epoch state machine — plus the layout-aware
``ManagedDeviceMesh.global_batch_slice`` partition property across
shrink/grow (the satellite the elastic sampler never had), the reshard
``part_<rank>`` serving of the HTTP transport, and row/column
process-group re-formation on layout commits.  The live multi-manager
switch protocol is exercised in tests/test_reshard_integ.py.
"""

import numpy as np
import pytest

import jax

from torchft_tpu.parallel import layout as lay
from torchft_tpu.parallel.layout import (
    Layout,
    LayoutConstraints,
    LayoutError,
    LayoutState,
    ReshardError,
    feasible_layouts,
    interval_intersect,
    interval_subtract,
    partition,
    plan_fetches,
    plan_layout,
    shard_interval,
)


class TestPlanner:
    def test_pure_dp_world_is_default(self):
        for world in (1, 2, 3, 5, 8):
            plan = plan_layout(world, LayoutConstraints())
            assert plan.key() == (world, 1, 1)

    def test_memory_ceiling_forces_sharding(self):
        c = LayoutConstraints(param_bytes=1000, shard_memory_bytes=500)
        assert plan_layout(4, c).key() == (2, 2, 1)  # dp maximized first
        assert plan_layout(3, c).key() == (1, 3, 1)  # 3 is prime: all-shard
        assert plan_layout(2, c).key() == (1, 2, 1)

    def test_min_dp_floor(self):
        c = LayoutConstraints(
            min_dp=2, param_bytes=1000, shard_memory_bytes=500
        )
        assert plan_layout(4, c).key() == (2, 2, 1)
        # world 2 cannot satisfy both min_dp=2 and shard>=2
        with pytest.raises(LayoutError):
            plan_layout(2, c)

    def test_pp_requires_layer_divisibility(self):
        c = LayoutConstraints(
            layers=6, max_pp=4, param_bytes=1000, shard_memory_bytes=300
        )
        for dp, shard, pp in feasible_layouts(12, c):
            assert 6 % pp == 0 and pp <= 4
            assert dp * shard * pp == 12

    def test_batch_caps_dp(self):
        c = LayoutConstraints(global_batch_size=2)
        assert plan_layout(4, c).key() == (2, 2, 1)

    def test_deterministic_and_epoch_stamped(self):
        c = LayoutConstraints(param_bytes=1 << 20, shard_memory_bytes=1 << 19)
        a = plan_layout(6, c, epoch=7)
        b = plan_layout(6, c, epoch=7)
        assert a == b and a.epoch == 7

    def test_movement_tiebreak_prefers_previous_shard_count(self):
        # world 4 with a loose ceiling: (1,4,1) and (1,2,2)... pick via
        # prev: coming from nshards=4 prefers the 4-shard option among
        # equal-dp, equal-pp candidates
        c = LayoutConstraints(
            min_dp=1, max_pp=1, param_bytes=100, shard_memory_bytes=30
        )
        prev = Layout(1, 4, 1, 3)
        assert plan_layout(4, c, prev=prev).key() == (1, 4, 1)

    def test_coords_round_trip(self):
        layout = Layout(2, 3, 2, 0)
        seen = set()
        for r in range(layout.world):
            dp, sh, pp = layout.coords(r)
            assert 0 <= dp < 2 and 0 <= sh < 3 and 0 <= pp < 2
            seen.add((dp, sh, pp))
            assert layout.shard_index(r) == sh * layout.pp + pp
        assert len(seen) == layout.world


class TestIntervalMath:
    @pytest.mark.parametrize("n", [0, 1, 5, 17, 4096])
    @pytest.mark.parametrize("k", [1, 2, 3, 7])
    def test_partition_tiles_exactly(self, n, k):
        ivs = partition(n, k)
        assert len(ivs) == k
        cursor = 0
        for (s, e) in ivs:
            assert s == cursor and e >= s
            cursor = e
        assert cursor == n

    def test_subtract_and_intersect(self):
        assert interval_intersect((0, 10), (5, 20)) == (5, 10)
        assert interval_intersect((0, 5), (5, 10)) is None
        assert interval_subtract((0, 10), [(2, 4), (6, 8)]) == [
            (0, 2), (4, 6), (8, 10)
        ]
        assert interval_subtract((0, 10), [(0, 10)]) == []

    @pytest.mark.parametrize("old_k,new_k", [(1, 3), (3, 1), (2, 3), (4, 2)])
    def test_plan_fetches_covers_exactly_the_diff(self, old_k, new_k):
        n = 101
        owners = list(enumerate(partition(n, old_k)))
        for new_rank, need in enumerate(partition(n, new_k)):
            for my_old in [None] + list(range(old_k)):
                have = [partition(n, old_k)[my_old]] if my_old is not None else []
                fetches = plan_fetches(need, have, owners)
                got = sorted(iv for ivs in fetches.values() for iv in ivs)
                # fetched + locally held tiles `need` exactly: no gap...
                assert interval_subtract(need, have + got) == []
                # ...no overlap between fetched pieces...
                for a, b in zip(got, got[1:]):
                    assert a[1] <= b[0]
                # ...and nothing fetched that is already held locally
                for iv in got:
                    for h in have:
                        assert interval_intersect(iv, h) is None

    def test_plan_fetches_raises_on_uncovered(self):
        # owners only cover [0, 5); needing [0, 10) must fail loudly
        with pytest.raises(ReshardError):
            plan_fetches((0, 10), [], [(0, (0, 5))])


class TestLayoutState:
    def test_epochs_are_monotone(self):
        st = LayoutState()
        st.active = Layout(2, 1, 1, 0)
        st.stage(Layout(1, 2, 1, 1))
        assert st.commit(1).epoch == 1
        with pytest.raises(LayoutError):
            st.stage(Layout(2, 1, 1, 1))  # not past the active epoch

    def test_rollback_burns_the_epoch_forever(self):
        st = LayoutState()
        st.active = Layout(2, 1, 1, 0)
        st.stage(Layout(1, 2, 1, 1))
        st.rollback(1)
        # the tft-verify resize model's layout-epoch-monotone invariant,
        # enforced at runtime: a burned epoch can never be staged again
        with pytest.raises(LayoutError):
            st.stage(Layout(1, 2, 1, 1))
        assert st.next_epoch() == 2

    def test_next_epoch_exceeds_wire_observations(self):
        st = LayoutState()
        st.observe_epoch(9)
        assert st.next_epoch() == 10


class TestHealCarry:
    """While unsharded (nshards == 1) the registered state rides ordinary
    heal transfers, so a mid-run joiner in a never-switched fleet gets
    real parameters; a sharded source ships only its epoch (the reshard
    path repairs the joiner at the next switch)."""

    @staticmethod
    def _ctrl(values):
        from torchft_tpu.parallel.layout import LayoutController

        store = {"w": np.array(values, dtype=np.float32)}
        ctrl = LayoutController(LayoutConstraints())
        ctrl.register_sharded_state(
            "model",
            {"w": len(values)},
            lambda: dict(store),
            lambda new: store.update(
                {k: np.array(v) for k, v in new.items()}
            ),
        )
        return ctrl, store

    def test_unsharded_state_rides_heal(self):
        src, _ = self._ctrl([1.0, 2.0, 3.0, 4.0])
        src.state.active = Layout(3, 1, 1, 0)
        dst, dst_store = self._ctrl([0.0, 0.0, 0.0, 0.0])
        dst._load_heal_state(src._heal_state())
        np.testing.assert_array_equal(
            dst_store["w"], np.array([1, 2, 3, 4], dtype=np.float32)
        )
        assert dst.state.active == Layout(3, 1, 1, 0)

    def test_sharded_source_ships_only_the_epoch(self):
        src, _ = self._ctrl([1.0, 2.0, 3.0, 4.0])
        src.state.active = Layout(1, 2, 1, 5)
        src._shard_index, src._nshards = 1, 2
        dst, dst_store = self._ctrl([0.0, 0.0, 0.0, 0.0])
        dst._load_heal_state(src._heal_state())
        np.testing.assert_array_equal(
            dst_store["w"], np.zeros(4, dtype=np.float32)
        )
        # the epoch is learned, so the joiner's next wire report is
        # visibly stale and the fleet re-plans its shard in
        assert dst.state.max_seen_epoch == 5
        assert dst.state.active is None

    def test_size_mismatch_is_skipped(self):
        src, _ = self._ctrl([1.0, 2.0])
        src.state.active = Layout(2, 1, 1, 0)
        dst, dst_store = self._ctrl([0.0, 0.0, 0.0])
        dst._load_heal_state(src._heal_state())
        np.testing.assert_array_equal(
            dst_store["w"], np.zeros(3, dtype=np.float32)
        )


class _StubManager:
    """Duck-typed Manager for mesh-level tests."""

    def __init__(self, n, rank):
        self._n, self._rank = n, rank

    def num_participants(self):
        return self._n

    def participating_rank(self):
        return self._rank

    def is_participating(self):
        return self._rank is not None

    def replica_id(self):
        return f"stub_{self._rank}"


def _mesh(manager):
    from torchft_tpu.parallel.device_mesh import ManagedDeviceMesh

    inner = jax.sharding.Mesh(
        np.array(jax.devices()[:1]), ("fsdp",)
    )
    return ManagedDeviceMesh(manager, inner)


class TestGlobalBatchSlicePartition:
    """ISSUE 11 satellite: across ANY shrink/grow the per-replica slices
    partition the global batch exactly — no overlap, no gap."""

    @pytest.mark.parametrize("batch", [1, 7, 32, 33])
    @pytest.mark.parametrize("world", [1, 2, 3, 5, 8, 40])
    def test_flat_slices_tile_batch(self, batch, world):
        slices = [
            _mesh(_StubManager(world, r)).global_batch_slice(batch)
            for r in range(world)
        ]
        assert sum(e - s for (s, e) in slices) == batch
        # strict tiling: the nonempty slices, sorted, walk [0, batch)
        # with no overlap and no gap (empty slices: world > batch ranks)
        walk = 0
        for (s, e) in sorted(sl for sl in slices if sl[0] != sl[1]):
            assert s == walk and e > s
            walk = e
        assert walk == batch

    def test_non_participant_gets_empty_slice(self):
        assert _mesh(_StubManager(3, None)).global_batch_slice(12) == (0, 0)

    @pytest.mark.parametrize("world,key", [(4, (2, 2, 1)), (6, (3, 2, 1))])
    def test_layout_slices_partition_by_dp_dim(self, world, key):
        from torchft_tpu.parallel.layout import LayoutController

        dp, shard, pp = key
        layout = Layout(dp, shard, pp, 1)
        slices = []
        for r in range(world):
            mesh = _mesh(_StubManager(world, r))
            ctrl = LayoutController(LayoutConstraints())
            ctrl.state.active = layout
            mesh.attach_layout(ctrl)
            slices.append(mesh.global_batch_slice(24))
        # shard/pp peers of one dp replica train the same slice; distinct
        # dp rows tile the batch exactly
        by_dp = {}
        for r, sl in enumerate(slices):
            dp_rank, _, _ = layout.coords(r)
            by_dp.setdefault(dp_rank, set()).add(sl)
        assert all(len(v) == 1 for v in by_dp.values())
        walk = 0
        for dp_rank in sorted(by_dp):
            (s, e) = next(iter(by_dp[dp_rank]))
            assert s == walk
            walk = e
        assert walk == 24

    def test_layout_grid_mismatch_falls_back_to_flat(self):
        """Mid-switch (membership changed, commit pending) the flat
        partition keeps the tiling exact."""
        from torchft_tpu.parallel.layout import LayoutController

        layout = Layout(2, 2, 1, 1)  # world 4, but only 3 live
        slices = []
        for r in range(3):
            mesh = _mesh(_StubManager(3, r))
            ctrl = LayoutController(LayoutConstraints())
            ctrl.state.active = layout
            mesh.attach_layout(ctrl)
            slices.append(mesh.global_batch_slice(9))
        walk = 0
        for (s, e) in sorted(slices):
            assert s == walk
            walk = e
        assert walk == 9


class TestReshardTransport:
    """The HTTP transport's reshard surface: multi-slot staging under
    negative step keys surviving per-step heal retirement, and the
    ``part_<rank>`` slice-diff resource."""

    def test_part_resource_serves_only_the_destination_slices(self):
        from torchft_tpu.checkpointing.http_transport import HTTPTransport

        src = HTTPTransport(timeout=10.0)
        dst = HTTPTransport(timeout=10.0)
        try:
            doc = {
                "for:1": {"model/w/0:4": np.arange(4, dtype=np.float32)},
                "for:2": {"model/w/4:8": np.arange(4, 8, dtype=np.float32)},
            }
            src.send_checkpoint(
                dst_ranks=[], step=-3, state_dict=doc, timeout=5.0
            )
            got = dst.recv_checkpoint(
                src_rank=0, metadata=src.metadata(), step=-3, timeout=5.0,
                resource="part_1",
            )
            assert list(got) == ["model/w/0:4"]
            np.testing.assert_array_equal(
                got["model/w/0:4"], np.arange(4, dtype=np.float32)
            )
            # a rank with nothing routed through this source gets an
            # empty doc (not a 404/503)
            got = dst.recv_checkpoint(
                src_rank=0, metadata=src.metadata(), step=-3, timeout=5.0,
                resource="part_9",
            )
            assert got == {}
        finally:
            src.shutdown()
            dst.shutdown()

    def test_reshard_slots_survive_heal_retirement(self):
        from torchft_tpu.checkpointing.http_transport import HTTPTransport

        t = HTTPTransport(timeout=10.0)
        try:
            t.send_checkpoint([], step=5, state_dict={"a": 1}, timeout=5.0)
            t.send_checkpoint([], step=-2, state_dict={"b": 2}, timeout=5.0)
            t.disallow_checkpoint()  # the per-step heal retirement
            assert 5 not in t._staged and -2 in t._staged
            t.retire_checkpoint(-2)
            assert t._staged == {}
        finally:
            t.shutdown()

    def test_staged_slots_are_bounded(self):
        from torchft_tpu.checkpointing import http_transport as ht

        t = ht.HTTPTransport(timeout=10.0)
        try:
            for step in range(ht._MAX_STAGED + 3):
                t.send_checkpoint([], step=step, state_dict={}, timeout=5.0)
            assert len(t._staged) == ht._MAX_STAGED
            assert 0 not in t._staged  # oldest evicted first
        finally:
            t.shutdown()


class TestMeshLayoutPGs:
    def test_row_and_col_pgs_reconfigure_on_commit(self):
        """A committed layout re-forms the dp-row and shard-column
        process groups under a per-epoch store prefix — the fleet-
        synchronous reconfigure an HSDP-across-groups algorithm needs."""
        from torchft_tpu.parallel.layout import LayoutController

        class _PG:
            def __init__(self):
                self.calls = []

            def configure(self, addr, replica_id, rank, world):
                self.calls.append((addr, rank, world))

        layout = Layout(2, 2, 1, 5)
        for rank in range(4):
            mesh = _mesh(_StubManager(4, rank))
            ctrl = LayoutController(LayoutConstraints())
            row, col = _PG(), _PG()
            mesh.attach_layout(ctrl, row_pg=row, col_pg=col)
            mesh._on_layout_commit(
                layout, {"rank": rank, "store_address": "host:1"}
            )
            dp_rank, shard_rank, pp_rank = layout.coords(rank)
            (addr, r, w) = row.calls[0]
            assert r == dp_rank and w == layout.dp
            assert f"/layout/{layout.epoch}/row/" in addr
            (addr, r, w) = col.calls[0]
            assert r == shard_rank and w == layout.shard
            assert f"/layout/{layout.epoch}/col/" in addr
