"""MoE expert-parallel FFN: routing parity, capacity, sharding, grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from torchft_tpu.models.moe import (
    MoEConfig,
    init_moe_params,
    moe_ffn,
    moe_ffn_reference,
    moe_param_specs,
)


def _cfg(**kw):
    base = dict(
        d_model=16, d_ff=32, n_experts=4, top_k=2, capacity_factor=4.0,
        dtype=jnp.float32,
    )
    base.update(kw)
    return MoEConfig(**base)


def _setup(cfg, b=2, t=8, seed=0):
    params = init_moe_params(jax.random.PRNGKey(seed), cfg)
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (b, t, cfg.d_model))
    return params, x


class TestRouting:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_reference_no_drops(self, top_k):
        cfg = _cfg(top_k=top_k)  # capacity 4.0: nothing dropped
        params, x = _setup(cfg)
        y, aux = moe_ffn(x, params, cfg)
        ref = moe_ffn_reference(x, params, cfg)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-4)
        assert np.isfinite(float(aux))

    def test_capacity_drops_pass_through_as_zero(self):
        # capacity so small most tokens drop; output shrinks toward zero but
        # stays finite, aux unchanged by drops
        cfg = _cfg(capacity_factor=0.1)
        params, x = _setup(cfg)
        y, aux = moe_ffn(x, params, cfg)
        assert np.isfinite(np.asarray(y)).all()
        full = moe_ffn(x, params, _cfg())[0]
        assert np.abs(np.asarray(y)).sum() < np.abs(np.asarray(full)).sum()

    def test_aux_loss_near_one_for_uniform_router(self):
        cfg = _cfg()
        params, x = _setup(cfg)
        params = dict(params, router=jnp.zeros_like(params["router"]))
        _, aux = moe_ffn(x, params, cfg)
        # uniform probs: E * sum_e f_e * (1/E) = sum_e f_e = 1
        np.testing.assert_allclose(float(aux), 1.0, atol=1e-5)


class TestSharded:
    def test_ep_sharded_matches_unsharded(self):
        # ep-only mesh: inner weight dims stay unsharded
        cfg = _cfg(n_experts=8, fsdp_axis=None, tp_axis=None)
        params, x = _setup(cfg, b=2, t=16)
        ref, _ = moe_ffn(x, params, cfg)

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("ep",))
        specs = moe_param_specs(cfg)
        sharded_params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(
                p, jax.sharding.NamedSharding(mesh, s)
            ),
            params,
            specs,
        )
        y, _ = jax.jit(lambda xx, pp: moe_ffn(xx, pp, cfg, mesh=mesh))(
            x, sharded_params
        )
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-4)

    def test_ep_with_fsdp_tp_axes(self):
        cfg = _cfg(n_experts=4)
        params, x = _setup(cfg, b=2, t=16)
        ref, _ = moe_ffn(x, params, cfg)
        mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("ep", "fsdp", "tp"))
        specs = moe_param_specs(cfg)
        sharded_params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, jax.sharding.NamedSharding(mesh, s)),
            params,
            specs,
        )
        y, _ = jax.jit(lambda xx, pp: moe_ffn(xx, pp, cfg, mesh=mesh))(
            x, sharded_params
        )
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-4)


class TestGrads:
    def test_grad_flows_through_router_and_experts(self):
        cfg = _cfg()
        params, x = _setup(cfg)

        def loss(p, xx):
            y, aux = moe_ffn(xx, p, cfg)
            return (y ** 2).mean() + 0.01 * aux

        grads = jax.grad(loss)(params, x)
        for name in ("router", "w_gate", "w_up", "w_down"):
            g = np.asarray(grads[name])
            assert np.isfinite(g).all()
            assert np.abs(g).sum() > 0, f"no gradient through {name}"

    def test_stacked_layers_init(self):
        cfg = _cfg()
        params = init_moe_params(jax.random.PRNGKey(0), cfg, n_layers=3)
        assert params["w_gate"].shape == (3, cfg.n_experts, 16, 32)
        specs = moe_param_specs(cfg, stacked=True)
        assert len(specs["w_gate"]) == 4


class TestTransformerMoE:
    def test_moe_transformer_forward_and_loss(self):
        from torchft_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=64,
            n_layers=2, max_seq_len=32, dtype=jnp.float32, n_experts=4,
        )
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        assert params["blocks"]["w_gate"].shape == (2, 4, 32, 64)
        assert "router" in params["blocks"]
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        logits, aux = tfm.forward(params, tokens, cfg, return_aux=True)
        assert logits.shape == (2, 16, 64)
        assert float(aux) > 0
        loss = tfm.loss_fn(params, tokens, cfg)
        assert np.isfinite(float(loss))
        grads = jax.grad(tfm.loss_fn)(params, tokens, cfg)
        g = np.asarray(grads["blocks"]["router"])
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_moe_transformer_sharded_ep(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torchft_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=64,
            n_layers=2, max_seq_len=32, dtype=jnp.float32, n_experts=4,
        )
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        # batch divides dp*fsdp*ep = 4 (ep rides the batch dims)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        ref = tfm.loss_fn(params, tokens, cfg)

        mesh = Mesh(
            np.array(jax.devices()).reshape(2, 1, 2, 1, 2),
            ("dp", "fsdp", "tp", "cp", "ep"),
        )
        sharded = tfm.shard_params(params, mesh, cfg)
        tok_sharded = jax.device_put(
            tokens, NamedSharding(mesh, tfm.batch_spec(cfg))
        )
        loss = jax.jit(
            lambda p, t: tfm.loss_fn(p, t, cfg, mesh=mesh)
        )(sharded, tok_sharded)
        np.testing.assert_allclose(float(loss), float(ref), rtol=2e-5)
