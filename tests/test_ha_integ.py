"""Coordination-plane HA chaos: SIGKILL the active leader out of a
3-peer replicated lighthouse — mid-quorum-round and mid-serving-fetch —
and prove the fleet never wedges (ISSUE 13 acceptance).

The peers run as REAL subprocesses (``python -m torchft_tpu.lighthouse
--peers ...``) so the kill is a true SIGKILL: no graceful shutdown, no
drained connections — clients see dead sockets and must walk the
``TORCHFT_LIGHTHOUSE`` endpoint list.  Asserted:

* quorum rounds resume within the failover budget and ``quorum_id``
  stays strictly monotone across the takeover (term-prefixed ids);
* the native manager's lighthouse client (heartbeat loop + quorum path)
  rides the same walk: a ManagerClient quorum succeeds across the kill;
* serving clients complete in-flight fetches bitwise-identical while
  the leader dies, and a post-takeover publish still reaches them.

``make ha-smoke`` runs exactly this file.
"""

from __future__ import annotations

import json
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from torchft_tpu.coordination import (
    LighthouseClient,
    ManagerClient,
    ManagerServer,
    StoreServer,
)
from torchft_tpu.ha import pick_free_ports
from torchft_tpu.serving import ServingClient, ServingReplica, WeightPublisher

LEASE_MS = 400
#: kill -> next formed quorum budget: detection (one lease of missed
#: renewals) + staggered election (~2 ticks) + client walk.  ~3 leases
#: in local runs; 20x headroom for loaded CI containers.
FAILOVER_BUDGET_S = 10.0


class SubprocessFleet:
    """Three lighthouse peers as real subprocesses, SIGKILL-able."""

    def __init__(self, n: int = 3, lease_ms: int = LEASE_MS) -> None:
        self.ports = pick_free_ports(n)
        self.endpoints = [f"127.0.0.1:{p}" for p in self.ports]
        full = ",".join(self.endpoints)
        self.procs: "list[subprocess.Popen | None]" = []
        for port in self.ports:
            self.procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-m",
                        "torchft_tpu.lighthouse",
                        "--bind",
                        f"127.0.0.1:{port}",
                        "--peers",
                        full,
                        "--lease-timeout-ms",
                        str(lease_ms),
                        "--min-replicas",
                        "1",
                        "--quorum-tick-ms",
                        "50",
                        "--heartbeat-timeout-ms",
                        "3000",
                        "--join-timeout-ms",
                        "100",
                    ],
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
            )

    def addresses(self) -> str:
        return ",".join(self.endpoints)

    def ha_info(self, i: int) -> "dict | None":
        try:
            with urllib.request.urlopen(
                f"http://{self.endpoints[i]}/status.json", timeout=2
            ) as resp:
                return json.load(resp).get("ha")
        except Exception:  # noqa: BLE001 - dead/starting peer
            return None

    def leader_index(self, timeout: float = 30.0) -> int:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for i, p in enumerate(self.procs):
                if p is None or p.poll() is not None:
                    continue
                info = self.ha_info(i)
                if info and info.get("is_leader"):
                    return i
            time.sleep(0.05)
        raise TimeoutError("no subprocess lighthouse leader elected")

    def sigkill(self, i: int) -> None:
        p = self.procs[i]
        assert p is not None
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)
        self.procs[i] = None

    def shutdown(self) -> None:
        for i, p in enumerate(self.procs):
            if p is not None and p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=5)
            self.procs[i] = None


@pytest.fixture
def fleet():
    f = SubprocessFleet()
    try:
        f.leader_index()  # up and elected before any test logic runs
        yield f
    finally:
        f.shutdown()


class TestLeaderKillMidQuorum:
    def test_sigkill_leader_mid_round_requorums_monotone(self, fleet):
        """Two replica groups quorum continuously; SIGKILL the leader
        mid-round; the fleet re-quorums within the failover budget with
        strictly monotone, term-advancing quorum ids."""
        addrs = fleet.addresses()
        stop = threading.Event()
        ids: "dict[str, list[int]]" = {"a": [], "b": []}
        errors: "list[Exception]" = []

        def rounds(name: str) -> None:
            cli = LighthouseClient(addrs, connect_timeout=5.0)
            inc = 0
            try:
                while not stop.is_set():
                    inc += 1
                    try:
                        q = cli.quorum(
                            f"grp_{name}:{inc}",
                            timeout=15.0,
                            address=f"{name}:1",
                            store_address=f"{name}:2",
                        )
                        ids[name].append(q.quorum_id)
                    except (TimeoutError, ConnectionError):
                        continue  # mid-election round: retry
            except Exception as e:  # noqa: BLE001 - asserted below
                errors.append(e)
            finally:
                cli.close()

        threads = [
            threading.Thread(target=rounds, args=(n,), daemon=True)
            for n in ("a", "b")
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while (not ids["a"] or not ids["b"]) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert ids["a"] and ids["b"], "no quorum rounds before the kill"

        leader = fleet.leader_index()
        pre_kill_max = max(ids["a"] + ids["b"])
        t_kill = time.monotonic()
        fleet.sigkill(leader)

        # the fleet must form a FRESH quorum (id above anything pre-kill)
        # within the failover budget
        while time.monotonic() - t_kill < FAILOVER_BUDGET_S:
            if max(ids["a"] + ids["b"], default=0) > pre_kill_max:
                break
            time.sleep(0.02)
        t_requorum = time.monotonic() - t_kill
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "quorum round thread wedged"
        assert not errors, f"round thread raised: {errors}"
        post_kill_max = max(ids["a"] + ids["b"])
        assert post_kill_max > pre_kill_max, (
            f"no quorum formed within {FAILOVER_BUDGET_S}s of the SIGKILL"
        )
        # strictly monotone per client stream, across the takeover
        for name in ("a", "b"):
            assert ids[name] == sorted(ids[name]), f"{name} ids regressed"
            assert all(
                b > a for a, b in zip(ids[name], ids[name][1:])
            ), f"{name} repeated a quorum_id"
        # the takeover is visible as a term advance in the id's high word
        assert (post_kill_max >> 32) > (pre_kill_max >> 32)
        # sanity: failover completed inside the budget (the budget is
        # deliberately loose for CI; locally this is ~1-2s at 400 ms lease)
        assert t_requorum < FAILOVER_BUDGET_S

    def test_native_manager_quorum_across_leader_kill(self, fleet):
        """The NATIVE manager's lighthouse client (HaRpcClient) walks the
        endpoint list: a ManagerClient quorum succeeds before and after a
        leader SIGKILL with monotone ids."""
        store = StoreServer()
        server = ManagerServer(
            replica_id="ha_native:1",
            lighthouse_addr=fleet.addresses(),
            store_address=store.address(),
            world_size=1,
            heartbeat_interval=0.1,
            quorum_retries=3,
        )
        client = ManagerClient(server.address(), connect_timeout=5.0)
        try:
            q1 = client._quorum(
                0, step=0, checkpoint_metadata="", shrink_only=False,
                timeout=20.0,
            )
            fleet.sigkill(fleet.leader_index())
            q2 = client._quorum(
                0, step=1, checkpoint_metadata="", shrink_only=False,
                timeout=30.0,
            )
            assert q2.quorum_id > q1.quorum_id
            assert (q2.quorum_id >> 32) > (q1.quorum_id >> 32)
        finally:
            client.close()
            server.shutdown()
            store.shutdown()


class TestLeaderKillMidServingFetch:
    def test_fetches_complete_bitwise_across_leader_kill(self, fleet):
        """Serving clients mid-fetch while the coordination leader dies:
        every fetch completes bitwise-identical (payload transfer never
        touches the lighthouse), and a post-takeover publish still
        reaches clients through re-registration on the new leader."""
        addrs = fleet.addresses()
        rng = np.random.default_rng(13)
        sd = {
            "w": rng.standard_normal((256, 128)).astype(np.float32),
            "b": rng.standard_normal((128,)).astype(np.float32),
        }
        pub = WeightPublisher(addrs, fragments=2, heartbeat_interval=0.1)
        reps = [
            ServingReplica(
                addrs, replica_id=f"ha_srv{i}", poll_interval=0.05,
                fetch_timeout=10.0,
            )
            for i in range(2)
        ]
        clients = [
            ServingClient(addrs, plan_ttl=0.1, client_id=str(i))
            for i in range(4)
        ]
        try:
            v1 = pub.publish(sd)
            results: "dict[int, object]" = {}

            def fetch(i: int) -> None:
                try:
                    results[i] = clients[i].fetch(version=v1, timeout=30)
                except Exception as e:  # noqa: BLE001 - asserted below
                    results[i] = e

            threads = [
                threading.Thread(target=fetch, args=(i,), daemon=True)
                for i in range(4)
            ]
            for t in threads:
                t.start()
            # kill the coordination leader while those fetches fly
            fleet.sigkill(fleet.leader_index())
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "serving fetch wedged"
            states = []
            for i, res in results.items():
                assert not isinstance(res, Exception), f"client {i}: {res}"
                state, got = res
                assert got == v1
                states.append(state)
            for s in states:
                np.testing.assert_array_equal(s["w"], states[0]["w"])
                np.testing.assert_array_equal(s["w"], sd["w"])
            # post-takeover: registrations re-form on the new leader and
            # a fresh publish flows end to end
            fleet.leader_index()
            sd2 = {"w": sd["w"] * 2.0, "b": sd["b"]}
            v2 = pub.publish(sd2)
            state2, got2 = clients[0].fetch(version=v2, timeout=30)
            assert got2 == v2
            np.testing.assert_array_equal(state2["w"], sd2["w"])
        finally:
            for c in clients:
                c.close()
            for r in reps:
                r.shutdown()
            pub.shutdown()
