"""Model-family tests: flagship transformer (sharded + single-device),
ring-vs-dense equivalence at the model level, CNN/MLP example models, and
the driver entry points."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding

from torchft_tpu.models import cnn, mlp
from torchft_tpu.models import transformer as tfm


def _tiny_cfg(**kw):
    base = dict(
        vocab_size=64,
        d_model=32,
        n_heads=4,
        n_kv_heads=2,
        d_ff=64,
        n_layers=2,
        max_seq_len=32,
    )
    base.update(kw)
    return tfm.TransformerConfig(**base)


def _mesh(shape, names):
    n = int(np.prod(shape))
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), names)


class TestTransformer:
    def test_forward_shapes_single_device(self):
        cfg = _tiny_cfg()
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        logits = jax.jit(lambda p, t: tfm.forward(p, t, cfg))(params, toks)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32
        assert bool(jnp.isfinite(logits).all())

    def test_causality(self):
        """Changing a future token must not change earlier logits."""
        cfg = _tiny_cfg(dtype=jnp.float32)
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
        toks2 = toks.at[0, -1].set((toks[0, -1] + 1) % cfg.vocab_size)
        l1 = tfm.forward(params, toks, cfg)
        l2 = tfm.forward(params, toks2, cfg)
        np.testing.assert_allclose(l1[:, :-1], l2[:, :-1], rtol=1e-5)

    def test_sharded_train_step(self):
        mesh = _mesh((1, 2, 2, 2), ("dp", "fsdp", "tp", "cp"))
        cfg = _tiny_cfg(attn_impl="ring")
        params = tfm.shard_params(tfm.init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)
        step = tfm.make_train_step(cfg, opt, mesh)
        toks = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size),
            NamedSharding(mesh, tfm.batch_spec(cfg)),
        )
        losses = []
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, toks)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], f"no learning: {losses}"

    def test_ring_matches_dense_model_level(self):
        """Full model fp32: ring attention over cp=8 == dense single device."""
        toks = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 64)
        cfg_d = _tiny_cfg(dtype=jnp.float32, attn_impl="dense")
        params = tfm.init_params(jax.random.PRNGKey(1), cfg_d)
        l_dense = tfm.loss_fn(params, toks, cfg_d)
        mesh = _mesh((1, 1, 1, 8), ("dp", "fsdp", "tp", "cp"))
        cfg_r = _tiny_cfg(dtype=jnp.float32, attn_impl="ring")
        l_ring = tfm.loss_fn(params, toks, cfg_r, mesh)
        np.testing.assert_allclose(float(l_dense), float(l_ring), rtol=1e-6)

    def test_grad_step_matches_param_structure(self):
        mesh = _mesh((1, 2, 2, 2), ("dp", "fsdp", "tp", "cp"))
        cfg = _tiny_cfg(attn_impl="ring")
        params = tfm.shard_params(tfm.init_params(jax.random.PRNGKey(0), cfg), mesh, cfg)
        gstep = tfm.make_grad_step(cfg, mesh)
        toks = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size),
            NamedSharding(mesh, tfm.batch_spec(cfg)),
        )
        loss, grads = gstep(params, toks)
        assert jax.tree_util.tree_structure(grads) == jax.tree_util.tree_structure(
            params
        )
        assert bool(jnp.isfinite(loss))


class TestExampleModels:
    def test_cnn_shapes(self):
        params = cnn.init_params(jax.random.PRNGKey(0))
        out = jax.jit(cnn.forward)(params, jnp.zeros((2, 32, 32, 3)))
        assert out.shape == (2, 10)

    def test_mlp_shapes_and_fragments(self):
        params = mlp.init_params(jax.random.PRNGKey(0), (784, 64, 64, 10))
        out = jax.jit(mlp.forward)(params, jnp.zeros((2, 784)))
        assert out.shape == (2, 10)
        frags = mlp.fragment_keys(params, 2)
        assert frags == [["layer_0", "layer_1"], ["layer_2"]]
        assert sum(len(f) for f in frags) == len(params)


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == args[1].shape[0]

    @pytest.mark.parametrize("n", [4, 8])
    def test_dryrun_multichip(self, n):
        import __graft_entry__ as g

        g.dryrun_multichip(n)


class TestAutoAttnImpl:
    """attn_impl='auto' (the default) resolves TPU-first: flash when the
    sequence is lane-aligned and unsharded, ring on cp meshes, dense as
    the logged fallback (VERDICT r03 #7)."""

    def test_default_is_auto(self):
        assert tfm.TransformerConfig().attn_impl == "auto"

    def test_resolution_rules(self, monkeypatch):
        cfg = _tiny_cfg()  # attn_impl defaults to auto
        # platform-aware: flash only where the Pallas kernel compiles
        # natively (interpret mode off-TPU is orders of magnitude slower
        # than XLA dense, so auto prefers dense there)
        on_tpu = "flash" if jax.default_backend() == "tpu" else "dense"
        assert tfm._resolve_attn_impl(cfg, None, False, 128) == on_tpu
        assert tfm._resolve_attn_impl(cfg, None, False, 100) == "dense"
        assert tfm._resolve_attn_impl(cfg, None, True, 128) == "ring"
        cp_mesh = _mesh((2,), ("cp",))
        assert tfm._resolve_attn_impl(cfg, cp_mesh, False, 128) == "ring"
        dp_mesh = _mesh((2,), ("dp",))
        assert tfm._resolve_attn_impl(cfg, dp_mesh, False, 128) == on_tpu
        # explicit settings are never overridden
        cfg_d = _tiny_cfg(attn_impl="dense")
        assert tfm._resolve_attn_impl(cfg_d, None, False, 128) == "dense"
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert tfm._resolve_attn_impl(cfg, None, False, 1024) == "flash"

    def test_auto_forward_matches_explicit_flash(self, monkeypatch):
        # force the auto->flash dispatch even off-TPU (interpret-mode
        # kernel), so the dispatch wiring is actually exercised in CI —
        # without the patch auto resolves to dense here and the test
        # would compare dense against dense
        from torchft_tpu.ops import flash_attention as fa

        monkeypatch.setattr(tfm.jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(fa, "_interpret", lambda: True)
        cfg_a = _tiny_cfg(dtype=jnp.float32)
        cfg_d = _tiny_cfg(dtype=jnp.float32, attn_impl="dense")
        assert cfg_a.attn_impl == "auto"
        assert tfm._resolve_attn_impl(cfg_a, None, False, 128) == "flash"
        params = tfm.init_params(jax.random.PRNGKey(0), cfg_a)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, cfg_a.vocab_size)
        la = tfm.forward(params, toks, cfg_a)
        ld = tfm.forward(params, toks, cfg_d)
        np.testing.assert_allclose(np.asarray(la), np.asarray(ld), atol=2e-5, rtol=1e-5)

    def test_auto_unaligned_falls_back_to_dense(self, caplog):
        cfg = _tiny_cfg(dtype=jnp.float32)
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 20), 0, cfg.vocab_size)
        logits = tfm.forward(params, toks, cfg)  # must not raise
        assert logits.shape == (1, 20, cfg.vocab_size)


class TestRematPolicy:
    def test_dots_policy_matches_full_remat_numerics(self):
        """remat_policy='dots' (save matmul outputs, recompute elementwise)
        must be numerically identical to full remat and to no remat — it
        only changes WHAT is saved for the backward, never the math."""
        cfgs = [
            _tiny_cfg(dtype=jnp.float32, remat=True, remat_policy="full"),
            _tiny_cfg(dtype=jnp.float32, remat=True, remat_policy="dots"),
            _tiny_cfg(dtype=jnp.float32, remat=False),
        ]
        params = tfm.init_params(jax.random.PRNGKey(0), cfgs[0])
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        grads = [
            jax.grad(lambda p, c=c: tfm.loss_fn(p, toks, c))(params)
            for c in cfgs
        ]
        for other in grads[1:]:
            for a, b in zip(
                jax.tree_util.tree_leaves(grads[0]),
                jax.tree_util.tree_leaves(other),
            ):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6
                )

    def test_unknown_policy_rejected(self):
        cfg = _tiny_cfg(remat=True, remat_policy="everything")
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 64)
        with pytest.raises(ValueError, match="remat_policy"):
            tfm.forward(params, toks, cfg)
