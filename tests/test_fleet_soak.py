"""Fleet-scale churn soak: the lighthouse status plane at 24-64 replicas.

ROADMAP open-item #2 made "coordination plane survives fleet scale" a
tested property.  Each replica is a lightweight stub thread (heartbeat +
quorum participation + per-step digests — no Manager/PG stack, so 64 of
them fit one process) driven through staggered joins, kills, rejoins
(new incarnations → supersession), and one deliberately wedged replica.

Asserted, not assumed:
- quorum_id observations are monotone non-decreasing per stub and
  quorums keep forming after the churn (no livelock);
- p99 lighthouse tick latency is bounded, measured via the
  ``torchft_lighthouse_tick_seconds`` histogram the tick loop exports;
- the dirty-set path is actually engaged: in steady state
  ``torchft_lighthouse_dirty_replicas`` is far below fleet size;
- the DEFAULT ``/status.json`` stays under a fixed byte budget at fleet
  size while the paginated form still exposes every row;
- ``torchft-diagnose --timeline`` consumes the lighthouse's
  ``/timeline.json`` and names the wedged replica.
"""

import json
import threading
import time
import urllib.request

import pytest

from torchft_tpu.coordination import LighthouseClient, LighthouseServer, Quorum
from torchft_tpu.utils.metrics import (
    parse_text_exposition,
    quantile_from_histogram,
)

STATUS_BYTE_BUDGET = 16 * 1024
TICK_P99_BUDGET_S = 0.1


class ReplicaStub:
    """One fleet member: a thread that heartbeats (with step progress and
    per-step digests) and joins every quorum round, recording the
    quorum_ids it observes.  ``wedge()`` freezes its step while the
    heartbeat keeps running — the classic live-but-stuck straggler."""

    def __init__(self, base_id: str, incarnation: int, addr: str):
        self.base_id = base_id
        self.replica_id = f"{base_id}:u{incarnation}"
        self.addr = addr
        self.step = 0
        self.quorum_ids: "list[int]" = []
        self.errors: "list[Exception]" = []
        self.superseded = False
        self._stop = threading.Event()
        self._wedged = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        """Simulate a kill: the thread just vanishes (no dereg RPC)."""
        self._stop.set()

    def wedge(self) -> None:
        self._wedged.set()

    def join(self, timeout: float = 10.0) -> None:
        self._thread.join(timeout=timeout)

    def _run(self) -> None:
        client = LighthouseClient(self.addr)
        try:
            while not self._stop.is_set():
                try:
                    if self._wedged.is_set():
                        # wedged: alive (heartbeating) but no progress and
                        # no quorum participation
                        reply = client.heartbeat(
                            self.replica_id, step=self.step,
                            inflight_op="wedged",
                        )
                        if reply.get("superseded"):
                            self.superseded = True
                            return
                        time.sleep(0.02)
                        continue
                    q = client.quorum(
                        replica_id=self.replica_id,
                        step=self.step,
                        timeout=3.0,
                    )
                    assert isinstance(q, Quorum)
                    self.quorum_ids.append(q.quorum_id)
                    self.step += 1
                    reply = client.heartbeat(
                        self.replica_id,
                        step=self.step,
                        inflight_op="train",
                        summary={
                            "step": self.step,
                            "phase_ms": {"quorum_rpc": 1.0, "ring": 2.0},
                            "codec_busy_s": 0.001,
                            "wire_busy_s": 0.002,
                        },
                    )
                    if reply.get("superseded"):
                        self.superseded = True
                        return
                    time.sleep(0.01)
                except TimeoutError:
                    continue  # churn: quorum didn't form this round
                except Exception as e:  # noqa: BLE001 - collected for asserts
                    msg = str(e).lower()
                    if "superseded" in msg:
                        self.superseded = True
                        return
                    if self._stop.is_set() or "shutting down" in msg:
                        return
                    if "timeout" in msg or "timed out" in msg:
                        continue
                    self.errors.append(e)
                    return
        finally:
            client.close()


def _http_get(addr: str, path: str) -> bytes:
    return urllib.request.urlopen(f"http://{addr}{path}", timeout=10).read()


def _run_churn_soak(fleet_size: int, tmp_path) -> None:
    server = LighthouseServer(
        min_replicas=4,
        join_timeout_ms=150,
        quorum_tick_ms=20,
        heartbeat_timeout_ms=2000,
        status_page_size=16,
        straggler_topk=8,
        timeline_ring=512,
    )
    addr = server.address()
    stubs: "dict[str, ReplicaStub]" = {}
    incarnation = {f"stub{i:03d}": 0 for i in range(fleet_size)}
    try:
        # phase 1: staggered joins
        for i, base in enumerate(sorted(incarnation)):
            stub = ReplicaStub(base, 0, addr)
            stubs[base] = stub
            stub.start()
            if i % 4 == 0:
                time.sleep(0.02)

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if sum(len(s.quorum_ids) for s in stubs.values()) >= fleet_size:
                break
            time.sleep(0.1)
        assert sum(len(s.quorum_ids) for s in stubs.values()) >= fleet_size, (
            "fleet never started forming quorums"
        )

        # phase 2: churn — kill a third of the fleet, rejoin each as a new
        # incarnation (supersession evicts the old one)
        victims = sorted(incarnation)[:: 3]
        for base in victims:
            stubs[base].stop()
        time.sleep(0.3)
        for base in victims:
            incarnation[base] += 1
            stub = ReplicaStub(base, incarnation[base], addr)
            stubs[base] = stub
            stub.start()
            time.sleep(0.01)

        # phase 3: wedge one replica (alive, heartbeating, zero progress)
        wedged = stubs[sorted(incarnation)[1]]
        wedged.wedge()
        time.sleep(2.0)  # straggler score needs real wall time to grow

        # phase 4: steady state — no churn; sample the dirty-set gauge
        dirty_samples = []
        for _ in range(6):
            fams = parse_text_exposition(_http_get(addr, "/metrics").decode())
            dirty_samples.append(
                fams["torchft_lighthouse_dirty_replicas"]["samples"][
                    ("torchft_lighthouse_dirty_replicas", ())
                ]
            )
            time.sleep(0.2)

        # no livelock: quorums still form after all the churn
        before = sum(len(s.quorum_ids) for s in stubs.values())
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if sum(len(s.quorum_ids) for s in stubs.values()) > before:
                break
            time.sleep(0.1)
        assert (
            sum(len(s.quorum_ids) for s in stubs.values()) > before
        ), "no quorum formed after churn: livelock"

        # -- status plane budget + pagination ---------------------------
        default_status = _http_get(addr, "/status.json")
        assert len(default_status) < STATUS_BYTE_BUDGET, (
            f"default /status.json is {len(default_status)}B at "
            f"{fleet_size} replicas (budget {STATUS_BYTE_BUDGET})"
        )
        doc = json.loads(default_status)
        assert doc["heartbeats_total"] >= fleet_size
        assert len(doc["heartbeats"]) <= doc["per_page"]
        assert doc["summary"]["stragglers_worst"], "summary lost the worst-K"
        # paginated union covers every tracked replica
        seen = set()
        for page in range(doc["pages"]):
            page_doc = json.loads(
                _http_get(addr, f"/status.json?page={page}&per_page=16")
            )
            seen.update(h["replica_id"] for h in page_doc["heartbeats"])
        assert len(seen) == doc["heartbeats_total"], (
            "paginated pages do not cover every heartbeat row"
        )
        live_ids = {s.replica_id for s in stubs.values()}
        assert live_ids <= seen
        # per-replica shard
        shard = json.loads(
            _http_get(
                addr,
                "/status.json?replica=" + wedged.replica_id.replace(":", "%3A"),
            )
        )
        assert [h["replica_id"] for h in shard["heartbeats"]] == [
            wedged.replica_id
        ]

        # -- tick cost --------------------------------------------------
        fams = parse_text_exposition(_http_get(addr, "/metrics").decode())
        tick_count = fams["torchft_lighthouse_tick_seconds"]["samples"][
            ("torchft_lighthouse_tick_seconds_count", ())
        ]
        assert tick_count > 50, "tick histogram barely populated"
        p99 = quantile_from_histogram(
            fams, "torchft_lighthouse_tick_seconds", 0.99
        )
        assert p99 <= TICK_P99_BUDGET_S, (
            f"p99 tick latency {p99}s over budget at {fleet_size} replicas"
        )
        # dirty-set engaged: steady state re-evaluates a small fraction of
        # the fleet, not all of it
        assert min(dirty_samples) < fleet_size / 4, (
            f"dirty set never dropped below fleet/4: {dirty_samples}"
        )
        # the bounded per-replica tier holds at fleet scale
        lag_rows = [
            k
            for k in fams["torchft_replica_step_lag"]["samples"]
            if k[0] == "torchft_replica_step_lag"
        ]
        assert len(lag_rows) <= 8, "per-replica /metrics labels unbounded"
        assert (
            fams["torchft_stragglers_tracked"]["samples"][
                ("torchft_stragglers_tracked", ())
            ]
            >= fleet_size
        )

        # -- timeline + diagnose ----------------------------------------
        timeline = json.loads(_http_get(addr, "/timeline.json"))
        assert timeline["steps"], "no timeline buckets aggregated"
        assert max(b["replicas"] for b in timeline["steps"]) >= 2
        assert any(b["phases"].get("ring") for b in timeline["steps"])
        worst = timeline["stragglers_worst"]
        assert worst and worst[0]["replica_id"] == wedged.replica_id, (
            f"wedged replica not the worst straggler: {worst[:3]}"
        )

        tl_path = tmp_path / "timeline.json"
        tl_path.write_text(json.dumps(timeline))
        from torchft_tpu import diagnose

        report = diagnose.analyze_timeline(timeline)
        assert report["culprit"] is not None
        assert report["culprit"]["replica_id"] == wedged.replica_id
        assert report["culprit"]["signal"] == "timeline_straggler"
        # ... and through the CLI, from the serialized scrape alone
        assert diagnose.main(["--timeline", str(tl_path)]) == 0

        # -- quorum_id monotonicity -------------------------------------
        for s in stubs.values():
            assert s.quorum_ids == sorted(s.quorum_ids), (
                f"{s.replica_id} observed non-monotone quorum ids: "
                f"{s.quorum_ids[:20]}"
            )
        assert not any(s.errors for s in stubs.values()), {
            s.replica_id: s.errors for s in stubs.values() if s.errors
        }
    finally:
        for s in stubs.values():
            s.stop()
        for s in stubs.values():
            s.join(timeout=5.0)
        server.shutdown()


class TestFleetChurnSoak:
    def test_churn_soak_24_replicas(self, tmp_path):
        """Tier-1 variant: 24 stubs under staggered joins/kills/rejoins in
        well under the 60 s soak budget."""
        t0 = time.monotonic()
        _run_churn_soak(24, tmp_path)
        assert time.monotonic() - t0 < 60.0

    @pytest.mark.slow
    def test_churn_soak_64_replicas(self, tmp_path):
        """Full fleet-scale variant (slow-marked): 64 stubs."""
        t0 = time.monotonic()
        _run_churn_soak(64, tmp_path)
        assert time.monotonic() - t0 < 60.0
