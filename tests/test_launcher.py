"""Launcher: replica-group env injection, restart budget, chaos hook.

Mirrors the reference's launcher semantics (torchx component roles + env
triple + torchrun --max_restarts, reference torchft/torchx.py:11-83).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from torchft_tpu.launcher import ReplicaGroupLauncher, main, replica_app_spec


class TestReplicaAppSpec:
    def test_roles_and_env(self):
        spec = replica_app_spec(
            "--steps", "5", replicas=3, script="train.py", lighthouse="lh:1234"
        )
        assert len(spec["roles"]) == 3
        for i, role in enumerate(spec["roles"]):
            assert role["env"]["REPLICA_GROUP_ID"] == str(i)
            assert role["env"]["NUM_REPLICA_GROUPS"] == "3"
            assert role["env"]["TORCHFT_LIGHTHOUSE"] == "lh:1234"
            assert role["args"] == ["train.py", "--steps", "5"]

    def test_rejects_zero_replicas(self):
        with pytest.raises(ValueError):
            replica_app_spec(replicas=0)

    def test_caller_env_cannot_override_role_identity(self):
        # forwarding os.environ from a process that itself runs under the
        # launcher must not clobber the per-role triple
        spec = replica_app_spec(
            replicas=2,
            env={"REPLICA_GROUP_ID": "7", "NUM_REPLICA_GROUPS": "99", "FOO": "x"},
            lighthouse="lh:1",
        )
        for i, role in enumerate(spec["roles"]):
            assert role["env"]["REPLICA_GROUP_ID"] == str(i)
            assert role["env"]["NUM_REPLICA_GROUPS"] == "2"
            assert role["env"]["FOO"] == "x"


def _script(tmp_path, body):
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(body))
    return str(path)


class TestReplicaGroupLauncher:
    def test_env_injection_and_success(self, tmp_path):
        script = _script(
            tmp_path,
            f"""
            import os
            out = os.path.join({str(tmp_path)!r}, "out_" + os.environ["REPLICA_GROUP_ID"])
            with open(out, "w") as f:
                f.write(os.environ["NUM_REPLICA_GROUPS"] + " " +
                        os.environ["TORCHFT_LIGHTHOUSE"])
            """,
        )
        launcher = ReplicaGroupLauncher(
            [sys.executable, script], replicas=2, lighthouse_addr="lh:9999"
        )
        codes = launcher.run(timeout=60)
        assert codes == {0: 0, 1: 0}
        for r in range(2):
            content = (tmp_path / f"out_{r}").read_text()
            assert content == "2 lh:9999"

    def test_restart_budget_until_success(self, tmp_path):
        # fails until a marker file exists (created on first attempt), then
        # succeeds — exercises exactly one restart
        script = _script(
            tmp_path,
            f"""
            import os, sys
            marker = os.path.join({str(tmp_path)!r},
                                  "m_" + os.environ["REPLICA_GROUP_ID"])
            if not os.path.exists(marker):
                open(marker, "w").close()
                sys.exit(3)
            sys.exit(0)
            """,
        )
        launcher = ReplicaGroupLauncher(
            [sys.executable, script], replicas=2, max_restarts=2,
            lighthouse_addr="lh:9999", restart_backoff=0.0,
        )
        codes = launcher.run(timeout=60)
        assert codes == {0: 0, 1: 0}

    def test_max_restarts_exhausted(self, tmp_path):
        script = _script(tmp_path, "import sys; sys.exit(7)\n")
        launcher = ReplicaGroupLauncher(
            [sys.executable, script], replicas=1, max_restarts=1,
            lighthouse_addr="lh:9999", restart_backoff=0.0,
        )
        codes = launcher.run(timeout=60)
        assert codes == {0: 7}

    def test_local_lighthouse_spawned(self, tmp_path, monkeypatch):
        monkeypatch.delenv("TORCHFT_LIGHTHOUSE", raising=False)
        script = _script(
            tmp_path,
            f"""
            import os
            with open(os.path.join({str(tmp_path)!r}, "lh"), "w") as f:
                f.write(os.environ["TORCHFT_LIGHTHOUSE"])
            """,
        )
        launcher = ReplicaGroupLauncher([sys.executable, script], replicas=1)
        codes = launcher.run(timeout=60)
        assert codes == {0: 0}
        addr = (tmp_path / "lh").read_text()
        assert ":" in addr

    def test_cli_roundtrip(self, tmp_path):
        script = _script(tmp_path, "import sys; sys.exit(0)\n")
        rc = main(
            ["--replicas", "1", "--lighthouse", "lh:9", "--timeout", "60",
             "--", sys.executable, script]
        )
        assert rc == 0


class TestSlurmRunnerDryRun:
    def test_dry_run_emits_sbatch_lines(self):
        out = subprocess.run(
            [sys.executable, "examples/slurm_runner.py", "--replicas", "2",
             "--dry-run", "--", sys.executable, "examples/train_ddp.py"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert out.returncode == 0, out.stderr
        lines = [l for l in out.stdout.splitlines() if l.startswith("sbatch")]
        assert len(lines) == 2
        assert "REPLICA_GROUP_ID=0" in lines[0]
        assert "REPLICA_GROUP_ID=1" in lines[1]
        assert "NUM_REPLICA_GROUPS=2" in lines[0]
        # wrapped command must be `<interpreter> <script> [args]` with the
        # leading `python` stripped, the script not duplicated
        assert lines[0].count("examples/train_ddp.py") == 1
        assert "python examples/train_ddp.py" not in lines[0].split("--wrap=")[0]

    def test_dry_run_with_script_args(self):
        out = subprocess.run(
            [sys.executable, "examples/slurm_runner.py", "--replicas", "1",
             "--dry-run", "--", "python", "examples/train_diloco.py",
             "--steps", "10"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert out.returncode == 0, out.stderr
        (line,) = [l for l in out.stdout.splitlines() if l.startswith("sbatch")]
        assert line.count("examples/train_diloco.py") == 1
        assert "--steps 10" in line
