"""Unit tests for the plumbing layer: RWLock, timeout engine, sampler.

Mirrors reference test coverage: torchft/checkpointing/rwlock_test.py,
torchft/futures_test.py:18-97, torchft/data_test.py:26.
"""

import threading
import time
from concurrent.futures import Future

import pytest

from torchft_tpu.data import DistributedSampler
from torchft_tpu.utils import RWLock, context_timeout, future_timeout, future_wait


class TestRWLock:
    def test_multiple_readers(self):
        lock = RWLock(timeout=1.0)
        lock.acquire_read()
        lock.acquire_read()
        lock.release_read()
        lock.release_read()

    def test_writer_excludes_readers(self):
        lock = RWLock(timeout=0.1)
        lock.acquire_write()
        with pytest.raises(TimeoutError):
            lock.acquire_read()
        lock.release_write()
        lock.acquire_read()
        lock.release_read()

    def test_reader_excludes_writer(self):
        lock = RWLock(timeout=0.1)
        with lock.r_lock():
            with pytest.raises(TimeoutError):
                lock.acquire_write()
        with lock.w_lock():
            pass

    def test_concurrent_handoff(self):
        lock = RWLock(timeout=5.0)
        results = []

        def writer():
            with lock.w_lock():
                results.append("w")

        with lock.r_lock():
            t = threading.Thread(target=writer)
            t.start()
            time.sleep(0.05)
            assert results == []
        t.join(timeout=2)
        assert results == ["w"]


class TestTimeouts:
    def test_future_timeout_fires(self):
        fut: Future = Future()
        wrapped = future_timeout(fut, 0.05)
        with pytest.raises(TimeoutError):
            wrapped.result(timeout=2)

    def test_future_timeout_success(self):
        fut: Future = Future()
        wrapped = future_timeout(fut, 5.0)
        fut.set_result(42)
        assert wrapped.result(timeout=2) == 42

    def test_future_timeout_exception(self):
        fut: Future = Future()
        wrapped = future_timeout(fut, 5.0)
        fut.set_exception(ValueError("boom"))
        with pytest.raises(ValueError):
            wrapped.result(timeout=2)

    def test_future_wait(self):
        fut: Future = Future()
        fut.set_result("ok")
        assert future_wait(fut, 1.0) == "ok"
        with pytest.raises(TimeoutError):
            future_wait(Future(), 0.05)

    def test_context_timeout_fires(self):
        fired = threading.Event()
        with context_timeout(fired.set, 0.05):
            time.sleep(0.2)
        assert fired.is_set()

    def test_context_timeout_cancelled(self):
        fired = threading.Event()
        with context_timeout(fired.set, 0.5):
            pass
        time.sleep(0.7)
        assert not fired.is_set()


class TestDistributedSampler:
    def test_shard_math(self):
        # reference torchft/data_test.py: rank 1 of 2, group 2 of 4
        s = DistributedSampler(100, replica_rank=2, num_replica_groups=4, rank=1, num_replicas=2)
        assert s.global_rank == 1 + 2 * 2
        assert s.global_world_size == 8
        idx = list(iter(s))
        assert len(idx) == len(s) == 13
        assert idx[0] == s.global_rank

    def test_disjoint_and_complete(self):
        n, groups, ranks = 64, 4, 2
        seen = []
        for g in range(groups):
            for r in range(ranks):
                s = DistributedSampler(n, g, groups, r, ranks)
                seen.extend(iter(s))
        assert sorted(seen) == list(range(n))

    def test_shuffle_deterministic(self):
        a = DistributedSampler(50, 0, 2, shuffle=True, seed=7)
        b = DistributedSampler(50, 0, 2, shuffle=True, seed=7)
        a.set_epoch(3)
        b.set_epoch(3)
        assert list(iter(a)) == list(iter(b))
        b.set_epoch(4)
        assert list(iter(a)) != list(iter(b))


class TestStatefulSampler:
    def test_position_checkpoint_roundtrip(self):
        from torchft_tpu.data import StatefulDistributedSampler

        s = StatefulDistributedSampler(
            100, replica_rank=0, num_replica_groups=2, shuffle=True, seed=3
        )
        it = iter(s)
        consumed = [next(it) for _ in range(10)]
        sd = s.state_dict()
        assert sd == {"epoch": 0, "position": 10}

        # a healed replica resumes exactly where the cohort left off
        s2 = StatefulDistributedSampler(
            100, replica_rank=0, num_replica_groups=2, shuffle=True, seed=3
        )
        s2.load_state_dict(sd)
        rest = list(iter(s2))
        full = list(iter(
            StatefulDistributedSampler(
                100, replica_rank=0, num_replica_groups=2, shuffle=True, seed=3
            )
        ))
        assert consumed + rest == full

    def test_epoch_reset_clears_position(self):
        from torchft_tpu.data import StatefulDistributedSampler

        s = StatefulDistributedSampler(20, replica_rank=0, num_replica_groups=1)
        it = iter(s)
        next(it), next(it)
        assert s.state_dict()["position"] == 2
        s.set_epoch(1)
        assert s.state_dict() == {"epoch": 1, "position": 0}

    def test_exhaustion_keeps_position_until_new_epoch(self):
        from torchft_tpu.data import StatefulDistributedSampler

        s = StatefulDistributedSampler(8, replica_rank=0, num_replica_groups=2)
        list(iter(s))
        # end-of-epoch checkpoint is distinguishable from a fresh epoch:
        # resuming it yields an empty remainder, not a replayed epoch
        assert s.state_dict()["position"] == s.num_samples
        assert s.remaining == 0
        assert list(iter(s)) == []
        assert len(s) == s.num_samples  # stable per-epoch constant
        s.set_epoch(1)
        assert s.state_dict() == {"epoch": 1, "position": 0}
        assert len(list(iter(s))) == s.num_samples
