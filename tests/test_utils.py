"""Unit tests for the plumbing layer: RWLock, timeout engine, sampler.

Mirrors reference test coverage: torchft/checkpointing/rwlock_test.py,
torchft/futures_test.py:18-97, torchft/data_test.py:26.
"""

import threading
import time
from concurrent.futures import Future

import pytest

from torchft_tpu.data import DistributedSampler
from torchft_tpu.utils import RWLock, context_timeout, future_timeout, future_wait


class TestRWLock:
    def test_multiple_readers(self):
        lock = RWLock(timeout=1.0)
        lock.acquire_read()
        lock.acquire_read()
        lock.release_read()
        lock.release_read()

    def test_writer_excludes_readers(self):
        lock = RWLock(timeout=0.1)
        lock.acquire_write()
        with pytest.raises(TimeoutError):
            lock.acquire_read()
        lock.release_write()
        lock.acquire_read()
        lock.release_read()

    def test_reader_excludes_writer(self):
        lock = RWLock(timeout=0.1)
        with lock.r_lock():
            with pytest.raises(TimeoutError):
                lock.acquire_write()
        with lock.w_lock():
            pass

    def test_concurrent_handoff(self):
        lock = RWLock(timeout=5.0)
        results = []

        def writer():
            with lock.w_lock():
                results.append("w")

        with lock.r_lock():
            t = threading.Thread(target=writer)
            t.start()
            time.sleep(0.05)
            assert results == []
        t.join(timeout=2)
        assert results == ["w"]


class TestTimeouts:
    def test_future_timeout_fires(self):
        fut: Future = Future()
        wrapped = future_timeout(fut, 0.05)
        with pytest.raises(TimeoutError):
            wrapped.result(timeout=2)

    def test_future_timeout_success(self):
        fut: Future = Future()
        wrapped = future_timeout(fut, 5.0)
        fut.set_result(42)
        assert wrapped.result(timeout=2) == 42

    def test_future_timeout_exception(self):
        fut: Future = Future()
        wrapped = future_timeout(fut, 5.0)
        fut.set_exception(ValueError("boom"))
        with pytest.raises(ValueError):
            wrapped.result(timeout=2)

    def test_future_wait(self):
        fut: Future = Future()
        fut.set_result("ok")
        assert future_wait(fut, 1.0) == "ok"
        with pytest.raises(TimeoutError):
            future_wait(Future(), 0.05)

    def test_context_timeout_fires(self):
        fired = threading.Event()
        with context_timeout(fired.set, 0.05):
            time.sleep(0.2)
        assert fired.is_set()

    def test_context_timeout_cancelled(self):
        fired = threading.Event()
        with context_timeout(fired.set, 0.5):
            pass
        time.sleep(0.7)
        assert not fired.is_set()


class TestDistributedSampler:
    def test_shard_math(self):
        # reference torchft/data_test.py: rank 1 of 2, group 2 of 4
        s = DistributedSampler(100, replica_rank=2, num_replica_groups=4, rank=1, num_replicas=2)
        assert s.global_rank == 1 + 2 * 2
        assert s.global_world_size == 8
        idx = list(iter(s))
        assert len(idx) == len(s) == 13
        assert idx[0] == s.global_rank

    def test_disjoint_and_complete(self):
        n, groups, ranks = 64, 4, 2
        seen = []
        for g in range(groups):
            for r in range(ranks):
                s = DistributedSampler(n, g, groups, r, ranks)
                seen.extend(iter(s))
        assert sorted(seen) == list(range(n))

    def test_shuffle_deterministic(self):
        a = DistributedSampler(50, 0, 2, shuffle=True, seed=7)
        b = DistributedSampler(50, 0, 2, shuffle=True, seed=7)
        a.set_epoch(3)
        b.set_epoch(3)
        assert list(iter(a)) == list(iter(b))
        b.set_epoch(4)
        assert list(iter(a)) != list(iter(b))


class TestStatefulSampler:
    def test_position_checkpoint_roundtrip(self):
        from torchft_tpu.data import StatefulDistributedSampler

        s = StatefulDistributedSampler(
            100, replica_rank=0, num_replica_groups=2, shuffle=True, seed=3
        )
        it = iter(s)
        consumed = [next(it) for _ in range(10)]
        sd = s.state_dict()
        assert sd == {"epoch": 0, "position": 10}

        # a healed replica resumes exactly where the cohort left off
        s2 = StatefulDistributedSampler(
            100, replica_rank=0, num_replica_groups=2, shuffle=True, seed=3
        )
        s2.load_state_dict(sd)
        rest = list(iter(s2))
        full = list(iter(
            StatefulDistributedSampler(
                100, replica_rank=0, num_replica_groups=2, shuffle=True, seed=3
            )
        ))
        assert consumed + rest == full

    def test_epoch_reset_clears_position(self):
        from torchft_tpu.data import StatefulDistributedSampler

        s = StatefulDistributedSampler(20, replica_rank=0, num_replica_groups=1)
        it = iter(s)
        next(it), next(it)
        assert s.state_dict()["position"] == 2
        s.set_epoch(1)
        assert s.state_dict() == {"epoch": 1, "position": 0}

    def test_exhaustion_keeps_position_until_new_epoch(self):
        from torchft_tpu.data import StatefulDistributedSampler

        s = StatefulDistributedSampler(8, replica_rank=0, num_replica_groups=2)
        list(iter(s))
        # end-of-epoch checkpoint is distinguishable from a fresh epoch:
        # resuming it yields an empty remainder, not a replayed epoch
        assert s.state_dict()["position"] == s.num_samples
        assert s.remaining == 0
        assert list(iter(s)) == []
        assert len(s) == s.num_samples  # stable per-epoch constant
        s.set_epoch(1)
        assert s.state_dict() == {"epoch": 1, "position": 0}
        assert len(list(iter(s))) == s.num_samples


class TestEventExporters:
    """The exporter seam (reference otel.py:42-86 Tee shape): custom sinks
    install via register_exporter, no monkeypatching."""

    def test_custom_exporter_receives_events(self):
        from torchft_tpu.utils.logging import (
            CallbackExporter,
            log_event,
            register_exporter,
            unregister_exporter,
        )

        seen = []
        exp = register_exporter(CallbackExporter(seen.append))
        try:
            log_event("commit", "hello", replica_id="r0", step=3)
        finally:
            unregister_exporter(exp)
        log_event("commit", "after-unregister", replica_id="r0", step=4)
        assert len(seen) == 1
        rec = seen[0]
        assert rec["kind"] == "commit" and rec["message"] == "hello"
        assert rec["replica_id"] == "r0" and rec["step"] == 3 and "ts" in rec

    def test_failing_exporter_never_breaks_logging(self):
        from torchft_tpu.utils.logging import (
            CallbackExporter,
            log_event,
            recent_events,
            register_exporter,
            unregister_exporter,
        )

        def boom(_):
            raise RuntimeError("sink down")

        exp = register_exporter(CallbackExporter(boom))
        try:
            log_event("error", "still records", replica_id="r1", step=0)
        finally:
            unregister_exporter(exp)
        assert any(
            e["message"] == "still records" for e in recent_events()
        )

    def test_ring_exporter_bounded(self):
        from torchft_tpu.utils.logging import RingExporter

        ring = RingExporter(maxlen=4)
        for i in range(10):
            ring.export({"i": i})
        assert [e["i"] for e in ring.events()] == [6, 7, 8, 9]

    def test_event_ring_size_env(self, monkeypatch):
        # satellite: TORCHFT_EVENTS_RING sizes the default ring (read at
        # import; the resolver itself is what's testable post-import)
        from torchft_tpu.utils import logging as tlog

        monkeypatch.setenv("TORCHFT_EVENTS_RING", "7")
        assert tlog._event_ring_size() == 7
        monkeypatch.setenv("TORCHFT_EVENTS_RING", "not-a-number")
        assert tlog._event_ring_size() == 256  # degrades to the default
        monkeypatch.setenv("TORCHFT_EVENTS_RING", "0")
        assert tlog._event_ring_size() == 1  # clamped
        monkeypatch.delenv("TORCHFT_EVENTS_RING")
        assert tlog._event_ring_size() == 256
        # the module singleton was built through the same resolver
        assert tlog._ring._events.maxlen == tlog._EVENT_RING_SIZE

    def test_abort_kind_accepted(self):
        from torchft_tpu.utils.logging import log_event, recent_events

        log_event("abort", "collective aborted", op="allreduce", peer=1)
        assert any(e["kind"] == "abort" for e in recent_events())

    def test_reentrant_exporter_does_not_deadlock(self):
        # the seam's contract: a sink may re-enter the logging module
        # (recent_events, even log_event) without deadlocking
        from torchft_tpu.utils.logging import (
            CallbackExporter,
            log_event,
            recent_events,
            register_exporter,
            unregister_exporter,
        )

        depth = []

        def reentrant(rec):
            if rec["message"] == "outer" and not depth:
                depth.append(1)
                assert isinstance(recent_events(), list)
                log_event("commit", "inner", step=1)

        exp = register_exporter(CallbackExporter(reentrant))
        try:
            done = []
            t = threading.Thread(
                target=lambda: (log_event("commit", "outer", step=0),
                                done.append(True)),
                daemon=True,
            )
            t.start()
            t.join(timeout=5)
            assert done, "log_event deadlocked on a re-entrant exporter"
            msgs = [e["message"] for e in recent_events()]
            assert "outer" in msgs and "inner" in msgs
        finally:
            unregister_exporter(exp)

    def test_jsonl_exporter_concurrent_writers(self, tmp_path, monkeypatch):
        # exports arrive from multiple threads (the pipeline calls sinks
        # outside its own lock); every line must still parse as one JSON
        # record with no interleaving
        import json

        from torchft_tpu.utils.logging import log_event

        events_file = tmp_path / "conc.jsonl"
        monkeypatch.setenv("TORCHFT_EVENTS_FILE", str(events_file))

        n_threads, per_thread = 4, 50

        def writer(tid):
            for i in range(per_thread):
                log_event("commit", f"t{tid}", step=i, replica_id=f"r{tid}")

        threads = [
            threading.Thread(target=writer, args=(t,), daemon=True)
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), "writer threads hung"
        lines = events_file.read_text().strip().splitlines()
        records = [json.loads(line) for line in lines]  # raises on tearing
        assert len(records) == n_threads * per_thread
        for tid in range(n_threads):
            mine = [r for r in records if r["message"] == f"t{tid}"]
            assert sorted(r["step"] for r in mine) == list(range(per_thread))
