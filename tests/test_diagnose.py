"""torchft-diagnose tests: selftest wiring, culprit attribution units,
and the tier-1 chaos smoke (kill one of two DDP replicas mid-step; every
survivor dumps flight state on abort; diagnose names the killed replica
and the failed phase; the lighthouse exports nonzero step lag for the
dead replica before eviction)."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from torchft_tpu import diagnose
from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroupTCP
from torchft_tpu.utils import faults
from torchft_tpu.utils import flightrecorder as fr
from torchft_tpu.utils.faults import FaultRule, InjectedFault
from torchft_tpu.utils.metrics import parse_text_exposition


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


# ---------------------------------------------------------------------------
# selftest wiring (satellite: the CLI can never silently rot)
# ---------------------------------------------------------------------------


class TestSelftest:
    def test_selftest_passes(self):
        assert diagnose.selftest(verbose=False)

    def test_cli_selftest_exit_code(self, capsys):
        assert diagnose.main(["--selftest"]) == 0
        assert "selftest OK" in capsys.readouterr().out

    def test_cli_no_input_is_usage_error(self, capsys):
        assert diagnose.main([]) == 2

    def test_cli_unreadable_input(self, capsys):
        assert diagnose.main(["/nonexistent/flight.jsonl"]) == 1


# ---------------------------------------------------------------------------
# cluster timeline (--timeline: the lighthouse's fleet view)
# ---------------------------------------------------------------------------


def _timeline_doc(worst=None, steps=None):
    return {
        "quorum_id": 3,
        "now_ms": 1_000_000,
        "ring": 256,
        "steps_tracked": len(steps or []),
        "steps": steps
        or [
            {
                "step": 41,
                "replicas": 4,
                "reports": 4,
                "first_ms": 999_000,
                "last_ms": 999_100,
                "span_ms": 100,
                "phases": {"ring": {"n": 4, "mean_ms": 12.0, "max_ms": 30.0}},
                "codec_busy_s": 0.4,
                "wire_busy_s": 0.8,
            }
        ],
        "stragglers_worst": worst or [],
    }


class TestClusterTimeline:
    def test_timeline_straggler_named_without_any_dumps(self, tmp_path, capsys):
        """One /timeline.json scrape alone (no flight dumps collected)
        names the wedged replica — the acceptance path the churn soak
        exercises live."""
        doc = _timeline_doc(
            worst=[
                {
                    "replica_id": "stub007:u2", "step": 38, "step_lag": 3,
                    "progress_age_ms": 9000, "straggler_score": 18.0,
                    "inflight_op": "wedged", "stale": False,
                },
                {
                    "replica_id": "stub001:u0", "step": 41, "step_lag": 0,
                    "progress_age_ms": 400, "straggler_score": 1.1,
                    "inflight_op": "train", "stale": False,
                },
            ]
        )
        path = tmp_path / "timeline.json"
        path.write_text(json.dumps(doc))
        assert diagnose.main(["--timeline", str(path)]) == 0
        out = capsys.readouterr().out
        assert "LIKELY CULPRIT: stub007:u2" in out
        assert "timeline_straggler" in out
        assert "cluster timeline" in out
        assert "step 41" in out and "replicas=4" in out
        assert "worst stragglers" in out

    def test_stale_replica_beats_score_threshold(self, tmp_path):
        doc = _timeline_doc(
            worst=[
                {
                    "replica_id": "dead:u1", "step": 10, "step_lag": 5,
                    "progress_age_ms": 30000, "straggler_score": 2.0,
                    "inflight_op": "", "stale": True,
                }
            ]
        )
        report = diagnose.analyze_timeline(doc)
        assert report["culprit"]["replica_id"] == "dead:u1"
        assert "stale" in report["culprit"]["reason"]

    def test_healthy_timeline_names_nobody(self):
        doc = _timeline_doc(
            worst=[
                {
                    "replica_id": "ok:u1", "step": 41, "step_lag": 0,
                    "progress_age_ms": 100, "straggler_score": 1.2,
                    "inflight_op": "train", "stale": False,
                }
            ]
        )
        assert diagnose.analyze_timeline(doc)["culprit"] is None

    def test_flight_evidence_outranks_timeline(self, tmp_path, capsys):
        """A dump-implicated replica wins over the timeline straggler:
        inside-the-replica evidence is stronger than the outside view."""
        t0 = 1_000_000_000_000
        dump = tmp_path / "a.jsonl"
        with open(dump, "w") as fh:
            for rid, last in (("replica_a:u1", 5), ("replica_b:u2", 1)):
                for step in range(last):
                    fh.write(json.dumps({
                        "flight": "rec", "op": "quorum_rpc", "status": "ok",
                        "start_ns": t0 + step * 10**9,
                        "end_ns": t0 + step * 10**9 + 10**6,
                        "replica_id": rid, "step": step, "quorum_id": 1,
                    }) + "\n")
            fh.write(json.dumps({
                "flight": "rec", "op": "allreduce", "status": "error",
                "start_ns": t0 + 5 * 10**9, "end_ns": t0 + 6 * 10**9,
                "replica_id": "replica_a:u1", "step": 4, "quorum_id": 1,
                "reason": "peer gone",
            }) + "\n")
        tl = tmp_path / "timeline.json"
        tl.write_text(json.dumps(_timeline_doc(worst=[{
            "replica_id": "unrelated:u9", "step": 2, "step_lag": 3,
            "progress_age_ms": 9000, "straggler_score": 30.0,
            "inflight_op": "", "stale": True,
        }])))
        assert diagnose.main([str(dump), "--timeline", str(tl)]) == 0
        out = capsys.readouterr().out
        # silent-death signal from the dumps wins; timeline still rendered
        assert "LIKELY CULPRIT: replica_b:u2" in out
        assert "cluster timeline" in out

    def test_unreadable_timeline_degrades_with_warning(self, tmp_path, capsys):
        assert diagnose.main(["--timeline", str(tmp_path / "nope.json")]) == 1
        assert "--timeline" in capsys.readouterr().err

    def test_load_timeline_rejects_non_timeline_json(self, tmp_path):
        p = tmp_path / "x.json"
        p.write_text('{"not": "a timeline"}')
        with pytest.raises(ValueError):
            diagnose.load_timeline(str(p))


# ---------------------------------------------------------------------------
# attribution units
# ---------------------------------------------------------------------------


class TestAttribution:
    def test_silent_death_culprit_and_text_render(self, tmp_path):
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            a, b = diagnose._synthetic_dumps(td)
            entries, warnings = diagnose.load_records([a, b])
            report = diagnose.analyze(entries)
            text = diagnose.render_text(entries, report, warnings)
        assert report["culprit"]["replica_id"] == "replica_b:u2"
        assert report["culprit"]["signal"] == "silent_death"
        assert report["failure"]["phase"] == "allreduce"
        assert report["failure"]["step"] == 3
        assert "LIKELY CULPRIT: replica_b:u2" in text
        assert "FAILED PHASE: allreduce" in text

    def test_injected_fault_wins_attribution(self, tmp_path):
        dump = tmp_path / "d.jsonl"
        s = 1_000_000_000  # 1s in ns
        t0 = 1_000 * s
        recs = [
            {"flight": "rec", "op": "quorum_rpc", "status": "ok",
             "start_ns": t0, "end_ns": t0 + s, "replica_id": "a", "step": 2},
            {"flight": "rec", "op": "fault", "status": "fault",
             "start_ns": t0 + 2 * s, "end_ns": t0 + 2 * s, "replica_id": "b",
             "step": 2, "fault": "train.step:raise", "site": "train.step",
             "action": "raise"},
            {"flight": "rec", "op": "allreduce", "status": "error",
             "start_ns": t0 + 3 * s, "end_ns": t0 + 10 * s,
             "replica_id": "a", "step": 2, "reason": "peer closed"},
        ]
        dump.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        entries, _ = diagnose.load_records([str(dump)])
        report = diagnose.analyze(entries)
        assert report["culprit"]["replica_id"] == "b"
        assert report["culprit"]["signal"] == "injected_fault"
        assert report["faults"][0]["fault"] == "train.step:raise"

    def test_recovered_fault_does_not_mask_real_death(self, tmp_path):
        """A fault the system survived (its replica kept producing records
        to the end) must NOT win attribution over a later silent death of
        a different replica."""
        dump = tmp_path / "d.jsonl"
        s = 1_000_000_000
        t0 = 1_000 * s
        recs = [
            # replica a absorbs an injected transport fault at step 1...
            {"flight": "rec", "op": "fault", "status": "fault",
             "start_ns": t0, "end_ns": t0, "replica_id": "a", "step": 1,
             "fault": "transport.recv:raise", "site": "transport.recv",
             "action": "raise"},
        ]
        # ...and both replicas keep training; b silently dies at step 8
        for step in range(1, 10):
            for rid in ("a", "b"):
                if rid == "b" and step >= 8:
                    continue
                base = t0 + step * s
                recs.append(
                    {"flight": "rec", "op": "ring", "status": "ok",
                     "start_ns": base, "end_ns": base + 1000,
                     "replica_id": rid, "step": step}
                )
        recs.append(
            {"flight": "rec", "op": "allreduce", "status": "error",
             "start_ns": t0 + 8 * s, "end_ns": t0 + 18 * s,
             "replica_id": "a", "step": 8, "reason": "deadline"}
        )
        dump.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        entries, _ = diagnose.load_records([str(dump)])
        report = diagnose.analyze(entries)
        assert report["culprit"]["replica_id"] == "b", report["culprit"]
        assert report["culprit"]["signal"] == "silent_death"

    def test_healthy_run_yields_no_culprit(self, tmp_path):
        """Staggered shutdown of a clean run (no error/abort/fault
        anywhere) must NOT produce a culprit, even when one replica's
        last record is seconds after the other's."""
        dump = tmp_path / "d.jsonl"
        s = 1_000_000_000
        t0 = 1_000 * s
        recs = []
        for step in range(5):
            for rid in ("a:u0", "b:u1"):
                base = t0 + step * s
                recs.append(
                    {"flight": "rec", "op": "ring", "status": "ok",
                     "start_ns": base, "end_ns": base + 1000,
                     "replica_id": rid, "step": step}
                )
        # a's shutdown-time dump logs one extra record much later
        recs.append(
            {"flight": "rec", "op": "commit", "status": "ok",
             "start_ns": t0 + 8 * s, "end_ns": t0 + 8 * s,
             "replica_id": "a:u0", "step": 4}
        )
        dump.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        entries, _ = diagnose.load_records([str(dump)])
        report = diagnose.analyze(entries)
        assert report["culprit"] is None, report["culprit"]
        assert report["failure"] is None

    def test_recovered_fault_phantom_id_not_blamed(self, tmp_path):
        """A bare-id fault record (the faults layer stamps no incarnation
        suffix) must not mint a phantom 'dead' replica: a run where the
        faulted replica restarted and kept training stays culprit-free."""
        dump = tmp_path / "d.jsonl"
        s = 1_000_000_000
        t0 = 1_000 * s
        recs = [
            {"flight": "rec", "op": "fault", "status": "fault",
             "start_ns": t0 + s, "end_ns": t0 + s, "replica_id": "b",
             "step": 1, "fault": "train.step:raise", "site": "train.step",
             "action": "raise"},
        ]
        for step in range(5):
            for rid in ("a:u0", "b:u1"):
                base = t0 + step * s
                recs.append(
                    {"flight": "rec", "op": "ring", "status": "ok",
                     "start_ns": base, "end_ns": base + 1000,
                     "replica_id": rid, "step": step}
                )
        dump.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        entries, _ = diagnose.load_records([str(dump)])
        report = diagnose.analyze(entries)
        # no phantom 'b' liveness entry, no verdict on a recovered run
        assert all(":" in rid for rid in report["replicas"]), report["replicas"]
        assert report["culprit"] is None, report["culprit"]

    def test_one_sided_evidence_points_at_peer_not_reporter(self, tmp_path):
        """Only the survivor's dump collected (the victim was SIGKILLed —
        no dump): the tool must NOT blame the replica that reported the
        failure; it points at the peer rank from the failing transfer."""
        dump = tmp_path / "d.jsonl"
        s = 1_000_000_000
        t0 = 1_000 * s
        recs = [
            {"flight": "rec", "op": "quorum_rpc", "status": "ok",
             "start_ns": t0, "end_ns": t0 + s, "replica_id": "a:u1",
             "step": 4, "quorum_id": 2},
            {"flight": "rec", "op": "allreduce", "status": "error",
             "start_ns": t0 + 2 * s, "end_ns": t0 + 12 * s,
             "replica_id": "a:u1", "rank": 0, "world": 2, "recv_peer": 1,
             "reason": "collective failed: timeout"},
        ]
        dump.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        entries, _ = diagnose.load_records([str(dump)])
        report = diagnose.analyze(entries)
        assert report["culprit"] is not None
        assert report["culprit"]["signal"] == "peer_without_evidence"
        assert "rank 1" in report["culprit"]["replica_id"]
        assert not report["culprit"]["replica_id"].startswith("a:")

    def test_retry_storm_flagged(self, tmp_path):
        dump = tmp_path / "d.jsonl"
        t0 = 1_000_000_000_000
        recs = [
            {"flight": "rec", "op": "retry", "status": "retry",
             "start_ns": t0 + i, "end_ns": t0 + i, "replica_id": "a",
             "retry_op": "rpc.connect", "attempt": i}
            for i in range(5)
        ]
        dump.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        entries, _ = diagnose.load_records([str(dump)])
        report = diagnose.analyze(entries)
        assert report["retry_storms"] == [
            {"replica_id": "a", "op": "rpc.connect", "retries": 5}
        ]
        assert report["culprit"]["signal"] == "retry_storm"

    def test_events_merge_and_dedupe(self, tmp_path):
        """TORCHFT_EVENTS_FILE records merge into the same timeline, and a
        record dumped twice (two ring snapshots) appears once."""
        dump = tmp_path / "d.jsonl"
        rec = {"flight": "rec", "op": "allreduce", "status": "error",
               "start_ns": 5, "end_ns": 9, "replica_id": "a", "step": 1}
        dump.write_text(json.dumps(rec) + "\n" + json.dumps(rec) + "\n")
        events = tmp_path / "ev.jsonl"
        events.write_text(json.dumps(
            {"ts": 1.0, "kind": "quorum", "message": "quorum changed",
             "replica_id": "a", "step": 1, "quorum_id": 3}
        ) + "\n")
        entries, warnings = diagnose.load_records(
            [str(dump)], [str(events)]
        )
        assert not warnings
        assert len(entries) == 2  # deduped flight rec + one event
        sources = {e["source"] for e in entries}
        assert sources == {"flight", "event"}

    def test_json_output(self, tmp_path, capsys):
        dump = tmp_path / "d.jsonl"
        dump.write_text(json.dumps(
            {"flight": "rec", "op": "ring", "status": "ok",
             "start_ns": 1, "end_ns": 2, "replica_id": "a", "step": 0}
        ) + "\n")
        assert diagnose.main([str(dump), "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["entries"] == 1
        assert payload["timeline"][0]["op"] == "ring"


# ---------------------------------------------------------------------------
# tier-1 chaos smoke (acceptance criteria end to end)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestDiagnoseChaosSmoke:
    def test_kill_mid_step_dump_diagnose_and_step_lag(
        self, tmp_path, monkeypatch
    ):
        """Kill one of two DDP replicas mid-step (after quorum, before its
        collective — the worst moment for its peer): the survivor's wedged
        collective fails and dumps flight state, torchft-diagnose names
        the killed replica and the failed phase, and the lighthouse
        exports nonzero torchft_replica_step_lag for the dead replica
        (its progress entry outlives its heartbeat until supersession)."""
        TOTAL, KILL_AT = 6, 2
        flight_file = tmp_path / "flight.jsonl"
        monkeypatch.setenv("TORCHFT_FLIGHT_FILE", str(flight_file))
        fr.RECORDER.clear()
        faults.FAULTS.configure(
            [FaultRule(site="train.step", replica="replica_1", step=KILL_AT)],
            seed=11,
        )

        # min_replicas=1 so the survivor can form a singleton quorum after
        # the permanent kill.  Warm-up heartbeats for two placeholder ids
        # arm the split-brain guard, holding the FIRST quorum open until
        # both real managers have joined (the placeholders expire after
        # heartbeat_timeout_ms and never participate).
        lighthouse = LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        from torchft_tpu.coordination import LighthouseClient

        warm = LighthouseClient(lighthouse.address())
        warm.heartbeat("warm_a")
        warm.heartbeat("warm_b")
        warm.close()
        results = {}
        errors = {}

        def run(rid: int) -> None:
            params = {"w": np.zeros(4, dtype=np.float32)}

            def load_state_dict(sd):
                params["w"] = np.array(sd["w"])

            def state_dict():
                return {"w": params["w"].copy()}

            pg = ProcessGroupTCP(timeout=10.0)
            manager = Manager(
                pg=pg,
                min_replica_size=1,
                load_state_dict=load_state_dict,
                state_dict=state_dict,
                lighthouse_addr=lighthouse.address(),
                replica_id=f"replica_{rid}",
                group_rank=0,
                group_world_size=1,
                use_async_quorum=False,  # quorum forms BEFORE the kill site
                timeout=20.0,
                quorum_timeout=20.0,
            )
            try:
                while manager.current_step() < TOTAL:
                    step = manager.current_step()
                    manager.start_quorum()
                    # kill site sits between quorum formation and the
                    # collective: the peer is left blocked mid-ring
                    faults.check(
                        "train.step", replica=f"replica_{rid}", step=step
                    )
                    grads = {
                        "w": np.full(4, float(step + 1), dtype=np.float32)
                        * (1.0 + 0.5 * rid)
                    }
                    avg = manager.allreduce(grads).wait(timeout=30)
                    if manager.should_commit():
                        params["w"] = params["w"] - 0.1 * avg["w"]
                results[rid] = {
                    "state": state_dict(), "step": manager.current_step()
                }
            except InjectedFault:
                # "process death": the OS would close every socket — abort
                # does exactly that (and dumps this replica's flight ring)
                pg.abort()
                results[rid] = {"killed_at": manager.current_step()}
            except BaseException as e:  # noqa: BLE001
                errors[rid] = e
            finally:
                manager.shutdown()

        threads = [
            threading.Thread(target=run, args=(r,), daemon=True)
            for r in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "replica hung"
        assert not errors, errors
        assert results[0].get("step") == TOTAL, results
        assert results[1].get("killed_at") == KILL_AT, results

        # --- every surviving process dumped on abort -------------------
        lines = [
            json.loads(l) for l in flight_file.read_text().splitlines()
        ]
        metas = [l for l in lines if l.get("flight") == "meta"]
        assert any(m["trigger"] == "pg_abort" for m in metas), metas
        recs = [l for l in lines if l.get("flight") == "rec"]
        # survivor's failed collective is in the dump with error status
        assert any(
            r["status"] == "error"
            and str(r.get("replica_id", "")).startswith("replica_0")
            for r in recs
        ), "survivor's collective failure not captured"

        # --- diagnose names the killed replica and the failed phase ----
        entries, _warnings = diagnose.load_records([str(flight_file)])
        report = diagnose.analyze(entries)
        assert report["culprit"] is not None, report
        assert report["culprit"]["replica_id"].startswith("replica_1"), report[
            "culprit"
        ]
        assert report["failure"] is not None
        assert report["failure"]["phase"] in ("allreduce", "manager.error", "abort")
        # the CLI agrees (exit 0, culprit in the rendered text)
        assert diagnose.main([str(flight_file)]) == 0

        # --- lighthouse exports nonzero step lag for the dead replica --
        body = (
            urllib.request.urlopen(
                f"http://{lighthouse.address()}/metrics", timeout=5
            )
            .read()
            .decode()
        )
        fams = parse_text_exposition(body)
        lags = fams["torchft_replica_step_lag"]["samples"]
        dead_lag = [
            v
            for (name, labels), v in lags.items()
            if name == "torchft_replica_step_lag"
            and dict(labels).get("replica", "").startswith("replica_1")
        ]
        assert dead_lag and dead_lag[0] > 0, lags
        survivor_lag = [
            v
            for (name, labels), v in lags.items()
            if name == "torchft_replica_step_lag"
            and dict(labels).get("replica", "").startswith("replica_0")
        ]
        assert survivor_lag and survivor_lag[0] == 0, lags
        # straggler score for the dead replica dwarfs the survivor's
        scores = fams["torchft_straggler_score"]["samples"]
        dead_score = [
            v
            for (name, labels), v in scores.items()
            if dict(labels).get("replica", "").startswith("replica_1")
        ]
        assert dead_score and dead_score[0] >= 1.0, scores
        lighthouse.shutdown()


# ---------------------------------------------------------------------------
# trace ledger (torchft-diagnose --trace)
# ---------------------------------------------------------------------------


def _span(name, trace, sid, parent, t0_ms, t1_ms, ok=True, **attrs):
    return {
        "name": name, "trace_id": trace, "span_id": sid,
        "parent_span_id": parent, "start_ns": t0_ms * 1_000_000,
        "end_ns": t1_ms * 1_000_000, "attributes": attrs, "ok": ok,
    }


class TestTraceLedger:
    """analyze_trace over synthetic span files: category attribution,
    the quant.pipeline codec/wire substitution, the lighthouse
    straggler-wait refinement, and the CLI with --trace as the ONLY
    input."""

    def _write(self, tmp_path, spans):
        path = tmp_path / "trace.jsonl"
        path.write_text("".join(json.dumps(s) + "\n" for s in spans))
        return path

    def test_categories_and_critical_path(self, tmp_path):
        T = "a" * 32
        spans = [
            _span("quorum_round", T, "ra" + "0" * 14, None, 0, 1000,
                  replica_id="rep_a", step=5, quorum_id=2),
            _span("quorum_rpc", T, "p1" + "0" * 14, "ra" + "0" * 14, 0, 100,
                  replica_id="rep_a", step=5),
            # quant.pipeline REPLACES ring in the sums
            _span("ring", T, "p2" + "0" * 14, "ra" + "0" * 14, 100, 900,
                  replica_id="rep_a", step=5),
            _span("quant.pipeline", T, "p3" + "0" * 14, "ra" + "0" * 14,
                  100, 900, collective="allreduce", codec_s=0.25,
                  wire_s=0.55),
            # faster replica, protocol-dominant
            _span("quorum_round", T, "rb" + "0" * 14, None, 0, 400,
                  replica_id="rep_b", step=5, quorum_id=2),
            _span("commit", T, "p4" + "0" * 14, "rb" + "0" * 14, 0, 300,
                  replica_id="rep_b", step=5),
        ]
        report = diagnose.analyze_trace(spans)
        assert len(report["steps"]) == 1
        row = report["steps"][0]
        assert row["step"] == 5 and row["quorum_id"] == 2
        assert row["critical_replica"] == "rep_a"
        a = row["replicas"]["rep_a"]
        # ring (0.8s) replaced by pipeline codec 0.25 + wire 0.55
        assert a["categories"]["codec"] == pytest.approx(0.25)
        assert a["categories"]["wire"] == pytest.approx(0.55)
        assert a["categories"]["protocol"] == pytest.approx(0.1)
        assert a["dominant"] == "wire" and row["dominant"] == "wire"
        assert row["replicas"]["rep_b"]["dominant"] == "protocol"
        assert report["culprit"] is None

    def test_lighthouse_span_refines_straggler_wait(self, tmp_path):
        T = "b" * 32
        spans = [
            _span("quorum_round", T, "r0" + "0" * 14, None, 0, 1000,
                  replica_id="rep_a", step=1, quorum_id=1),
            # the caller blocked 0.9 s; the lighthouse says 0.7 s of that
            # was waiting for the quorum to form
            _span("quorum_wait", T, "w0" + "0" * 14, "r0" + "0" * 14, 0, 900,
                  replica_id="rep_a", step=1),
            _span("rpc.quorum", T, "l0" + "0" * 14, "r0" + "0" * 14, 0, 700,
                  server="lighthouse", method="quorum"),
        ]
        report = diagnose.analyze_trace(spans)
        cats = report["steps"][0]["replicas"]["rep_a"]["categories"]
        # 0.7 measured + 0.2 excess quorum_wait = 0.9 total, not 1.6
        assert cats["straggler-wait"] == pytest.approx(0.9)

    def test_cli_trace_only_names_culprit(self, tmp_path, capsys):
        T = "c" * 32
        spans = [
            _span("quorum_round", T, "r0" + "0" * 14, None, 0, 500,
                  replica_id="rep_a", step=2, quorum_id=1),
            _span("quorum_round", T, "r1" + "0" * 14, None, 0, 400, ok=False,
                  replica_id="rep_bad", step=2, quorum_id=1),
        ]
        path = self._write(tmp_path, spans)
        rc = diagnose.main(["--trace", str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "critical-path ledger" in out
        # the verdict block names the failed replica, trace-only input
        assert "LIKELY CULPRIT: rep_bad" in out
        assert "[trace_error]" in out

    def test_bench_vocabulary_matches(self):
        """bench.py's per-leg dominant field uses this module's mapping —
        pin the vocabulary so the tail stays joinable with the ledger."""
        assert diagnose.dominant_contributor(
            {"quorum_rpc": 1.0, "ring": 5.0}
        ) == "wire"
        assert diagnose.dominant_contributor(
            {"quorum_wait": 9.0, "commit": 1.0}
        ) == "straggler-wait"
        assert diagnose.dominant_contributor({}) is None
        for cat in diagnose.PHASE_CATEGORY.values():
            assert cat in diagnose.LEDGER_CATEGORIES
