"""Flash attention (Pallas, interpret mode on CPU) vs dense reference.

The kernel must match dense_attention in both directions of AD — it is
the bench flagship's attention (attn_impl='flash') so a numerics drift
here is a silent model-quality bug.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchft_tpu.ops.flash_attention import flash_attention
from torchft_tpu.ops.ring_attention import dense_attention


def _qkv(b=2, t=256, h=4, hkv=2, d=64, dtype=jnp.float32, seed=0):
    key = jax.random.PRNGKey(seed)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, t, h, d), dtype)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, t, hkv, d), dtype)
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, t, hkv, d), dtype)
    return q, k, v


class TestFlashForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv()
        ref = dense_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-5
        )

    def test_multiple_block_sizes(self):
        # 128 / 256 / 512 / 1024 block selection paths (1024 engages at
        # head_dim <= 256 when it divides T — the flagship tile)
        for t in (128, 384, 512, 1024):
            q, k, v = _qkv(t=t, seed=t)
            ref = dense_attention(q, k, v)
            out = flash_attention(q, k, v)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-5
            )

    def test_block_ladder_head_dim_gate(self):
        from torchft_tpu.ops.flash_attention import _block_size

        assert _block_size(1024, 256) == 1024
        assert _block_size(1024, 512) == 512  # wide heads keep 512 tiles
        assert _block_size(512, 256) == 512
        assert _block_size(384, 64) == 128

    def test_fully_masked_rows_yield_zero_not_mean_of_v(self):
        # A chunk whose queries all PRECEDE every key (causal ring chunk
        # with q_off < k_off) has zero live keys per row: the kernel must
        # emit O == 0 and lse ~ -inf for such rows, not exp(-inf - -inf)=1
        # weights (a garbage mean of V).
        from torchft_tpu.ops.flash_attention import _fwd, _to3

        q, k, v = _qkv(t=128)
        scale = 1.0 / np.sqrt(q.shape[-1])
        h = q.shape[2]
        ke = jnp.repeat(k, h // k.shape[2], axis=2)
        ve = jnp.repeat(v, h // v.shape[2], axis=2)
        # keys start INSIDE the first tile (k_off=64): rows 0..63 are fully
        # masked within a tile the block-level `needed` gate keeps live, so
        # this exercises the p-masking line (an out-of-tile offset like 4096
        # would be skipped by the gate and pass even without the fix)
        offs = jnp.array([0, 64], jnp.int32)
        o, lse = _fwd(_to3(q), _to3(ke), _to3(ve), scale, True, offs)
        o, lse = np.asarray(o), np.asarray(lse)
        np.testing.assert_array_equal(o[:, :64], 0.0)
        assert np.all(lse[:, :64] < -1e20)
        # live rows are untouched by the masking
        assert np.all(np.isfinite(o[:, 64:])) and np.any(o[:, 64:] != 0.0)

    def test_rejects_unaligned_seq(self):
        q, k, v = _qkv(t=100)
        with pytest.raises(ValueError, match="128"):
            flash_attention(q, k, v)

    def test_gqa_head_broadcast(self):
        q, k, v = _qkv(h=8, hkv=2)
        ref = dense_attention(q, k, v)
        out = flash_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-5
        )


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_dense(self, causal):
        q, k, v = _qkv()

        def make_loss(fn):
            def loss(q, k, v):
                out = fn(q, k, v, causal=causal)
                # non-uniform cotangent exercises dq/dk/dv paths properly
                w = jnp.arange(out.size, dtype=out.dtype).reshape(out.shape)
                return (out * w).mean()

            return jax.grad(loss, argnums=(0, 1, 2))

        g_ref = make_loss(dense_attention)(q, k, v)
        g_out = make_loss(flash_attention)(q, k, v)
        for name, a, b in zip("qkv", g_out, g_ref):
            scale = float(np.abs(np.asarray(b)).max()) + 1e-12
            np.testing.assert_allclose(
                np.asarray(a) / scale, np.asarray(b) / scale,
                atol=1e-5, err_msg=f"d{name}",
            )


class TestFlashInTransformer:
    def test_forward_matches_dense_impl(self):
        from torchft_tpu.models import transformer as tfm

        base = dict(
            vocab_size=64, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
            n_layers=2, max_seq_len=128, dtype=jnp.float32,
        )
        params = tfm.init_params(
            jax.random.PRNGKey(0), tfm.TransformerConfig(**base)
        )
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 64)
        ref = tfm.forward(
            params, tokens, tfm.TransformerConfig(attn_impl="dense", **base)
        )
        out = tfm.forward(
            params, tokens, tfm.TransformerConfig(attn_impl="flash", **base)
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
        )

    def test_train_step_grads_finite(self):
        import optax

        from torchft_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
            n_layers=2, max_seq_len=128, dtype=jnp.float32,
            attn_impl="flash",
        )
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        optimizer = optax.adamw(1e-3)
        step = tfm.make_train_step(cfg, optimizer, donate=False)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 64)
        params2, _, loss = step(params, optimizer.init(params), tokens)
        assert np.isfinite(float(loss))
        for leaf in jax.tree_util.tree_leaves(params2):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_rejects_manual_context(self):
        # flash does not nest in the pipeline's manual shard_map context
        from torchft_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
            n_layers=2, max_seq_len=128, attn_impl="flash",
            dtype=jnp.float32,
        )
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        block = tfm._make_block(cfg, "manual")
        x = jnp.zeros((2, 128, 64), jnp.float32)
        layer0 = jax.tree_util.tree_map(lambda a: a[0], params["blocks"])
        with pytest.raises(ValueError, match="manual shard_map"):
            block(x, layer0, jnp.arange(128))


class TestFlashOnMesh:
    def test_batch_and_head_sharded_matches_dense(self):
        # flash on a dp x tp mesh: batch and heads shard, each device runs
        # the kernel on its full-sequence shard
        from jax.sharding import Mesh, NamedSharding

        from torchft_tpu.models import transformer as tfm

        base = dict(
            vocab_size=64, d_model=64, n_heads=4, n_kv_heads=4, d_ff=128,
            n_layers=2, max_seq_len=128, dtype=jnp.float32,
        )
        cfg = tfm.TransformerConfig(attn_impl="flash", **base)
        cfg_dense = tfm.TransformerConfig(attn_impl="dense", **base)
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 128), 0, 64)
        ref = tfm.forward(params, tokens, cfg_dense)

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
        sharded = tfm.shard_params(params, mesh, cfg)
        tok_sh = jax.device_put(
            tokens, NamedSharding(mesh, tfm.batch_spec(cfg, mesh))
        )
        out = jax.jit(lambda p, t: tfm.forward(p, t, cfg, mesh))(sharded, tok_sh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
        )

    def test_rejects_cp_mesh(self):
        from jax.sharding import Mesh

        from torchft_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
            n_layers=2, max_seq_len=128, attn_impl="flash", dtype=jnp.float32,
        )
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("cp",))
        block = tfm._make_block(cfg, mesh)
        x = jnp.zeros((2, 128, 64), jnp.float32)
        layer0 = jax.tree_util.tree_map(lambda a: a[0], params["blocks"])
        with pytest.raises(ValueError, match="sequence unsharded"):
            block(x, layer0, jnp.arange(128))


class TestFlashBf16:
    def test_bf16_matches_dense_within_tolerance(self):
        # the production dtype: matmuls in bf16 with f32 accumulation in
        # BOTH impls — agreement bound is bf16 resolution, not exactness
        q, k, v = _qkv(dtype=jnp.bfloat16, seed=3)
        ref = np.asarray(dense_attention(q, k, v), np.float32)
        out = np.asarray(flash_attention(q, k, v), np.float32)
        scale = np.abs(ref).max() + 1e-9
        assert np.abs(out - ref).max() / scale < 3e-2
