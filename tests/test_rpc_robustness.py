"""Framed-JSON RPC robustness: fuzz/negative frames on both sides of the
wire, and the idempotent/non-idempotent resend contract.

Three suites:

* client vs hostile server — ``_RpcClient._recv_frame``/``_recv_exact``
  against truncated frames, oversized length prefixes, non-UTF8 and
  non-object reply payloads: every case must surface a clean
  ``ConnectionError``/``TimeoutError``/``RpcError``, never hang or leak
  a desynchronized connection into the next call;
* native server vs hostile client — the same malformed frames thrown at
  a real ``LighthouseServer``: the server must drop or error the bad
  connection and keep serving well-formed requests;
* resend contract (PR 2's ``idempotent=`` flag) — with a connection that
  dies after delivery but before the reply, idempotent methods are
  re-sent exactly once and non-idempotent ``should_commit`` is NOT
  (the delivery count proves it), plus the native barrier's stale-step
  vote rejection (the server-side half of the same invariant).
"""

import json
import socket
import struct
import threading
import time

import pytest

from torchft_tpu.coordination import (
    LighthouseClient,
    LighthouseServer,
    ManagerClient,
    ManagerServer,
    RpcError,
    StoreServer,
    _MAX_FRAME_BYTES,
    _RpcClient,
)
from torchft_tpu.utils import faults


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


def _frame(payload: bytes) -> bytes:
    return struct.pack(">I", len(payload)) + payload


def _read_frame(sock: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (length,) = struct.unpack(">I", hdr)
    buf = b""
    while len(buf) < length:
        chunk = sock.recv(length - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


class _FakeServer:
    """One-thread scripted peer: each accepted connection pops the next
    handler.  Handlers receive the connected socket and run to completion;
    ``deliveries`` counts full request frames parsed."""

    def __init__(self, handlers):
        self.handlers = list(handlers)
        self.deliveries = []
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.addr = f"127.0.0.1:{self.sock.getsockname()[1]}"
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while self.handlers:
            handler = self.handlers.pop(0)
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            try:
                handler(self, conn)
            except (OSError, ConnectionError):
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass

    # -- scripted behaviors -------------------------------------------------

    def recv_request(self, conn) -> dict:
        req = json.loads(_read_frame(conn))
        self.deliveries.append(req["method"])
        return req

    @staticmethod
    def ok_reply(conn, result=None):
        conn.sendall(_frame(json.dumps({"ok": True, "result": result or {}}).encode()))


def _client(server: "_FakeServer") -> _RpcClient:
    return _RpcClient(server.addr, connect_timeout=5.0)


class TestClientAgainstHostileServer:
    def test_truncated_reply_then_close(self):
        def handler(srv, conn):
            srv.recv_request(conn)
            conn.sendall(struct.pack(">I", 100) + b"short")

        srv = _FakeServer([handler])
        c = _client(srv)
        try:
            with pytest.raises(ConnectionError):
                c.call("m", {}, timeout=5.0, idempotent=False)
        finally:
            c.close()
            srv.close()

    def test_truncated_reply_stall_times_out(self):
        def handler(srv, conn):
            srv.recv_request(conn)
            conn.sendall(struct.pack(">I", 100) + b"short")
            time.sleep(3.0)  # stall mid-frame, connection open

        srv = _FakeServer([handler])
        c = _client(srv)
        t0 = time.monotonic()
        try:
            with pytest.raises(TimeoutError):
                c.call("m", {}, timeout=0.5, idempotent=False)
            assert time.monotonic() - t0 < 2.0  # deadline, not the stall
        finally:
            c.close()
            srv.close()

    def test_oversized_length_prefix_rejected(self):
        """A reply header claiming > _MAX_FRAME_BYTES must fail cleanly
        BEFORE the client tries to buffer gigabytes."""

        def handler(srv, conn):
            srv.recv_request(conn)
            conn.sendall(struct.pack(">I", _MAX_FRAME_BYTES + 1))
            time.sleep(1.0)

        srv = _FakeServer([handler])
        c = _client(srv)
        try:
            with pytest.raises(ConnectionError, match="ceiling"):
                c.call("m", {}, timeout=5.0, idempotent=False)
        finally:
            c.close()
            srv.close()

    def test_non_utf8_reply_is_clean_rpc_error(self):
        def handler(srv, conn):
            srv.recv_request(conn)
            conn.sendall(_frame(b"\xff\xfe{bad utf8"))

        srv = _FakeServer([handler])
        c = _client(srv)
        try:
            with pytest.raises(RpcError, match="malformed"):
                c.call("m", {}, timeout=5.0, idempotent=False)
        finally:
            c.close()
            srv.close()

    def test_non_object_reply_is_clean_rpc_error(self):
        def handler(srv, conn):
            srv.recv_request(conn)
            conn.sendall(_frame(b"[1, 2, 3]"))
            srv.recv_request(conn)  # must NOT be reached on same conn
            _FakeServer.ok_reply(conn)

        srv = _FakeServer([handler, lambda srv, conn: (srv.recv_request(conn), _FakeServer.ok_reply(conn))])
        c = _client(srv)
        try:
            with pytest.raises(RpcError, match="not a JSON object"):
                c.call("m", {}, timeout=5.0, idempotent=False)
            # the poisoned connection was dropped: the next call dials fresh
            assert c.call("m2", {}, timeout=5.0) == {}
        finally:
            c.close()
            srv.close()


@pytest.fixture
def lighthouse():
    server = LighthouseServer(min_replicas=1, join_timeout_ms=50)
    yield server
    server.shutdown()


def _raw(addr: str) -> socket.socket:
    host, port = addr.rsplit(":", 1)
    s = socket.create_connection((host or "127.0.0.1", int(port)), timeout=5.0)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _rpc(sock: socket.socket, method: str, params: dict) -> dict:
    sock.sendall(_frame(json.dumps(
        {"method": method, "params": params, "timeout_ms": 5000}
    ).encode()))
    return json.loads(_read_frame(sock))


def _assert_server_alive(addr: str):
    s = _raw(addr)
    try:
        resp = _rpc(s, "heartbeat", {"replica_id": "fuzz_alive:x"})
        assert resp["ok"] is True
    finally:
        s.close()


class TestNativeServerAgainstHostileClient:
    def test_oversized_length_prefix_drops_connection(self, lighthouse):
        s = _raw(lighthouse.address())
        try:
            s.sendall(struct.pack(">I", 0xFFFFFFFF))
            # server must close on us rather than wait for 4 GiB
            s.settimeout(5.0)
            assert s.recv(1) == b""
        finally:
            s.close()
        _assert_server_alive(lighthouse.address())

    def test_truncated_frame_then_close(self, lighthouse):
        s = _raw(lighthouse.address())
        s.sendall(struct.pack(">I", 100) + b"only ten b")
        s.close()
        _assert_server_alive(lighthouse.address())

    def test_non_utf8_payload_errors_cleanly(self, lighthouse):
        s = _raw(lighthouse.address())
        try:
            s.sendall(_frame(b"\xff\xfe\x00garbage"))
            s.settimeout(5.0)
            try:
                resp = json.loads(_read_frame(s))
                assert resp["ok"] is False
            except ConnectionError:
                pass  # dropping the connection is equally clean
        finally:
            s.close()
        _assert_server_alive(lighthouse.address())

    def test_non_object_payload_errors_cleanly(self, lighthouse):
        s = _raw(lighthouse.address())
        try:
            s.sendall(_frame(b"[1, 2, 3]"))
            resp = json.loads(_read_frame(s))
            assert resp["ok"] is False
            # the connection stays usable for a well-formed request
            resp = _rpc(s, "heartbeat", {"replica_id": "fuzz_obj:x"})
            assert resp["ok"] is True
        finally:
            s.close()

    def test_empty_frame_errors_cleanly(self, lighthouse):
        s = _raw(lighthouse.address())
        try:
            s.sendall(_frame(b""))
            resp = json.loads(_read_frame(s))
            assert resp["ok"] is False
        finally:
            s.close()
        _assert_server_alive(lighthouse.address())

    def test_unknown_method_errors_cleanly(self, lighthouse):
        s = _raw(lighthouse.address())
        try:
            resp = _rpc(s, "no_such_method", {})
            assert resp["ok"] is False and "error" in resp
        finally:
            s.close()

    @pytest.mark.slow
    def test_mid_frame_stall_is_reaped(self, lighthouse):
        """A half-sent request whose sender stalls (connection open, body
        never completes) must not pin a server connection thread past the
        kFrameBodyTimeoutMs (30 s) body deadline — the server closes the
        connection instead of waiting out the 24 h idle window."""
        s = _raw(lighthouse.address())
        try:
            s.sendall(struct.pack(">I", 64) + b"stalled-half-frame")
            s.settimeout(40.0)
            t0 = time.monotonic()
            assert s.recv(1) == b""  # server reaped us...
            assert time.monotonic() - t0 < 35.0  # ...within the body window
        finally:
            s.close()
        _assert_server_alive(lighthouse.address())


class TestResendContract:
    """PR 2's ``idempotent=`` flag, proven by delivery counting: the
    connection dies after the server consumed the request but before the
    reply — the exact window where a blind resend double-delivers."""

    @staticmethod
    def _die_after_delivery(srv, conn):
        srv.recv_request(conn)  # request consumed...
        conn.close()  # ...connection dies before any reply

    @staticmethod
    def _serve_one(srv, conn):
        req = srv.recv_request(conn)
        result = {"should_commit": True} if req["method"] == "should_commit" else {}
        _FakeServer.ok_reply(conn, result)

    def test_idempotent_method_is_resent_once(self):
        srv = _FakeServer([self._die_after_delivery, self._serve_one])
        c = _RpcClient(srv.addr, connect_timeout=5.0)
        try:
            assert c.call("heartbeat", {"replica_id": "r"}, timeout=10.0) == {}
            assert srv.deliveries == ["heartbeat", "heartbeat"]
        finally:
            c.close()
            srv.close()

    def test_should_commit_is_never_resent(self):
        srv = _FakeServer([self._die_after_delivery, self._serve_one])
        mc = ManagerClient(srv.addr, connect_timeout=5.0)
        try:
            with pytest.raises(ConnectionError):
                mc.should_commit(0, step=3, should_commit=True, timeout=10.0)
            # exactly one delivery: the vote must not reach the barrier twice
            assert srv.deliveries == ["should_commit"]
        finally:
            mc.close()
            srv.close()

    def test_faults_layer_drop_retries_idempotent_call(self, lighthouse):
        """The chaos-layer form of the same contract: an injected
        connection drop on the pooled lighthouse connection is absorbed
        by the idempotent resend path against the REAL server."""
        faults.FAULTS.configure(
            [faults.FaultRule(site="lighthouse.rpc", action="drop", times=1)]
        )
        c = LighthouseClient(lighthouse.address())
        try:
            resp = c.heartbeat("drop_test:x", timeout=10.0)
            assert isinstance(resp, dict)
            assert faults.FAULTS.injected() == 1
        finally:
            c.close()


class TestBarrierStepValidation:
    """The native should_commit barrier's stale-vote rejection — the
    server-side half of the vote-integrity invariant the tft-verify vote
    sub-model checks (a delivered-then-resent vote carries the OLD step
    and must not satisfy a later round's tally)."""

    @pytest.fixture
    def stack(self):
        lh = LighthouseServer(min_replicas=1, join_timeout_ms=50)
        store = StoreServer()
        mgr = ManagerServer(
            replica_id="barrier_0:a",
            lighthouse_addr=lh.address(),
            store_address=store.address(),
            world_size=2,
        )
        yield mgr
        mgr.shutdown()
        store.shutdown()
        lh.shutdown()

    def test_stale_step_vote_is_rejected(self, stack):
        c0 = ManagerClient(stack.address())
        c1 = ManagerClient(stack.address())
        results = {}

        def rank0():
            results["r0"] = c0.should_commit(0, step=5, should_commit=True,
                                             timeout=20.0)

        t = threading.Thread(target=rank0)
        t.start()
        time.sleep(0.2)  # let rank 0 open the round at step 5
        try:
            with pytest.raises(RpcError, match="stale or double-delivered"):
                c1.should_commit(1, step=4, should_commit=True, timeout=5.0)
            # a correct-step vote still completes the barrier
            assert c1.should_commit(1, step=5, should_commit=True,
                                    timeout=20.0) is True
            t.join(timeout=20.0)
            assert results.get("r0") is True
        finally:
            c0.close()
            c1.close()

    def test_timed_out_vote_is_withdrawn(self, stack):
        """A failed commit retries the SAME step, so a vote whose barrier
        wait timed out must be withdrawn from the open tally: left behind,
        it would complete the retry round with only one fresh vote — and
        an orphaned NO vote would force the retry's decision to False even
        when every fresh vote is yes."""
        c0 = ManagerClient(stack.address())
        c1 = ManagerClient(stack.address())
        try:
            # rank 0 votes NO at step 3 and times out waiting for rank 1.
            # The server's barrier deadline coincides with the client's
            # socket deadline, so either the server's TimeoutError reply
            # (RpcError) or the client's own socket timeout can win.
            with pytest.raises((RpcError, TimeoutError), match="time"):
                c0.should_commit(0, step=3, should_commit=False, timeout=0.3)
            # let the server-side handler reach its own deadline and
            # withdraw the vote before the retry round opens
            time.sleep(2.0)
            # the retry round at the SAME step: both ranks vote yes; a
            # surviving orphan tally would decide False (poisoned) or
            # strand one voter on a ghost round
            out = {}

            def vote(c, rank):
                out[rank] = c.should_commit(rank, step=3, should_commit=True,
                                            timeout=20.0)

            threads = [
                threading.Thread(target=vote, args=(c, r))
                for r, c in enumerate((c0, c1))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=20.0)
            assert out == {0: True, 1: True}
        finally:
            c0.close()
            c1.close()

    def test_next_round_accepts_new_step(self, stack):
        c0 = ManagerClient(stack.address())
        c1 = ManagerClient(stack.address())

        def vote(c, rank, step, out):
            out[rank] = c.should_commit(rank, step=step, should_commit=True,
                                        timeout=20.0)

        try:
            for step in (0, 1):
                out = {}
                threads = [
                    threading.Thread(target=vote, args=(c, r, step, out))
                    for r, c in enumerate((c0, c1))
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=20.0)
                assert out == {0: True, 1: True}
        finally:
            c0.close()
            c1.close()
