"""LocalSGD / DiLoCo unit tests with mocked manager.

Mirrors reference torchft/local_sgd_test.py: sync cadence, allreduce
call-count bound (:191), pseudogradient math, fragment schedule validation.
"""

from unittest.mock import MagicMock, create_autospec

import numpy as np
import optax
import pytest

from torchft_tpu.local_sgd import DiLoCo, LocalSGD
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.work import completed_work


def mock_manager(use_async=False):
    manager = create_autospec(Manager, instance=True)
    manager._use_async_quorum = use_async
    manager._timeout = 10.0
    manager.current_step.return_value = 0
    manager.should_commit.return_value = True
    manager.allreduce.side_effect = lambda v, **kw: completed_work(v)
    return manager


class ParamStore:
    def __init__(self, params):
        self.params = dict(params)

    def get(self):
        return dict(self.params)

    def set(self, p):
        self.params = dict(p)


class TestLocalSGD:
    def test_sync_cadence(self):
        manager = mock_manager()
        store = ParamStore({"w": np.ones(2, dtype=np.float32)})
        with LocalSGD(manager, store.get, store.set, sync_every=3) as lsgd:
            for _ in range(2):
                lsgd.step()
            assert manager.start_quorum.call_count == 0
            lsgd.step()
            assert manager.start_quorum.call_count == 1
            assert manager.allreduce.call_count == 1
            for _ in range(3):
                lsgd.step()
            assert manager.start_quorum.call_count == 2

    def test_sync_applies_average(self):
        manager = mock_manager()
        manager.allreduce.side_effect = lambda v, **kw: completed_work(
            {k: x * 0.5 for k, x in v.items()}
        )
        store = ParamStore({"w": np.full(2, 4.0, dtype=np.float32)})
        lsgd = LocalSGD(manager, store.get, store.set, sync_every=1)
        lsgd.step()
        np.testing.assert_allclose(store.params["w"], np.full(2, 2.0))

    def test_failed_commit_keeps_local(self):
        manager = mock_manager()
        manager.should_commit.return_value = False
        store = ParamStore({"w": np.full(2, 4.0, dtype=np.float32)})
        lsgd = LocalSGD(manager, store.get, store.set, sync_every=1)
        lsgd.step()
        np.testing.assert_allclose(store.params["w"], np.full(2, 4.0))

    def test_registers_state_dict_fn(self):
        manager = mock_manager()
        store = ParamStore({"w": np.ones(1)})
        LocalSGD(manager, store.get, store.set, sync_every=2)
        manager.register_state_dict_fn.assert_called_once()


class TestDiLoCoValidation:
    def test_requires_sync_quorum(self):
        manager = mock_manager(use_async=True)
        store = ParamStore({"w": np.ones(1, dtype=np.float32)})
        with pytest.raises(ValueError, match="synchronous quorum"):
            DiLoCo(manager, [["w"]], store.get, store.set, optax.sgd(0.1), sync_every=2)

    def test_sync_every_divisibility(self):
        manager = mock_manager()
        store = ParamStore({"a": np.ones(1, dtype=np.float32), "b": np.ones(1, dtype=np.float32)})
        with pytest.raises(ValueError, match="divisible"):
            DiLoCo(
                manager,
                [["a"], ["b"]],
                store.get,
                store.set,
                optax.sgd(0.1),
                sync_every=3,
            )

    def test_fragment_sync_delay_bound(self):
        manager = mock_manager()
        store = ParamStore({"a": np.ones(1, dtype=np.float32)})
        with pytest.raises(ValueError, match="synced before"):
            DiLoCo(
                manager,
                [["a"]],
                store.get,
                store.set,
                optax.sgd(0.1),
                sync_every=2,
                fragment_sync_delay=2,
            )


class TestDiLoCoMath:
    def test_allreduce_only_on_sync_steps(self):
        # reference local_sgd_test.py:191 — allreduce call-count bound
        manager = mock_manager()
        store = ParamStore({"w": np.ones(4, dtype=np.float32)})
        diloco = DiLoCo(
            manager, [["w"]], store.get, store.set, optax.sgd(0.5), sync_every=4
        )
        for _ in range(8):
            diloco.step()
        assert manager.allreduce.call_count == 2
        assert manager.start_quorum.call_count == 2

    def test_outer_sgd_applies_pseudograds(self):
        manager = mock_manager()
        store = ParamStore({"w": np.full(2, 10.0, dtype=np.float32)})
        diloco = DiLoCo(
            manager, [["w"]], store.get, store.set, optax.sgd(1.0), sync_every=1
        )
        # inner training moves w from 10 -> 8: pseudograd = backup - local = 2
        store.set({"w": np.full(2, 8.0, dtype=np.float32)})
        diloco.step()
        # outer sgd(lr=1): global = 10 - 1*2 = 8 (alpha=0 -> take global)
        np.testing.assert_allclose(store.params["w"], np.full(2, 8.0))
        np.testing.assert_allclose(
            diloco._fragments[0].original_parameters["w"], np.full(2, 8.0)
        )

    def test_failed_commit_restores_backup(self):
        manager = mock_manager()
        manager.should_commit.return_value = False
        store = ParamStore({"w": np.full(2, 10.0, dtype=np.float32)})
        diloco = DiLoCo(
            manager, [["w"]], store.get, store.set, optax.sgd(1.0), sync_every=1
        )
        store.set({"w": np.full(2, 8.0, dtype=np.float32)})
        diloco.step()
        # rollback to the global backup: skip data rather than overtrain
        np.testing.assert_allclose(store.params["w"], np.full(2, 10.0))

    def test_fragment_update_alpha_merges(self):
        manager = mock_manager()
        store = ParamStore({"w": np.full(2, 10.0, dtype=np.float32)})
        diloco = DiLoCo(
            manager,
            [["w"]],
            store.get,
            store.set,
            optax.sgd(1.0),
            sync_every=1,
            fragment_update_alpha=0.5,
        )
        store.set({"w": np.full(2, 8.0, dtype=np.float32)})
        diloco.step()
        # global=8, local=8 -> merged = 8 (degenerate); use distinct values:
        store.set({"w": np.full(2, 0.0, dtype=np.float32)})
        diloco.step()
        # backup=8, local=0 -> pseudograd=8 -> global=0; merged=0.5*0+0.5*0
        np.testing.assert_allclose(store.params["w"], np.full(2, 0.0))

    def test_streaming_fragments_rotate(self):
        manager = mock_manager()
        step_counter = {"n": 0}
        manager.current_step.side_effect = lambda: step_counter["n"]

        def commit():
            step_counter["n"] += 1
            return True

        manager.should_commit.side_effect = commit
        store = ParamStore(
            {
                "a": np.ones(2, dtype=np.float32),
                "b": np.ones(2, dtype=np.float32),
            }
        )
        diloco = DiLoCo(
            manager,
            [["a"], ["b"]],
            store.get,
            store.set,
            optax.sgd(0.1),
            sync_every=4,  # cycle = 2 per fragment
        )
        synced = []
        orig_a = diloco._fragments[0].perform_sync
        orig_b = diloco._fragments[1].perform_sync
        diloco._fragments[0].perform_sync = lambda: synced.append("a") or orig_a()
        diloco._fragments[1].perform_sync = lambda: synced.append("b") or orig_b()
        for _ in range(8):
            diloco.step()
        assert synced == ["a", "b", "a", "b"]

    def test_prepare_delay_overlap(self):
        # fragment_sync_delay=1: allreduce kicked off one step before the
        # blocking sync (the streaming overlap).
        manager = mock_manager()
        store = ParamStore({"w": np.ones(2, dtype=np.float32)})
        diloco = DiLoCo(
            manager,
            [["w"]],
            store.get,
            store.set,
            optax.sgd(0.1),
            sync_every=3,
            fragment_sync_delay=1,
        )
        diloco.step()  # step 1
        assert manager.allreduce.call_count == 0
        diloco.step()  # step 2 == cycle - delay -> prepare
        assert manager.allreduce.call_count == 1
        assert manager.should_commit.call_count == 0
        diloco.step()  # step 3 == cycle -> perform
        assert manager.should_commit.call_count == 1
