"""Weight-serving tier: tree synthesis, payload codec, live fan-out
round trips, and the chaos smoke (kill a tree node mid-fetch -> the
client completes from a failover source with bitwise-identical weights).

docs/architecture.md "Weight-serving tier"; ISSUE 12.
"""

import threading
import time

import numpy as np
import pytest

from torchft_tpu.coordination import LighthouseClient, LighthouseServer
from torchft_tpu.ops import quantization as q
from torchft_tpu.serving import (
    ServingClient,
    ServingReplica,
    WeightPublisher,
    changed_fragments,
    decode_payload,
    encode_payload,
    fetch_resource,
)
from torchft_tpu.utils import faults as _faults


def _wait_until(cond, timeout=15.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": rng.randn(16, 32).astype(np.float32),
        "b": rng.randn(8).astype(np.float32),
        "step": int(seed),
    }


def _int8_roundtrip(a):
    return q.dequantize(
        *q.quantize(a, q.WIRE_INT8), a.shape, np.dtype(np.float32)
    )


# ---------------------------------------------------------------------------
# lighthouse plan synthesis
# ---------------------------------------------------------------------------


class TestServingPlan:
    def test_tree_shape_and_determinism(self):
        with LighthouseServer(min_replicas=1, serving_fanout=2) as server:
            c = LighthouseClient(server.address())
            c.serving_heartbeat("pub", "http://p:1", role="publisher",
                                version=3)
            for i in range(7):
                c.serving_heartbeat(f"s{i}", f"http://s{i}:1", role="server")
            plan = c.serving_plan()
            assert plan["root_source"] == "http://p:1"
            assert plan["latest_version"] == 3
            assert plan["fanout"] == 2
            nodes = {n["replica_id"]: n for n in plan["nodes"]}
            assert len(nodes) == 7
            roots = [n for n in plan["nodes"] if n["parent"] == ""]
            assert len(roots) == 1 and roots[0]["replica_id"] == "s0"
            # binary fan-out: depths 0,1,1,2,2,2,2
            assert sorted(n["depth"] for n in plan["nodes"]) == [
                0, 1, 1, 2, 2, 2, 2,
            ]
            assert plan["depth"] == 2
            # every non-root parent is a real node address
            addrs = {n["address"] for n in plan["nodes"]}
            for n in plan["nodes"]:
                if n["parent"]:
                    assert n["parent"] in addrs
            # child counts match the parent edges
            for rid, n in nodes.items():
                kids = sum(
                    1 for m in plan["nodes"] if m["parent"] == n["address"]
                )
                assert kids == n["children"], rid
            # identical membership -> identical tree on re-read
            plan2 = c.serving_plan()
            assert plan2["nodes"] == plan["nodes"]
            assert plan2["epoch"] == plan["epoch"]

    def test_epoch_bumps_on_membership_not_version(self):
        with LighthouseServer(min_replicas=1) as server:
            c = LighthouseClient(server.address())
            e0 = c.serving_heartbeat("a", "http://a:1", role="server")[
                "plan_epoch"
            ]
            # refresh with a new VERSION only: no tree-shape change
            e1 = c.serving_heartbeat(
                "a", "http://a:1", role="server", version=9
            )["plan_epoch"]
            assert e1 == e0
            # a join changes the shape
            e2 = c.serving_heartbeat("b", "http://b:1", role="server")[
                "plan_epoch"
            ]
            assert e2 > e1
            # so does an address change of an existing member
            e3 = c.serving_heartbeat("a", "http://a:2", role="server")[
                "plan_epoch"
            ]
            assert e3 > e2

    def test_expiry_reforms_tree(self):
        with LighthouseServer(
            min_replicas=1, heartbeat_timeout_ms=300, quorum_tick_ms=50
        ) as server:
            c = LighthouseClient(server.address())
            c.serving_heartbeat("a", "http://a:1", role="server")
            e = c.serving_heartbeat("b", "http://b:1", role="server")[
                "plan_epoch"
            ]

            def alive():
                # keep "a" fresh; let "b" expire
                c.serving_heartbeat("a", "http://a:1", role="server")
                plan = c.serving_plan()
                return (
                    [n["replica_id"] for n in plan["nodes"]],
                    plan["epoch"],
                )

            _wait_until(
                lambda: alive() == (["a"], e + 1) or alive()[0] == ["a"],
                timeout=10,
                msg="expired member pruned",
            )
            ids, epoch = alive()
            assert ids == ["a"]
            assert epoch > e

    def test_capacity_overrides_fanout(self):
        with LighthouseServer(min_replicas=1, serving_fanout=2) as server:
            c = LighthouseClient(server.address())
            c.serving_heartbeat("s0", "http://s0:1", role="server",
                                capacity=4)
            for i in range(1, 5):
                c.serving_heartbeat(f"s{i}", f"http://s{i}:1", role="server")
            plan = c.serving_plan()
            root = [n for n in plan["nodes"] if n["parent"] == ""][0]
            assert root["replica_id"] == "s0"
            assert root["children"] == 4  # capacity=4 beat the fanout
            assert plan["depth"] == 1

    def test_bad_role_rejected(self):
        from torchft_tpu.coordination import RpcError

        with LighthouseServer(min_replicas=1) as server:
            c = LighthouseClient(server.address())
            with pytest.raises(RpcError, match="role"):
                c.serving_heartbeat("x", "http://x:1", role="tree")

    def test_status_and_serving_json_surface(self):
        import json as _json
        import urllib.request

        with LighthouseServer(min_replicas=1) as server:
            c = LighthouseClient(server.address())
            c.serving_heartbeat("pub", "http://p:1", role="publisher",
                                version=5)
            c.serving_heartbeat("s0", "http://s0:1", role="server")
            st = c.status()
            assert st["serving"]["publishers"] == 1
            assert st["serving"]["servers"] == 1
            assert st["serving"]["latest_version"] == 5
            with urllib.request.urlopen(
                f"http://{server.address()}/serving.json"
            ) as f:
                doc = _json.load(f)
            assert doc["latest_version"] == 5
            assert [n["replica_id"] for n in doc["nodes"]] == ["s0"]
            mtx = urllib.request.urlopen(
                f"http://{server.address()}/metrics"
            ).read().decode()
            assert "torchft_lighthouse_serving_epoch" in mtx
            assert (
                'torchft_lighthouse_serving_replicas{role="publisher"} 1'
                in mtx
            )


# ---------------------------------------------------------------------------
# payload codec
# ---------------------------------------------------------------------------


class TestPayloadCodec:
    def test_f32_roundtrip_bitwise(self):
        sd = _state(1)
        doc = encode_payload(sd, 7, wire="f32", fragments=2)
        state, manifest, _ = decode_payload(doc)
        assert manifest["version"] == 7
        np.testing.assert_array_equal(state["w"], sd["w"])
        np.testing.assert_array_equal(state["b"], sd["b"])
        assert state["step"] == sd["step"]

    def test_int8_matches_collective_codec(self):
        sd = _state(2)
        doc = encode_payload(sd, 1, wire="int8")
        state, _, _ = decode_payload(doc)
        np.testing.assert_array_equal(state["w"], _int8_roundtrip(sd["w"]))
        np.testing.assert_array_equal(state["b"], _int8_roundtrip(sd["b"]))
        # non-float leaves pass through untouched
        assert state["step"] == sd["step"]

    def test_encoding_deterministic(self):
        sd = _state(3)
        d1 = encode_payload(sd, 1, wire="int8", fragments=3)
        d2 = encode_payload(sd, 1, wire="int8", fragments=3)
        m1 = d1["frag:manifest"]["digests"]
        m2 = d2["frag:manifest"]["digests"]
        assert m1 == m2

    def test_changed_fragments_detects_delta(self):
        sd = _state(4)
        doc1 = encode_payload(sd, 1, fragments=4)
        man1 = doc1["frag:manifest"]
        sd2 = dict(sd)
        sd2["b"] = sd["b"] + 1.0
        doc2 = encode_payload(sd2, 2, fragments=4)
        man2 = doc2["frag:manifest"]
        moved = changed_fragments(man2, man1)
        # only the fragment holding "b" moved
        assert len(moved) == 1
        # no previous manifest -> everything moved
        assert changed_fragments(man2, None) == man2["fragments"]
        # delta decode: merge the moved fragment over v1's leaves
        _, _, leaves1 = decode_payload(doc1)
        subset = {"frag:manifest": man2}
        for name in moved:
            subset[f"frag:{name}"] = doc2[f"frag:{name}"]
        state, _, _ = decode_payload(subset, prev=(man1, leaves1))
        np.testing.assert_array_equal(state["b"], sd2["b"])
        np.testing.assert_array_equal(state["w"], sd["w"])

    def test_incomplete_delta_is_loud(self):
        sd = _state(5)
        doc = encode_payload(sd, 1, fragments=2)
        subset = {
            "frag:manifest": doc["frag:manifest"],
            "frag:0": doc["frag:0"],
        }
        with pytest.raises(ValueError, match="missing leaf"):
            decode_payload(subset)

    def test_bad_wire_rejected(self):
        with pytest.raises(ValueError, match="wire"):
            encode_payload(_state(0), 1, wire="fp4")


# ---------------------------------------------------------------------------
# live fan-out round trips
# ---------------------------------------------------------------------------


@pytest.fixture
def tier():
    """lighthouse + int8 publisher + 3 serving replicas + client."""
    lh = LighthouseServer(
        min_replicas=1, heartbeat_timeout_ms=1000, quorum_tick_ms=50,
        serving_fanout=2,
    )
    pub = WeightPublisher(
        lh.address(), wire="int8", fragments=2, heartbeat_interval=0.1
    )
    reps = [
        ServingReplica(
            lh.address(), replica_id=f"srv{i}", poll_interval=0.05,
            fetch_timeout=10.0,
        )
        for i in range(3)
    ]
    client = ServingClient(lh.address(), plan_ttl=0.1)
    yield lh, pub, reps, client
    client.close()
    for r in reps:
        try:
            r.shutdown()
        except Exception:  # noqa: BLE001 - some are killed by the test
            pass
    pub.shutdown()
    lh.shutdown()


class TestServingRoundtrip:
    def test_publish_relay_fetch_bitwise(self, tier):
        lh, pub, reps, client = tier
        sd = _state(10)
        v = pub.publish(sd)
        state, got = client.fetch(timeout=20)
        assert got == v
        np.testing.assert_array_equal(state["w"], _int8_roundtrip(sd["w"]))
        assert state["step"] == sd["step"]
        # relays converge to the published version
        _wait_until(
            lambda: all(r.version() == v for r in reps),
            msg="relays converged",
        )
        # every node serves BITWISE-identical decoded weights
        from torchft_tpu.serving import fetch_resource, payload as _p

        docs = [
            fetch_resource(r.address(), v, "full", timeout=10) for r in reps
        ]
        states = [_p.decode_payload(d)[0] for d in docs]
        for s in states:
            np.testing.assert_array_equal(s["w"], states[0]["w"])
            np.testing.assert_array_equal(s["w"], state["w"])

    def test_delta_fetch_moves_changed_fragment_only(self, tier):
        lh, pub, reps, client = tier
        sd = _state(11)
        v1 = pub.publish(sd)
        state1, _ = client.fetch(timeout=20)
        sd2 = dict(sd)
        sd2["b"] = sd["b"] + 1.0
        v2 = pub.publish(sd2)

        def fetched_v2():
            state, got = client.fetch(timeout=10)
            return got == v2 and np.array_equal(
                state["b"], _int8_roundtrip(sd2["b"])
            )

        _wait_until(fetched_v2, msg="delta fetch of v2")
        # the held version advanced (delta path keeps the leaf cache)
        assert client._held_version == v2

    def test_publish_version_monotone(self, tier):
        lh, pub, reps, client = tier
        pub.publish(_state(0), version=5)
        with pytest.raises(ValueError, match="monotone"):
            pub.publish(_state(0), version=5)

    def test_manager_publish_hook(self, tier):
        """Manager.attach_weight_publisher publishes the committed user
        state as version=step — DEFERRED until the next round / shutdown
        (the user's optimizer update lands after should_commit returns),
        and a publisher failure never escapes."""
        from torchft_tpu.manager import Manager

        lh, pub, reps, client = tier
        m = object.__new__(Manager)
        from torchft_tpu.utils.rwlock import RWLock
        import logging as _logging

        m._state_dict_lock = RWLock(timeout=5)
        m._user_state_dicts = {"model": lambda: _state(12)}
        m._logger = _logging.getLogger("test_manager_publish")
        m._weight_publisher = None
        m._publish_executor = None
        m._publish_pending = 3
        m._flush_pending_publish()  # unattached: no-op, pending cleared
        assert m._publish_pending is None
        assert pub.latest_version() == 0
        m.attach_weight_publisher(pub)
        m._publish_pending = 3  # what a committed step 3 would set
        # publish runs on the manager's single-worker executor (the
        # training thread only snapshots); wait=True drains it
        m._flush_pending_publish(wait=True)
        assert pub.latest_version() == 3
        m._flush_pending_publish(wait=True)  # idempotent: nothing pending
        assert pub.latest_version() == 3
        state, got = client.fetch(timeout=20)
        assert got == 3
        np.testing.assert_array_equal(
            state["model"]["w"], _int8_roundtrip(_state(12)["w"])
        )

        class _Boom:
            def publish(self, *a, **k):
                raise RuntimeError("publisher down")

        m.attach_weight_publisher(_Boom())
        m._publish_pending = 4
        m._flush_pending_publish(wait=True)  # logged, never raised


# ---------------------------------------------------------------------------
# chaos: kill a tree node mid-fetch -> failover completes bitwise
# ---------------------------------------------------------------------------


class TestServingChaos:
    def test_kill_tree_node_mid_fetch_failover_bitwise(self, tier):
        """The tier-1 serving chaos smoke (`make serve-smoke`): one
        interior/root tree node dies while clients fetch; every client
        completes from a failover source with weights bitwise-identical
        to the published payload, and the lighthouse re-forms the tree
        (epoch bump) around the corpse."""
        lh, pub, reps, client = tier
        sd = _state(20)
        v = pub.publish(sd)
        expected, _ = client.fetch(timeout=20)
        _wait_until(
            lambda: all(r.version() == v for r in reps),
            msg="relays converged",
        )
        plan = client.plan(refresh=True)
        epoch0 = plan["epoch"]
        # victim: the ROOT relay (every other node's ancestor — the
        # worst-case interior death)
        root = [n for n in plan["nodes"] if n["parent"] == ""][0]
        victim = next(r for r in reps if r.replica_id() == root["replica_id"])

        results = {}

        def _fetch(i):
            try:
                state, got = ServingClient(
                    lh.address(), plan_ttl=0.1, client_id=str(i)
                ).fetch(version=v, timeout=30)
                results[i] = (state, got)
            except Exception as e:  # noqa: BLE001 - asserted below
                results[i] = e

        threads = [
            threading.Thread(target=_fetch, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        victim.shutdown()  # mid-fetch kill
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "client fetch wedged"
        for i, res in results.items():
            assert not isinstance(res, Exception), f"client {i}: {res}"
            state, got = res
            assert got == v
            np.testing.assert_array_equal(state["w"], expected["w"])
            np.testing.assert_array_equal(state["b"], expected["b"])
        # the tree re-forms without the victim
        def reformed():
            p = client.plan(refresh=True)
            ids = [n["replica_id"] for n in p["nodes"]]
            return victim.replica_id() not in ids and p["epoch"] > epoch0

        _wait_until(reformed, msg="tree re-formed after node death")
        # and a NEW publish still reaches clients through the survivors
        sd2 = _state(21)
        v2 = pub.publish(sd2)
        state2, got2 = client.fetch(version=v2, timeout=30)
        assert got2 == v2
        np.testing.assert_array_equal(
            state2["w"], _int8_roundtrip(sd2["w"])
        )

    def test_injected_fetch_fault_fails_over(self, tier):
        """serving.fetch chaos injection: the client's own site firing
        surfaces (scheduled), while relay-side transport drops are
        absorbed by failover."""
        lh, pub, reps, client = tier
        v = pub.publish(_state(30))
        client.fetch(timeout=20)  # warm, no faults
        _faults.FAULTS.configure(
            [_faults.FaultRule(site="serving.fetch", action="raise",
                               step=v, times=1)],
            seed=7,
        )
        try:
            with pytest.raises(_faults.InjectedFault):
                client.fetch(version=v, timeout=10)
            assert _faults.FAULTS.injected("serving.fetch") == 1
            # schedule exhausted: the next fetch completes normally
            state, got = client.fetch(version=v, timeout=20)
            assert got == v
        finally:
            _faults.FAULTS.clear()

    def test_tree_commit_fault_degrades_not_wedges(self):
        """An injected serving.tree_commit failure leaves the replica on
        its old plan (serving what it holds); the next beat adopts."""
        lh = LighthouseServer(
            min_replicas=1, heartbeat_timeout_ms=1000, quorum_tick_ms=50
        )
        pub = WeightPublisher(lh.address(), heartbeat_interval=0.1)
        _faults.FAULTS.configure(
            [_faults.FaultRule(site="serving.tree_commit", action="raise",
                               times=1)],
            seed=3,
        )
        try:
            rep = ServingReplica(
                lh.address(), replica_id="solo", poll_interval=0.05
            )
            v = pub.publish(_state(31))
            # despite the first adoption failing, the replica converges
            _wait_until(lambda: rep.version() == v, msg="replica converged")
            assert _faults.FAULTS.injected("serving.tree_commit") == 1
            assert rep.plan_epoch() >= 0
            rep.shutdown()
        finally:
            _faults.FAULTS.clear()
            pub.shutdown()
            lh.shutdown()


# ---------------------------------------------------------------------------
# streaming relay (ISSUE 14): cut-through, delta relay pulls, zero-decode
# passthrough, poisoned-fragment integrity, deep-tree chaos
# ---------------------------------------------------------------------------


def _chain_tier(n_relays, fragments=4, wire="f32", stream=None,
                poll=0.02):
    """fanout=1 lighthouse + publisher + a CHAIN of n relays (depth
    0..n-1): the deep-tree shape the cut-through path exists for."""
    lh = LighthouseServer(
        min_replicas=1, heartbeat_timeout_ms=1500, quorum_tick_ms=50,
        serving_fanout=1,
    )
    pub = WeightPublisher(
        lh.address(), wire=wire, fragments=fragments,
        heartbeat_interval=0.05,
    )
    reps = [
        ServingReplica(
            lh.address(), replica_id=f"chain{i}", poll_interval=poll,
            fetch_timeout=10.0, stream=stream,
        )
        for i in range(n_relays)
    ]
    return lh, pub, reps


def _teardown(lh, pub, reps):
    for r in reps:
        try:
            r.shutdown()
        except Exception:  # noqa: BLE001 - some are killed by the test
            pass
    pub.shutdown()
    lh.shutdown()


class TestStreamingRelay:
    def test_chain_converges_bitwise_and_decode_stays_manifest_only(self):
        """Depth-3 chain on the streaming path: every relay ends up
        serving bitwise-identical raw fragment bytes (zero-decode
        passthrough — the relay never re-encodes), and the relay decode
        histogram's stream leg stays manifest-sized (~0) while a flat
        pull decodes the whole payload."""
        from torchft_tpu.serving import fetcher as _fetcher
        from torchft_tpu.utils import metrics as _m
        from torchft_tpu.utils.bufpool import POOL

        dec0 = _m.SERVING_RELAY_DECODE.labels(mode="stream").get()
        lh, pub, reps = _chain_tier(3, fragments=4, wire="int8")
        try:
            sd = _state(40)
            v = pub.publish(sd)
            _wait_until(
                lambda: all(r.version() == v for r in reps),
                msg="chain converged",
            )
            # depth really is a chain
            plan = LighthouseClient(lh.address()).serving_plan()
            assert sorted(n["depth"] for n in plan["nodes"]) == [0, 1, 2]
            # passthrough: the raw fragment bytes on every relay are the
            # PUBLISHER'S bytes, verbatim
            man = fetch_resource(
                pub.address(), v, "frag_manifest", timeout=10
            )
            for name in man["fragments"]:
                src = _fetcher.fetch_raw(
                    pub.address(), v, f"frag_{name}", timeout=10
                )
                want = bytes(memoryview(src))
                POOL.give(src)
                for r in reps:
                    got = _fetcher.fetch_raw(
                        r.address(), v, f"frag_{name}", timeout=10
                    )
                    assert bytes(memoryview(got)) == want, (
                        f"relay {r.replica_id()} frag {name} not verbatim"
                    )
                    POOL.give(got)
            # relay decode on the streaming path = manifests only: the
            # 3-relay chain pulled a multi-fragment int8 payload, yet
            # total decode time stays ~0 (no payload codec pass)
            dec = _m.SERVING_RELAY_DECODE.labels(mode="stream").get()
            assert dec["count"] - dec0["count"] >= 3
            assert dec["sum"] - dec0["sum"] < 0.25
            # cut-through occupancy gauge was set to a sane value
            occ = _m.SERVING_CUT_OCCUPANCY.get()
            assert 0.0 <= occ <= 1.0
        finally:
            _teardown(lh, pub, reps)

    def test_relay_delta_pull_moves_only_changed_fragment_bytes(self):
        """Steady-state relay wire bytes scale with the update delta:
        a publish changing ONE leaf moves ~one fragment + manifest per
        relay, not the payload (asserted via
        torchft_serving_fetch_bytes{role=relay})."""
        from torchft_tpu.utils import metrics as _m

        lh, pub, reps = _chain_tier(2, fragments=4, wire="f32")
        try:
            rng = np.random.RandomState(3)
            sd = {
                f"l{i}": rng.randn(256, 32).astype(np.float32)
                for i in range(4)
            }
            payload_bytes = sum(a.nbytes for a in sd.values())
            v1 = pub.publish(sd)
            _wait_until(
                lambda: all(r.version() == v1 for r in reps),
                msg="v1 converged",
            )
            b0 = _m.SERVING_FETCH_BYTES.labels(role="relay").get()
            sd2 = dict(sd)
            sd2["l0"] = sd["l0"] + 1.0
            v2 = pub.publish(sd2)
            _wait_until(
                lambda: all(r.version() == v2 for r in reps),
                msg="v2 converged",
            )
            moved = _m.SERVING_FETCH_BYTES.labels(role="relay").get() - b0
            # 2 relays x (manifest + 1 changed fragment of 4): well under
            # one full payload, let alone two
            assert moved < payload_bytes, (
                f"delta relay pull moved {moved} bytes "
                f">= payload {payload_bytes}"
            )
            # and the content is right everywhere
            state, _, _ = decode_payload(
                fetch_resource(reps[-1].address(), v2, "full", timeout=10)
            )
            np.testing.assert_array_equal(state["l0"], sd2["l0"])
            np.testing.assert_array_equal(state["l1"], sd["l1"])
        finally:
            _teardown(lh, pub, reps)

    def test_flat_mode_roundtrip_still_works(self):
        """TORCHFT_SERVING_STREAM=0 (stream=False) keeps the whole-
        payload store-and-forward path functional — the depth-bench
        baseline — and its decode histogram leg is NON-zero."""
        from torchft_tpu.utils import metrics as _m

        dec0 = _m.SERVING_RELAY_DECODE.labels(mode="flat").get()
        lh, pub, reps = _chain_tier(2, fragments=2, wire="int8",
                                    stream=False)
        try:
            sd = _state(41)
            v = pub.publish(sd)
            _wait_until(
                lambda: all(r.version() == v for r in reps),
                msg="flat chain converged",
            )
            state, _, _ = decode_payload(
                fetch_resource(reps[-1].address(), v, "full", timeout=10)
            )
            np.testing.assert_array_equal(
                state["w"], _int8_roundtrip(sd["w"])
            )
            dec = _m.SERVING_RELAY_DECODE.labels(mode="flat").get()
            assert dec["count"] - dec0["count"] >= 2
        finally:
            _teardown(lh, pub, reps)

    def test_torn_version_never_serves_whole_document(self):
        """Cut-through safety at the transport: while a version streams
        in, staged fragments serve individually but full/metadata 503
        (retryable) — a torn payload can never be read whole."""
        import urllib.error
        import urllib.request

        from torchft_tpu.checkpointing.http_transport import HTTPTransport

        tr = HTTPTransport(timeout=5.0)
        try:
            doc = encode_payload(_state(42), 7, fragments=2)
            manifest = doc["frag:manifest"]
            tr.begin_streamed_checkpoint(7, {"frag:manifest": manifest})
            tr.stage_streamed_part(7, "frag:0", doc["frag:0"])
            base = tr.metadata()
            # staged fragment serves mid-stream (this IS cut-through)
            raw = urllib.request.urlopen(
                f"{base}/checkpoint/7/frag_0", timeout=5
            ).read()
            assert raw == doc["frag:0"]
            # missing fragment: retryable 503, not 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{base}/checkpoint/7/frag_1", timeout=5
                )
            assert ei.value.code == 503
            # whole-document reads refuse the torn version
            for what in ("full", "metadata"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(
                        f"{base}/checkpoint/7/{what}", timeout=5
                    )
                assert ei.value.code == 503, what
            tr.stage_streamed_part(7, "frag:1", doc["frag:1"])
            tr.finish_streamed_checkpoint(7)
            got = urllib.request.urlopen(
                f"{base}/checkpoint/7/full", timeout=5
            )
            assert got.status == 200
            # complete document: an unknown fragment is back to 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{base}/checkpoint/7/frag_9", timeout=5
                )
            assert ei.value.code == 404
        finally:
            tr.shutdown()


class TestRelayIntegrity:
    def _poisoned_pair(self, version=1):
        """Two standalone staged sources for one version: POISONED (one
        fragment's bytes flipped, manifest digests untouched) and GOOD."""
        from torchft_tpu.checkpointing.http_transport import HTTPTransport

        sd = _state(50)
        doc = encode_payload(sd, version, fragments=2)
        bad = dict(doc)
        raw = bytearray(doc["frag:0"])
        raw[-1] ^= 0xFF
        bad["frag:0"] = bytes(raw)
        poisoned = HTTPTransport(timeout=5.0)
        poisoned.send_checkpoint([], version, bad, timeout=5)
        good = HTTPTransport(timeout=5.0)
        good.send_checkpoint([], version, doc, timeout=5)
        return sd, doc, poisoned, good

    def test_poisoned_fragment_refetched_from_other_source(self):
        """Digest mismatch on a relayed fragment = dead source: the pull
        fails over and completes from a good source, and the poisoned
        bytes are NEVER staged or served."""
        lh = LighthouseServer(
            min_replicas=1, heartbeat_timeout_ms=1500, quorum_tick_ms=50
        )
        sd, doc, poisoned, good = self._poisoned_pair()
        rep = ServingReplica(
            lh.address(), replica_id="victim", poll_interval=5.0,
            fetch_timeout=8.0,
        )
        try:
            rep._parent = poisoned.metadata()
            rep._root_source = good.metadata()
            rep._pull(1)
            assert rep.version() == 1
            # served fragment bytes are the GOOD ones
            from torchft_tpu.serving import fetcher as _fetcher
            from torchft_tpu.utils.bufpool import POOL

            buf = _fetcher.fetch_raw(rep.address(), 1, "frag_0", timeout=5)
            got = bytes(memoryview(buf))
            POOL.give(buf)
            assert got == doc["frag:0"]
            state, _, _ = decode_payload(
                fetch_resource(rep.address(), 1, "full", timeout=5)
            )
            np.testing.assert_array_equal(state["w"], sd["w"])
        finally:
            rep.shutdown()
            poisoned.shutdown()
            good.shutdown()
            lh.shutdown()

    def test_poisoned_only_source_never_stages(self):
        """With no clean source, the pull fails loudly and the relay
        keeps advertising nothing — children polling the fragment get
        503s, never poisoned bytes."""
        import urllib.error
        import urllib.request

        lh = LighthouseServer(
            min_replicas=1, heartbeat_timeout_ms=1500, quorum_tick_ms=50
        )
        _sd, _doc, poisoned, good = self._poisoned_pair()
        good.shutdown()  # only the poisoned source remains
        rep = ServingReplica(
            lh.address(), replica_id="victim2", poll_interval=5.0,
            fetch_timeout=2.0,
        )
        try:
            rep._parent = poisoned.metadata()
            rep._root_source = ""
            with pytest.raises(ConnectionError):
                rep._pull(1)
            assert rep.version() == 0
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{rep.address()}/checkpoint/1/frag_0", timeout=5
                )
            assert ei.value.code == 503
        finally:
            rep.shutdown()
            poisoned.shutdown()
            lh.shutdown()


class TestDeepTreeChaos:
    def test_depth3_kill_interior_mid_stream_bitwise(self):
        """Depth-3 chaos variant of the tree test: an INTERIOR relay is
        killed while the cut-through stream is in flight (serving.frag
        delay stretches it); the chain re-forms, every concurrent client
        completes bitwise-identical, and the leaf still converges."""
        lh, pub, reps = _chain_tier(3, fragments=6, wire="int8",
                                    poll=0.02)
        try:
            sd0 = _state(60)
            v0 = pub.publish(sd0)
            _wait_until(
                lambda: all(r.version() == v0 for r in reps),
                msg="warm converge",
            )
            plan = LighthouseClient(lh.address()).serving_plan()
            interior = [
                n for n in plan["nodes"] if 0 < n["depth"] < 2
            ][0]
            victim = next(
                r for r in reps if r.replica_id() == interior["replica_id"]
            )
            # stretch every fragment fetch so the kill lands mid-stream
            _faults.FAULTS.configure(
                [_faults.FaultRule(site="serving.frag", action="delay",
                                   delay=0.08, times=-1)],
                seed=11,
            )
            sd1 = _state(61)
            expected = {
                k: (_int8_roundtrip(a) if isinstance(a, np.ndarray) else a)
                for k, a in sd1.items()
            }
            results = {}

            def _fetch(i):
                try:
                    c = ServingClient(
                        lh.address(), plan_ttl=0.1, client_id=f"deep{i}"
                    )
                    state, got = c.fetch(version=v0 + 1, timeout=45)
                    c.close()
                    results[i] = (state, got)
                except Exception as e:  # noqa: BLE001 - asserted below
                    results[i] = e

            v1 = pub.publish(sd1)
            threads = [
                threading.Thread(target=_fetch, args=(i,), daemon=True)
                for i in range(4)
            ]
            for t in threads:
                t.start()
            time.sleep(0.15)  # the stream is mid-flight (6 x 80 ms/hop)
            victim.shutdown()
            for t in threads:
                t.join(timeout=90)
                assert not t.is_alive(), "client fetch wedged"
            _faults.FAULTS.clear()
            for i, res in results.items():
                assert not isinstance(res, Exception), f"client {i}: {res}"
                state, got = res
                assert got == v1
                np.testing.assert_array_equal(state["w"], expected["w"])
                np.testing.assert_array_equal(state["b"], expected["b"])
            # survivors (root + leaf) converge to v1 despite the corpse
            survivors = [r for r in reps if r is not victim]
            _wait_until(
                lambda: all(r.version() >= v1 for r in survivors),
                timeout=30, msg="survivors converged past the kill",
            )
        finally:
            _faults.FAULTS.clear()
            _teardown(lh, pub, reps)


class TestClientDeterminism:
    def test_rotation_stable_across_processes(self):
        """Source rotation must not depend on PYTHONHASHSEED: the seed
        is a sha256 digest of the client id (pinned literal), so a
        restarted client lands on the same leaf."""
        import hashlib

        lh = LighthouseServer(min_replicas=1)
        try:
            a = ServingClient(lh.address(), client_id="client_a")
            b = ServingClient(lh.address(), client_id="client_a")
            c = ServingClient(lh.address(), client_id="client_b")
            want = int.from_bytes(
                hashlib.sha256(b"client_a").digest()[:8], "big"
            )
            assert a._rot == b._rot == want
            assert c._rot != a._rot
            for cl in (a, b, c):
                cl.close()
        finally:
            lh.shutdown()

    def test_frag_drop_absorbed_by_poll_policy(self):
        """The documented serving.frag contract: an injected drop takes
        the broken-connection path INSIDE the 503-poll policy and is
        retried within the budget — the fetch still completes."""
        from torchft_tpu.checkpointing.http_transport import HTTPTransport
        from torchft_tpu.serving import fetcher as _fetcher
        from torchft_tpu.utils.bufpool import POOL

        tr = HTTPTransport(timeout=5.0)
        try:
            doc = encode_payload(_state(70), 1, fragments=2)
            tr.send_checkpoint([], 1, doc, timeout=5)
            _faults.FAULTS.configure(
                [_faults.FaultRule(site="serving.frag", action="drop",
                                   times=1)],
                seed=2,
            )
            buf = _fetcher.fetch_raw(tr.metadata(), 1, "frag_0", timeout=10)
            assert bytes(memoryview(buf)) == doc["frag:0"]
            POOL.give(buf)
            assert _faults.FAULTS.injected("serving.frag") == 1
        finally:
            _faults.FAULTS.clear()
            tr.shutdown()

    def test_exhausted_budget_never_goes_negative(self):
        """Satellite regression: the delta manifest fetch clamps its
        deadline — an exhausted budget surfaces as a timeout/connection
        error, never a negative-timeout ValueError from the socket
        layer."""
        lh = LighthouseServer(min_replicas=1)
        try:
            client = ServingClient(lh.address())
            client._held = ({"fragments": [], "digests": {},
                             "num_leaves": 0}, {})
            client._held_version = 1
            with pytest.raises((TimeoutError, ConnectionError, OSError)):
                client._fetch_from(
                    "http://127.0.0.1:9", 2, budget=-1.0, delta=True
                )
            client.close()
        finally:
            lh.shutdown()


# ---------------------------------------------------------------------------
# slow soak: 32 clients, staggered server kills
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestServingSoak:
    def test_soak_32_clients_staggered_kills(self):
        """32 stub clients fetch continuously while versions publish at
        a cadence and two servers die mid-run: p99 fetch latency stays
        bounded and — after the tree settles around each kill — zero
        fetches fail (failovers are allowed and counted)."""
        lh = LighthouseServer(
            min_replicas=1, heartbeat_timeout_ms=800, quorum_tick_ms=50,
            serving_fanout=2,
        )
        pub = WeightPublisher(
            lh.address(), wire="int8", fragments=2, heartbeat_interval=0.1
        )
        reps = [
            ServingReplica(
                lh.address(), replica_id=f"soak{i}", poll_interval=0.05,
                fetch_timeout=10.0,
            )
            for i in range(6)
        ]
        stop = threading.Event()
        lat: "list" = []
        errors: "list" = []
        lock = threading.Lock()

        def _client_loop(i):
            c = ServingClient(lh.address(), plan_ttl=0.2, client_id=str(i))
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    _, got = c.fetch(timeout=20)
                    with lock:
                        lat.append(time.perf_counter() - t0)
                except Exception as e:  # noqa: BLE001 - tallied
                    with lock:
                        errors.append(repr(e))
                time.sleep(0.02)
            c.close()

        try:
            pub.publish(_state(0))
            threads = [
                threading.Thread(target=_client_loop, args=(i,), daemon=True)
                for i in range(32)
            ]
            for t in threads:
                t.start()
            t_end = time.monotonic() + 20
            vi = 1
            killed = 0
            while time.monotonic() < t_end:
                pub.publish(_state(vi))
                vi += 1
                # staggered kills at ~1/3 and ~2/3 of the run
                elapsed = 20 - (t_end - time.monotonic())
                if killed == 0 and elapsed > 6:
                    reps[0].shutdown()
                    killed = 1
                elif killed == 1 and elapsed > 13:
                    reps[3].shutdown()
                    killed = 2
                time.sleep(0.25)
            stop.set()
            for t in threads:
                t.join(timeout=30)
                assert not t.is_alive(), "soak client wedged"
            assert killed == 2
            assert len(lat) > 200, f"too few fetches completed: {len(lat)}"
            # zero failed fetches: every fetch either completed directly
            # or failed over within its deadline
            assert not errors, f"{len(errors)} failed fetches: {errors[:3]}"
            p99 = sorted(lat)[int(len(lat) * 0.99)]
            assert p99 < 10.0, f"p99 fetch latency {p99:.2f}s out of bound"
        finally:
            stop.set()
            for r in reps:
                try:
                    r.shutdown()
                except Exception:  # noqa: BLE001
                    pass
            pub.shutdown()
            lh.shutdown()
