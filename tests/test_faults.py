"""Unit tests for the chaos layer (torchft_tpu/utils/faults.py):
schedule determinism under a fixed seed, env-spec parsing round-trip,
site accounting, matching semantics, and the three actions."""

import time

import pytest

from torchft_tpu.utils import faults, metrics
from torchft_tpu.utils.faults import (
    FAULTS,
    FaultRegistry,
    FaultRule,
    InjectedConnectionDrop,
    InjectedFault,
    configure_from_env,
    format_spec,
    parse_spec,
)


@pytest.fixture(autouse=True)
def clean_global_registry():
    FAULTS.configure([], seed=0)
    yield
    FAULTS.configure([])


# ---------------------------------------------------------------------------
# matching + actions
# ---------------------------------------------------------------------------


class TestMatching:
    def test_exact_step_and_replica(self):
        reg = FaultRegistry(seed=1)
        reg.configure([FaultRule(site="pg.allreduce", replica="r1", step=3)])
        # wrong site / replica / step: no fire
        reg.check("pg.reconfigure", replica="r1", step=3)
        reg.check("pg.allreduce", replica="r0", step=3)
        reg.check("pg.allreduce", replica="r1", step=2)
        assert reg.injected() == 0
        with pytest.raises(InjectedFault):
            reg.check("pg.allreduce", replica="r1", step=3)
        assert reg.injected() == 1

    def test_replica_incarnation_suffix_stripped(self):
        reg = FaultRegistry()
        reg.configure([FaultRule(site="manager.quorum", replica="replica_1")])
        with pytest.raises(InjectedFault):
            reg.check("manager.quorum", replica="replica_1:some-uuid-suffix")

    def test_constrained_rule_never_fires_without_context(self):
        reg = FaultRegistry()
        reg.configure(
            [
                FaultRule(site="transport.recv", replica="r0"),
                FaultRule(site="transport.send", after_step=2),
            ]
        )
        # caller supplied no replica/step: constrained rules must not match
        reg.check("transport.recv")
        reg.check("transport.send")
        assert reg.injected() == 0

    def test_after_step(self):
        reg = FaultRegistry()
        reg.configure([FaultRule(site="train.step", after_step=5, times=-1)])
        reg.check("train.step", step=4)
        assert reg.injected() == 0
        for s in (5, 6, 100):
            with pytest.raises(InjectedFault):
                reg.check("train.step", step=s)
        assert reg.injected() == 3

    def test_times_exhaustion(self):
        reg = FaultRegistry()
        reg.configure([FaultRule(site="store.barrier", times=2)])
        for _ in range(2):
            with pytest.raises(InjectedFault):
                reg.check("store.barrier")
        # exhausted: subsequent checks pass through
        reg.check("store.barrier")
        assert reg.injected("store.barrier") == 2

    def test_drop_is_a_connection_error(self):
        reg = FaultRegistry()
        reg.configure([FaultRule(site="lighthouse.rpc", action="drop")])
        with pytest.raises(ConnectionError) as ei:
            reg.check("lighthouse.rpc")
        assert isinstance(ei.value, InjectedConnectionDrop)

    def test_delay_sleeps_and_returns(self):
        reg = FaultRegistry()
        reg.configure([FaultRule(site="manager.quorum", action="delay", delay=0.05)])
        t0 = time.monotonic()
        reg.check("manager.quorum")  # must NOT raise
        assert time.monotonic() - t0 >= 0.05
        assert reg.counts() == {("manager.quorum", "delay"): 1}

    def test_first_matching_rule_wins(self):
        reg = FaultRegistry()
        reg.configure(
            [
                FaultRule(site="pg.allreduce", action="delay", delay=0.0),
                FaultRule(site="pg.allreduce", action="raise"),
            ]
        )
        reg.check("pg.allreduce")  # delay rule fires, no raise
        with pytest.raises(InjectedFault):
            reg.check("pg.allreduce")  # first rule exhausted; second fires

    def test_invalid_rules_rejected(self):
        with pytest.raises(ValueError):
            FaultRule(site="x", action="explode")
        with pytest.raises(ValueError):
            FaultRule(site="x", prob=1.5)
        with pytest.raises(ValueError):
            FaultRule(site="x", delay=-1.0)
        with pytest.raises(ValueError):
            FaultRule(site="")


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------


def _drive(reg: FaultRegistry, steps: int = 200) -> list:
    fired = []
    for s in range(steps):
        try:
            reg.check("pg.allreduce", replica="r0", step=s)
        except InjectedFault:
            fired.append(s)
    return fired


class TestDeterminism:
    RULES = lambda self: [  # noqa: E731 - fresh rule objects per registry
        FaultRule(site="pg.allreduce", prob=0.15, after_step=10, times=-1)
    ]

    def test_same_seed_same_schedule(self):
        a, b = FaultRegistry(), FaultRegistry()
        a.configure(self.RULES(), seed=42)
        b.configure(self.RULES(), seed=42)
        fired_a, fired_b = _drive(a), _drive(b)
        assert fired_a, "probabilistic rule never fired in 200 steps"
        assert fired_a == fired_b
        assert all(s >= 10 for s in fired_a)

    def test_different_seed_different_schedule(self):
        a, b = FaultRegistry(), FaultRegistry()
        a.configure(self.RULES(), seed=42)
        b.configure(self.RULES(), seed=43)
        assert _drive(a) != _drive(b)

    def test_reconfigure_replays(self):
        reg = FaultRegistry()
        reg.configure(self.RULES(), seed=7)
        first = _drive(reg)
        reg.configure(self.RULES(), seed=7)  # reset counts + rng streams
        assert reg.injected() == 0
        assert _drive(reg) == first


# ---------------------------------------------------------------------------
# spec round-trip
# ---------------------------------------------------------------------------


class TestSpec:
    def test_round_trip(self):
        rules = [
            FaultRule(site="pg.allreduce", replica="replica_1", step=2),
            FaultRule(site="transport.recv", after_step=0, action="drop", times=2),
            FaultRule(
                site="manager.quorum",
                prob=0.05,
                after_step=3,
                times=-1,
                action="delay",
                delay=0.2,
            ),
            FaultRule(site="train.step"),
        ]
        spec = format_spec(rules)
        assert parse_spec(spec) == rules
        # stable: formatting the reparse is identical
        assert format_spec(parse_spec(spec)) == spec

    def test_parse_defaults(self):
        (rule,) = parse_spec("pg.reconfigure")
        assert rule == FaultRule(site="pg.reconfigure")
        assert rule.action == "raise" and rule.times == 1 and rule.prob == 1.0

    def test_parse_whitespace_and_empty_segments(self):
        rules = parse_spec(" pg.allreduce : step=1 ; ; transport.send ")
        assert [r.site for r in rules] == ["pg.allreduce", "transport.send"]
        assert rules[0].step == 1

    def test_parse_errors(self):
        with pytest.raises(ValueError):
            parse_spec("pg.allreduce:bogus_key=1")
        with pytest.raises(ValueError):
            parse_spec("pg.allreduce:step")  # no '='
        with pytest.raises(ValueError):
            parse_spec("pg.allreduce:step=abc")
        with pytest.raises(ValueError):
            parse_spec("pg.allreduce:action=explode")

    def test_configure_from_env(self, monkeypatch):
        monkeypatch.setenv(
            "TORCHFT_FAULTS", "train.step:replica=r9,step=4;store.barrier:times=3"
        )
        monkeypatch.setenv("TORCHFT_FAULTS_SEED", "99")
        assert configure_from_env()
        rules = FAULTS.rules()
        assert [r.site for r in rules] == ["train.step", "store.barrier"]
        assert rules[0].replica == "r9" and rules[1].times == 3

    def test_configure_from_env_empty(self, monkeypatch):
        monkeypatch.delenv("TORCHFT_FAULTS", raising=False)
        assert not configure_from_env()


# ---------------------------------------------------------------------------
# accounting: registry counters + metrics + structured events
# ---------------------------------------------------------------------------


class TestAccounting:
    def test_counts_by_site_and_action(self):
        reg = FaultRegistry()
        reg.configure(
            [
                FaultRule(site="pg.allreduce", times=2),
                FaultRule(site="transport.send", action="drop"),
                FaultRule(site="manager.heal", action="delay", delay=0.0),
            ]
        )
        for _ in range(2):
            with pytest.raises(InjectedFault):
                reg.check("pg.allreduce")
        with pytest.raises(InjectedConnectionDrop):
            reg.check("transport.send")
        reg.check("manager.heal")
        assert reg.counts() == {
            ("pg.allreduce", "raise"): 2,
            ("transport.send", "drop"): 1,
            ("manager.heal", "delay"): 1,
        }
        assert reg.injected() == 4
        assert reg.injected("pg.allreduce") == 2

    def test_metrics_and_event_emitted(self):
        before = metrics.FAULTS_INJECTED.labels(
            site="train.step", action="raise"
        ).get()
        FAULTS.configure([FaultRule(site="train.step")])
        with pytest.raises(InjectedFault):
            faults.check("train.step", replica="rX", step=7)
        after = metrics.FAULTS_INJECTED.labels(
            site="train.step", action="raise"
        ).get()
        assert after == before + 1
        from torchft_tpu.utils.logging import recent_events

        ev = [
            e
            for e in recent_events()
            if e["kind"] == "fault" and e.get("site") == "train.step"
        ]
        assert ev and ev[-1]["action"] == "raise" and ev[-1]["step"] == 7

    def test_empty_registry_check_is_noop(self):
        reg = FaultRegistry()
        reg.check("pg.allreduce", replica="r", step=1)
        assert reg.injected() == 0
