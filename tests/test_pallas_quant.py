"""Pallas quantization kernels vs the host codec (wire-format parity).

Mirrors the reference's quantization correctness tests
(reference: torchft/quantization_test.py) — kernel output must match the
eager/host implementation so device-quantized buffers interop with the
host DCN collective path.  Runs in pallas interpret mode on CPU.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from torchft_tpu.ops import quantization as host_q
from torchft_tpu.ops.pallas_quant import (
    fused_dequantize_from_int8,
    fused_quantize_into_int8,
    fused_reduce_int8,
    quantize_pytree,
)


def _rand(shape, seed=0, scale=3.0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape) * scale).astype(np.float32)


class TestQuantizeParity:
    @pytest.mark.parametrize(
        "shape", [(4, 16), (1, 1), (32, 128), (5, 130), (33, 7), (3, 4, 5), (17,)]
    )
    def test_matches_host_codec(self, shape):
        x = _rand(shape, seed=hash(shape) % 1000)
        h_scales, h_payload = host_q.quantize(x)
        d_scales, d_payload = fused_quantize_into_int8(x)
        np.testing.assert_allclose(np.asarray(d_scales), h_scales, rtol=1e-6)
        # round-half-even ties can land one step apart across backends only
        # if the scaled value differs in the last ulp; require exactness.
        np.testing.assert_array_equal(np.asarray(d_payload), h_payload)

    def test_zero_rows_scale_one(self):
        x = np.zeros((4, 8), np.float32)
        scales, payload = fused_quantize_into_int8(x)
        np.testing.assert_array_equal(np.asarray(scales), np.ones(4, np.float32))
        np.testing.assert_array_equal(np.asarray(payload), np.zeros((4, 8), np.int8))

    def test_roundtrip_error_bound(self):
        x = _rand((8, 64), seed=7)
        scales, payload = fused_quantize_into_int8(x)
        out = np.asarray(fused_dequantize_from_int8(scales, payload, shape=x.shape))
        # max error is half a quantization step per row
        step = np.abs(x).max(axis=1, keepdims=True) / 127.0
        assert np.all(np.abs(out - x) <= step * 0.5 + 1e-7)

    def test_quantize_pytree_structure(self):
        tree = {"a": _rand((4, 8), 1), "b": [_rand((2, 3), 2)]}
        out = quantize_pytree(tree)
        s, p = out["a"]
        hs, hp = host_q.quantize(tree["a"])
        np.testing.assert_allclose(np.asarray(s), hs, rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(p), hp)
        assert isinstance(out["b"], list) and len(out["b"][0]) == 2

    def test_dequantize_matches_host(self):
        x = _rand((6, 40), seed=3)
        scales, payload = host_q.quantize(x)
        d = np.asarray(
            fused_dequantize_from_int8(scales, payload, shape=x.shape)
        )
        h = host_q.dequantize(scales, payload, x.shape, np.float32)
        np.testing.assert_allclose(d, h, rtol=1e-6)


class TestFusedReduce:
    @pytest.mark.parametrize("average_by", [0, 3])
    def test_matches_host_reduce(self, average_by):
        n, rows, cols = 3, 5, 33
        shards = [_rand((rows, cols), seed=i) for i in range(n)]
        quantized = [host_q.quantize(s) for s in shards]
        scales = np.stack([q[0] for q in quantized])
        payloads = np.stack([q[1] for q in quantized])

        d_scales, d_payload = fused_reduce_int8(scales, payloads, average_by)

        bufs = [host_q.pack(s, p) for s, p in quantized]
        h_buf = host_q.reduce_quantized(bufs, rows, cols, average_by=average_by)
        h_scales, h_payload = host_q.unpack(h_buf, rows, cols)

        np.testing.assert_allclose(np.asarray(d_scales), h_scales, rtol=1e-5)
        # requant after an f32 accumulation: allow off-by-one codes on ties
        assert np.abs(np.asarray(d_payload).astype(np.int32) - h_payload.astype(np.int32)).max() <= 1

    def test_reduce_numerics_vs_exact(self):
        n, rows, cols = 4, 8, 64
        shards = [_rand((rows, cols), seed=10 + i) for i in range(n)]
        scales = np.stack([host_q.quantize(s)[0] for s in shards])
        payloads = np.stack([host_q.quantize(s)[1] for s in shards])
        d_scales, d_payload = fused_reduce_int8(scales, payloads, average_by=n)
        out = np.asarray(
            fused_dequantize_from_int8(d_scales, d_payload, shape=(rows, cols))
        )
        exact = np.mean(shards, axis=0)
        # two quantization stages; error bounded by ~2 steps of the mean's range
        step = np.abs(exact).max() / 127.0
        assert np.abs(out - exact).max() <= 4 * step


class TestNativeHostCodec:
    """The C fused codec (native/quant.cc) must be bit-identical to the
    numpy reference codec — same wire bytes, same decode, same reduce."""

    def _toggle(self, monkeypatch, native: bool):
        if native:
            monkeypatch.delenv("TORCHFT_NO_NATIVE_QUANT", raising=False)
        else:
            monkeypatch.setenv("TORCHFT_NO_NATIVE_QUANT", "1")

    def test_native_available(self):
        # the target environment always has g++/make; the fallback exists
        # for exotic deploys, but HERE the fast path must actually engage
        assert host_q._native_lib() is not None

    @pytest.mark.parametrize("shape", [(1, 1), (3, 7), (64, 2048), (5, 1)])
    def test_quantize_bitwise(self, shape, monkeypatch):
        a = _rand(shape, seed=3)
        self._toggle(monkeypatch, native=False)
        s_np, p_np = host_q.quantize(a)
        self._toggle(monkeypatch, native=True)
        s_c, p_c = host_q.quantize(a)
        np.testing.assert_array_equal(s_np, s_c)
        np.testing.assert_array_equal(p_np, p_c)

    def test_quantize_degenerate_rows_bitwise(self, monkeypatch):
        a = np.zeros((4, 16), dtype=np.float32)
        a[1] = 1e-38  # below the absmax threshold -> zeros, scale 1.0
        a[2] = np.linspace(-1, 1, 16, dtype=np.float32)
        self._toggle(monkeypatch, native=False)
        s_np, p_np = host_q.quantize(a)
        self._toggle(monkeypatch, native=True)
        s_c, p_c = host_q.quantize(a)
        np.testing.assert_array_equal(s_np, s_c)
        np.testing.assert_array_equal(p_np, p_c)

    def test_quantize_packed_bitwise(self, monkeypatch):
        a = _rand((9, 131), seed=4)
        self._toggle(monkeypatch, native=False)
        buf_np = host_q.quantize_packed(a)
        self._toggle(monkeypatch, native=True)
        buf_c = host_q.quantize_packed(a)
        np.testing.assert_array_equal(buf_np, buf_c)

    @pytest.mark.parametrize("average_by", [0, 3])
    def test_reduce_bitwise(self, average_by, monkeypatch):
        rows, cols = 6, 97
        shards = [_rand((rows, cols), seed=20 + i) for i in range(3)]
        bufs = [host_q.pack(*host_q.quantize(s)) for s in shards]
        raw = _rand((rows, cols), seed=30)
        self._toggle(monkeypatch, native=False)
        out_np = host_q.reduce_quantized(
            bufs, rows, cols, average_by=average_by, raw=raw
        )
        self._toggle(monkeypatch, native=True)
        out_c = host_q.reduce_quantized(
            bufs, rows, cols, average_by=average_by, raw=raw
        )
        np.testing.assert_array_equal(out_np, out_c)

    def test_reduce_raw_none_requantize_false_bitwise(self, monkeypatch):
        rows, cols = 4, 33
        bufs = [
            host_q.pack(*host_q.quantize(_rand((rows, cols), seed=40 + i)))
            for i in range(2)
        ]
        self._toggle(monkeypatch, native=False)
        out_np = host_q.reduce_quantized(bufs, rows, cols, requantize=False)
        self._toggle(monkeypatch, native=True)
        out_c = host_q.reduce_quantized(bufs, rows, cols, requantize=False)
        np.testing.assert_array_equal(out_np, out_c)

    def test_dequantize_bitwise(self, monkeypatch):
        a = _rand((7, 55), seed=5)
        s, p = host_q.quantize(a)
        self._toggle(monkeypatch, native=False)
        out_np = host_q.dequantize(s, p, a.shape, np.float32)
        self._toggle(monkeypatch, native=True)
        out_c = host_q.dequantize(s, p, a.shape, np.float32)
        np.testing.assert_array_equal(out_np, out_c)


class TestNativeFp8Codec:
    """The C fp8_e4m3fn codec must match the numpy/ml_dtypes reference
    bit-for-bit on finite inputs (the codec's contract); decode goes
    through a LUT built FROM ml_dtypes so it is exact by construction."""

    def _toggle(self, monkeypatch, native: bool):
        if native:
            monkeypatch.delenv("TORCHFT_NO_NATIVE_QUANT", raising=False)
        else:
            monkeypatch.setenv("TORCHFT_NO_NATIVE_QUANT", "1")

    @pytest.mark.parametrize("shape", [(1, 1), (3, 7), (64, 2048), (5, 1)])
    def test_quantize_bitwise(self, shape, monkeypatch):
        a = _rand(shape, seed=13)
        self._toggle(monkeypatch, native=False)
        s_np, p_np = host_q.quantize(a, "fp8_e4m3")
        self._toggle(monkeypatch, native=True)
        s_c, p_c = host_q.quantize(a, "fp8_e4m3")
        np.testing.assert_array_equal(s_np, s_c)
        np.testing.assert_array_equal(
            p_np.view(np.uint8), p_c.view(np.uint8)
        )

    def test_quantize_edge_values_bitwise(self, monkeypatch):
        # rows hitting subnormal grid points, RNE midpoints, +-max, and
        # the degenerate-row rule
        import ml_dtypes

        vals = (
            np.arange(256, dtype=np.uint8)
            .view(ml_dtypes.float8_e4m3fn)
            .astype(np.float32)
        )
        vals = vals[np.isfinite(vals)]
        mids = ((np.sort(vals)[:-1] + np.sort(vals)[1:]) / 2.0).astype(
            np.float32
        )
        row = np.concatenate([vals, mids, [448.0, -448.0, 0.0, -0.0]])
        a = np.stack([row, row * 1e-3, np.full_like(row, 1e-38)])
        self._toggle(monkeypatch, native=False)
        s_np, p_np = host_q.quantize(a, "fp8_e4m3")
        self._toggle(monkeypatch, native=True)
        s_c, p_c = host_q.quantize(a, "fp8_e4m3")
        np.testing.assert_array_equal(s_np, s_c)
        np.testing.assert_array_equal(
            p_np.view(np.uint8), p_c.view(np.uint8)
        )

    def test_nan_row_payload_bitwise_and_propagates(self, monkeypatch):
        """A NaN element sends its row down the degenerate branch where
        RAW values hit the encoder: the native path must emit the SAME
        payload bytes as ml_dtypes — NaN stays the 0x7f NaN code (sign
        preserved), inf and past-464 overflow fold to NaN per the "fn"
        rule — so a NaN pseudograd round-trips as NaN instead of being
        laundered into finite ±448 (ADVICE r5)."""
        row = np.array(
            [np.nan, -np.nan, np.inf, -np.inf, 1e6, 464.0, 465.0, 1.5, -2.0,
             0.0],
            dtype=np.float32,
        )
        a = row.reshape(1, -1)
        self._toggle(monkeypatch, native=False)
        s_np, p_np = host_q.quantize(a, "fp8_e4m3")
        self._toggle(monkeypatch, native=True)
        s_c, p_c = host_q.quantize(a, "fp8_e4m3")
        np.testing.assert_array_equal(
            p_np.view(np.uint8), p_c.view(np.uint8)
        )
        # both scales take the degenerate rule (NaN absmax -> 1.0)
        np.testing.assert_array_equal(s_np, s_c)
        # decode (LUT path) must propagate the NaNs, not finite garbage
        out = host_q.dequantize(s_c, p_c, a.shape, np.float32)
        assert np.isnan(out[0, 0]) and np.isnan(out[0, 1])
        assert np.isnan(out[0, 2]) and np.isnan(out[0, 3])  # inf -> fn NaN
        assert np.isnan(out[0, 4]) and np.isnan(out[0, 6])  # overflow -> NaN
        assert out[0, 5] == 448.0  # 464 rounds even to max finite

    @pytest.mark.parametrize("average_by", [0, 3])
    def test_reduce_bitwise(self, average_by, monkeypatch):
        rows, cols = 6, 97
        shards = [_rand((rows, cols), seed=50 + i) for i in range(3)]
        bufs = [
            host_q.pack(*host_q.quantize(s, "fp8_e4m3"), "fp8_e4m3")
            for s in shards
        ]
        raw = _rand((rows, cols), seed=60)
        self._toggle(monkeypatch, native=False)
        out_np = host_q.reduce_quantized(
            bufs, rows, cols, average_by=average_by, wire_dtype="fp8_e4m3",
            raw=raw,
        )
        self._toggle(monkeypatch, native=True)
        out_c = host_q.reduce_quantized(
            bufs, rows, cols, average_by=average_by, wire_dtype="fp8_e4m3",
            raw=raw,
        )
        np.testing.assert_array_equal(out_np, out_c)

    def test_dequantize_bitwise(self, monkeypatch):
        a = _rand((7, 55), seed=15)
        s, p = host_q.quantize(a, "fp8_e4m3")
        self._toggle(monkeypatch, native=False)
        out_np = host_q.dequantize(s, p, a.shape, np.float32)
        self._toggle(monkeypatch, native=True)
        out_c = host_q.dequantize(s, p, a.shape, np.float32)
        np.testing.assert_array_equal(out_np, out_c)

    def test_roundtrip_error_bound_fp8(self):
        a = _rand((16, 256), seed=16)
        s, p = host_q.quantize(a, "fp8_e4m3")
        out = host_q.dequantize(s, p, a.shape, np.float32)
        # e4m3: 3 mantissa bits -> relative error <= 2^-4 per element
        # (plus the row scale); generous bound
        assert np.abs(out - a).max() <= np.abs(a).max() * 0.08
