"""Durable content-addressed fragment store (ISSUE 17) — unit layer.

FragmentStore invariants: bitwise spill/load round-trip, digest dedup
across versions, atomic manifests (no torn files under the final name),
torn blobs detected at read and treated as missing (never served, never
silently wrong), the TORCHFT_STORE_VERSIONS retirement window with
refcount-by-scan blob GC, deterministic fleet-wide cut selection
(newest complete consistent cut, degrade-never-wedge), the HTTP
``/store/versions`` + disk-backed ``frag_<name>`` surface, and the
single-worker StoreSpiller that keeps spill off the training hot path
and degrades (skip + count) on failure.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from torchft_tpu.checkpointing import fragments as frags
from torchft_tpu.checkpointing import serialization as ser
from torchft_tpu.checkpointing import store as store_mod
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.store import (
    FragmentStore,
    StoreSpiller,
    cut_id,
    select_cut,
    store_from_env,
)
from torchft_tpu.utils import faults
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils.faults import FaultRule


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


def make_state(leaves: int = 8, seed: int = 7) -> dict:
    rng = np.random.default_rng(seed)
    return {
        "user": {
            f"w{i}": rng.standard_normal(129).astype(np.float32)
            for i in range(leaves)
        },
        "torchft": {"step": 1, "batches_committed": 1},
    }


def assert_state_equal(a: dict, b: dict) -> None:
    assert a["torchft"] == b["torchft"]
    assert set(a["user"]) == set(b["user"])
    for k in a["user"]:
        np.testing.assert_array_equal(a["user"][k], b["user"][k])


def blob_names(store: FragmentStore) -> set:
    return set(os.listdir(os.path.join(store.directory, "blobs")))


class TestFragmentStore:
    def test_spill_load_round_trip_bitwise(self, tmp_path):
        store = FragmentStore(str(tmp_path), max_versions=0)
        state = make_state()
        manifest = store.put_state(3, state, fragments=4)
        assert manifest["version"] == 3
        assert store.versions() == [3]
        out = store.load_state(store.manifest(3))
        assert_state_equal(out, state)

    def test_unchanged_fragments_dedup_across_versions(self, tmp_path):
        """Content addressing: re-spilling identical state writes zero
        new blob bytes; a one-leaf change writes exactly the changed
        fragment's blob."""
        store = FragmentStore(str(tmp_path), max_versions=0)
        state = make_state()
        store.put_state(1, state, fragments=4)
        before = blob_names(store)
        spilled = _metrics.STORE_SPILL_BYTES.get()
        store.put_state(2, state, fragments=4)
        assert blob_names(store) == before
        assert _metrics.STORE_SPILL_BYTES.get() == spilled
        # one changed leaf -> exactly one new blob
        state["user"]["w0"][:] = -1.0
        store.put_state(3, state, fragments=4)
        assert len(blob_names(store)) == len(before) + 1
        assert _metrics.STORE_SPILL_BYTES.get() > spilled

    def test_no_tmp_files_survive_a_spill(self, tmp_path):
        store = FragmentStore(str(tmp_path), max_versions=0)
        store.put_state(1, make_state(), fragments=4)
        leftovers = [
            n
            for root, _d, names in os.walk(str(tmp_path))
            for n in names
            if ".tmp" in n
        ]
        assert leftovers == []

    def test_torn_blob_is_missing_never_served(self, tmp_path):
        store = FragmentStore(str(tmp_path), max_versions=0)
        state = make_state()
        manifest = store.put_state(1, state, fragments=4)
        name = manifest["fragments"][1]
        digest = manifest["digests"][name]
        torn_before = _metrics.STORE_TORN_BLOBS.get()
        with open(store.blob_path(digest), "r+b") as f:
            f.seek(8)
            f.write(b"\xff\xff\xff\xff")
        assert store.read_blob(digest) is None
        assert store.fragment(1, name) is None
        assert _metrics.STORE_TORN_BLOBS.get() > torn_before
        # loud, never silently wrong weights
        with pytest.raises(ValueError, match="digest"):
            store.load_state(store.manifest(1))
        # the catalog reports the hole so cut selection can fail over
        cat = store.catalog()
        assert not cat[1]["complete"]
        assert name not in cat[1]["frags_ok"]

    def test_version_window_retires_and_gcs_blobs(self, tmp_path):
        store = FragmentStore(str(tmp_path), max_versions=2)
        for v in range(1, 5):
            state = make_state(seed=v)
            store.put_state(v, state, fragments=4)
        assert store.versions() == [3, 4]
        # every surviving blob is referenced by a surviving manifest
        referenced = set()
        for v in store.versions():
            referenced.update(store.manifest(v)["digests"].values())
        assert blob_names(store) == referenced
        assert _metrics.STORE_VERSIONS.get() == 2

    def test_torn_manifest_is_not_a_restorable_version(self, tmp_path):
        store = FragmentStore(str(tmp_path), max_versions=0)
        store.put_state(1, make_state(), fragments=4)
        path = os.path.join(str(tmp_path), "manifest_v1.tft")
        with open(path, "wb") as f:
            f.write(b"garbage")
        assert store.manifest(1) is None
        assert store.manifest_bytes(1) is None
        assert store.catalog() == {}

    def test_store_from_env_is_opt_in(self, tmp_path, monkeypatch):
        monkeypatch.delenv("TORCHFT_STORE_DIR", raising=False)
        assert store_from_env("r0") is None
        monkeypatch.setenv("TORCHFT_STORE_DIR", str(tmp_path))
        s0 = store_from_env("r0")
        s1 = store_from_env("r0", group_rank=1)
        assert s0.directory != s1.directory
        assert s0.directory.startswith(str(tmp_path))


class TestSelectCut:
    def _catalog(self, store: FragmentStore) -> dict:
        return store.catalog()

    def test_newest_complete_cut_wins(self, tmp_path):
        a = FragmentStore(str(tmp_path / "a"), max_versions=0)
        b = FragmentStore(str(tmp_path / "b"), max_versions=0)
        state = make_state()
        for s in (a, b):
            s.put_state(1, state, fragments=4)
            s.put_state(2, state, fragments=4)
        got = select_cut({"http://a": a.catalog(), "http://b": b.catalog()})
        assert got is not None
        version, bases = got
        assert version == 2
        assert sorted(bases) == ["http://a", "http://b"]

    def test_incomplete_newest_degrades_to_older_complete(self, tmp_path):
        """v2 torn on EVERY disk -> the fleet restores v1, never wedges
        and never splices v1 blobs into the v2 cut."""
        a = FragmentStore(str(tmp_path / "a"), max_versions=0)
        state = make_state()
        a.put_state(1, state, fragments=4)
        state["user"]["w0"][:] = 5.0
        m2 = a.put_state(2, state, fragments=4)
        # tear v2's changed fragment (its only non-shared blob)
        changed = [
            n for n in m2["fragments"]
            if m2["digests"][n] not in a.manifest(1)["digests"].values()
        ]
        for n in changed:
            with open(a.blob_path(m2["digests"][n]), "r+b") as f:
                f.seek(0)
                f.write(b"\x00\x00\x00\x00\xff")
        got = select_cut({"http://a": a.catalog()})
        assert got is not None
        assert got[0] == 1

    def test_union_coverage_across_disks_restores_newest(self, tmp_path):
        """Each disk is torn on a DIFFERENT fragment of the same cut:
        neither alone is complete, their union is — the striped restore
        can fail over per-fragment, so the cut is selectable."""
        a = FragmentStore(str(tmp_path / "a"), max_versions=0)
        b = FragmentStore(str(tmp_path / "b"), max_versions=0)
        state = make_state()
        ma = a.put_state(1, state, fragments=4)
        mb = b.put_state(1, state, fragments=4)
        assert cut_id(ma) == cut_id(mb)
        for s, m, idx in ((a, ma, 0), (b, mb, 1)):
            name = m["fragments"][idx]
            with open(s.blob_path(m["digests"][name]), "r+b") as f:
                f.seek(4)
                f.write(b"\xde\xad\xbe\xef")
        got = select_cut({"http://a": a.catalog(), "http://b": b.catalog()})
        assert got is not None
        version, bases = got
        assert version == 1 and len(bases) == 2

    def test_complete_disks_order_first(self, tmp_path):
        a = FragmentStore(str(tmp_path / "a"), max_versions=0)
        b = FragmentStore(str(tmp_path / "b"), max_versions=0)
        state = make_state()
        ma = a.put_state(1, state, fragments=4)
        b.put_state(1, state, fragments=4)
        name = ma["fragments"][0]
        with open(a.blob_path(ma["digests"][name]), "r+b") as f:
            f.seek(4)
            f.write(b"\xde\xad\xbe\xef")
        _v, bases = select_cut(
            {"http://a": a.catalog(), "http://b": b.catalog()}
        )
        assert bases[0] == "http://b"  # the complete disk is primary

    def test_nothing_restorable_returns_none(self, tmp_path):
        empty = FragmentStore(str(tmp_path), max_versions=0)
        assert select_cut({}) is None
        assert select_cut({"http://a": empty.catalog()}) is None

    def test_selection_is_deterministic(self, tmp_path):
        a = FragmentStore(str(tmp_path / "a"), max_versions=0)
        b = FragmentStore(str(tmp_path / "b"), max_versions=0)
        state = make_state()
        a.put_state(1, state, fragments=4)
        b.put_state(1, state, fragments=4)
        cats = {"http://b": b.catalog(), "http://a": a.catalog()}
        assert select_cut(cats) == select_cut(dict(reversed(cats.items())))


class TestStoreHTTPSurface:
    def test_catalog_and_fragments_served_from_disk(self, tmp_path):
        """A transport with NO RAM staging serves manifests + fragments
        straight off the attached store — the cold-start surface."""
        store = FragmentStore(str(tmp_path), max_versions=0)
        state = make_state()
        manifest = store.put_state(7, state, fragments=4)
        t = HTTPTransport(timeout=5.0)
        t.attach_store(store)
        try:
            base = t.metadata()
            with urllib.request.urlopen(f"{base}/store/versions", timeout=5) as r:
                cat = json.loads(r.read().decode())
            assert cat["7"]["complete"] is True
            raw = frags.fetch_raw(
                base, 7, f"frag_{frags.MANIFEST_FRAG}", timeout=5.0,
                role="heal",
            )
            served = frags.decode_manifest(raw)
            assert served["digests"] == manifest["digests"]
            name = manifest["fragments"][0]
            raw = frags.fetch_raw(base, 7, f"frag_{name}", timeout=5.0,
                                  role="heal")
            frags.verify_fragment(name, raw, manifest)  # raises on mismatch
        finally:
            t.shutdown()

    def test_torn_blob_on_disk_is_a_permanent_404(self, tmp_path):
        """A torn blob must read as MISSING over HTTP (404 -> striped
        failover), never as bytes."""
        store = FragmentStore(str(tmp_path), max_versions=0)
        manifest = store.put_state(7, make_state(), fragments=4)
        name = manifest["fragments"][2]
        with open(store.blob_path(manifest["digests"][name]), "r+b") as f:
            f.seek(4)
            f.write(b"\xde\xad\xbe\xef")
        t = HTTPTransport(timeout=5.0)
        t.attach_store(store)
        try:
            base = t.metadata()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{base}/checkpoint/7/frag_{name}", timeout=5
                )
            assert ei.value.code == 404
        finally:
            t.shutdown()

    def test_no_store_no_catalog(self):
        t = HTTPTransport(timeout=5.0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{t.metadata()}/store/versions", timeout=5
                )
            assert ei.value.code == 404
        finally:
            t.shutdown()


class TestStoreSpiller:
    def test_spill_happens_off_the_submitting_thread(self, tmp_path):
        """Hot-path budget: submit() returns immediately even when the
        disk write is slow (a scheduled delay on store.spill), and the
        spill completes in the background."""
        store = FragmentStore(str(tmp_path), max_versions=0)
        spiller = StoreSpiller(store)
        faults.FAULTS.configure(
            [FaultRule(site="store.spill", action="delay", delay=0.5,
                       times=1)],
            seed=1,
        )
        try:
            t0 = time.perf_counter()
            assert spiller.submit(1, make_state(), fragments=4)
            submit_cost = time.perf_counter() - t0
            assert submit_cost < 0.2, (
                f"submit blocked the training thread for {submit_cost:.3f}s"
            )
            spiller.flush(timeout=10)
            assert store.versions() == [1]
        finally:
            spiller.shutdown()

    def test_spill_failure_degrades_skip_and_count(self, tmp_path):
        store = FragmentStore(str(tmp_path), max_versions=0)
        spiller = StoreSpiller(store)
        failures = _metrics.STORE_SPILL_FAILURES.get()
        faults.FAULTS.configure(
            [FaultRule(site="store.spill", action="raise", times=1)],
            seed=2,
        )
        try:
            assert spiller.submit(1, make_state(), fragments=4)
            spiller.flush(timeout=10)  # never raises into the caller
            assert store.versions() == []  # version skipped, not torn
            assert _metrics.STORE_SPILL_FAILURES.get() == failures + 1
            # the next spill succeeds: degraded, not wedged
            assert spiller.submit(2, make_state(), fragments=4)
            spiller.flush(timeout=10)
            assert store.versions() == [2]
        finally:
            spiller.shutdown()

    def test_inflight_spill_skips_not_backlogs(self, tmp_path):
        store = FragmentStore(str(tmp_path), max_versions=0)
        spiller = StoreSpiller(store)
        gate = threading.Event()
        orig = store.put_state

        def slow_put(version, state_dict, fragments=None, **kw):
            gate.wait(timeout=10)
            return orig(version, state_dict, fragments, **kw)

        store.put_state = slow_put
        try:
            assert spiller.submit(1, make_state(), fragments=4)
            assert not spiller.submit(2, make_state(), fragments=4)
            gate.set()
            spiller.flush(timeout=10)
            assert store.versions() == [1]
        finally:
            gate.set()
            spiller.shutdown()

    def test_submit_after_shutdown_is_refused(self, tmp_path):
        spiller = StoreSpiller(FragmentStore(str(tmp_path), max_versions=0))
        spiller.shutdown()
        assert not spiller.submit(1, make_state(), fragments=4)


class TestDurableOnStore:
    """Satellite 1/2: durable.py rides the content-addressed store —
    same API, deduped blobs, and the no-integrity-check bug fixed."""

    def test_saved_checkpoints_dedup_unchanged_fragments(self, tmp_path):
        from torchft_tpu.checkpointing import save_checkpoint

        state = make_state()
        save_checkpoint(str(tmp_path), 1, state)
        blobs = set(os.listdir(str(tmp_path / "blobs")))
        save_checkpoint(str(tmp_path), 2, state)
        assert set(os.listdir(str(tmp_path / "blobs"))) == blobs

    def test_corrupt_blob_fails_loudly_on_load(self, tmp_path):
        """Regression for the no-integrity-check bug: flipped bits in a
        checkpoint blob must raise, never load silently wrong weights."""
        from torchft_tpu.checkpointing import (
            latest_checkpoint,
            save_checkpoint,
            load_checkpoint,
        )

        state = make_state()
        save_checkpoint(str(tmp_path), 3, state)
        blob_dir = str(tmp_path / "blobs")
        victim = sorted(os.listdir(blob_dir))[0]
        with open(os.path.join(blob_dir, victim), "r+b") as f:
            f.seek(8)
            f.write(b"\xff\x00\xff\x00")
        with pytest.raises(ValueError, match="digest"):
            load_checkpoint(latest_checkpoint(str(tmp_path)))

    def test_legacy_whole_payload_checkpoints_still_load(self, tmp_path):
        """Read-only fallback: pre-store ``.tft`` files (one serialized
        state dict, no manifest) keep loading."""
        from torchft_tpu.checkpointing import load_checkpoint

        state = make_state()
        path = str(tmp_path / "ckpt_step4.tft")
        with open(path, "wb") as f:
            f.write(bytes(memoryview(ser.serialize(state))))
        assert_state_equal(load_checkpoint(path), state)
