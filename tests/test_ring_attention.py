"""Ring attention (context parallelism) correctness vs dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from torchft_tpu.ops.ring_attention import dense_attention, ring_attention


def _qkv(b=2, t=16, h=4, d=8, dtype=jnp.float32):
    key = jax.random.PRNGKey(0)
    return [
        jax.random.normal(jax.random.fold_in(key, i), (b, t, h, d), dtype)
        for i in range(3)
    ]


def _cp_mesh(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("cp",))


@pytest.mark.parametrize("ring_size", [1, 2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense(ring_size, causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, _cp_mesh(ring_size), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_uneven_heads_batch_mesh():
    """Batch and heads sharded over extra axes alongside the ring axis."""
    q, k, v = _qkv(b=4, t=16, h=4, d=8)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dp", "cp", "tp"))
    out = ring_attention(
        q, k, v, mesh, axis_name="cp", batch_axes=("dp",), head_axis="tp"
    )
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_bf16_inputs():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = ring_attention(q, k, v, _cp_mesh(4))
    ref = dense_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def test_grad_flows():
    q, k, v = _qkv()
    mesh = _cp_mesh(4)

    def loss(q, k, v):
        return (ring_attention(q, k, v, mesh) ** 2).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def loss_ref(q, k, v):
        return (dense_attention(q, k, v) ** 2).sum()

    rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=1e-4)


class TestRingFlashComposition:
    """Lane-aligned local chunks route through the Pallas flash tiles
    (ops/flash_attention.py ring_flash_local) — same contract, O(T_local)
    tile memory, bwd against the global logsumexp."""

    def _qkv(self, t, hkv=2, seed=0):
        key = jax.random.PRNGKey(seed)
        q = jax.random.normal(jax.random.fold_in(key, 0), (2, t, 4, 64))
        k = jax.random.normal(jax.random.fold_in(key, 1), (2, t, hkv, 64))
        v = jax.random.normal(jax.random.fold_in(key, 2), (2, t, hkv, 64))
        return q, k, v

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_dense(self, causal):
        q, k, v = self._qkv(256)  # T_local=128 over cp=2 -> flash tiles
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("cp",))
        ref = dense_attention(q, k, v, causal=causal)
        out = ring_attention(q, k, v, mesh, axis_name="cp", causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-5
        )

    def test_grads_match_dense(self):
        q, k, v = self._qkv(256, seed=7)
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("cp",))

        def make_loss(fn):
            def loss(q, k, v):
                o = fn(q, k, v)
                w = jnp.arange(o.size, dtype=o.dtype).reshape(o.shape) / o.size
                return (o * w).mean()

            return jax.grad(loss, argnums=(0, 1, 2))

        g_ref = make_loss(lambda q, k, v: dense_attention(q, k, v, causal=True))(
            q, k, v
        )
        g_out = make_loss(
            lambda q, k, v: ring_attention(q, k, v, mesh, axis_name="cp")
        )(q, k, v)
        for name, a, b in zip("qkv", g_out, g_ref):
            scale = float(np.abs(np.asarray(b)).max()) + 1e-12
            np.testing.assert_allclose(
                np.asarray(a) / scale, np.asarray(b) / scale,
                atol=2e-5, err_msg=f"d{name}",
            )

    def test_four_way_ring(self):
        q, k, v = self._qkv(512, seed=3)  # T_local=128 over cp=4
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("cp",))
        ref = dense_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, mesh, axis_name="cp", causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-5
        )
