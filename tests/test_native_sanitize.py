"""Native sanitizer builds: the Makefile's SANITIZE= modes and the TSan
lighthouse+manager quorum smoke (slow-marked — a TSan rebuild+run is
tens of seconds).

The smoke is a standalone C++ executable (native/smoke.cc) rather than a
dlopen'd .so: the sanitizer runtime must own the process from startup to
interpose on every thread.  See docs/static_analysis.md "native
sanitizer builds"."""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")


def _make(*args, timeout=600):
    return subprocess.run(
        ["make", "-C", NATIVE, *args],
        capture_output=True,
        text=True,
        timeout=timeout,
    )


class TestMakefileModes:
    def test_bad_sanitize_value_is_rejected(self):
        proc = _make("SANITIZE=bogus", timeout=60)
        assert proc.returncode != 0
        assert "SANITIZE must be" in proc.stderr + proc.stdout

    def test_production_flags_carry_werror(self):
        """The -Wno-unused-parameter escape hatch is gone: the tree owns
        -Wall -Wextra -Werror."""
        text = open(os.path.join(NATIVE, "Makefile")).read()
        assert "-Werror" in text
        assert "-Wno-unused-parameter" not in text


@pytest.mark.slow
class TestTsanQuorumSmoke:
    def test_tsan_build_and_quorum_smoke(self):
        """Acceptance bar: `make -C native SANITIZE=thread` builds, and
        the quorum smoke (a concurrent codec round over the row-range
        quant entry points, then 2 replica groups x 3 live quorum+commit
        rounds through a real lighthouse) runs with ZERO ThreadSanitizer
        reports."""
        proc = _make("SANITIZE=thread", "smoke")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        binary = os.path.join(NATIVE, "build-tsan", "quorum_smoke")
        assert os.path.exists(binary)
        run = subprocess.run(
            [binary],
            capture_output=True,
            text=True,
            timeout=300,
            env={**os.environ, "TSAN_OPTIONS": "halt_on_error=0 exitcode=66"},
        )
        # the threaded-codec leg runs first: 4 threads over disjoint row
        # ranges of shared buffers (the codec_pool access pattern)
        assert "CODEC OK" in run.stdout, run.stdout + run.stderr
        # fragment data-plane leg: concurrent stagers vs long-poll
        # readers vs a mid-stream retire on the zero-copy server
        assert "FRAGMENT OK" in run.stdout, run.stdout + run.stderr
        assert "SMOKE OK" in run.stdout, run.stdout + run.stderr
        assert "WARNING: ThreadSanitizer" not in run.stderr, run.stderr
        assert run.returncode == 0, f"exit={run.returncode}\n{run.stderr}"

    def test_sanitized_objects_stay_out_of_production_dir(self):
        """SANITIZE builds land in build-tsan/ — the production .so that
        _native.py loads in-place must never silently become an
        instrumented one."""
        if not os.path.isdir(os.path.join(NATIVE, "build-tsan")):
            # selective run on a clean checkout: the sibling test (or a
            # manual `make SANITIZE=thread`) produces the TSan tree
            pytest.skip("no TSan build present; run the smoke test first")
        # the production lib path is untouched by the sanitize build
        prod = os.path.join(NATIVE, "libtorchft_tpu_native.so")
        if os.path.exists(prod):
            with open(prod, "rb") as fh:
                blob = fh.read()
            assert b"__tsan_init" not in blob
