"""Multi-host (real spawned processes) integration.

VERDICT r2 item #4: per-host Manager ranks over a jax multi-process mesh —
real OS processes, one jit mesh spanning each group's processes (CPU
backend, Gloo collectives), the elastic FT ring between groups.
Reference wiring: torchft/manager.py:277-325, torchft/fsdp_test.py:96-120.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_groups_of_two_processes_converge():
    """2 replica groups x 2 processes each: every process runs a Manager
    rank (rank 0 hosts the group server, rank 1 discovers it via the store
    handoff); the jit dp-mean spans each group's two processes; the
    cross-group ring averages gradients.  All four processes must end
    bitwise identical."""
    out = subprocess.run(
        [sys.executable, "examples/train_multihost.py",
         "--groups", "2", "--procs-per-group", "2", "--steps", "3"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "params converged bitwise across 4 processes" in out.stdout
    # each group's rank-1 process reached its server through the store
    # handoff and committed every step
    for tag in ("g0p0", "g0p1", "g1p0", "g1p1"):
        assert f"[{tag}] done step=3" in out.stdout, out.stdout


def test_chaos_kill_group_rejoin_heal_converge():
    """VERDICT r3 item #4: kill one whole group's REAL processes mid-run
    (SIGKILL, no shutdown), restart them; the new incarnation supersedes
    the dead one at the lighthouse, heals live from a surviving group
    (first commit lands at the survivors' step, not 0), and the run ends
    bitwise-converged across every process.
    Reference: torchft/manager_integ_test.py:236-249 (restart semantics),
    fsdp_test.py:96-120 (real spawned workers)."""
    out = subprocess.run(
        [sys.executable, "examples/train_multihost.py",
         "--groups", "2", "--procs-per-group", "2", "--steps", "10",
         "--chaos", "--step-sleep", "0.4"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "after chaos kill+rejoin" in out.stdout, out.stdout
    assert "restarted group healed to step" in out.stdout, out.stdout


def test_diloco_across_real_process_groups_with_chaos():
    """The BASELINE north-star config over real processes: Streaming
    DiLoCo across replica groups (inner dp-mean per group mesh, outer
    pseudograd sync every --sync-every inner steps), one whole group
    SIGKILLed mid-run, restarted, superseded, and healed live — including
    its DiLoCo outer state (fragment backups + outer optimizer, the
    per-fragment heal slices local_sgd.py registers).  Bitwise-converged
    at the final sync boundary."""
    out = subprocess.run(
        [sys.executable, "examples/train_multihost.py",
         "--groups", "2", "--procs-per-group", "2", "--algo", "diloco",
         "--steps", "6", "--chaos", "--step-sleep", "0.25"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "after chaos kill+rejoin" in out.stdout, out.stdout
    assert "restarted group healed to step" in out.stdout, out.stdout

def test_diloco_quantized_wire_across_real_process_groups():
    """The int8 quantized outer sync over REAL process boundaries (the
    reference exercises its quantized allreduce over NCCL ranks;
    threads/Baby cover the in-process cases): 2 groups x 2 processes,
    every outer pseudograd sync rides the int8+rowscale wire through the
    native codec, and all four processes end bitwise identical — the
    quantized allreduce's allgather hop guarantees every rank decodes
    the same requantized slices."""
    out = subprocess.run(
        [sys.executable, "examples/train_multihost.py",
         "--groups", "2", "--procs-per-group", "2", "--algo", "diloco",
         "--steps", "4", "--quantize"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "params converged bitwise across 4 processes" in out.stdout, out.stdout
