"""Manager server + store behavior tests (live C++ servers, port 0).

Scenario parity with reference src/manager.rs:626-1218 tests: local-rank
aggregation, should_commit AND-ing, checkpoint metadata, lighthouse retry.
"""

import threading
import time

import pytest

from torchft_tpu.coordination import (
    LighthouseServer,
    ManagerClient,
    ManagerServer,
    StoreClient,
    StoreServer,
)


class TestStore:
    def test_set_get(self):
        with StoreServer() as server:
            client = StoreClient(server.address())
            client.set("k", "v")
            assert client.get("k") == "v"
            assert client.num_keys() == 1
            client.close()

    def test_get_wait_blocks_until_set(self):
        with StoreServer() as server:
            c1 = StoreClient(server.address())
            c2 = StoreClient(server.address())
            result = {}

            def waiter():
                result["v"] = c1.get("later", timeout=5.0)

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.1)
            c2.set("later", "arrived")
            t.join(timeout=5)
            assert result["v"] == "arrived"

    def test_get_nowait_raises(self):
        with StoreServer() as server:
            client = StoreClient(server.address())
            with pytest.raises(RuntimeError, match="not found"):
                client.get("missing", wait=False)

    def test_get_wait_times_out(self):
        with StoreServer() as server:
            client = StoreClient(server.address())
            with pytest.raises(TimeoutError):
                client.get("never", timeout=0.3)

    def test_delete_prefix(self):
        with StoreServer() as server:
            client = StoreClient(server.address())
            client.set("/q/1/a", "1")
            client.set("/q/1/b", "2")
            client.set("/q/2/a", "3")
            assert client.delete_prefix("/q/1/") == 2
            assert client.num_keys() == 1


class TestManagerServer:
    def _managed_pair(self, lighthouse, replica_id, world_size=2):
        manager = ManagerServer(
            replica_id=replica_id,
            lighthouse_addr=lighthouse.address(),
            store_address=f"store_{replica_id}",
            world_size=world_size,
        )
        return manager

    def test_local_rank_aggregation_single_group(self):
        with LighthouseServer(min_replicas=1, join_timeout_ms=100) as lh:
            with self._managed_pair(lh, "g0", world_size=2) as mgr:
                results = {}

                def rank_call(rank):
                    client = ManagerClient(mgr.address())
                    results[rank] = client._quorum(
                        group_rank=rank,
                        step=0,
                        checkpoint_metadata=f"meta_rank{rank}",
                        shrink_only=False,
                        timeout=10.0,
                    )
                    client.close()

                threads = [
                    threading.Thread(target=rank_call, args=(r,)) for r in range(2)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=15)

                assert results[0].quorum_id == results[1].quorum_id == 1
                assert results[0].replica_world_size == 1
                assert results[0].store_address == "store_g0"
                # metadata from both ranks is retrievable
                client = ManagerClient(mgr.address())
                assert client._checkpoint_metadata(0, 5.0) == "meta_rank0"
                assert client._checkpoint_metadata(1, 5.0) == "meta_rank1"
                client.close()

    def test_two_replica_groups_quorum(self):
        with LighthouseServer(min_replicas=2, join_timeout_ms=100) as lh:
            with self._managed_pair(lh, "g0", 1) as m0, self._managed_pair(
                lh, "g1", 1
            ) as m1:
                results = {}

                def call(rid, mgr):
                    client = ManagerClient(mgr.address())
                    results[rid] = client._quorum(
                        group_rank=0,
                        step=0,
                        checkpoint_metadata="",
                        shrink_only=False,
                        timeout=10.0,
                    )
                    client.close()

                threads = [
                    threading.Thread(target=call, args=("g0", m0)),
                    threading.Thread(target=call, args=("g1", m1)),
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=15)

                assert results["g0"].replica_world_size == 2
                assert results["g0"].replica_rank == 0
                assert results["g1"].replica_rank == 1
                # init_sync at step 0: non-primary heals from primary
                assert not results["g0"].heal
                assert results["g1"].heal
                assert (
                    results["g1"].recover_src_manager_address == m0.address()
                )

    def test_should_commit_and_of_votes(self):
        with LighthouseServer(min_replicas=1, join_timeout_ms=100) as lh:
            with self._managed_pair(lh, "g0", world_size=2) as mgr:

                def vote(rank, value, out):
                    client = ManagerClient(mgr.address())
                    out[rank] = client.should_commit(rank, 0, value, timeout=10.0)
                    client.close()

                # one dissenting vote -> everyone gets False
                out = {}
                threads = [
                    threading.Thread(target=vote, args=(0, True, out)),
                    threading.Thread(target=vote, args=(1, False, out)),
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=15)
                assert out == {0: False, 1: False}

                # unanimous -> True (round state reset correctly)
                out = {}
                threads = [
                    threading.Thread(target=vote, args=(0, True, out)),
                    threading.Thread(target=vote, args=(1, True, out)),
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=15)
                assert out == {0: True, 1: True}

    def test_quorum_survives_lighthouse_late_start(self):
        # Manager created while the lighthouse is down: heartbeats fail
        # silently, and a quorum call issued before the lighthouse exists
        # succeeds once it comes up (connect backoff, reference
        # src/net.rs:10-36 behavior).
        probe = LighthouseServer(min_replicas=1, join_timeout_ms=100)
        addr = probe.address()
        probe.shutdown()  # free the port; manager now points at a dead addr

        mgr = ManagerServer(
            replica_id="g0",
            lighthouse_addr=addr,
            store_address="store_g0",
            world_size=1,
            quorum_retries=3,
        )
        try:
            result = {}

            def call():
                client = ManagerClient(mgr.address())
                result["r"] = client._quorum(
                    group_rank=0,
                    step=0,
                    checkpoint_metadata="",
                    shrink_only=False,
                    timeout=15.0,
                )
                client.close()

            t = threading.Thread(target=call)
            t.start()
            time.sleep(1.0)
            # Bring the lighthouse up on the same port.
            host, _, port = addr.rpartition(":")
            lh = LighthouseServer(
                bind=f":{port}", min_replicas=1, join_timeout_ms=100
            )
            t.join(timeout=20)
            assert result["r"].quorum_id == 1
            lh.shutdown()
        finally:
            mgr.shutdown()

    def test_checkpoint_metadata_unknown_rank(self):
        with LighthouseServer(min_replicas=1, join_timeout_ms=100) as lh:
            with self._managed_pair(lh, "g0", world_size=1) as mgr:
                client = ManagerClient(mgr.address())
                with pytest.raises(RuntimeError, match="rank not found"):
                    client._checkpoint_metadata(7, 5.0)
                client.close()
