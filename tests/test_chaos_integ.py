"""Chaos suite: multi-replica training under scheduled fault injection.

The production chaos layer (``torchft_tpu.utils.faults``) drives every
failure here — the same registry a deployment configures with
``TORCHFT_FAULTS``.  Two tiers:

- ``test_chaos_smoke_*`` (marker ``chaos``, tier-1, seeded, <60s): a
  2-replica DDP run through an injected quorum failure, transport failure,
  allreduce failure and a replica crash must recover, converge bitwise,
  and report ``torchft_faults_injected_total`` counters exactly matching
  the schedule.
- ``test_chaos_soak_*`` (markers ``chaos, slow``, excluded from tier-1): a
  randomized-but-seeded schedule hitting every registered production site
  over longer DDP and DiLoCo runs.

Every run is watchdog-bounded (``utils.futures.context_timeout`` aborting
the live process groups + bounded future waits), so a deadlock fails fast
with a diagnostic instead of eating the suite timeout.
"""

import random
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np
import optax
import pytest

from torchft_tpu.coordination import LighthouseClient, LighthouseServer
from torchft_tpu.local_sgd import DiLoCo
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroupTCP
from torchft_tpu.utils import faults, metrics
from torchft_tpu.utils.faults import FaultRule, InjectedFault
from torchft_tpu.utils.futures import context_timeout

from tests.test_manager_integ import Runner, assert_bitwise_equal

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


@pytest.fixture
def lighthouse():
    server = LighthouseServer(
        min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=1000
    )
    yield server
    server.shutdown()


# every (site, action) pair any chaos test can schedule — snapshotting a
# fixed key set keeps before/after deltas comparable across tests sharing
# one process-wide metrics registry
_SNAPSHOT_KEYS = [
    (site, action)
    for site in faults.KNOWN_SITES
    for action in faults.ACTIONS
]


def _metrics_snapshot() -> "Dict[tuple, float]":
    """Per-(site, action) values of torchft_faults_injected_total."""
    return {
        key: metrics.FAULTS_INJECTED.labels(site=key[0], action=key[1]).get()
        for key in _SNAPSHOT_KEYS
    }


# The replica harness is the DDP Runner from test_manager_integ (same
# training loop, same train.step crash-and-restart semantics) — one
# harness for plain-recovery AND chaos tests, with the `pgs` sink giving
# the chaos watchdog a handle to abort live groups on deadline expiry.
def ChaosRunner(
    replica_id: int,
    lighthouse_addr: str,
    total_steps: int,
    pgs: "List[ProcessGroupTCP]",
    attempts: int = 4,
) -> Runner:
    return Runner(
        replica_id,
        lighthouse_addr,
        total_steps=total_steps,
        min_replica_size=1,
        attempts=attempts,
        pgs=pgs,
    )


def run_with_watchdog(runners: "List[Runner]", budget: float) -> "List[dict]":
    """Run replicas concurrently under a hard deadline.

    Arms the shared timeout engine (utils/futures.py — itself guarded by
    the process watchdog): on expiry every live PG is aborted, unwedging
    any stuck collective so the bounded future waits below fail with a
    real error instead of hanging to the suite timeout.
    """
    pgs: "List[ProcessGroupTCP]" = []
    for r in runners:
        r.pgs = pgs
    tripped = threading.Event()

    def _trip() -> None:
        tripped.set()
        for pg in list(pgs):
            try:
                pg.abort()
            except Exception:  # noqa: BLE001 - unwedge best-effort
                pass

    with context_timeout(_trip, budget):
        with ThreadPoolExecutor(max_workers=len(runners)) as ex:
            futures = [ex.submit(r.run) for r in runners]
            results = [f.result(timeout=budget + 10) for f in futures]
    assert not tripped.is_set(), "chaos watchdog tripped: run wedged past deadline"
    return results


# ---------------------------------------------------------------------------
# tier-1 seeded smoke (<60s)
# ---------------------------------------------------------------------------


class TestChaosSmoke:
    def test_chaos_smoke_ddp(self, lighthouse):
        """Seeded 2-replica run: one injected quorum failure, one transport
        failure, one allreduce failure, one replica crash.  Must recover,
        converge bitwise, and the faults-injected counters (registry AND
        the metrics surface) must match the schedule exactly."""
        schedule = [
            FaultRule(site="manager.quorum", replica="replica_0", step=1),
            FaultRule(site="pg.allreduce", replica="replica_1", step=2),
            FaultRule(site="train.step", replica="replica_1", step=3),
            # first heal recv anywhere fails once; the protocol must retry
            # the heal on the next quorum round
            FaultRule(site="transport.recv", after_step=0),
        ]
        before = _metrics_snapshot()
        faults.FAULTS.configure(list(schedule), seed=1234)

        runners = [
            ChaosRunner(i, lighthouse.address(), total_steps=6, pgs=[])
            for i in range(2)
        ]
        results = run_with_watchdog(runners, budget=120.0)

        assert all(r["manager_state"]["step"] == 6 for r in results)
        assert_bitwise_equal(results)

        # accounting: every scheduled one-shot rule fired exactly once...
        expected = {
            ("manager.quorum", "raise"): 1,
            ("pg.allreduce", "raise"): 1,
            ("train.step", "raise"): 1,
            ("transport.recv", "raise"): 1,
        }
        assert faults.FAULTS.counts() == expected
        # ...and the metrics registry tells the identical story
        after = _metrics_snapshot()
        deltas = {k: after[k] - before[k] for k in after if after[k] != before[k]}
        assert deltas == {k: float(v) for k, v in expected.items()}

    def test_chaos_smoke_latency_and_drop(self, lighthouse):
        """Delay and drop actions on the quorum path: latency injection
        must not break the protocol, and an injected lighthouse-RPC drop
        must ride the client's reconnect path."""
        faults.FAULTS.configure(
            [
                FaultRule(
                    site="manager.quorum",
                    action="delay",
                    delay=0.2,
                    after_step=0,
                    times=2,
                ),
            ],
            seed=7,
        )
        runners = [
            ChaosRunner(i, lighthouse.address(), total_steps=3, pgs=[])
            for i in range(2)
        ]
        results = run_with_watchdog(runners, budget=90.0)
        assert all(r["manager_state"]["step"] == 3 for r in results)
        assert_bitwise_equal(results)
        assert faults.FAULTS.counts() == {("manager.quorum", "delay"): 2}

        # lighthouse.rpc drop: the persistent client reconnects and retries
        # the (idempotent) call transparently
        faults.FAULTS.configure(
            [FaultRule(site="lighthouse.rpc", action="drop")], seed=8
        )
        client = LighthouseClient(lighthouse.address(), connect_timeout=5.0)
        try:
            status = client.status(timeout=5.0)
        finally:
            client.close()
        assert isinstance(status, dict) and status
        assert faults.FAULTS.counts() == {("lighthouse.rpc", "drop"): 1}

    def test_quorum_retries_ride_injected_drop(self):
        """TORCHFT_QUORUM_RETRIES backoff semantics end to end: an injected
        connection drop at the manager.quorum site is retried with backoff
        inside the quorum budget — the step completes with NO error latched
        and the retry counter moves."""
        retries_before = metrics.RETRIES.labels(op="manager.quorum").get()
        faults.FAULTS.configure(
            [FaultRule(site="manager.quorum", action="drop")], seed=3
        )
        state = {"w": np.zeros(2, dtype=np.float32)}
        server = LighthouseServer(min_replicas=1, join_timeout_ms=100)
        try:
            manager = Manager(
                pg=ProcessGroupTCP(timeout=10.0),
                min_replica_size=1,
                load_state_dict=lambda sd: state.update(sd),
                state_dict=lambda: dict(state),
                lighthouse_addr=server.address(),
                replica_id="retryer",
                group_rank=0,
                group_world_size=1,
                use_async_quorum=False,
                timeout=10.0,
                quorum_timeout=10.0,
                quorum_retries=2,
            )
            try:
                manager.start_quorum()
                manager.allreduce({"g": np.ones(2, np.float32)}).wait(timeout=10)
                assert manager.errored() is None, manager.errored()
                assert manager.should_commit()
            finally:
                manager.shutdown()
        finally:
            server.shutdown()
        assert faults.FAULTS.counts() == {("manager.quorum", "drop"): 1}
        assert metrics.RETRIES.labels(op="manager.quorum").get() == retries_before + 1


# ---------------------------------------------------------------------------
# soaks (slow; excluded from tier-1)
# ---------------------------------------------------------------------------


def _soak_schedule(rng: "random.Random", n_replicas: int, total_steps: int):
    """Randomized-but-seeded schedule hitting every production site the
    DDP path exercises.

    Step-targeted rules use ``after_step`` thresholds, not exact steps: a
    healing replica jumps its step straight to max_step, so an exact step
    can legitimately be skipped — a threshold fires at the first
    opportunity past it, keeping "faults injected == faults scheduled"
    exact under every interleaving while the threshold/replica choices
    stay randomized."""
    pick = lambda: f"replica_{rng.randrange(n_replicas)}"  # noqa: E731
    mid = lambda: rng.randrange(1, max(total_steps - 2, 2))  # noqa: E731
    return [
        FaultRule(site="train.step", replica=pick(), after_step=mid()),
        FaultRule(site="manager.quorum", replica=pick(), after_step=mid()),
        FaultRule(
            site="manager.quorum", action="delay", delay=0.05, after_step=0, times=3
        ),
        FaultRule(site="manager.heal", action="delay", delay=0.05, after_step=0),
        FaultRule(site="pg.allreduce", replica=pick(), after_step=mid()),
        FaultRule(site="pg.reconfigure", replica=pick()),
        FaultRule(site="transport.recv", after_step=0),
        FaultRule(site="transport.send", after_step=0),
        FaultRule(site="store.barrier", action="drop"),
    ]


@pytest.mark.slow
class TestChaosSoak:
    def test_chaos_soak_ddp(self, lighthouse):
        """3-replica DDP soak under a seeded randomized schedule touching
        every DDP-path site; convergence + no deadlock + exact accounting."""
        SEED, REPLICAS, STEPS = 20260803, 3, 10
        schedule = _soak_schedule(random.Random(SEED), REPLICAS, STEPS)
        faults.FAULTS.configure(list(schedule), seed=SEED)

        runners = [
            ChaosRunner(i, lighthouse.address(), total_steps=STEPS, pgs=[])
            for i in range(REPLICAS)
        ]
        results = run_with_watchdog(runners, budget=300.0)
        assert all(r["manager_state"]["step"] == STEPS for r in results)
        assert_bitwise_equal(results)

        counts = faults.FAULTS.counts()
        # every one-shot raise/drop rule fired exactly once (after_step
        # thresholds guarantee an eventual opportunity on every site)
        assert counts[("train.step", "raise")] == 1
        assert counts[("manager.quorum", "raise")] == 1
        assert counts[("pg.allreduce", "raise")] == 1
        assert counts[("pg.reconfigure", "raise")] == 1
        assert counts[("transport.recv", "raise")] == 1
        assert counts[("transport.send", "raise")] == 1
        assert counts[("store.barrier", "drop")] == 1
        # the train.step crash forces a heal, so the heal-latency rule fired
        assert counts[("manager.heal", "delay")] == 1
        # quorum latency: bounded by its times budget
        assert counts[("manager.quorum", "delay")] == 3
        # registry total == sum over the metrics surface story
        assert faults.FAULTS.injected() == sum(counts.values())

    def test_chaos_soak_diloco(self, lighthouse):
        """2-replica Streaming-DiLoCo soak: a replica crash at the
        fragment-sync boundary (local_sgd.sync) plus an allreduce failure;
        the semi-sync protocol must re-form and converge exactly."""
        SEED = 77
        faults.FAULTS.configure(
            [
                FaultRule(site="local_sgd.sync", replica="diloco_1", step=2),
                FaultRule(site="pg.allreduce", replica="diloco_0", step=4),
            ],
            seed=SEED,
        )

        outer_syncs, sync_every, n_fragments = 4, 4, 2
        target_steps = outer_syncs * n_fragments
        results: "Dict[int, dict]" = {}
        errors: "Dict[int, BaseException]" = {}
        pgs: "List[ProcessGroupTCP]" = []

        def run(rid: int) -> None:
            try:
                for _ in range(4):  # restart loop: crash-and-heal
                    try:
                        results[rid] = _diloco_train(rid)
                        return
                    except InjectedFault:
                        continue
                raise RuntimeError(f"diloco_{rid} exhausted restarts")
            except BaseException as e:  # noqa: BLE001
                errors[rid] = e

        def _diloco_train(rid: int) -> dict:
            params = {
                "layer0": np.zeros(4, dtype=np.float32),
                "layer1": np.zeros(4, dtype=np.float32),
            }
            holder = {"p": params}

            def get_params():
                return dict(holder["p"])

            def set_params(p):
                holder["p"] = dict(p)

            pg = ProcessGroupTCP(timeout=10.0)
            pgs.append(pg)
            manager = Manager(
                pg=pg,
                min_replica_size=1,
                lighthouse_addr=lighthouse.address(),
                replica_id=f"diloco_{rid}",
                group_rank=0,
                group_world_size=1,
                use_async_quorum=False,
                timeout=20.0,
                quorum_timeout=20.0,
                load_state_dict=lambda sd: holder.__setitem__(
                    "p", {k: np.array(v) for k, v in sd.items()}
                ),
                state_dict=lambda: {k: np.array(v) for k, v in holder["p"].items()},
            )
            try:
                algo = DiLoCo(
                    manager,
                    [["layer0"], ["layer1"]],
                    get_params,
                    set_params,
                    optax.sgd(0.5, momentum=0.9, nesterov=True),
                    sync_every=sync_every,
                )
                while manager.current_step() < target_steps:
                    p = get_params()
                    set_params(
                        {
                            k: v - 0.01 * (1.0 + i)
                            for i, (k, v) in enumerate(sorted(p.items()))
                        }
                    )
                    algo.step()
                return {"params": get_params(), "manager_state": manager.state_dict()}
            finally:
                manager.shutdown()

        tripped = threading.Event()

        def _trip() -> None:
            tripped.set()
            for pg in list(pgs):
                try:
                    pg.abort()
                except Exception:  # noqa: BLE001
                    pass

        threads = [
            threading.Thread(target=run, args=(r,), daemon=True) for r in range(2)
        ]
        with context_timeout(_trip, 300.0):
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=310.0)
        assert not tripped.is_set(), "diloco chaos watchdog tripped"
        assert not any(t.is_alive() for t in threads), "diloco replica hung"
        assert not errors, errors
        assert set(results) == {0, 1}

        assert all(
            r["manager_state"]["step"] == target_steps for r in results.values()
        )
        base = results[0]["params"]
        for k in base:
            np.testing.assert_array_equal(base[k], results[1]["params"][k])
        counts = faults.FAULTS.counts()
        assert counts[("local_sgd.sync", "raise")] == 1
        assert counts[("pg.allreduce", "raise")] == 1
