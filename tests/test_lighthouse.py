"""Lighthouse server behavior tests (live C++ server, port 0).

Scenario parity with reference src/lighthouse.rs:612-1298 tests: join
timeout, heartbeat expiry, fast quorum, shrink_only, split brain,
commit-failure quorum bump — plus the HTTP dashboard.
"""

import threading
import time
import urllib.request

import pytest

from torchft_tpu.coordination import (
    LighthouseClient,
    LighthouseServer,
    Quorum,
)


def _concurrent_quorums(addr, requests, timeout=10.0):
    """Issue quorum requests concurrently; returns {replica_id: Quorum|Exception}."""
    results = {}

    def call(kwargs):
        client = LighthouseClient(addr)
        try:
            results[kwargs["replica_id"]] = client.quorum(timeout=timeout, **kwargs)
        except Exception as e:  # noqa: BLE001 - collected for assertions
            results[kwargs["replica_id"]] = e
        finally:
            client.close()

    threads = [threading.Thread(target=call, args=(r,)) for r in requests]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 5)
    return results


class TestLighthouse:
    def test_two_replica_quorum(self):
        with LighthouseServer(min_replicas=2) as server:
            results = _concurrent_quorums(
                server.address(),
                [{"replica_id": "a", "step": 1}, {"replica_id": "b", "step": 1}],
            )
            qa, qb = results["a"], results["b"]
            assert isinstance(qa, Quorum) and isinstance(qb, Quorum)
            assert qa.quorum_id == qb.quorum_id == 1
            assert [p.replica_id for p in qa.participants] == ["a", "b"]

    def test_quorum_id_stable_when_membership_unchanged(self):
        with LighthouseServer(min_replicas=2) as server:
            reqs = [{"replica_id": "a"}, {"replica_id": "b"}]
            r1 = _concurrent_quorums(server.address(), reqs)
            r2 = _concurrent_quorums(server.address(), reqs)
            assert r1["a"].quorum_id == 1
            # same members again -> fast quorum, no id bump
            assert r2["a"].quorum_id == 1

    def test_quorum_id_bumps_on_commit_failures(self):
        with LighthouseServer(min_replicas=2) as server:
            reqs = [{"replica_id": "a"}, {"replica_id": "b"}]
            r1 = _concurrent_quorums(server.address(), reqs)
            assert r1["a"].quorum_id == 1
            reqs_fail = [
                {"replica_id": "a", "commit_failures": 1},
                {"replica_id": "b"},
            ]
            r2 = _concurrent_quorums(server.address(), reqs_fail)
            assert r2["a"].quorum_id == 2

    def test_quorum_timeout_when_not_enough_replicas(self):
        with LighthouseServer(min_replicas=2) as server:
            client = LighthouseClient(server.address())
            with pytest.raises(TimeoutError):
                client.quorum(replica_id="lonely", timeout=0.5)
            client.close()

    def test_join_timeout_admits_straggler(self):
        # b heartbeats (known-healthy) but doesn't join; a joins. With
        # min_replicas=1 the quorum must wait join_timeout for b, and b
        # joining within the window lands both in one quorum.
        with LighthouseServer(min_replicas=1, join_timeout_ms=2000) as server:
            client = LighthouseClient(server.address())
            client.heartbeat("b")

            results = {}

            def join_a():
                c = LighthouseClient(server.address())
                results["a"] = c.quorum(replica_id="a", timeout=10.0)
                c.close()

            t = threading.Thread(target=join_a)
            t.start()
            time.sleep(0.5)  # a is waiting on the straggler window
            assert "a" not in results
            results["b"] = LighthouseClient(server.address()).quorum(
                replica_id="b", timeout=10.0
            )
            t.join(timeout=10)
            assert [p.replica_id for p in results["a"].participants] == ["a", "b"]

    def test_join_timeout_expires_without_straggler(self):
        # a and c join; b heartbeats but never joins. Quorum is valid (2 of 3
        # healthy participating beats the split-brain bar) but waits
        # join_timeout for b before forming without it.
        with LighthouseServer(min_replicas=1, join_timeout_ms=300) as server:
            hb = LighthouseClient(server.address())
            hb.heartbeat("b")
            start = time.monotonic()
            results = _concurrent_quorums(
                server.address(), [{"replica_id": "a"}, {"replica_id": "c"}]
            )
            elapsed = time.monotonic() - start
            assert [p.replica_id for p in results["a"].participants] == ["a", "c"]
            assert elapsed >= 0.25
            hb.close()

    def test_split_brain_guard(self):
        # 3 healthy replicas known; only 1 joins; min_replicas=1. The guard
        # (participants must exceed half the healthy replicas) blocks quorum.
        with LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=60000
        ) as server:
            client = LighthouseClient(server.address())
            client.heartbeat("b")
            client.heartbeat("c")
            with pytest.raises(TimeoutError):
                client.quorum(replica_id="a", timeout=1.0)
            client.close()

    def test_heartbeat_expiry_shrinks_quorum(self):
        # quorum {a,b}; b dies (no heartbeat); a re-requests and forms {a}
        # after the heartbeat timeout passes.
        with LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=500
        ) as server:
            # Pre-heartbeat both so the split-brain guard holds the first
            # quorum open until both have joined (min_replicas=1 would
            # otherwise let the first joiner form a singleton).
            hb = LighthouseClient(server.address())
            hb.heartbeat("a")
            hb.heartbeat("b")
            r1 = _concurrent_quorums(
                server.address(), [{"replica_id": "a"}, {"replica_id": "b"}]
            )
            assert r1["a"].quorum_id == 1
            time.sleep(0.8)  # b's heartbeat expires
            client = LighthouseClient(server.address())
            q = client.quorum(replica_id="a", timeout=10.0)
            assert [p.replica_id for p in q.participants] == ["a"]
            assert q.quorum_id == 2
            client.close()

    def test_shrink_only_excludes_new_member(self):
        with LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=500
        ) as server:
            hb = LighthouseClient(server.address())
            hb.heartbeat("a")
            hb.heartbeat("b")
            r1 = _concurrent_quorums(
                server.address(), [{"replica_id": "a"}, {"replica_id": "b"}]
            )
            assert [p.replica_id for p in r1["a"].participants] == ["a", "b"]
            time.sleep(0.8)  # b expires
            # Refresh a's heartbeat so a concurrent join by newcomer c can't
            # form a singleton {c} quorum before a registers.
            hb.heartbeat("a")
            # a requests shrink_only; newcomer c also asks to join.
            results = {}

            def join(rid, **kw):
                c = LighthouseClient(server.address())
                try:
                    results[rid] = c.quorum(replica_id=rid, timeout=2.0, **kw)
                except Exception as e:  # noqa: BLE001
                    results[rid] = e
                c.close()

            ta = threading.Thread(target=join, args=("a",), kwargs={"shrink_only": True})
            tc = threading.Thread(target=join, args=("c",))
            ta.start()
            tc.start()
            ta.join(10)
            tc.join(10)
            # a's shrink-only quorum excludes the newcomer c...
            assert [p.replica_id for p in results["a"].participants] == ["a"]
            # ...and c is only admitted to a later quorum (after a's
            # heartbeat lapses), never the shrink-only one.
            assert isinstance(results["c"], Quorum)
            assert results["c"].quorum_id > results["a"].quorum_id
            assert "c" in [p.replica_id for p in results["c"].participants]

    def test_dashboard(self):
        with LighthouseServer(min_replicas=1, join_timeout_ms=100) as server:
            _concurrent_quorums(server.address(), [{"replica_id": "web"}])
            html = (
                urllib.request.urlopen(f"http://{server.address()}/status", timeout=5)
                .read()
                .decode()
            )
            assert "torchft_tpu lighthouse" in html
            assert "web" in html

    def test_metrics_endpoint(self):
        """GET /metrics on the dashboard port returns valid Prometheus text
        exposition: the native lighthouse counters plus this process's
        telemetry registry (the provider-callback seam)."""
        from torchft_tpu.utils.metrics import parse_text_exposition

        with LighthouseServer(min_replicas=1, join_timeout_ms=100) as server:
            _concurrent_quorums(server.address(), [{"replica_id": "m"}])
            body = (
                urllib.request.urlopen(
                    f"http://{server.address()}/metrics", timeout=5
                )
                .read()
                .decode()
            )
        fams = parse_text_exposition(body)  # strict: raises on bad lines
        # native lighthouse counters reflect the quorum that just formed
        assert fams["torchft_lighthouse_quorums_formed_total"]["type"] == "counter"
        assert (
            fams["torchft_lighthouse_quorums_formed_total"]["samples"][
                ("torchft_lighthouse_quorums_formed_total", ())
            ]
            >= 1
        )
        assert (
            fams["torchft_lighthouse_quorum_id"]["samples"][
                ("torchft_lighthouse_quorum_id", ())
            ]
            == 1
        )
        # the Python registry rides the same scrape (acceptance criteria):
        # histogram buckets + the pg abort counter are present even before
        # any manager has run in this process
        assert fams["torchft_quorum_duration_seconds"]["type"] == "histogram"
        assert any(
            name == "torchft_quorum_duration_seconds_bucket"
            and dict(labels).get("le") == "+Inf"
            for name, labels in fams["torchft_quorum_duration_seconds"]["samples"]
        )
        assert ("torchft_pg_aborts_total", ()) in fams[
            "torchft_pg_aborts_total"
        ]["samples"]

    def test_status_rpc(self):
        with LighthouseServer(min_replicas=1, join_timeout_ms=100) as server:
            _concurrent_quorums(server.address(), [{"replica_id": "s"}])
            client = LighthouseClient(server.address())
            status = client.status()
            assert status["quorum_id"] == 1
            assert status["prev_quorum"]["participants"][0]["replica_id"] == "s"
            client.close()

    def test_status_schema_roundtrip(self):
        """Lighthouse.status() and GET /status.json serve the SAME
        document: participant, heartbeat-age, and the new straggler
        fields all round-trip through both surfaces."""
        import json as _json

        with LighthouseServer(
            min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=60000
        ) as server:
            _concurrent_quorums(
                server.address(),
                [
                    {"replica_id": "lead", "step": 9, "store_address": "st:9"},
                    {"replica_id": "lag", "step": 4, "store_address": "st:4"},
                ],
            )
            client = LighthouseClient(server.address())
            # progress piggyback on a plain heartbeat updates the table too
            reply = client.heartbeat("lag", step=5, inflight_op="heal_recv")
            assert reply == {}  # not superseded
            rpc_status = client.status()
            client.close()
            http_status = _json.loads(
                urllib.request.urlopen(
                    f"http://{server.address()}/status.json", timeout=5
                ).read().decode()
            )

        for status in (rpc_status, http_status):
            # participant fields
            by_id = {
                p["replica_id"]: p
                for p in status["prev_quorum"]["participants"]
            }
            assert by_id["lag"]["store_address"] == "st:4"
            assert by_id["lag"]["recovering"] is True
            # heartbeat ages
            hbs = {h["replica_id"]: h for h in status["heartbeats"]}
            assert {"lead", "lag"} <= set(hbs)
            assert all(
                h["age_ms"] >= 0 and h["stale"] is False for h in hbs.values()
            )
            # straggler fields (new): step, step_lag, age, score, op, stale
            stragglers = {
                s["replica_id"]: s for s in status["stragglers"]
            }
            assert {"lead", "lag"} <= set(stragglers)
            assert stragglers["lead"]["step"] == 9
            assert stragglers["lead"]["step_lag"] == 0
            assert stragglers["lag"]["step"] == 5  # heartbeat advanced it
            assert stragglers["lag"]["step_lag"] == 4
            assert stragglers["lag"]["inflight_op"] == "heal_recv"
            assert stragglers["lag"]["progress_age_ms"] >= 0
            # sender-clock stamp round-trips when reported
            assert "last_step_wall_ms" in stragglers["lag"]
            # full QuorumMember fields survive the status unification
            assert "shrink_only" in by_id["lag"]
            assert "commit_failures" in by_id["lag"]
            assert stragglers["lag"]["straggler_score"] >= 0.0
            assert stragglers["lag"]["stale"] is False
            assert status["max_step"] == 9
            # legacy field kept for the status RPC's original schema
            assert "reason" in status and "num_participants" in status

    def test_dashboard_recovering_badge_and_heartbeats(self):
        """Dashboard parity with reference templates/status.html:17-43 +
        src/lighthouse.rs:415-452: a member behind max_step renders with
        the 'recovering' badge, the prev-quorum summary carries id/count/
        age, heartbeat ages are listed, and the page auto-refreshes."""
        import json as _json

        with LighthouseServer(min_replicas=2, join_timeout_ms=100) as server:
            # 'behind' is mid-heal: three steps behind its peer
            _concurrent_quorums(
                server.address(),
                [
                    {"replica_id": "ahead", "step": 5,
                     "store_address": "st:1", "world_size": 2},
                    {"replica_id": "behind", "step": 2,
                     "store_address": "st:2", "world_size": 2},
                ],
            )
            html = (
                urllib.request.urlopen(
                    f"http://{server.address()}/status", timeout=5
                ).read().decode()
            )
            # recovering badge on the lagging replica's row, not the leader's
            assert 'class="recovering"' in html
            row = html.split("behind</td>")[0].rsplit("<tr", 1)[1]
            assert "recovering" in row
            assert "next quorum status:" in html
            assert "quorum age:" in html
            assert "participants: 2" in html
            assert "st:2" in html  # store address column
            assert "heartbeats (" in html
            assert 'http-equiv="refresh"' in html  # auto-refresh

            status = _json.loads(
                urllib.request.urlopen(
                    f"http://{server.address()}/status.json", timeout=5
                ).read().decode()
            )
            by_id = {
                p["replica_id"]: p
                for p in status["prev_quorum"]["participants"]
            }
            assert by_id["behind"]["recovering"] is True
            assert by_id["ahead"]["recovering"] is False
            assert by_id["behind"]["store_address"] == "st:2"
            assert by_id["behind"]["world_size"] == 2
            assert status["prev_quorum"]["age_ms"] >= 0
            assert "live_status" in status
            assert all("stale" in h for h in status["heartbeats"])


class TestStatusPlanePagination:
    """The fleet-scale status surface: paginated/sharded /status.json,
    byte-budgeted dashboard, tick-cost metrics, and the cluster
    step-timeline (ISSUE 6 tentpole b/c)."""

    FLEET = 64

    def _populate(self, server, n):
        client = LighthouseClient(server.address())
        for i in range(n):
            client.heartbeat(
                f"replica{i:03d}", step=100 + (i % 7), inflight_op="train",
                summary={
                    "step": 100 + (i % 7),
                    "phase_ms": {"ring": 10.0 + i, "commit": 1.0},
                    "codec_busy_s": 0.01,
                    "wire_busy_s": 0.02,
                },
            )
        return client

    def test_paginated_roundtrip_native_python_dashboard(self):
        """The same paginated document through all three surfaces: the
        native HTTP render, the status RPC (LighthouseClient.status with
        page/per_page/replica), and the dashboard's data — rows slice
        without loss and fleet-wide totals stay truthful on every page."""
        import json as _json

        with LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=60000,
            status_page_size=10,
        ) as server:
            client = self._populate(server, self.FLEET)
            # default document: first page, server page size
            rpc = client.status()
            http = _json.loads(
                urllib.request.urlopen(
                    f"http://{server.address()}/status.json", timeout=5
                ).read().decode()
            )
            for doc in (rpc, http):
                assert doc["page"] == 0 and doc["per_page"] == 10
                assert doc["heartbeats_total"] == self.FLEET
                assert doc["stragglers_total"] == self.FLEET
                assert doc["pages"] == 7
                assert len(doc["heartbeats"]) == 10
                assert len(doc["stragglers"]) == 10
                assert doc["max_step"] == 106  # fleet-wide, not page-wide
                assert doc["summary"]["replicas_tracked"] == self.FLEET
                assert len(doc["summary"]["stragglers_worst"]) <= 8
            # explicit paging round-trips identically RPC vs HTTP, and the
            # union of pages is exactly the fleet
            seen_rpc, seen_http = set(), set()
            for page in range(rpc["pages"]):
                p_rpc = client.status(page=page, per_page=10)
                p_http = _json.loads(
                    urllib.request.urlopen(
                        f"http://{server.address()}"
                        f"/status.json?page={page}&per_page=10",
                        timeout=5,
                    ).read().decode()
                )
                assert [h["replica_id"] for h in p_rpc["heartbeats"]] == [
                    h["replica_id"] for h in p_http["heartbeats"]
                ]
                assert [s["replica_id"] for s in p_rpc["stragglers"]] == [
                    s["replica_id"] for s in p_http["stragglers"]
                ]
                seen_rpc.update(h["replica_id"] for h in p_rpc["heartbeats"])
                seen_http.update(h["replica_id"] for h in p_http["heartbeats"])
            expected = {f"replica{i:03d}" for i in range(self.FLEET)}
            assert seen_rpc == expected and seen_http == expected
            # replica shard: one replica's rows from every array
            shard = client.status(replica="replica007")
            assert shard["replica"] == "replica007"
            assert [h["replica_id"] for h in shard["heartbeats"]] == [
                "replica007"
            ]
            assert [s["replica_id"] for s in shard["stragglers"]] == [
                "replica007"
            ]
            assert shard["heartbeats_total"] == self.FLEET  # totals intact
            # straggler row fields survive pagination (schema round-trip)
            row = shard["stragglers"][0]
            for field in (
                "step", "step_lag", "progress_age_ms", "last_step_wall_ms",
                "straggler_score", "inflight_op", "stale",
            ):
                assert field in row, field
            client.close()

    def test_dashboard_byte_budget_at_fleet_scale(self):
        """At 64 replicas the default /status.json and the dashboard HTML
        both stay under fixed byte budgets while ?page= walks every row
        (ISSUE 6 acceptance: < 16 KB default document)."""
        with LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=60000,
            status_page_size=16,
        ) as server:
            client = self._populate(server, self.FLEET)
            body = urllib.request.urlopen(
                f"http://{server.address()}/status.json", timeout=5
            ).read()
            assert len(body) < 16 * 1024, f"default status {len(body)}B"
            html = urllib.request.urlopen(
                f"http://{server.address()}/status", timeout=5
            ).read()
            assert len(html) < 32 * 1024, f"dashboard page {len(html)}B"
            page_html = html.decode()
            assert "page 0 of 4" in page_html
            assert "/status?page=1" in page_html  # next link
            # straggler table is the bounded worst-K tier
            assert "worst 8 of 64 by score" in page_html
            # the last page still renders the last replica
            last = urllib.request.urlopen(
                f"http://{server.address()}/status?page=3", timeout=5
            ).read().decode()
            assert "replica063" in last
            client.close()

    def test_tick_metrics_and_bounded_labels(self):
        """/metrics exports the tick-cost histogram + dirty gauge, and the
        per-replica straggler series are capped at straggler_topk with
        fleet-wide aggregates alongside."""
        from torchft_tpu.utils.metrics import (
            parse_text_exposition,
            quantile_from_histogram,
        )

        with LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=60000,
            straggler_topk=5,
        ) as server:
            client = self._populate(server, 20)
            time.sleep(0.3)  # a few tick-loop iterations
            body = urllib.request.urlopen(
                f"http://{server.address()}/metrics", timeout=5
            ).read().decode()
            client.close()
        fams = parse_text_exposition(body)
        assert fams["torchft_lighthouse_tick_seconds"]["type"] == "histogram"
        count = fams["torchft_lighthouse_tick_seconds"]["samples"][
            ("torchft_lighthouse_tick_seconds_count", ())
        ]
        assert count >= 1
        # bounded even on a loaded host: ticks are O(dirty), not O(fleet)
        assert quantile_from_histogram(
            fams, "torchft_lighthouse_tick_seconds", 0.99
        ) <= 1.0
        assert ("torchft_lighthouse_dirty_replicas", ()) in fams[
            "torchft_lighthouse_dirty_replicas"
        ]["samples"]
        lag_rows = [
            k for k in fams["torchft_replica_step_lag"]["samples"]
        ]
        assert 0 < len(lag_rows) <= 5
        assert (
            fams["torchft_stragglers_tracked"]["samples"][
                ("torchft_stragglers_tracked", ())
            ]
            == 20
        )
        assert ("torchft_replica_step_lag_max", ()) in fams[
            "torchft_replica_step_lag_max"
        ]["samples"]

    def test_timeline_aggregation_and_manager_piggyback(self):
        """/timeline.json aggregates heartbeat-piggybacked digests (means,
        maxes, replica counts per step) — including through the native
        ManagerServer.report_summary -> heartbeat-loop path the real
        Manager uses."""
        import json as _json

        from torchft_tpu.coordination import ManagerServer, StoreServer

        with LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=60000,
            timeline_ring=4,
        ) as server:
            client = LighthouseClient(server.address())
            for step in range(6):  # ring=4: steps 0,1 must be evicted
                for rid in ("a", "b"):
                    client.heartbeat(
                        rid, step=step,
                        summary={
                            "step": step,
                            "phase_ms": {"ring": 10.0 if rid == "a" else 20.0},
                            "codec_busy_s": 0.5,
                            "wire_busy_s": 0.25,
                        },
                    )
            tl = client.timeline()
            assert [b["step"] for b in tl["steps"]] == [2, 3, 4, 5]
            bucket = tl["steps"][-1]
            assert bucket["replicas"] == 2 and bucket["reports"] == 2
            assert bucket["phases"]["ring"]["mean_ms"] == pytest.approx(15.0)
            assert bucket["phases"]["ring"]["max_ms"] == pytest.approx(20.0)
            assert bucket["codec_busy_s"] == pytest.approx(1.0)
            assert bucket["wire_busy_s"] == pytest.approx(0.5)
            # HTTP serves the same document
            http = _json.loads(
                urllib.request.urlopen(
                    f"http://{server.address()}/timeline.json", timeout=5
                ).read().decode()
            )
            assert http["steps"] == tl["steps"]

            # the native manager path: report_summary rides the next
            # heartbeat exactly once
            store = StoreServer()
            manager = ManagerServer(
                replica_id="mgr:u1",
                lighthouse_addr=server.address(),
                store_address=store.address(),
                world_size=1,
                heartbeat_interval=0.05,
            )
            try:
                manager.report_progress(7, "train")
                manager.report_summary(
                    {
                        "step": 7,
                        "phase_ms": {"commit": 3.0},
                        "codec_busy_s": 0.0,
                        "wire_busy_s": 0.0,
                    }
                )
                deadline = time.monotonic() + 5.0
                bucket = None
                while time.monotonic() < deadline:
                    tl = client.timeline()
                    bucket = next(
                        (b for b in tl["steps"] if b["step"] == 7), None
                    )
                    if bucket is not None:
                        break
                    time.sleep(0.05)
                assert bucket is not None, "manager digest never arrived"
                assert bucket["phases"]["commit"]["mean_ms"] == pytest.approx(3.0)
                first_reports = bucket["reports"]
                # consumed-on-send: later heartbeats must not re-deliver it
                time.sleep(0.3)
                tl = client.timeline()
                bucket = next(b for b in tl["steps"] if b["step"] == 7)
                assert bucket["reports"] == first_reports
            finally:
                manager.shutdown()
                store.shutdown()
            client.close()


class TestCoordinationDocs:
    def test_public_api_documented(self):
        """Every public coordination class + method carries a docstring
        (reference: torchft/coordination_test.py:15)."""
        import inspect

        from torchft_tpu import coordination as c

        classes = [
            c.LighthouseServer, c.LighthouseClient, c.ManagerServer,
            c.ManagerClient, c.StoreServer, c.StoreClient,
            c.Quorum, c.QuorumMember, c.QuorumResult,
        ]
        for cls in classes:
            assert cls.__doc__ and cls.__doc__.strip(), cls
            for name, fn in inspect.getmembers(cls, predicate=inspect.isfunction):
                if name.startswith("_"):
                    continue
                assert fn.__doc__ and fn.__doc__.strip(), f"{cls.__name__}.{name}"


class TestFastRestartSupersession:
    def test_new_incarnation_evicts_stale_same_prefix_member(self):
        # replica ids carry a ":uuid" incarnation suffix; a rejoin with a
        # new uuid proves the old incarnation is dead, so quorum formation
        # must NOT wait out the join timeout for its stale heartbeat
        with LighthouseServer(
            min_replicas=2, join_timeout_ms=5000, heartbeat_timeout_ms=60000
        ) as server:
            # first quorum: survivor + old incarnation
            hb = LighthouseClient(server.address())
            hb.heartbeat("survivor:aaa")
            hb.heartbeat("victim:old")
            results = _concurrent_quorums(
                server.address(),
                [{"replica_id": "survivor:aaa"}, {"replica_id": "victim:old"}],
            )
            assert [p.replica_id for p in results["survivor:aaa"].participants] == [
                "survivor:aaa",
                "victim:old",
            ]
            # victim dies (no leave RPC; heartbeat would stay "healthy" for
            # 60 s) and restarts with a new uuid. Without supersession this
            # quorum would block on the 5 s join timeout for "victim:old".
            start = time.monotonic()
            results = _concurrent_quorums(
                server.address(),
                [{"replica_id": "survivor:aaa"}, {"replica_id": "victim:new"}],
            )
            elapsed = time.monotonic() - start
            assert [p.replica_id for p in results["victim:new"].participants] == [
                "survivor:aaa",
                "victim:new",
            ]
            assert elapsed < 2.0, (
                f"rejoin quorum took {elapsed:.1f}s — stale incarnation "
                "was not evicted"
            )
            hb.close()

    def test_empty_prefix_ids_never_evict_each_other(self):
        # Manager's default replica_id="" gives ids of the shape ":uuid" —
        # DISTINCT logical replicas sharing the empty prefix; supersession
        # must not apply (a mutual eviction would deadlock quorum)
        with LighthouseServer(
            min_replicas=2, join_timeout_ms=5000, heartbeat_timeout_ms=60000
        ) as server:
            results = _concurrent_quorums(
                server.address(),
                [{"replica_id": ":uuidA"}, {"replica_id": ":uuidB"}],
            )
            assert [p.replica_id for p in results[":uuidA"].participants] == [
                ":uuidA",
                ":uuidB",
            ]

    def test_same_prefix_concurrent_ids_supersede(self):
        # ids sharing a non-empty prefix are BY CONVENTION incarnations of
        # one logical replica (the segment after the last ':' is the
        # incarnation suffix — the Manager appends ':uuid4').  Two
        # concurrent same-prefix joiners therefore supersede each other:
        # the earlier registrant is aborted with a 'superseded' error even
        # if its process is alive (a double-start misconfiguration), and
        # the survivor alone cannot meet min_replicas=2.
        with LighthouseServer(
            min_replicas=2, join_timeout_ms=200, heartbeat_timeout_ms=60000
        ) as server:
            results = _concurrent_quorums(
                server.address(),
                [{"replica_id": "host:1"}, {"replica_id": "host:2"}],
                timeout=2.0,
            )
            errors = [r for r in results.values() if isinstance(r, Exception)]
            assert len(errors) == 2, results
            assert any("superseded" in str(e) for e in errors), results

    def test_zombie_heartbeat_cannot_rewedge_quorum(self):
        # A superseded-but-still-alive predecessor (hung, then rescheduled)
        # keeps its background heartbeat thread running.  If the lighthouse
        # accepted those heartbeats after eviction, the zombie would be
        # "healthy but not participating" and every post-rejoin quorum
        # would wait out the full join timeout again.
        with LighthouseServer(
            min_replicas=2, join_timeout_ms=5000, heartbeat_timeout_ms=60000
        ) as server:
            results = _concurrent_quorums(
                server.address(),
                [{"replica_id": "survivor:aaa"}, {"replica_id": "victim:old"}],
            )
            assert isinstance(results["victim:old"], Quorum)

            stop = threading.Event()

            def zombie():
                c = LighthouseClient(server.address())
                try:
                    while not stop.is_set():
                        c.heartbeat("victim:old")
                        time.sleep(0.02)
                finally:
                    c.close()

            t = threading.Thread(target=zombie, daemon=True)
            t.start()
            try:
                start = time.monotonic()
                results = _concurrent_quorums(
                    server.address(),
                    [{"replica_id": "survivor:aaa"}, {"replica_id": "victim:new"}],
                )
                elapsed = time.monotonic() - start
                assert [
                    p.replica_id for p in results["victim:new"].participants
                ] == ["survivor:aaa", "victim:new"]
                assert elapsed < 2.0, (
                    f"rejoin quorum took {elapsed:.1f}s — zombie heartbeat "
                    "re-wedged quorum formation"
                )
            finally:
                stop.set()
                t.join(timeout=5)

    def test_restart_storm_only_latest_incarnation_survives(self):
        # N sequential incarnations of one logical replica: each join
        # evicts the previous, every superseded id stays permanently
        # rejected (stamps never age out), and only the newest is in the
        # final quorum alongside the survivor.
        with LighthouseServer(
            min_replicas=2, join_timeout_ms=5000, heartbeat_timeout_ms=60000
        ) as server:
            incarnations = [f"victim:{i}" for i in range(5)]
            for inc in incarnations:
                results = _concurrent_quorums(
                    server.address(),
                    [{"replica_id": "survivor:aaa"}, {"replica_id": inc}],
                )
                assert isinstance(results[inc], Quorum), results
            # every superseded incarnation is permanently rejected
            for inc in incarnations[:-1]:
                res = _concurrent_quorums(
                    server.address(), [{"replica_id": inc}], timeout=2.0
                )
                assert isinstance(res[inc], Exception), (inc, res)
                assert "superseded" in str(res[inc])
            # the latest one still forms quorum fast
            start = time.monotonic()
            results = _concurrent_quorums(
                server.address(),
                [{"replica_id": "survivor:aaa"},
                 {"replica_id": incarnations[-1]}],
            )
            assert [p.replica_id for p in results[incarnations[-1]].participants] == [
                "survivor:aaa", incarnations[-1],
            ]
            assert time.monotonic() - start < 2.0

    def test_restart_storm_soak_no_livelock(self):
        """Soak (VERDICT r4 item 9): 20 rapid kill/restart cycles of one
        logical replica under a tight 2 s quorum timeout, with the
        survivor continuously re-requesting quorum AND each superseded
        zombie retrying concurrently.  Must finish well under 60 s with
        monotone quorum_id growth and no mutual-eviction livelock (every
        new incarnation forms a quorum; every zombie retry is rejected)."""
        CYCLES = 20
        with LighthouseServer(
            min_replicas=2, join_timeout_ms=200, heartbeat_timeout_ms=60000
        ) as server:
            stop = threading.Event()
            survivor_ids: "list[int]" = []
            survivor_errs: "list[Exception]" = []

            def survivor_loop():
                client = LighthouseClient(server.address())
                try:
                    while not stop.is_set():
                        try:
                            q = client.quorum(
                                replica_id="survivor:aaa", timeout=2.0
                            )
                            survivor_ids.append(q.quorum_id)
                        except Exception as e:  # noqa: BLE001
                            # timeouts while the storm churns are fine;
                            # anything else is collected for the assert
                            if not isinstance(e, TimeoutError) and (
                                "timed out" not in str(e).lower()
                                and "timeout" not in str(e).lower()
                            ):
                                survivor_errs.append(e)
                                return
                finally:
                    client.close()

            t = threading.Thread(target=survivor_loop, daemon=True)
            t.start()
            t0 = time.monotonic()
            zombie_retries: "list[threading.Thread]" = []
            try:
                for i in range(CYCLES):
                    inc = f"victim:{i}"
                    client = LighthouseClient(server.address())
                    try:
                        q = client.quorum(replica_id=inc, timeout=2.0)
                        assert isinstance(q, Quorum)
                        assert inc in [p.replica_id for p in q.participants]
                    finally:
                        client.close()
                    if i > 0:
                        # the just-killed incarnation's zombie retries in
                        # the background, racing the next cycle
                        def zombie(prev=f"victim:{i-1}"):
                            c = LighthouseClient(server.address())
                            try:
                                c.quorum(replica_id=prev, timeout=2.0)
                            except Exception:  # noqa: BLE001 - expected
                                pass
                            finally:
                                c.close()

                        zt = threading.Thread(target=zombie, daemon=True)
                        zt.start()
                        zombie_retries.append(zt)
            finally:
                stop.set()
                t.join(timeout=10)
                for zt in zombie_retries:
                    zt.join(timeout=5)
            elapsed = time.monotonic() - t0
            assert elapsed < 60.0, f"storm took {elapsed:.1f}s"
            assert not survivor_errs, survivor_errs
            # monotone quorum_id growth across the survivor's observations
            assert survivor_ids == sorted(survivor_ids), survivor_ids
            # the storm churned membership: id must have grown
            assert survivor_ids and survivor_ids[-1] > survivor_ids[0]
            # Aftermath: latest incarnation + survivor still form quorum.
            # One retry allowed: the storm's final in-flight handler (its
            # client is dead, but the server-side wait lives to its RPC
            # deadline) can re-register and absorb one quorum formation —
            # a requester that misses it re-requests, exactly like the
            # Manager does every step.
            start = time.monotonic()
            for attempt in range(2):
                results = _concurrent_quorums(
                    server.address(),
                    [{"replica_id": "survivor:aaa"},
                     {"replica_id": f"victim:{CYCLES-1}"}],
                    timeout=5.0,
                )
                if all(isinstance(v, Quorum) for v in results.values()):
                    break
            assert all(
                isinstance(v, Quorum) for v in results.values()
            ), results
            assert time.monotonic() - start < 15.0

    def test_timed_out_requester_leaves_no_ghost_participant(self):
        """A quorum handler that exits on timeout must take its
        registration with it: a later peer's request must NOT pair with
        the dead requester's leftover entry (that 'ghost' satisfied the
        formation barrier with nobody behind it — the repeating 5 s miss
        the storm soak exposed).  After lone replica 'a' times out, a
        lone request from 'b' must also time out (no quorum can form
        with just one live requester at min_replicas=2), not receive a
        quorum containing the departed 'a'."""
        with LighthouseServer(
            min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=60000
        ) as server:
            res_a = _concurrent_quorums(
                server.address(), [{"replica_id": "a"}], timeout=1.0
            )
            assert isinstance(res_a["a"], Exception), res_a
            # b arrives AFTER a's server-side handler exits: the handler
            # deregisters at its deadline check, which under load can wake
            # up to a wait slice late — poll the dashboard until the
            # registration is actually gone instead of sleeping a guess
            status_client = LighthouseClient(server.address())

            def wait_deregistered():
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    if status_client.status()["num_participants"] == 0:
                        return
                    time.sleep(0.05)
                raise AssertionError(
                    "timed-out requester's registration never cleared"
                )

            wait_deregistered()
            res_b = _concurrent_quorums(
                server.address(), [{"replica_id": "b"}], timeout=1.5
            )
            assert isinstance(res_b["b"], Exception), (
                "ghost participant: a timed-out requester's registration "
                f"formed a quorum for a lone later peer: {res_b}"
            )
            # b's own lone request leaves a server-side handler alive to
            # ITS deadline too — wait for that deregistration as well, or
            # the final round races b's ghost the same way
            wait_deregistered()
            status_client.close()
            # both live -> quorum forms normally
            res = _concurrent_quorums(
                server.address(),
                [{"replica_id": "a"}, {"replica_id": "b"}],
            )
            assert isinstance(res["a"], Quorum) and isinstance(res["b"], Quorum)

    def test_evicted_incarnation_cannot_evict_successor(self):
        # Supersession is one-directional: once evicted, the old incarnation
        # can never re-register — a zombie's quorum retry is rejected with
        # 'superseded' instead of evicting the legitimate successor (which
        # would make the two incarnations mutually evict forever).
        with LighthouseServer(
            min_replicas=2, join_timeout_ms=5000, heartbeat_timeout_ms=60000
        ) as server:
            _concurrent_quorums(
                server.address(),
                [{"replica_id": "survivor:aaa"}, {"replica_id": "victim:old"}],
            )
            results = _concurrent_quorums(
                server.address(),
                [{"replica_id": "survivor:aaa"}, {"replica_id": "victim:new"}],
            )
            assert isinstance(results["victim:new"], Quorum)

            # the zombie predecessor retries its quorum RPC
            res = _concurrent_quorums(
                server.address(), [{"replica_id": "victim:old"}], timeout=2.0
            )
            assert isinstance(res["victim:old"], Exception), res
            assert "superseded" in str(res["victim:old"])

            # the successor is unaffected: the next round still forms fast
            start = time.monotonic()
            results = _concurrent_quorums(
                server.address(),
                [{"replica_id": "survivor:aaa"}, {"replica_id": "victim:new"}],
            )
            elapsed = time.monotonic() - start
            assert [
                p.replica_id for p in results["victim:new"].participants
            ] == ["survivor:aaa", "victim:new"]
            assert elapsed < 2.0, f"successor quorum took {elapsed:.1f}s"
