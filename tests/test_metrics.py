"""Telemetry-layer unit tests: registry thread-safety, histogram bucket
math, Prometheus text-format round-trip, the scrape server, and the OTLP
metrics/traces JSON encodings against an in-process fake collector (the
same no-egress pattern as tests/test_otel.py)."""

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from torchft_tpu.utils.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsHTTPServer,
    OTLPMetricsExporter,
    Registry,
    counter,
    gauge,
    histogram,
    parse_text_exposition,
)
from torchft_tpu.utils.tracing import (
    OTLPHTTPSpanExporter,
    Tracer,
    new_span_id,
    new_trace_id,
)


class _FakeCollector:
    """Records every POST body by path (OTLP metrics + traces)."""

    def __init__(self, status: int = 200):
        self.requests = []
        self.status = status
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                body = self.rfile.read(int(self.headers["Content-Length"]))
                outer.requests.append(
                    {"path": self.path, "body": json.loads(body)}
                )
                self.send_response(outer.status)
                self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self._srv.server_address[1]}"

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


@pytest.fixture
def collector():
    c = _FakeCollector()
    yield c
    c.close()


class TestRegistry:
    def test_counter_gauge_basics(self):
        reg = Registry()
        c = Counter("c_total", "a counter", registry=reg)
        g = Gauge("g", "a gauge", registry=reg)
        c.inc()
        c.inc(2.5)
        g.set(7)
        g.dec(3)
        assert c.get() == 3.5
        assert g.get() == 4
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_labeled_children_and_aggregate(self):
        reg = Registry()
        c = Counter("jobs_total", "jobs", ("queue",), registry=reg)
        c.labels(queue="a").inc()
        c.labels(queue="a").inc()
        c.labels(queue="b").inc(3)
        # unlabeled family series aggregates across children
        assert c.get() == 5
        assert c.labels(queue="a").get() == 2
        with pytest.raises(ValueError):
            c.labels(wrong="x")

    def test_name_collision_and_get_or_create(self):
        reg = Registry()
        a = counter("dup_total", "h", registry=reg)
        assert counter("dup_total", "h", registry=reg) is a
        with pytest.raises(ValueError):
            gauge("dup_total", "h", registry=reg)
        with pytest.raises(ValueError):
            counter("dup_total", "h", ("lbl",), registry=reg)
        with pytest.raises(ValueError):
            Counter("bad name", "h", registry=reg)
        with pytest.raises(ValueError):
            Counter("ok_total", "h", ("le",), registry=reg)

    def test_thread_safety_concurrent_increments(self):
        reg = Registry()
        c = Counter("race_total", "r", ("worker",), registry=reg)
        h = Histogram("race_seconds", "r", registry=reg)
        n, threads = 2000, 8

        def worker(i):
            child = c.labels(worker=str(i % 2))
            for _ in range(n):
                child.inc()
                h.observe(0.01)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.get() == n * threads
        assert c.labels(worker="0").get() == n * threads / 2
        assert h.get()["count"] == n * threads

    def test_histogram_bucket_math(self):
        reg = Registry()
        h = Histogram(
            "lat_seconds", "l", buckets=(0.1, 1.0, 10.0), registry=reg
        )
        for v in (0.05, 0.1, 0.5, 5.0, 100.0):
            h.observe(v)
        snap = h.get()
        # le is inclusive: 0.1 lands in the 0.1 bucket
        assert snap["buckets"] == [2, 3, 4, 5]  # cumulative, +Inf last
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(105.65)

    def test_default_buckets_exponential(self):
        assert DEFAULT_BUCKETS[0] == pytest.approx(0.001)
        ratios = [
            b / a for a, b in zip(DEFAULT_BUCKETS, DEFAULT_BUCKETS[1:])
        ]
        assert all(r == pytest.approx(2.0) for r in ratios)


class TestExposition:
    def test_render_round_trip(self):
        reg = Registry()
        c = Counter("rt_total", "round trip", ("replica_id",), registry=reg)
        g = Gauge("rt_gauge", "a gauge", registry=reg)
        h = Histogram(
            "rt_seconds", "hist", ("phase",), buckets=(0.5, 1.5), registry=reg
        )
        c.labels(replica_id="r0:uuid").inc(4)
        g.set(-2.5)
        h.labels(phase="commit").observe(1.0)
        fams = parse_text_exposition(reg.render())
        assert fams["rt_total"]["type"] == "counter"
        assert fams["rt_total"]["help"] == "round trip"
        assert (
            fams["rt_total"]["samples"][
                ("rt_total", (("replica_id", "r0:uuid"),))
            ]
            == 4
        )
        # aggregate series present too
        assert fams["rt_total"]["samples"][("rt_total", ())] == 4
        assert fams["rt_gauge"]["samples"][("rt_gauge", ())] == -2.5
        hs = fams["rt_seconds"]["samples"]
        assert hs[("rt_seconds_bucket", (("phase", "commit"), ("le", "0.5")))] == 0
        assert hs[("rt_seconds_bucket", (("phase", "commit"), ("le", "1.5")))] == 1
        assert hs[("rt_seconds_bucket", (("phase", "commit"), ("le", "+Inf")))] == 1
        assert hs[("rt_seconds_count", (("phase", "commit"),))] == 1
        assert hs[("rt_seconds_sum", (("phase", "commit"),))] == 1.0

    def test_label_escaping_round_trip(self):
        reg = Registry()
        c = Counter("esc_total", "escapes", ("path",), registry=reg)
        # includes the literal-backslash-before-n case a sequential
        # str.replace unescape corrupts
        nasty = 'a"b\\c\nd\\ne'
        c.labels(path=nasty).inc()
        text = reg.render()
        fams = parse_text_exposition(text)  # strict parse must succeed
        assert (
            fams["esc_total"]["samples"][("esc_total", (("path", nasty),))]
            == 1
        )

    def test_parser_rejects_malformed(self):
        for bad in (
            "no_value_here\n",
            'x{unclosed="v} 1\n',
            "name 1\nname 2\n",  # duplicate sample
            "ok_metric notanumber\n",
        ):
            with pytest.raises(ValueError):
                parse_text_exposition(bad)

    def test_http_scrape_server(self):
        reg = Registry()
        Counter("srv_total", "s", registry=reg).inc(9)
        server = MetricsHTTPServer(port=0, registry=reg)
        try:
            body = (
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics", timeout=5
                )
                .read()
                .decode()
            )
        finally:
            server.close()
        fams = parse_text_exposition(body)
        assert fams["srv_total"]["samples"][("srv_total", ())] == 9

    def test_serve_from_env_gate(self, monkeypatch):
        from torchft_tpu.utils import metrics as m

        monkeypatch.delenv("TORCHFT_METRICS_PORT", raising=False)
        assert m.maybe_serve_from_env() is None


class TestOTLPMetrics:
    def test_encoding_against_stub(self, collector):
        reg = Registry()
        c = Counter("otlp_total", "c", ("replica_id",), registry=reg)
        c.labels(replica_id="r0").inc(3)
        Gauge("otlp_gauge", "g", registry=reg).set(1.5)
        h = Histogram("otlp_seconds", "h", buckets=(1.0, 2.0), registry=reg)
        h.observe(1.5)
        exp = OTLPMetricsExporter(
            collector.endpoint, registry=reg, interval_s=3600
        )
        try:
            assert exp.flush()
        finally:
            exp.close()
        req = collector.requests[0]
        assert req["path"] == "/v1/metrics"
        sm = req["body"]["resourceMetrics"][0]["scopeMetrics"][0]
        by_name = {m["name"]: m for m in sm["metrics"]}
        csum = by_name["otlp_total"]["sum"]
        assert csum["isMonotonic"] and csum["aggregationTemporality"] == 2
        # data points: aggregate (no attrs) + the labeled child
        vals = {
            tuple(
                (a["key"], a["value"]["stringValue"])
                for a in p["attributes"]
            ): p["asDouble"]
            for p in csum["dataPoints"]
        }
        assert vals[()] == 3.0
        assert vals[(("replica_id", "r0"),)] == 3.0
        assert by_name["otlp_gauge"]["gauge"]["dataPoints"][0]["asDouble"] == 1.5
        hp = by_name["otlp_seconds"]["histogram"]["dataPoints"][0]
        assert hp["explicitBounds"] == [1.0, 2.0]
        assert hp["bucketCounts"] == ["0", "1", "0"]  # per-bucket, not cum
        assert hp["count"] == "1"
        assert exp.exported == 1 and exp.dropped == 0

    def test_collector_down_never_raises(self):
        reg = Registry()
        Counter("down_total", "c", registry=reg).inc()
        exp = OTLPMetricsExporter(
            "http://127.0.0.1:9", registry=reg, interval_s=3600, timeout_s=0.5
        )
        try:
            assert exp.flush() is False
        finally:
            exp.close()
        assert exp.dropped == 1 and exp.exported == 0

    def test_export_from_env_gate(self, monkeypatch):
        from torchft_tpu.utils import metrics as m

        monkeypatch.delenv("TORCHFT_USE_OTEL", raising=False)
        assert m.maybe_export_from_env() is None


class TestOTLPTraces:
    def test_span_tree_encoding(self, collector):
        exp = OTLPHTTPSpanExporter(
            collector.endpoint, flush_interval_s=0.1
        )
        tracer = Tracer(exp)
        trace_id = new_trace_id()
        root = new_span_id()
        try:
            t0 = time.time_ns()
            tracer.export_span(
                name="quorum_rpc",
                trace_id=trace_id,
                parent_span_id=root,
                start_ns=t0,
                end_ns=t0 + 1_000_000,
                attributes={"step": 3, "quorum_id": 7, "replica_id": "r0"},
            )
            tracer.export_span(
                name="quorum_round",
                trace_id=trace_id,
                span_id=root,
                start_ns=t0,
                end_ns=t0 + 2_000_000,
                attributes={"step": 3, "quorum_id": 7, "commit_result": True},
            )
            assert exp.flush(timeout=5.0)
        finally:
            exp.close()
        req = collector.requests[0]
        assert req["path"] == "/v1/traces"
        spans = req["body"]["resourceSpans"][0]["scopeSpans"][0]["spans"]
        by_name = {s["name"]: s for s in spans}
        child, parent = by_name["quorum_rpc"], by_name["quorum_round"]
        assert len(parent["traceId"]) == 32 and len(parent["spanId"]) == 16
        assert child["traceId"] == parent["traceId"] == trace_id
        assert child["parentSpanId"] == parent["spanId"] == root
        assert "parentSpanId" not in parent
        attrs = {a["key"]: a["value"] for a in child["attributes"]}
        # the correlation keys shared with the structured-event pipeline
        assert attrs["step"] == {"intValue": "3"}
        assert attrs["quorum_id"] == {"intValue": "7"}
        assert exp.exported == 2 and exp.dropped == 0

    def test_collector_down_never_raises(self):
        exp = OTLPHTTPSpanExporter(
            "http://127.0.0.1:9", flush_interval_s=0.05, timeout_s=0.5
        )
        try:
            exp.export(
                {
                    "name": "x",
                    "trace_id": new_trace_id(),
                    "span_id": new_span_id(),
                    "start_ns": 1,
                    "end_ns": 2,
                }
            )
            deadline = time.monotonic() + 5.0
            while exp.dropped == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            exp.close()
        assert exp.dropped == 1 and exp.exported == 0

    def test_export_after_close_counts_dropped(self):
        exp = OTLPHTTPSpanExporter("http://127.0.0.1:9", timeout_s=0.5)
        exp.close()
        exp.export(
            {
                "name": "late",
                "trace_id": new_trace_id(),
                "span_id": new_span_id(),
                "start_ns": 1,
                "end_ns": 2,
            }
        )
        assert exp.dropped == 1


class TestNewEventKinds:
    def test_heal_and_reconfigure_are_valid_kinds(self):
        from torchft_tpu.utils.logging import log_event, recent_events

        log_event("heal", "healing peer", direction="recv", step=5)
        log_event("reconfigure", "pg reconfigured", quorum_id=2)
        kinds = [e["kind"] for e in recent_events()[-2:]]
        assert kinds == ["heal", "reconfigure"]
        with pytest.raises(ValueError):
            log_event("bogus", "nope")

    def test_otel_severity_covers_every_kind(self):
        from torchft_tpu.utils.logging import _LOGGERS
        from torchft_tpu.utils.otel import _SEVERITY

        assert set(_SEVERITY) == set(_LOGGERS)
