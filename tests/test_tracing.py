"""Unit tests for the distributed-tracing layer (utils/tracing.py):
context encoding, deterministic per-step trace ids, sampling, the JSONL
file sink, thread-local propagation state, and the zero-cost budget of
the disabled path (same bar discipline as the flight recorder's)."""

import json
import os
import threading
import time

import pytest

from torchft_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _clean_tracer():
    tracing.uninstall_tracer()
    yield
    tracing.uninstall_tracer()


class TestTraceContext:
    def test_traceparent_round_trip(self):
        ctx = tracing.TraceContext(
            tracing.new_trace_id(), tracing.new_span_id(), True
        )
        tp = ctx.to_traceparent()
        assert tp.startswith("00-") and tp.endswith("-01")
        back = tracing.TraceContext.from_traceparent(tp)
        assert back == ctx

    def test_unsampled_flag(self):
        ctx = tracing.TraceContext("a" * 32, "b" * 16, sampled=False)
        assert ctx.to_traceparent().endswith("-00")
        back = tracing.TraceContext.from_traceparent(ctx.to_traceparent())
        assert back is not None and not back.sampled

    @pytest.mark.parametrize(
        "bad",
        [
            None,
            "",
            "garbage",
            "00-short-span-01",
            "00-" + "x" * 32 + "-" + "b" * 16 + "-01",  # non-hex trace
            "00-" + "a" * 31 + "_" + "-" + "b" * 16 + "-01",  # underscore
            "00-" + "a" * 32 + "-" + "b" * 15 + "-01",  # short span
            "00-" + "a" * 32 + "-" + "b" * 16 + "-0",  # short flags
            "00-" + "a" * 32 + "-" + "b" * 16 + "-zz",  # non-hex flags
            "00-" + "a" * 32 + "-" + "b" * 16 + "-01-extra",
            42,
        ],
    )
    def test_malformed_traceparent_parses_to_none(self, bad):
        assert tracing.TraceContext.from_traceparent(bad) is None

    def test_child_keeps_trace_changes_span(self):
        ctx = tracing.TraceContext("a" * 32, "b" * 16)
        kid = ctx.child()
        assert kid.trace_id == ctx.trace_id
        assert kid.span_id != ctx.span_id

    def test_step_trace_id_deterministic_and_distinct(self):
        assert tracing.step_trace_id(7) == tracing.step_trace_id(7)
        assert tracing.step_trace_id(7) != tracing.step_trace_id(8)
        assert tracing.step_trace_id(7, "jobA") != tracing.step_trace_id(
            7, "jobB"
        )
        assert len(tracing.step_trace_id(0)) == 32
        int(tracing.step_trace_id(0), 16)  # valid hex


class TestSampling:
    def test_extremes(self):
        always = tracing.Tracer(sample=1.0)
        never = tracing.Tracer(sample=0.0)
        assert all(always.sample_step(s) for s in range(50))
        assert not any(never.sample_step(s) for s in range(50))

    def test_deterministic_across_instances(self):
        """Every replica must make the SAME per-step decision — a sampled
        step's trace is complete or absent, never partial."""
        a = tracing.Tracer(sample=0.5)
        b = tracing.Tracer(sample=0.5)
        decisions = [a.sample_step(s, "job") for s in range(200)]
        assert decisions == [b.sample_step(s, "job") for s in range(200)]
        # a half-rate sampler actually samples some and skips some
        assert 20 < sum(decisions) < 180


class TestFileSpanSink:
    def test_jsonl_round_trip(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        tracer = tracing.Tracer(sink=tracing.FileSpanSink(str(path)))
        sid = tracer.export_span(
            "ring", "a" * 32, 100, 200,
            parent_span_id="b" * 16,
            attributes={"step": 3, "replica_id": "r0"},
        )
        tracer.export_span("commit", "a" * 32, 200, 300, ok=False)
        tracer.close()
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert len(lines) == 2
        assert lines[0]["name"] == "ring"
        assert lines[0]["span_id"] == sid
        assert lines[0]["parent_span_id"] == "b" * 16
        assert lines[0]["attributes"]["step"] == 3
        assert lines[1]["ok"] is False

    def test_append_across_sinks(self, tmp_path):
        """Two sinks on one path (≈ two processes sharing the file) must
        append, not clobber — the O_APPEND contract."""
        path = tmp_path / "trace.jsonl"
        for i in range(2):
            sink = tracing.FileSpanSink(str(path))
            sink.export({"name": f"s{i}", "trace_id": "t", "span_id": "x",
                         "start_ns": 0, "end_ns": 1, "ok": True})
            sink.close()
        lines = path.read_text().splitlines()
        assert len(lines) == 2

    def test_closed_sink_drops_instead_of_reopening(self, tmp_path):
        """A racing emitter that grabbed the tracer before uninstall must
        not resurrect the file after close() (that fd would leak)."""
        path = tmp_path / "trace.jsonl"
        sink = tracing.FileSpanSink(str(path))
        sink.export({"name": "ring", "trace_id": "t", "span_id": "s",
                     "start_ns": 0, "end_ns": 1, "ok": True})
        sink.close()
        sink.export({"name": "late", "trace_id": "t", "span_id": "s2",
                     "start_ns": 0, "end_ns": 1, "ok": True})
        assert len(path.read_text().splitlines()) == 1

    def test_env_install(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHFT_TRACE_FILE", str(tmp_path / "t.jsonl"))
        monkeypatch.setenv("TORCHFT_TRACE_SAMPLE", "0.25")
        tracer = tracing.maybe_install_from_env()
        assert tracer is not None
        assert tracer.sink is not None and tracer.exporter is None
        assert tracer.sample == 0.25
        assert tracing.get_tracer() is tracer

    def test_env_disabled(self, monkeypatch):
        monkeypatch.delenv("TORCHFT_TRACE_FILE", raising=False)
        monkeypatch.delenv("TORCHFT_USE_OTEL", raising=False)
        assert tracing.maybe_install_from_env() is None


class TestCurrentContext:
    def test_no_tracer_means_no_context(self):
        tracing.set_current(tracing.TraceContext("a" * 32, "b" * 16))
        try:
            # fast path: without an installed tracer nothing propagates
            assert tracing.get_current() is None
            assert tracing.current_traceparent() is None
        finally:
            tracing.set_current(None)

    def test_thread_local(self, tmp_path):
        tracing.install_tracer(
            tracing.Tracer(sink=tracing.FileSpanSink(str(tmp_path / "t")))
        )
        ctx = tracing.TraceContext("a" * 32, "b" * 16)
        tracing.set_current(ctx)
        seen = {}

        def other():
            seen["ctx"] = tracing.get_current()

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert seen["ctx"] is None  # contexts do not leak across threads
        assert tracing.get_current() == ctx
        assert tracing.current_traceparent() == ctx.to_traceparent()
        tracing.set_current(None)

    def test_unsampled_context_not_injected(self, tmp_path):
        tracing.install_tracer(
            tracing.Tracer(sink=tracing.FileSpanSink(str(tmp_path / "t")))
        )
        tracing.set_current(
            tracing.TraceContext("a" * 32, "b" * 16, sampled=False)
        )
        assert tracing.current_traceparent() is None
        tracing.set_current(None)


class TestDisabledPathBudget:
    def test_disabled_injection_is_zero_cost(self):
        """Acceptance bar: the disabled hot path (no tracer installed) —
        exactly what every RPC call and collective submit runs — must be
        a single module-global check, ≤ the flight recorder's record()
        budget (2.5 us; this is ~50 ns in practice).  Best-of-batches so
        a loaded CI host doesn't flake the measurement."""
        assert tracing.get_tracer() is None
        n = 50_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                tracing.current_traceparent()
                tracing.get_current()
            best = min(best, (time.perf_counter() - t0) / n)
        assert best <= 2.5e-6, f"disabled trace path {best * 1e9:.0f} ns/call"

    def test_disabled_sampling_check_is_cheap(self):
        """Manager.start_quorum's disabled path is one get_tracer() call."""
        assert tracing.get_tracer() is None
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            if tracing.get_tracer() is not None:  # pragma: no cover
                raise AssertionError
        per = (time.perf_counter() - t0) / n
        assert per <= 1e-6, f"get_tracer {per * 1e9:.0f} ns/call"
