"""Packaging: pip-installable project with console scripts and a native
build step (reference analog: /root/reference/pyproject.toml
[project.scripts] + build.rs; here setuptools + native/Makefile)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestPackaging:
    def test_pyproject_declares_package_and_script(self):
        text = open(os.path.join(REPO, "pyproject.toml")).read()
        assert 'name = "torchft-tpu"' in text
        assert "torchft-tpu-lighthouse" in text
        assert "torchft_tpu.lighthouse:main" in text

    def test_lighthouse_console_entry_callable(self):
        # the console script target must be importable and behave as a CLI
        from torchft_tpu.lighthouse import main

        with pytest.raises(SystemExit) as e:
            main(["--help"])
        assert e.value.code == 0

    def test_diagnose_console_entry_callable(self):
        # torchft-diagnose rides the same [project.scripts] wiring
        text = open(os.path.join(REPO, "pyproject.toml")).read()
        assert "torchft_tpu.diagnose:main" in text
        from torchft_tpu.diagnose import main

        with pytest.raises(SystemExit) as e:
            main(["--help"])
        assert e.value.code == 0

    def test_tft_lint_console_entry_callable(self):
        # tft-lint (torchft_tpu/analysis/) ships as a console script too
        text = open(os.path.join(REPO, "pyproject.toml")).read()
        assert 'tft-lint = "torchft_tpu.analysis.cli:main"' in text
        from torchft_tpu.analysis.cli import main

        with pytest.raises(SystemExit) as e:
            main(["--help"])
        assert e.value.code == 0
        # the baseline data files ship in the wheel
        assert "analysis/baselines/*.txt" in text

    def test_tft_verify_console_entry_callable(self):
        # tft-verify (model checker + wire-schema lock) ships alongside
        text = open(os.path.join(REPO, "pyproject.toml")).read()
        assert 'tft-verify = "torchft_tpu.analysis.verify_cli:main"' in text
        from torchft_tpu.analysis.verify_cli import main

        with pytest.raises(SystemExit) as e:
            main(["--help"])
        assert e.value.code == 0

    def test_protocol_lock_ships_as_package_data(self):
        # the committed wire-schema lock must ride the wheel: it is the
        # machine-readable wire contract installed consumers read via
        # wire_schema.default_lock_path()/load_lock() (the full --drift
        # cross-check needs the native sources, i.e. a repo checkout)
        text = open(os.path.join(REPO, "pyproject.toml")).read()
        assert "analysis/protocol.lock" in text
        lock = os.path.join(REPO, "torchft_tpu", "analysis", "protocol.lock")
        assert os.path.isfile(lock)
        import json

        doc = json.load(open(lock, encoding="utf-8"))
        assert doc["version"] >= 1 and "servers" in doc and "structs" in doc

    def test_native_lib_search_order(self, monkeypatch):
        from torchft_tpu import _native

        # explicit override wins and must exist
        monkeypatch.setenv("TORCHFT_NATIVE_LIB", "/nonexistent/lib.so")
        with pytest.raises(FileNotFoundError):
            _native._find_lib()
        monkeypatch.delenv("TORCHFT_NATIVE_LIB")
        # repo layout resolves (and is already built by the session)
        path = _native._find_lib()
        assert path.endswith("libtorchft_tpu_native.so") and os.path.exists(path)

    def test_wheel_metadata_buildable(self):
        # `pip install -e .` ran in CI/dev is the real check; here assert
        # the setuptools entry point wiring stays importable
        import importlib.metadata as md

        try:
            eps = md.entry_points(group="console_scripts")
        except TypeError:  # older API
            eps = md.entry_points()["console_scripts"]
        names = {e.name for e in eps}
        if "torchft-tpu-lighthouse" not in names:
            pytest.skip("package not pip-installed in this environment")
        (ep,) = [e for e in eps if e.name == "torchft-tpu-lighthouse"]
        assert ep.value == "torchft_tpu.lighthouse:main"
