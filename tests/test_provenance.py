"""Fragment provenance plane (ISSUE 18): the per-fragment version
vector's semantics (newest-version-wins, dirty consume/restore, bounded
digests), the hop-audit ring (bounded, crash-durable ``.prov`` companion
dumps), the heartbeat-digest -> lighthouse version matrix ->
/fragments.json aggregation round trip at fleet scale, and
``torchft-diagnose --fragment`` rebuilding a journey from the dumps
alone."""

import json
import urllib.request

import pytest

from torchft_tpu.checkpointing import provenance
from torchft_tpu.checkpointing.provenance import PROV, frag_id
from torchft_tpu.coordination import LighthouseClient, LighthouseServer
from torchft_tpu.utils import flightrecorder as _flightrec


@pytest.fixture(autouse=True)
def _fresh_registry(monkeypatch):
    monkeypatch.setenv("TORCHFT_FRAG_REPORT_S", "0")
    PROV.reset()
    yield
    PROV.reset()


class TestFragId:
    def test_identity_is_payload_slash_index(self):
        assert frag_id("weights", 3) == "weights/3"
        assert frag_id("heal", "7") == "heal/7"


class TestVersionVector:
    def test_newest_version_wins_and_stale_rehold_never_regresses(self):
        PROV.note_hold("weights/0", 5, digest="aaaa1111", version_ms=500)
        PROV.note_hold("weights/0", 3, digest="bbbb2222", version_ms=300)
        row = PROV.snapshot()["weights/0"]
        assert row["version"] == 5
        assert row["digest8"] == "aaaa1111"
        assert row["version_ms"] == 500

    def test_publisher_flag_sticks(self):
        PROV.note_hold("weights/0", 1, publisher=True)
        PROV.note_hold("weights/0", 2, publisher=False)
        assert PROV.snapshot()["weights/0"]["pub"] is True

    def test_digest_consumed_on_send(self):
        # version_ms=0 keeps the row out of the always-reported
        # worst-K-stalest tier, so the second digest must be empty
        PROV.note_hold("weights/0", 1)
        d = PROV.maybe_digest("h0")
        assert d is not None and d["host"] == "h0"
        assert [r["frag"] for r in d["frags"]] == ["weights/0"]
        assert PROV.maybe_digest("h0") is None

    def test_restore_digest_re_reports_on_next_beat(self):
        PROV.note_hold("weights/0", 1)
        d = PROV.maybe_digest("h0")
        assert d is not None
        assert PROV.maybe_digest("h0") is None
        PROV.restore_digest(d)  # the RPC failed: hand the digest back
        d2 = PROV.maybe_digest("h0")
        assert d2 is not None
        assert [r["frag"] for r in d2["frags"]] == ["weights/0"]

    def test_stamped_worst_k_always_reports(self):
        # a stamped fragment is fleet-staleness input: it re-reports
        # every digest even with nothing dirty
        PROV.note_hold("weights/0", 1, version_ms=1000)
        assert PROV.maybe_digest("h0") is not None
        assert PROV.maybe_digest("h0") is not None

    def test_rate_limit_holds_back_digests(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_FRAG_REPORT_S", "3600")
        PROV.reset()
        PROV.note_hold("weights/0", 1, version_ms=1000)
        assert PROV.maybe_digest("h0") is not None
        PROV.note_hold("weights/1", 1, version_ms=1000)
        assert PROV.maybe_digest("h0") is None  # not due yet

    def test_digest_is_hard_capped_at_8x_topk(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_FRAG_TOPK", "4")
        PROV.reset()
        for i in range(200):
            PROV.note_hold(f"weights/{i}", 1, version_ms=1000 + i)
        d = PROV.maybe_digest("h0")
        assert d is not None
        assert len(d["frags"]) <= 8 * 4

    def test_frag_topk_label_is_bounded(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_FRAG_TOPK", "4")
        PROV.reset()
        labels = {PROV.frag_topk_label(f"weights/{i}") for i in range(32)}
        assert "other" in labels
        assert len(labels) <= 4 + 1  # first-K names + the fold tier


class TestHopRing:
    def test_ring_is_bounded(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_FRAG_RING", "16")
        PROV.reset()
        for i in range(100):
            PROV.note_hop("weights/0", i, "http://src:1", "serving")
        assert len(PROV.hop_records()) <= 16

    def test_hop_record_carries_the_audit_fields(self):
        PROV.set_holder("me:1")
        PROV.note_hop(
            "weights/0", 7, "http://src:1", "heal",
            verdict="mismatch", nbytes=4096, first_byte_ms=1.25,
        )
        (rec,) = PROV.hop_records()
        assert rec["op"] == "fragment.hop"
        assert rec["status"] == "error"  # mismatch is an error hop
        assert rec["frag"] == "weights/0"
        assert rec["version"] == 7
        assert rec["source"] == "http://src:1"
        assert rec["plane"] == "heal"
        assert rec["verdict"] == "mismatch"
        assert rec["bytes"] == 4096
        assert rec["first_byte_ms"] == 1.25
        assert rec["holder"] == "me:1"

    def test_hold_records_join_the_ring(self):
        PROV.note_hold("weights/0", 3, digest="ff00ff00", version_ms=10,
                       role="relay")
        (rec,) = PROV.hop_records()
        assert rec["op"] == "fragment.hold"
        assert rec["role"] == "relay"
        assert rec["digest8"] == "ff00ff00"


class TestCompanionDump:
    def test_explicit_dump_writes_flight_format_jsonl(self, tmp_path):
        PROV.note_hold("weights/0", 1, version_ms=10)
        PROV.note_hop("weights/0", 1, "http://src:1", "serving")
        out = tmp_path / "prov.jsonl"
        assert PROV.dump("test", path=str(out)) == str(out)
        lines = [json.loads(ln) for ln in out.read_text().splitlines()]
        assert lines[0]["flight"] == "meta"
        assert {ln["op"] for ln in lines[1:]} == {
            "fragment.hold", "fragment.hop",
        }

    def test_process_flight_dump_cascades_to_prov(self, tmp_path,
                                                  monkeypatch):
        """One crash trigger freezes BOTH rings: dumping the process
        recorder leaves <target>.prov next to <target>."""
        PROV.note_hop("weights/0", 1, "http://src:1", "serving")
        target = tmp_path / "flight.jsonl"
        _flightrec.RECORDER.record("test.op")
        assert _flightrec.RECORDER.dump("test", path=str(target))
        prov_path = tmp_path / "flight.jsonl.prov"
        assert prov_path.exists()
        recs = [json.loads(ln) for ln in prov_path.read_text().splitlines()]
        assert any(r.get("op") == "fragment.hop" for r in recs[1:])

    def test_private_ring_dump_does_not_cascade(self, tmp_path):
        priv = _flightrec.FlightRecorder(capacity=16)
        priv.record("x")
        target = tmp_path / "private.jsonl"
        assert priv.dump("test", path=str(target))
        assert not (tmp_path / "private.jsonl.prov").exists()

    def test_diagnose_rebuilds_the_journey_from_the_dump_alone(
        self, tmp_path
    ):
        """note_hop records -> .prov dump -> torchft-diagnose names the
        FIRST mismatch hop's source as poisoned_hop (downstream victims
        are not culprits)."""
        from torchft_tpu import diagnose

        PROV.note_hold("weights/2", 9, digest="deadbeef", version_ms=10,
                       role="publisher", publisher=True)
        PROV.note_hop("weights/2", 9, "http://pub:1", "serving",
                      verdict="ok", nbytes=100)
        PROV.note_hop("weights/2", 9, "http://relay:2", "serving",
                      verdict="mismatch", nbytes=100)
        PROV.note_hop("weights/2", 9, "http://relay:2", "serving",
                      verdict="mismatch", nbytes=100)
        out = tmp_path / "x.prov"
        PROV.dump("test", path=str(out))
        entries, _skipped = diagnose.load_records([str(out)])
        report = diagnose.analyze_fragment(entries, "weights/2")
        assert report["hops"] == 3 and report["holds"] == 1
        culprit = report["culprit"]
        assert culprit is not None
        assert culprit["signal"] == "poisoned_hop"
        assert culprit["replica_id"] == "http://relay:2"
        assert diagnose.render_fragment_text(report)


def _frag_digest(host, nfrags=16, version=3, base_ms=1_000_000):
    return {
        "host": host,
        "frags": [
            {
                "frag": f"weights/{j}", "version": version,
                "digest8": f"{j:08x}", "version_ms": base_ms + j,
                "held_ms": base_ms + j,
            }
            for j in range(nfrags)
        ],
    }


class TestFleetMatrix:
    def test_upsert_never_wipes_unreported_rows(self):
        """Provenance digests are PARTIAL: a later report for one frag
        must not drop the host's other rows (unlike the links wipe-all
        fold)."""
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                c.heartbeat("r0", fragments={"host": "h0", "frags": [
                    {"frag": "weights/0", "version": 1,
                     "digest8": "a" * 8, "version_ms": 100},
                ]})
                c.heartbeat("r0", fragments={"host": "h0", "frags": [
                    {"frag": "weights/1", "version": 2,
                     "digest8": "b" * 8, "version_ms": 200},
                ]})
                doc = c.fragments()
                frags = {r["frag"]: r for r in doc["rows"]}
                assert set(frags) == {"weights/0", "weights/1"}
                assert doc["reports_total"] == 2
            finally:
                c.close()

    def test_version_regression_is_skipped(self):
        """A late-restored digest can arrive out of order: an older
        version never rolls a row backwards."""
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                c.heartbeat("r0", fragments={"host": "h0", "frags": [
                    {"frag": "weights/0", "version": 5,
                     "digest8": "new00000", "version_ms": 500},
                ]})
                c.heartbeat("r0", fragments={"host": "h0", "frags": [
                    {"frag": "weights/0", "version": 3,
                     "digest8": "old00000", "version_ms": 300},
                ]})
                (row,) = c.fragments()["rows"]
                assert row["version"] == 5
                assert row["digest8"] == "new00000"
            finally:
                c.close()

    def test_staleness_is_skew_free_and_unknown_is_minus_one(self):
        """staleness = latest publish stamp for that frag minus the held
        stamp — two stamps from ONE clock.  A missing stamp reads -1 and
        never joins the worst-K ranking."""
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                c.heartbeat("r0", fragments={"host": "pub", "frags": [
                    {"frag": "weights/0", "version": 4,
                     "digest8": "d" * 8, "version_ms": 10_000, "pub": True},
                ]})
                c.heartbeat("r1", fragments={"host": "lag", "frags": [
                    {"frag": "weights/0", "version": 3,
                     "digest8": "c" * 8, "version_ms": 7_500},
                ]})
                c.heartbeat("r2", fragments={"host": "mystery", "frags": [
                    {"frag": "weights/0", "version": 3,
                     "digest8": "c" * 8, "version_ms": 0},
                ]})
                doc = c.fragments()
                rows = {r["host"]: r for r in doc["rows"]}
                assert rows["pub"]["staleness_ms"] == 0
                assert rows["lag"]["staleness_ms"] == 2_500
                assert rows["mystery"]["staleness_ms"] == -1
                stale_hosts = [s["host"] for s in doc["stalest"]]
                assert "mystery" not in stale_hosts
                assert stale_hosts[0] == "lag"
            finally:
                c.close()

    def test_serving_heartbeat_carries_the_digest_too(self):
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                c.serving_heartbeat(
                    "srv0", "http://x:1", role="server", version=2,
                    capacity=1,
                    fragments={"host": "sh0", "frags": [
                        {"frag": "weights/0", "version": 2,
                         "digest8": "e" * 8, "version_ms": 100},
                    ]},
                )
                doc = c.fragments()
                assert doc["hosts"] == 1
                assert doc["rows"][0]["host"] == "sh0"
            finally:
                c.close()

    def test_matrix_version_is_monotone(self):
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                c.heartbeat("r0", fragments=_frag_digest("h0", nfrags=1))
                v1 = c.fragments()["version"]
                c.heartbeat("r0", fragments=_frag_digest(
                    "h0", nfrags=1, version=4))
                assert c.fragments()["version"] > v1
            finally:
                c.close()

    def test_http_fragments_json_bounded_at_64_nodes(self):
        """The acceptance bar: 64 hosts x 16 fragments each — the
        default GET /fragments.json document stays under 16 KB while
        every held fragment's staleness is reachable by paging."""
        with LighthouseServer(min_replicas=1, join_timeout_ms=50) as srv:
            c = LighthouseClient(srv.address())
            try:
                for i in range(64):
                    c.heartbeat(f"r{i}", fragments=_frag_digest(
                        f"h{i:02d}", nfrags=16))
                raw = urllib.request.urlopen(
                    f"http://{srv.address()}/fragments.json", timeout=5
                ).read()
                assert len(raw) < 16 * 1024, (
                    f"/fragments.json default page is {len(raw)} B"
                )
                doc = json.loads(raw.decode())
                assert doc["rows_total"] == 64 * 16
                assert doc["hosts"] == 64
                assert doc["pages"] * doc["per_page"] >= 64 * 16
                # fleet truth survives pagination: walk every page via
                # the RPC and find a staleness verdict per held fragment
                seen = 0
                page, version = 0, doc["version"]
                while True:
                    pg = c.fragments(page=page, per_page=256)
                    assert pg["version"] == version
                    if not pg["rows"]:
                        break
                    for row in pg["rows"]:
                        assert "staleness_ms" in row
                        assert row["staleness_ms"] >= 0  # all stamped
                        seen += 1
                    page += 1
                assert seen == 64 * 16
            finally:
                c.close()


class TestPoisonedHopChaos:
    """ISSUE 18 acceptance: inject a digest mismatch at a mid-tree
    serving relay (and a torn durable-store blob) — ``torchft-diagnose
    --fragment`` names the injecting hop as ``poisoned_hop`` from the
    serialized ``.prov`` dumps ALONE (the live registry is reset before
    diagnosis)."""

    def _diagnose(self, capsys, prov_path, fid):
        from torchft_tpu import diagnose

        rc = diagnose.main(["--fragment", fid, "--json", str(prov_path)])
        out = capsys.readouterr().out
        assert rc == 0
        return json.loads(out)

    def test_mid_tree_relay_mismatch_named_from_dumps_alone(
        self, tmp_path, capsys
    ):
        import numpy as np

        from torchft_tpu.checkpointing.http_transport import HTTPTransport
        from torchft_tpu.serving import ServingReplica, encode_payload

        rng = np.random.RandomState(0)
        sd = {"w": rng.randn(8, 8).astype(np.float32)}
        doc = encode_payload(sd, 1, fragments=2)
        bad = dict(doc)
        raw = bytearray(doc["frag:0"])
        raw[-1] ^= 0xFF  # flip payload bytes, manifest digests untouched
        bad["frag:0"] = bytes(raw)
        poisoned = HTTPTransport(timeout=5.0)
        poisoned.send_checkpoint([], 1, bad, timeout=5)
        good = HTTPTransport(timeout=5.0)
        good.send_checkpoint([], 1, doc, timeout=5)
        lh = LighthouseServer(
            min_replicas=1, heartbeat_timeout_ms=1500, quorum_tick_ms=50
        )
        rep = ServingReplica(
            lh.address(), replica_id="victim", poll_interval=5.0,
            fetch_timeout=8.0,
        )
        try:
            # mid-tree: the victim relay's parent serves poisoned bytes;
            # the pull fails over to the clean root and completes
            rep._parent = poisoned.metadata()
            rep._root_source = good.metadata()
            rep._pull(1)
            assert rep.version() == 1
            prov_path = tmp_path / "flight.jsonl.prov"
            assert PROV.dump("chaos", path=str(prov_path))
        finally:
            rep.shutdown()
            poisoned.shutdown()
            good.shutdown()
            lh.shutdown()
        PROV.reset()  # attribution must need nothing live
        report = self._diagnose(capsys, prov_path, "weights/0")
        culprit = report["culprit"]
        assert culprit["signal"] == "poisoned_hop"
        assert culprit["replica_id"] == poisoned.metadata()
        assert culprit["verdict"] == "mismatch"
        assert culprit["plane"] == "serving"
        journey = report["fragment_journey"]
        assert journey["poisoned_hop"]["source"] == poisoned.metadata()
        # the clean root's ok hop is audited too but never blamed
        sources = {h["fields"]["source"] for h in journey["journey"]
                   if h["op"] == "fragment.hop"}
        assert good.metadata() in sources

    def test_torn_store_blob_named_from_dumps_alone(self, tmp_path,
                                                    capsys):
        import numpy as np

        from torchft_tpu.checkpointing.store import FragmentStore

        store = FragmentStore(str(tmp_path / "disk"), max_versions=0)
        manifest = store.put_state(
            3, {"w": np.arange(16, dtype=np.float32)}
        )
        name, digest = sorted(manifest["digests"].items())[0]
        blob = store.blob_path(digest)
        raw = bytearray(open(blob, "rb").read())
        raw[0] ^= 0xFF  # tear the blob under its content address
        with open(blob, "wb") as f:
            f.write(bytes(raw))
        assert store.fragment(3, name) is None  # torn: never served
        prov_path = tmp_path / "x.prov"
        assert PROV.dump("chaos", path=str(prov_path))
        PROV.reset()
        report = self._diagnose(capsys, prov_path, f"heal/{name}")
        culprit = report["culprit"]
        assert culprit["signal"] == "poisoned_hop"
        assert culprit["replica_id"] == f"disk:{store.directory}"
        assert culprit["verdict"] == "torn"
        assert culprit["plane"] == "restore"


class TestWiring:
    def test_production_planes_feed_the_registry(self):
        """Every fragment mover imports the provenance hooks — the
        wiring the chaos/e2e suites then exercise live."""
        import inspect

        from torchft_tpu.checkpointing import fragments as frag_mod
        from torchft_tpu.checkpointing import http_transport, store
        from torchft_tpu.serving import client as sclient
        from torchft_tpu.serving import publisher as spub
        from torchft_tpu.serving import replica as sreplica

        for mod in (frag_mod, http_transport, store, sclient, spub,
                    sreplica):
            src = inspect.getsource(mod)
            assert "provenance" in src, mod.__name__

    def test_module_shorthands_bind_the_global_registry(self):
        assert provenance.note_hold.__self__ is PROV
        assert provenance.note_hop.__self__ is PROV
