"""tft-lint tier-1 gate: the whole suite runs clean over torchft_tpu/,
every pass's selftest passes, and a seeded violation of EACH pass is
caught (the suite must distrust itself before CI trusts it)."""

import os
import subprocess
import sys
import textwrap

import pytest

from torchft_tpu.analysis import PASSES, Project, run_passes
from torchft_tpu.analysis.cli import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "torchft_tpu")


class TestSuiteIsClean:
    def test_tree_lints_clean_with_empty_baselines(self, capsys):
        """The acceptance bar: `python -m torchft_tpu.analysis torchft_tpu/`
        exits 0 — every project invariant holds on the shipped tree, with
        nothing grandfathered."""
        rc = lint_main([PKG])
        out = capsys.readouterr().out
        assert rc == 0, f"tft-lint found violations:\n{out}"
        assert "0 finding(s)" in out
        # nothing hides behind the baselines either
        assert "baselined" not in out

    def test_baseline_files_ship_empty(self):
        bdir = os.path.join(PKG, "analysis", "baselines")
        for p in PASSES:
            path = os.path.join(bdir, f"{p.id}.txt")
            assert os.path.isfile(path), f"missing baseline file for {p.id}"
            lines = [
                ln
                for ln in open(path, encoding="utf-8").read().splitlines()
                if ln.strip() and not ln.lstrip().startswith("#")
            ]
            assert lines == [], f"{p.id} baseline is not empty: {lines}"

    def test_module_entrypoint_subprocess(self):
        """The exact CI invocation, end to end."""
        proc = subprocess.run(
            [sys.executable, "-m", "torchft_tpu.analysis", "torchft_tpu/"],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


class TestSelftests:
    @pytest.mark.parametrize("lint_pass", PASSES, ids=lambda p: p.id)
    def test_pass_selftest(self, lint_pass):
        lint_pass.selftest()  # raises SelftestError on miss

    def test_selftest_cli(self, capsys):
        assert lint_main(["--selftest"]) == 0


# One seeded violation per pass: source planted in a synthetic project
# tree; the named pass must flag it and the CLI must exit 1.
_SEEDED = {
    "lock-discipline": {
        "pkg/bad.py": textwrap.dedent(
            """
            import time, threading
            _lock = threading.Lock()
            def f():
                with _lock:
                    time.sleep(1)
            """
        ),
    },
    "env-hygiene": {
        "pkg/bad.py": 'import os\nX = os.environ.get("TORCHFT_SNEAKY", "")\n',
    },
    "metrics-sync": {
        "pkg/bad.py": (
            "from torchft_tpu.utils.metrics import counter\n"
            'M = counter("myapp_rogue_total", "wrong namespace")\n'
        ),
    },
    "metrics-cardinality": {
        "pkg/bad.py": textwrap.dedent(
            """
            from torchft_tpu.utils.metrics import gauge
            G = gauge("torchft_peer_lag", "d")
            def export(peers):
                for p in peers:
                    G.labels(peer=p.addr).set(p.lag)
            """
        ),
    },
    "retry-ban": {
        "pkg/bad.py": textwrap.dedent(
            """
            import time
            def fetch():
                while True:
                    try:
                        return do()
                    except ConnectionError:
                        time.sleep(1)
            """
        ),
    },
    "fault-coverage": {
        "pkg/utils/faults.py": 'KNOWN_SITES = ("pg.allreduce",)\n',
        "pkg/bad.py": (
            "from torchft_tpu.utils import faults\n"
            'faults.check("pg.allreduce")\n'
            'faults.check("pg.not_a_site")\n'
        ),
    },
    "plan-discipline": {
        "pkg/bad.py": textwrap.dedent(
            """
            from torchft_tpu.ops import topology

            def sneaky_side_channel(world):
                # peer-communication structure built OUTSIDE the plan
                # layer: invisible to the tft-plan verifier
                topo = topology.parse_topology("hosts:2", world)
                return topology.synthesize_plan(topo, 0)
            """
        ),
    },
    "span-vocab": {
        "pkg/manager.py": 'PROTOCOL_PHASES = ("ring", "commit")\n',
        "pkg/bad.py": textwrap.dedent(
            """
            def emit(tracer):
                # off-vocabulary name AND no flight-recorder reach
                tracer.export_span("made_up_phase", "t", 0, 1)
            """
        ),
    },
}


def _plant(tmp_path, files):
    (tmp_path / "docs").mkdir(exist_ok=True)
    (tmp_path / "docs" / "observability.md").write_text("")
    (tmp_path / "docs" / "robustness.md").write_text("`pg.allreduce`\n")
    paths = []
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        paths.append(str(path))
    return paths


class TestSeededViolations:
    @pytest.mark.parametrize("pass_id", sorted(_SEEDED), ids=str)
    def test_seeded_violation_is_caught(self, tmp_path, pass_id):
        paths = _plant(tmp_path, _SEEDED[pass_id])
        project = Project(str(tmp_path), paths)
        lint_pass = next(p for p in PASSES if p.id == pass_id)
        results = run_passes([lint_pass], project, baseline_dir=str(tmp_path / "nobase"))
        findings = [f for r in results for f in r.findings]
        assert findings, f"{pass_id} missed its seeded violation"
        assert any(f.pass_id == pass_id for f in findings)

    def test_cli_exits_nonzero_on_seeded_violation(self, tmp_path, capsys):
        paths = _plant(tmp_path, _SEEDED["retry-ban"])
        rc = lint_main([*paths, "--passes", "retry-ban", "--baseline-dir", str(tmp_path / "nb")])
        assert rc == 1
        assert "sleep-in-loop" in capsys.readouterr().out


class TestFragmentSpanFamily:
    """ISSUE 18: `fragment.*` is a first-class span family — the vocab
    pass must accept a well-formed fragment.hop emitter and still bite
    on a near-miss family name."""

    def _run(self, tmp_path, src):
        paths = _plant(tmp_path, {"pkg/frag.py": textwrap.dedent(src)})
        project = Project(str(tmp_path), paths)
        lint_pass = next(p for p in PASSES if p.id == "span-vocab")
        results = run_passes(
            [lint_pass], project, baseline_dir=str(tmp_path / "nb")
        )
        return [f for r in results for f in r.findings]

    def test_fragment_hop_span_with_flight_reach_is_clean(self, tmp_path):
        findings = self._run(
            tmp_path,
            """
            from torchft_tpu.utils import flightrecorder as _flightrec

            def note_hop(tracer):
                _flightrec.RECORDER.record(op="fragment.hop", status="ok")
                tracer.export_span("fragment.hop", "t", 0, 1)
            """,
        )
        assert findings == [], [f.message for f in findings]

    def test_near_miss_fragment_family_is_caught(self, tmp_path):
        findings = self._run(
            tmp_path,
            """
            from torchft_tpu.utils import flightrecorder as _flightrec

            def note_hop(tracer):
                _flightrec.RECORDER.record(op="fragments.hop", status="ok")
                tracer.export_span("fragments.hop", "t", 0, 1)
            """,
        )
        assert any(
            f.pass_id == "span-vocab" and "fragments.hop" in f.message
            for f in findings
        ), [f.message for f in findings]


class TestBaselineWorkflow:
    def test_write_baseline_then_clean(self, tmp_path, capsys):
        """Grandfathering: --write-baseline makes a dirty tree pass, and
        the fingerprints are line-number-free (stable under edits above)."""
        paths = _plant(tmp_path, _SEEDED["retry-ban"])
        bdir = str(tmp_path / "baselines")
        assert lint_main([*paths, "--passes", "retry-ban", "--baseline-dir", bdir, "--write-baseline"]) == 0
        capsys.readouterr()
        assert lint_main([*paths, "--passes", "retry-ban", "--baseline-dir", bdir]) == 0
        out = capsys.readouterr().out
        assert "1 baselined" in out
        # shifting the finding down two lines must not churn the baseline
        bad = tmp_path / "pkg" / "bad.py"
        bad.write_text("# moved\n# down\n" + bad.read_text())
        assert lint_main([*paths, "--passes", "retry-ban", "--baseline-dir", bdir]) == 0

    def test_rewrite_baseline_keeps_grandfathered_findings(self, tmp_path, capsys):
        """--write-baseline twice in a row must be idempotent: the second
        write grandfathers the FULL finding set, not just the (already
        filtered, hence empty) fresh ones."""
        paths = _plant(tmp_path, _SEEDED["retry-ban"])
        bdir = str(tmp_path / "baselines")
        base = [*paths, "--passes", "retry-ban", "--baseline-dir", bdir]
        assert lint_main([*base, "--write-baseline"]) == 0
        assert lint_main([*base, "--write-baseline"]) == 0  # re-run: no erase
        capsys.readouterr()
        assert lint_main(base) == 0
        assert "1 baselined" in capsys.readouterr().out
