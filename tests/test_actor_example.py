"""Actor-supervision example smoke (reference monarch example analog)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_actor_trainer_healthy():
    out = subprocess.run(
        [sys.executable, "examples/actor_trainer.py", "--replicas", "2",
         "--steps", "6"],
        capture_output=True, text=True, cwd=REPO, timeout=240,
    )
    assert out.returncode == 0, out.stderr + out.stdout
    assert "weights converged bitwise" in out.stdout


def test_actor_trainer_chaos_restart():
    out = subprocess.run(
        [sys.executable, "examples/actor_trainer.py", "--replicas", "2",
         "--steps", "12", "--chaos", "--step-time", "0.3"],
        capture_output=True, text=True, cwd=REPO, timeout=240,
    )
    assert out.returncode == 0, out.stderr + out.stdout
    assert "[chaos] killing trainer" in out.stdout
    assert "restart 1" in out.stdout
    assert "weights converged bitwise" in out.stdout

