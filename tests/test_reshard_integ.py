"""Live online-parallelism-switching integration (ISSUE 11 tentpole).

Real Managers + native lighthouse, single-rank replica groups as
threads.  Proves the end-to-end switch protocol:

- **shrink** (golden fixture ``reshard_shrink.json``): 4 groups under a
  memory ceiling forcing ``nshards >= 2`` shard up to (2,2,1) at
  bootstrap; a fixed-step kill shrinks the fleet to 3, which re-plans to
  (1,3,1) and re-shards live — halves re-partitioned into thirds fetched
  from their current owners.  The committed per-step parameter history
  (per-group shard sums) is compared bitwise against the committed
  golden (regen: TORCHFT_TPU_REGEN_FIXTURES=1).
- **grow**: the killed group restarts as a new incarnation; its stale
  epoch-0 report triggers a fleet re-plan back to (2,2,1) and the
  reshard path fetches its entire shard from current owners — heal,
  generalized to sharded state.
- **chaos mid-reshard** (`make reshard-smoke` runs these standalone):
  an injected ``mesh.reshard`` transfer failure, and a replica KILLED
  between staging and the commit round.  Either way the fleet must
  complete the switch without the victim or roll back to the old layout
  and keep training — never wedge — with the burned epoch never reused.
"""

import json
import os
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.layout import (
    LayoutConstraints,
    LayoutController,
    shard_interval,
)
from torchft_tpu.parallel.process_group import ProcessGroupTCP
from torchft_tpu.utils import faults
from torchft_tpu.utils.faults import FaultRule, InjectedFault

FIXTURES = Path(__file__).parent / "fixtures"
REGEN = os.environ.get("TORCHFT_TPU_REGEN_FIXTURES") == "1"

N = 1024  # flat param elements (4 KiB — wire cost negligible, math exact)


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


def _constraints() -> LayoutConstraints:
    # the ceiling that forces nshards >= 2 at any world
    return LayoutConstraints(param_bytes=N * 4, shard_memory_bytes=N * 2)


class _Group:
    """One deterministic replica group: params start as arange(N); each
    committed step applies ``owned -= 0.1 * g`` with ``g = step`` over
    its owned interval.  Identical gradients on every group make the
    committed values membership-invariant, so the history is bit-stable
    under any kill timing."""

    def __init__(self, gid, lighthouse_addr, total_steps, prefix,
                 die_at=None, attempts=1):
        self.gid = gid
        self.lighthouse_addr = lighthouse_addr
        self.total_steps = total_steps
        self.prefix = prefix
        self.die_at = die_at
        self.attempts = attempts
        self.history = []
        self.final = None  # (shard_index, nshards, shard_array)
        self.controller = None

    def run(self):
        for attempt in range(self.attempts):
            try:
                self._train(attempt)
                return
            except InjectedFault:
                continue  # simulated process death -> new incarnation
        if self.die_at is None:
            raise RuntimeError(f"group {self.gid} exhausted attempts")

    def _train(self, attempt):
        shard = {"w": np.arange(N, dtype=np.float32)}
        ctrl = LayoutController(_constraints())
        self.controller = ctrl
        ctrl.register_sharded_state(
            "model",
            {"w": N},
            lambda: dict(shard),
            lambda new: shard.update(
                {k: np.array(v) for k, v in new.items()}
            ),
        )
        user = {"marker": float(self.gid)}
        manager = Manager(
            pg=ProcessGroupTCP(timeout=15.0),
            min_replica_size=1,
            load_state_dict=lambda sd: user.update(sd),
            state_dict=lambda: dict(user),
            lighthouse_addr=self.lighthouse_addr,
            replica_id=f"{self.prefix}_{self.gid}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=True,
            init_sync=False,
            timeout=15.0,
            quorum_timeout=15.0,
            max_retries=6 * self.total_steps,
        )
        manager.attach_layout(ctrl)
        try:
            while manager.current_step() < self.total_steps:
                step = manager.current_step()
                if self.die_at is not None and attempt == 0:
                    faults.check(
                        "train.step",
                        replica=f"{self.prefix}_{self.gid}",
                        step=step,
                    )
                manager.start_quorum()
                g = np.full(N, float(step + 1), dtype=np.float32)
                avg = manager.allreduce({"g": g}).wait(timeout=15)
                if manager.should_commit():
                    # the migration-safe mutation path: double-writes any
                    # staged reshard buffer so the switch installs data
                    # that includes this step's update
                    ctrl.update_sharded(
                        "model",
                        lambda leaf, arr, start: arr.__isub__(
                            np.float32(0.1) * avg["g"][start : start + arr.size]
                        ),
                    )
                    layout = ctrl.active_layout()
                    idx, nsh = ctrl.shard_coords()
                    self.history.append(
                        {
                            "step": manager.current_step(),
                            "layout": list(layout.key()) if layout else None,
                            "shard": idx,
                            "nshards": nsh,
                            "first": float(shard["w"][0]),
                            "sum": float(
                                np.float64(shard["w"].sum(dtype=np.float64))
                            ),
                        }
                    )
            idx, nsh = ctrl.shard_coords()
            self.final = (idx, nsh, shard["w"].copy())
        finally:
            manager.shutdown()


def _run_fleet(groups, wall_s=150.0):
    errs = {}
    threads = []
    for g in groups:

        def runner(g=g):
            try:
                g.run()
            except BaseException as e:  # noqa: BLE001
                errs[g.gid] = e

        threads.append(
            threading.Thread(target=runner, daemon=True, name=f"grp{g.gid}")
        )
    for t in threads:
        t.start()
    deadline = time.monotonic() + wall_s
    for t in threads:
        t.join(timeout=max(deadline - time.monotonic(), 0.1))
    # never wedged: every worker exited inside the wall budget
    assert not any(t.is_alive() for t in threads), "fleet wedged mid-switch"
    if errs:
        raise next(iter(errs.values()))


def _reassemble(groups):
    """Full param vector from the groups' final shards, asserting
    dp-peer shards are bitwise identical."""
    by_shard = {}
    nsh = None
    for g in groups:
        if g.final is None:
            continue
        idx, n, w = g.final
        nsh = n if nsh is None else nsh
        assert n == nsh, "groups ended on different layouts"
        if idx in by_shard:
            np.testing.assert_array_equal(by_shard[idx], w)
        else:
            by_shard[idx] = w
    assert sorted(by_shard) == list(range(nsh)), "missing shards"
    return np.concatenate([by_shard[i] for i in range(nsh)])


def _expected_params(total_steps):
    w = np.arange(N, dtype=np.float32)
    for step in range(total_steps):
        w = w - np.float32(0.1) * np.full(N, float(step + 1), dtype=np.float32)
    return w


KILL_STEP = 3
TOTAL_STEPS = 6


class TestShrinkGolden:
    def test_shrink_reshard_resume_matches_fixture(self):
        """4 groups shard up to (2,2,1) at bootstrap; a fixed-step kill
        shrinks to 3 -> live re-plan to (1,3,1), halves re-sharded into
        thirds from their current owners, training resumes — param
        history bit-stable vs the committed golden."""
        faults.FAULTS.configure(
            [
                FaultRule(
                    site="train.step",
                    replica=f"rs_{3}",
                    step=KILL_STEP,
                )
            ]
        )
        server = LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        try:
            groups = [
                _Group(
                    i, server.address(), TOTAL_STEPS, "rs",
                    die_at=KILL_STEP if i == 3 else None,
                )
                for i in range(4)
            ]
            _run_fleet(groups)
        finally:
            server.shutdown()
        assert faults.FAULTS.injected() == 1

        survivors = [g for g in groups if g.gid != 3]
        # the shrink actually switched parallelism, fleet-wide
        for g in survivors:
            layout = g.controller.active_layout()
            assert layout is not None and layout.key() == (1, 3, 1)
            assert [e["step"] for e in g.history] == list(
                range(1, TOTAL_STEPS + 1)
            )
        # live re-shard preserved every element: reassembled params match
        # the sequential single-process replay bitwise
        full = _reassemble(survivors)
        np.testing.assert_array_equal(full, _expected_params(TOTAL_STEPS))

        produced = {
            "n": N,
            "kill_step": KILL_STEP,
            "total_steps": TOTAL_STEPS,
            "history": {
                f"group_{g.gid}": g.history for g in groups
            },
            "final_first8": [float(x) for x in full[:8]],
            "final_sum": float(np.float64(full.sum(dtype=np.float64))),
        }
        path = FIXTURES / "reshard_shrink.json"
        if REGEN or not path.exists():
            path.write_text(
                json.dumps(produced, indent=1, sort_keys=True) + "\n"
            )
            if REGEN:
                pytest.skip(f"regenerated {path.name}")
        golden = json.loads(path.read_text())
        assert produced == golden, (
            f"{path.name} drifted; if intentional, regenerate with "
            "TORCHFT_TPU_REGEN_FIXTURES=1"
        )


class TestGrow:
    def test_rejoin_triggers_replan_and_shard_fetch(self):
        """The killed group restarts as a new incarnation: its stale
        epoch-0 report triggers a fleet re-plan back to the 4-group
        layout, and the reshard path fetches its whole shard from the
        current owners — a join is no longer wasted capacity."""
        faults.FAULTS.configure(
            [FaultRule(site="train.step", replica="rg_3", step=KILL_STEP)]
        )
        server = LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        try:
            groups = [
                _Group(
                    i, server.address(), TOTAL_STEPS + 2, "rg",
                    die_at=KILL_STEP if i == 3 else None,
                    attempts=2 if i == 3 else 1,
                )
                for i in range(4)
            ]
            _run_fleet(groups, wall_s=180.0)
        finally:
            server.shutdown()

        finished = [g for g in groups if g.final is not None]
        assert len(finished) == 4, "the rejoined group must finish too"
        layouts = {g.controller.active_layout().key() for g in finished}
        assert layouts == {(2, 2, 1)}, layouts
        # the re-grown fleet is consistent: dp peers bitwise equal and
        # the reassembled params match the sequential replay
        full = _reassemble(finished)
        np.testing.assert_array_equal(full, _expected_params(TOTAL_STEPS + 2))


@pytest.mark.chaos
class TestChaosMidReshard:
    def test_transfer_failure_rolls_the_fleet_back(self):
        """An injected mesh.reshard failure on one group mid-transfer:
        that group's stage burns its epoch, the commit round sees mixed
        reports and the WHOLE fleet rolls back to the old layout, then
        re-plans under a fresh epoch and completes — bitwise-converged
        either way, epoch never reused."""
        faults.FAULTS.configure(
            [FaultRule(site="mesh.reshard", replica="rc_1", times=1)]
        )
        server = LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        try:
            groups = [
                _Group(i, server.address(), TOTAL_STEPS, "rc")
                for i in range(4)
            ]
            _run_fleet(groups)
        finally:
            server.shutdown()
        assert faults.FAULTS.injected("mesh.reshard") == 1

        for g in groups:
            layout = g.controller.active_layout()
            assert layout is not None and layout.key() == (2, 2, 1)
            # the burned epoch was never committed: the active epoch is
            # strictly beyond at least one burned epoch on every group
            st = g.controller.state
            assert any(
                st.is_burned(e) for e in range(1, st.max_seen_epoch + 1)
            ), "expected a rolled-back epoch somewhere below the active one"
            assert not st.is_burned(layout.epoch)
        full = _reassemble(groups)
        np.testing.assert_array_equal(full, _expected_params(TOTAL_STEPS))

    def test_victim_killed_between_stage_and_commit(self):
        """A replica dies holding a staged switch (after the reshard
        transfers, before the commit round): the survivors see the world
        change, roll the staged epoch back, re-plan for the smaller
        fleet and keep training — completed switch without the victim,
        never a wedge."""
        faults.FAULTS.configure(
            [
                # first kill starts the shrink re-plan...
                FaultRule(site="train.step", replica="rk_3", step=KILL_STEP),
                # ...second kill lands mid-switch: after its stage for
                # the world-3 plan, before that plan's commit round
                FaultRule(
                    site="train.step", replica="rk_2", step=KILL_STEP + 1
                ),
            ]
        )
        server = LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        try:
            groups = [
                _Group(
                    i, server.address(), TOTAL_STEPS, "rk",
                    die_at=KILL_STEP if i == 3
                    else (KILL_STEP + 1 if i == 2 else None),
                )
                for i in range(4)
            ]
            _run_fleet(groups, wall_s=180.0)
        finally:
            server.shutdown()
        assert faults.FAULTS.injected("train.step") == 2

        survivors = [g for g in groups if g.gid in (0, 1)]
        for g in survivors:
            layout = g.controller.active_layout()
            assert layout is not None and layout.key() == (1, 2, 1)
            assert g.final is not None
        full = _reassemble(survivors)
        np.testing.assert_array_equal(full, _expected_params(TOTAL_STEPS))
