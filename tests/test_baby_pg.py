"""Subprocess-isolated ("Baby") process groups + monitored pipe.

Mirrors the reference's Baby-PG tests (reference:
torchft/process_group_test.py:910-1020 and multiprocessing tests): ops run
in a spawned worker, worker crash surfaces as a clean error in the parent,
reconfigure restarts the worker, and the parent process always survives.
"""

import multiprocessing as mp
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from torchft_tpu.coordination import StoreServer
from torchft_tpu.multiprocessing import _MonitoredPipe
from torchft_tpu.parallel.process_group import ProcessGroupBabyTCP


@pytest.fixture
def store():
    server = StoreServer()
    yield server
    server.shutdown()


def _configure_pair(store, prefix, timeout=30.0):
    pgs = [ProcessGroupBabyTCP(timeout=timeout) for _ in range(2)]
    with ThreadPoolExecutor(max_workers=2) as ex:
        futs = [
            ex.submit(
                pgs[r].configure, f"{store.address()}/{prefix}", f"rank{r}", r, 2
            )
            for r in range(2)
        ]
        for f in futs:
            f.result(timeout=60)
    return pgs


class TestMonitoredPipe:
    def test_roundtrip_and_timeout(self):
        a, b = mp.Pipe()
        pa, pb = _MonitoredPipe(a), _MonitoredPipe(b)
        pa.send({"x": 1})
        assert pb.recv(timeout=5) == {"x": 1}
        with pytest.raises(TimeoutError):
            pb.recv(timeout=0.2)

    def test_exception_passthrough(self):
        a, b = mp.Pipe()
        pa, pb = _MonitoredPipe(a), _MonitoredPipe(b)
        pa.send(ValueError("shipped"))
        with pytest.raises(ValueError, match="shipped"):
            pb.recv(timeout=5)

    def test_eof_on_close(self):
        a, b = mp.Pipe()
        pa, pb = _MonitoredPipe(a), _MonitoredPipe(b)
        pa.close()
        with pytest.raises(EOFError):
            pb.recv(timeout=5)


class TestProcessGroupBabyTCP:
    def test_configure_failure_propagates_root_cause(self):
        pg = ProcessGroupBabyTCP(timeout=10.0)
        # unreachable store: the worker's configure error must surface in
        # the parent with the real cause, not a generic protocol error
        with pytest.raises(Exception) as exc_info:
            pg.configure("127.0.0.1:1/none", "rank0", 0, 2)
        assert not isinstance(exc_info.value, AssertionError)
        pg.shutdown()

    def test_allreduce_and_broadcast(self, store):
        pgs = _configure_pair(store, "baby1")
        try:
            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(
                        lambda r: pgs[r]
                        .allreduce([np.full(4, float(r + 1), np.float32)])
                        .wait(timeout=30),
                        r,
                    )
                    for r in range(2)
                ]
                results = [f.result(timeout=60) for f in futs]
            for res in results:
                np.testing.assert_array_equal(res[0], np.full(4, 3.0, np.float32))

            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(
                        lambda r: pgs[r]
                        .broadcast(
                            np.arange(4, dtype=np.float32) if r == 0 else np.zeros(4, np.float32),
                            root=0,
                        )
                        .wait(timeout=30),
                        r,
                    )
                    for r in range(2)
                ]
                results = [f.result(timeout=60) for f in futs]
            for res in results:
                np.testing.assert_array_equal(res, np.arange(4, dtype=np.float32))
        finally:
            for pg in pgs:
                pg.shutdown()

    def test_pipelined_ops_preserve_order(self, store):
        # submit two collectives without waiting in between: the worker
        # must enqueue them in pipe order so ranks' streams match
        pgs = _configure_pair(store, "babyp")
        try:
            def both(r):
                w1 = pgs[r].allreduce([np.full(4, 1.0 + r, np.float32)])
                w2 = pgs[r].allreduce([np.full(2, 10.0 * (1 + r), np.float32)])
                return w1.wait(timeout=30), w2.wait(timeout=30)

            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [ex.submit(both, r) for r in range(2)]
                results = [f.result(timeout=60) for f in futs]
            for r1, r2 in results:
                np.testing.assert_array_equal(r1[0], np.full(4, 3.0, np.float32))
                np.testing.assert_array_equal(r2[0], np.full(2, 30.0, np.float32))
        finally:
            for pg in pgs:
                pg.shutdown()

    def test_live_reconfigure_keeps_clean_state(self, store):
        # reconfigure over a healthy PG (quorum-change path): the stale
        # reader of the old worker must not latch an error afterwards
        import time

        pgs = _configure_pair(store, "babyr1")
        try:
            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(
                        pgs[r].configure, f"{store.address()}/babyr2", f"rank{r}", r, 2
                    )
                    for r in range(2)
                ]
                for f in futs:
                    f.result(timeout=60)
            time.sleep(0.5)  # give the old readers time to wake on the closed pipe
            assert all(pg.errored() is None for pg in pgs)
            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(
                        lambda r: pgs[r]
                        .allreduce([np.ones(2, np.float32)])
                        .wait(timeout=30),
                        r,
                    )
                    for r in range(2)
                ]
                for f in futs:
                    np.testing.assert_array_equal(
                        f.result(timeout=60)[0], np.full(2, 2.0, np.float32)
                    )
            assert all(pg.errored() is None for pg in pgs)
        finally:
            for pg in pgs:
                pg.shutdown()

    def test_worker_crash_is_isolated(self, store):
        pgs = _configure_pair(store, "baby2")
        try:
            # kill rank 1's worker out from under it — the parent must see a
            # clean error on both sides (peer detects the dropped socket)
            pgs[1]._proc.kill()
            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(
                        lambda r: pgs[r]
                        .allreduce([np.zeros(2, np.float32)])
                        .wait(timeout=30),
                        r,
                    )
                    for r in range(2)
                ]
                errs = 0
                for f in futs:
                    try:
                        f.result(timeout=60)
                    except Exception:
                        errs += 1
            assert errs == 2
            assert pgs[1].errored() is not None
        finally:
            for pg in pgs:
                pg.shutdown()

    def test_reconfigure_after_abort(self, store):
        pgs = _configure_pair(store, "baby3")
        try:
            for pg in pgs:
                pg.abort()
            assert all(pg.errored() is not None for pg in pgs)
            # ops fail fast while aborted
            with pytest.raises(Exception):
                pgs[0].allreduce([np.zeros(1)]).wait(timeout=5)

            # reconfigure restarts workers and clears the error
            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(
                        pgs[r].configure,
                        f"{store.address()}/baby3b",
                        f"rank{r}",
                        r,
                        2,
                    )
                    for r in range(2)
                ]
                for f in futs:
                    f.result(timeout=60)
            assert all(pg.errored() is None for pg in pgs)

            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(
                        lambda r: pgs[r]
                        .allreduce([np.ones(2, np.float32)])
                        .wait(timeout=30),
                        r,
                    )
                    for r in range(2)
                ]
                for f in futs:
                    np.testing.assert_array_equal(
                        f.result(timeout=60)[0], np.full(2, 2.0, np.float32)
                    )
        finally:
            for pg in pgs:
                pg.shutdown()


class TestShmDataPath:
    def test_large_allreduce_uses_shm_and_is_correct(self, store):
        """Arrays >= 1 MiB cross the pipe as shared-memory refs (zero pickle
        of the payload); results must match the direct-PG math exactly."""
        pgs = _configure_pair(store, "shm")
        try:
            n = 2 * 1024 * 1024  # 8 MB f32, well over _SHM_MIN_BYTES
            data = [np.full(n, 1.0 + r, dtype=np.float32) for r in range(2)]

            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(
                        lambda r: pgs[r].allreduce([data[r]], "sum").wait(timeout=60),
                        r,
                    )
                    for r in range(2)
                ]
                results = [f.result(timeout=90) for f in futs]
            for (got,) in results:
                np.testing.assert_array_equal(got, np.full(n, 3.0, np.float32))
            # no leaked segments
            import glob
            assert not glob.glob("/dev/shm/psm_*"), glob.glob("/dev/shm/*")
        finally:
            for pg in pgs:
                pg.shutdown()

    def test_mixed_small_and_large_leaves(self, store):
        pgs = _configure_pair(store, "shmmix")
        try:
            small = np.arange(16, dtype=np.float32)
            big = np.full(512 * 1024, 2.0, dtype=np.float32)  # 2 MB

            def run(r):
                return pgs[r].allreduce([small.copy(), big.copy()], "sum").wait(
                    timeout=60
                )

            with ThreadPoolExecutor(max_workers=2) as ex:
                results = [f.result(timeout=90)
                           for f in [ex.submit(run, r) for r in range(2)]]
            for got_small, got_big in results:
                np.testing.assert_array_equal(got_small, 2 * small)
                np.testing.assert_array_equal(got_big, 2 * big)
        finally:
            for pg in pgs:
                pg.shutdown()

    def test_backpressure_bounds_inflight_ops(self, store):
        """max_active_work caps queued ops; submissions past the cap wait
        and everything still completes in order."""
        pgs = [ProcessGroupBabyTCP(timeout=30.0, max_active_work=2) for _ in range(2)]
        with ThreadPoolExecutor(max_workers=2) as ex:
            futs = [
                ex.submit(
                    pgs[r].configure, f"{store.address()}/bp", f"rank{r}", r, 2
                )
                for r in range(2)
            ]
            for f in futs:
                f.result(timeout=60)
        try:
            def run(r):
                works = [
                    pgs[r].allreduce([np.full(1024, float(i), np.float32)], "sum")
                    for i in range(8)
                ]
                return [w.wait(timeout=60)[0][0] for w in works]

            with ThreadPoolExecutor(max_workers=2) as ex:
                results = [f.result(timeout=90)
                           for f in [ex.submit(run, r) for r in range(2)]]
            for vals in results:
                assert vals == [2.0 * i for i in range(8)]
        finally:
            for pg in pgs:
                pg.shutdown()


class TestBabyQuantizedCollective:
    def test_quantized_allreduce_over_baby(self, store):
        """The int8 quantized allreduce composes with the subprocess-
        isolated backend: packed wire buffers cross the parent<->worker
        boundary (pipe or shm), and the pool-recycling in the collective
        must only ever recycle parent-side allocations it owns."""
        from torchft_tpu.ops.collectives import allreduce_quantized
        from torchft_tpu.parallel.process_group import REDUCE_SUM

        pgs = _configure_pair(store, "qbaby")
        try:
            data = [
                np.full(60_000, 1.0 + r, dtype=np.float32) for r in range(2)
            ]
            expected = np.full(60_000, 3.0, dtype=np.float32)

            def run(rank):
                return allreduce_quantized(
                    [data[rank]], REDUCE_SUM, pgs[rank]
                ).wait(timeout=60)

            with ThreadPoolExecutor(max_workers=2) as ex:
                results = [
                    f.result(timeout=90)
                    for f in [ex.submit(run, r) for r in range(2)]
                ]
            for (got,) in results:
                rel = np.abs(got - expected).max() / 3.0
                assert rel < 0.05, rel
            np.testing.assert_array_equal(results[0][0], results[1][0])
            # run a second round so any wrongly-recycled buffer from round
            # one would corrupt round two
            with ThreadPoolExecutor(max_workers=2) as ex:
                results2 = [
                    f.result(timeout=90)
                    for f in [ex.submit(run, r) for r in range(2)]
                ]
            np.testing.assert_array_equal(results2[0][0], results2[1][0])
        finally:
            for pg in pgs:
                pg.shutdown()


def test_wire_gbps_env_reaches_baby_worker(store, monkeypatch):
    """TORCHFT_WIRE_GBPS must shape the SUBPROCESS worker's sends too:
    the Baby worker builds its inner ProcessGroupTCP in the spawned
    process, which inherits the env — an 8 MB allreduce at 50 MB/s
    must take >= ~80 ms where unshaped loopback takes < 40 ms."""
    import time as _time

    monkeypatch.setenv("TORCHFT_WIRE_GBPS", "0.05")
    pgs = _configure_pair(store, "shapedbaby", timeout=60.0)
    try:
        data = np.ones(2 << 20, dtype=np.float32)  # 8 MB

        def run(rank):
            t0 = _time.monotonic()
            pgs[rank].allreduce([data.copy()], "sum").wait(timeout=60)
            return _time.monotonic() - t0

        with ThreadPoolExecutor(max_workers=2) as ex:
            walls = [f.result(timeout=90) for f in [ex.submit(run, r) for r in range(2)]]
        assert max(walls) >= 0.06, walls
    finally:
        for pg in pgs:
            pg.shutdown()
    # unshaped control: without the env the same transfer must be faster
    # (guards against the shaped assertion passing vacuously on a slow
    # host where even unshaped baby allreduces exceed the floor)
    monkeypatch.delenv("TORCHFT_WIRE_GBPS")
    pgs2 = _configure_pair(store, "unshapedbaby", timeout=60.0)
    try:
        data = np.ones(2 << 20, dtype=np.float32)

        def run2(rank):
            t0 = _time.monotonic()
            pgs2[rank].allreduce([data.copy()], "sum").wait(timeout=60)
            return _time.monotonic() - t0

        with ThreadPoolExecutor(max_workers=2) as ex:
            walls2 = [
                f.result(timeout=90) for f in [ex.submit(run2, r) for r in range(2)]
            ]
        assert max(walls2) < max(walls), (walls2, walls)
    finally:
        for pg in pgs2:
            pg.shutdown()
