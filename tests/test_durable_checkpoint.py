"""Durable (on-disk) checkpoint + cold-start resume.

Covers the total-failure case live healing can't: every replica died, the
job restarts from disk (reference demonstrates the save path in
train_ddp.py:201-208; the resume leg is this framework's addition).
"""

import os
import subprocess
import sys

import numpy as np

from torchft_tpu.checkpointing import (
    latest_checkpoint,
    list_checkpoints,
    load_checkpoint,
    save_checkpoint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestDurable:
    def test_roundtrip(self, tmp_path):
        sd = {
            "user": {
                "params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4)},
                "opt_state": {"mu": np.ones(5), "count": 3},
            },
            "torchft": {"step": 7, "batches_committed": 14},
        }
        path = save_checkpoint(str(tmp_path), 7, sd)
        assert os.path.basename(path) == "ckpt_step7.tft"
        out = load_checkpoint(path)
        np.testing.assert_array_equal(
            out["user"]["params"]["w"], sd["user"]["params"]["w"]
        )
        assert out["torchft"] == sd["torchft"]
        # no tmp litter: the write is atomic
        assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))

    def test_latest_and_prune(self, tmp_path):
        for step in (2, 4, 6, 8):
            save_checkpoint(str(tmp_path), step, {"s": step}, keep_last=2)
        steps = [s for s, _ in list_checkpoints(str(tmp_path))]
        assert steps == [6, 8]
        latest = latest_checkpoint(str(tmp_path))
        assert latest is not None and latest.endswith("ckpt_step8.tft")
        assert load_checkpoint(latest)["s"] == 8

    def test_latest_empty(self, tmp_path):
        assert latest_checkpoint(str(tmp_path / "nope")) is None


class TestTrainDDPResume:
    def test_save_then_resume_continues_step(self, tmp_path):
        """train_ddp with --save-dir, then a fresh run with --resume: the
        resumed job must continue from the checkpointed step, not step 0."""
        save_dir = str(tmp_path / "ckpts")
        common = [
            sys.executable, "examples/train_ddp.py", "--cpu",
            "--local-replicas", "2", "--min-replicas", "2",
            "--batch-size", "4", "--save-dir", save_dir, "--save-every", "2",
        ]
        first = subprocess.run(
            common + ["--steps", "6"],
            capture_output=True, text=True, cwd=REPO, timeout=240,
        )
        assert first.returncode == 0, first.stderr + first.stdout
        assert "saved checkpoint" in first.stdout
        steps = [s for s, _ in list_checkpoints(save_dir)]
        assert steps and steps[-1] == 6

        second = subprocess.run(
            common + ["--steps", "10", "--resume"],
            capture_output=True, text=True, cwd=REPO, timeout=240,
        )
        assert second.returncode == 0, second.stderr + second.stdout
        assert "resumed from" in second.stdout and "at step 6" in second.stdout
        steps = [s for s, _ in list_checkpoints(save_dir)]
        assert steps[-1] == 10


class TestCorruption:
    def test_truncated_checkpoint_raises_cleanly(self, tmp_path):
        # a partial write that somehow survived (e.g. torn disk) must fail
        # loudly at load, never return garbage state
        import pytest

        path = save_checkpoint(str(tmp_path), 3, {"w": np.arange(1000.0)})
        data = open(path, "rb").read()
        open(path, "wb").write(data[: len(data) // 2])
        with pytest.raises(EOFError):
            load_checkpoint(path)

    def test_atomic_write_never_replaces_on_failure(self, tmp_path):
        # save_checkpoint writes tmp + os.replace: a failed serialize must
        # leave the previous checkpoint intact
        path = save_checkpoint(str(tmp_path), 5, {"w": np.ones(4)})
        before = open(path, "rb").read()

        class Unpicklable:
            def __reduce__(self):
                raise RuntimeError("nope")

        try:
            save_checkpoint(str(tmp_path), 5, {"bad": Unpicklable()})
        except Exception:
            pass
        assert open(path, "rb").read() == before
        np.testing.assert_array_equal(load_checkpoint(path)["w"], np.ones(4))
