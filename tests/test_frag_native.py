"""Native zero-copy fragment data plane (chaos + contract tests).

Contract layer: bitwise serve with zero user-space copies server-side
(allocation/copy counters), pool-miss-flat republish idiom, GIL-free
receive+digest (budget test), the ``TORCHFT_FRAG_NATIVE`` gate, the
``/nativeport`` discovery route, and per-fetch Python fallback for
unmirrored resources.

Chaos layer: a native-served relay killed mid-stripe fails over
per-fragment and the heal converges bitwise; a poisoned fragment over
the native path is rejected by the digest-of-record (source treated
dead, provenance hop verdict ``mismatch``); a mixed native<->python
fleet interoperates bitwise.

Everything here requires the native library; the suite skips cleanly
where the ``.so`` cannot build.
"""

import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from torchft_tpu.checkpointing import fragdata
from torchft_tpu.checkpointing import fragments as frags
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.provenance import PROV
from torchft_tpu.utils import faults
from torchft_tpu.utils import flightrecorder as fr
from torchft_tpu.utils.faults import FaultRule

pytestmark = pytest.mark.skipif(
    not fragdata.available(), reason="native fragment library unavailable"
)


@pytest.fixture(autouse=True)
def clean_slate():
    faults.FAULTS.configure([], seed=0)
    fragdata.reset_port_cache()
    yield
    faults.FAULTS.configure([])
    fragdata.reset_port_cache()


def make_state(leaves: int = 12, seed: int = 7) -> dict:
    rng = np.random.default_rng(seed)
    return {
        "user": {
            f"w{i}": rng.standard_normal(257).astype(np.float32)
            for i in range(leaves)
        },
        "torchft": {"step": 5, "batches_committed": 10},
    }


def clone_state(state: dict) -> dict:
    return {
        "user": {k: v.copy() for k, v in state["user"].items()},
        "torchft": dict(state["torchft"]),
    }


def assert_state_equal(a: dict, b: dict) -> None:
    assert a["torchft"] == b["torchft"]
    assert set(a["user"]) == set(b["user"])
    for k in a["user"]:
        np.testing.assert_array_equal(a["user"][k], b["user"][k])


def stage_raw(transport: HTTPTransport, step: int, parts: dict) -> None:
    transport.begin_streamed_checkpoint(step, {"frag:header": {"n": 1}})
    for name, payload in parts.items():
        transport.stage_streamed_part(step, f"frag:{name}", payload)
    transport.finish_streamed_checkpoint(step)


def fetch_bytes(base: str, step: int, resource: str, timeout=5.0) -> bytes:
    buf = frags.fetch_raw(base, step, resource, timeout=timeout)
    return bytes(memoryview(buf).cast("B"))


@pytest.fixture
def sources():
    """Three native-armed transports stream-staging the SAME state at
    step 5 — bitwise-replicated heal sources over the native plane."""
    state = make_state()
    transports = [HTTPTransport(timeout=10.0, native=True) for _ in range(3)]
    threads = [
        threading.Thread(
            target=t.send_checkpoint_streamed,
            args=([1], 5, state, 10.0, 6),
        )
        for t in transports
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    yield state, transports
    for t in transports:
        t.shutdown()


class TestNativeContract:
    def test_serves_bitwise_with_zero_copies(self):
        payload = np.random.default_rng(0).integers(
            0, 256, size=1 << 20, dtype=np.uint8
        ).tobytes()
        t = HTTPTransport(timeout=10.0, native=True)
        try:
            assert t._frag_native is not None
            base = t.metadata()
            stage_raw(t, 7, {"w0": payload})
            for _ in range(3):
                assert fetch_bytes(base, 7, "frag_w0") == payload
            c = t._frag_native.counters()
            # steady-state serve is pure writev out of the staged pooled
            # buffer: the ONE copy in the plane is at stage time
            assert c["serves"] >= 3
            assert c["serve_copies"] == 0
            assert c["serve_bytes"] >= 3 * len(payload)
            assert c["stage_copy_bytes"] == len(payload)
        finally:
            t.shutdown()

    def test_pool_misses_flat_across_republishes(self):
        """Fragment sizes repeat across publishes, so after the first
        version warms the pool every restage is a pool hit — the bufpool
        miss-flat idiom, natively."""
        sizes = [1 << 16, 1 << 16, 1 << 18]
        t = HTTPTransport(timeout=10.0, native=True)
        try:
            srv = t._frag_native
            assert srv is not None
            for v in range(5):
                if v > 0:
                    t.retire_checkpoint(v - 1)
                stage_raw(
                    t, v,
                    {f"w{i}": bytes([v]) * n for i, n in enumerate(sizes)},
                )
                if v == 0:
                    warm = srv.counters()["pool_misses"]
            c = srv.counters()
            assert c["pool_misses"] == warm, c
            assert c["pool_hits"] >= 4 * len(sizes)
        finally:
            t.shutdown()

    def test_gate_off_forces_python_path(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_FRAG_NATIVE", "0")
        payload = b"x" * 4096
        t = HTTPTransport(timeout=10.0, native=True)
        try:
            stage_raw(t, 2, {"w0": payload})
            assert fetch_bytes(t.metadata(), 2, "frag_w0") == payload
            # the gate is consulted on the CLIENT: the armed server saw
            # no data request
            assert t._frag_native.counters()["serves"] == 0
        finally:
            t.shutdown()

    def test_unmirrored_resource_falls_back_per_fetch(self):
        """A part that is not raw wire bytes (here a dict) is never
        mirrored natively: the native 404 falls back to the Python
        serializer for THAT fetch — and the fallback is flight-recorded
        so a fleet on the slow path is visible post-mortem."""
        t = HTTPTransport(timeout=10.0, native=True)
        try:
            raw = b"r" * 2048
            t.begin_streamed_checkpoint(9, {"frag:header": {"n": 1}})
            t.stage_streamed_part(9, "frag:raw", raw)
            t.stage_streamed_part(9, "frag:obj", {"k": 1})
            t.finish_streamed_checkpoint(9)
            base = t.metadata()
            assert fetch_bytes(base, 9, "frag_raw") == raw  # native
            assert len(fetch_bytes(base, 9, "frag_obj")) > 0  # python
            ops = [
                r for r in fr.snapshot()
                if r["op"] == "fragment.native_fallback"
                and r.get("resource") == "frag_obj"
            ]
            assert ops, "fallback fetch not flight-recorded"
            assert t._frag_native.counters()["serves"] == 1
        finally:
            t.shutdown()

    def test_nativeport_discovery_route(self):
        armed = HTTPTransport(timeout=5.0, native=True)
        plain = HTTPTransport(timeout=5.0, native=False)
        try:
            armed_url = (
                f"http://127.0.0.1:{armed._server.server_address[1]}"
                "/nativeport"
            )
            with urllib.request.urlopen(armed_url, timeout=5) as resp:
                assert int(resp.read()) == armed._frag_native.port
            plain_url = (
                f"http://127.0.0.1:{plain._server.server_address[1]}"
                "/nativeport"
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(plain_url, timeout=5)
            assert ei.value.code == 404
        finally:
            armed.shutdown()
            plain.shutdown()

    def test_receive_and_digest_release_the_gil(self):
        """Budget test: while the native client is blocked in a fetch
        (server delays the body via chaos injection), OTHER Python
        threads must keep executing — ctypes drops the GIL around the
        begin/body calls, so a pure-Python ticker makes real progress
        during the native wait.  A GIL-holding receive would freeze it."""
        payload = b"g" * (1 << 20)
        t = HTTPTransport(timeout=10.0, native=True)
        try:
            stage_raw(t, 1, {"w0": payload})
            base = t.metadata()
            fetch_bytes(base, 1, "frag_w0")  # warm conn + port cache
            t._frag_native.inject("delay", param_ms=300, count=1)
            stop = threading.Event()
            ticks = [0]

            def ticker():
                while not stop.is_set():
                    ticks[0] += 1

            th = threading.Thread(target=ticker, daemon=True)
            th.start()
            time.sleep(0.02)
            before = ticks[0]
            t0 = time.monotonic()
            got = fetch_bytes(base, 1, "frag_w0")
            elapsed = time.monotonic() - t0
            during = ticks[0] - before
            stop.set()
            th.join(timeout=5)
            assert got == payload
            assert elapsed >= 0.25, elapsed  # the delay actually applied
            # generous floor: a held GIL would yield ~0 progress
            assert during > 10_000, during
            assert t._frag_native.counters()["injected_delays"] == 1
        finally:
            t.shutdown()


class TestNativeChaos:
    def test_kill_native_relay_mid_stripe(self, sources):
        """SIGKILL-equivalent (full shutdown: Python control + native
        data server) of a native-served source MID-heal: its in-flight
        fragments fail over per-fragment and the heal converges
        bitwise."""
        state, transports = sources
        assert all(t._frag_native is not None for t in transports)
        faults.FAULTS.configure(
            [FaultRule(site="transport.heal.frag", action="delay",
                       delay=0.15, times=100)],
            seed=0,
        )
        local = clone_state(state)
        for v in local["user"].values():
            v[:] = 0.0
        killer = threading.Timer(0.05, transports[2].shutdown)
        killer.start()
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=30.0,
                local_state_fn=lambda: local, delta=False,
            )
        finally:
            killer.cancel()
            healer.shutdown()
        assert_state_equal(got, state)
        assert info["failovers"] >= 1
        assert info["sources_used"] >= 2
        # the survivors actually served over the native plane
        native_serves = sum(
            t._frag_native.counters()["serves"] for t in transports[:2]
        )
        assert native_serves >= 1

    def test_poisoned_fragment_over_native_path(self, sources):
        """Bitwise-corrupt bytes arriving over the NATIVE plane are
        rejected by the Python digest-of-record exactly like the Python
        plane: the source is treated dead for that fragment and the
        provenance trail records the ``mismatch`` hop verdict."""
        state, transports = sources
        victim = transports[1]
        # poison EVERY fragment on the victim, restaged through the
        # transport API so the corruption lands in the Python slot AND
        # the native mirror; pacing below guarantees the dynamic stripe
        # routes the victim at least one fragment
        for i in range(6):
            with victim._staged_lock.r_lock():
                raw = bytearray(victim._staged[5].sd[f"frag:{i}"])
            raw[len(raw) // 2] ^= 0xFF
            victim.stage_streamed_part(5, f"frag:{i}", bytes(raw))
        faults.FAULTS.configure(
            [FaultRule(site="transport.heal.frag", action="delay",
                       delay=0.02, times=100)],
            seed=0,
        )
        hops_before = len(PROV.hop_records())
        local = clone_state(state)
        for v in local["user"].values():
            v[:] = 0.0
        healer = HTTPTransport(timeout=10.0)
        try:
            got, info = healer.recv_checkpoint_striped(
                [t.metadata() for t in transports], 5, timeout=30.0,
                local_state_fn=lambda: local, delta=True,
            )
        finally:
            healer.shutdown()
        # healed state is bitwise the fleet's, never the poison
        assert_state_equal(got, state)
        mismatches = [
            r for r in PROV.hop_records()[hops_before:]
            if r.get("verdict") == "mismatch"
        ]
        assert mismatches, "poisoned native fetch left no mismatch hop"
        assert any(
            victim.metadata() in str(r.get("source", "")) for r in mismatches
        )
        # the poison travelled the native plane, not a Python serve
        assert victim._frag_native.counters()["serves"] >= 1

    def test_mixed_fleet_interop_bitwise(self):
        """A stripe across native-armed AND python-only sources heals
        bitwise — per-fetch fallback makes the fleets interoperable in
        any mix."""
        state = make_state()
        transports = [
            HTTPTransport(timeout=10.0, native=True),
            HTTPTransport(timeout=10.0, native=False),
            HTTPTransport(timeout=10.0, native=True),
        ]
        try:
            threads = [
                threading.Thread(
                    target=t.send_checkpoint_streamed,
                    args=([1], 5, state, 10.0, 6),
                )
                for t in transports
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            # pace fetches so every source holds work: both planes serve
            faults.FAULTS.configure(
                [FaultRule(site="transport.heal.frag", action="delay",
                           delay=0.02, times=100)],
                seed=0,
            )
            local = clone_state(state)
            for v in local["user"].values():
                v[:] = 0.0
            healer = HTTPTransport(timeout=10.0)
            try:
                got, info = healer.recv_checkpoint_striped(
                    [t.metadata() for t in transports], 5, timeout=30.0,
                    local_state_fn=lambda: local, delta=False,
                )
            finally:
                healer.shutdown()
            assert_state_equal(got, state)
            assert info["sources"] == 3
            assert transports[1]._frag_native is None
        finally:
            for t in transports:
                t.shutdown()

    def test_injected_native_drop_is_absorbed(self):
        """A native-side injected drop (connection closed mid-exchange)
        takes the transport-error path: the fetch falls back to Python
        for that attempt and still lands the right bytes."""
        payload = b"d" * 8192
        t = HTTPTransport(timeout=10.0, native=True)
        try:
            stage_raw(t, 6, {"w0": payload})
            base = t.metadata()
            fetch_bytes(base, 6, "frag_w0")  # warm
            t._frag_native.inject("drop", count=1)
            assert fetch_bytes(base, 6, "frag_w0") == payload
            assert t._frag_native.counters()["injected_drops"] == 1
        finally:
            t.shutdown()
