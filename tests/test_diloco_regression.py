"""DiLoCo / LocalSGD numerics-exact regression vs committed golden files.

Mirrors the reference's golden-file strategy
(reference: torchft/diloco_regression_test.py + test_fixtures/*.json):
deterministic fixed-delta inner updates drive the real Manager + DiLoCo
stack over 2 thread-replicas; the full per-sync parameter history is
compared bitwise against JSON fixtures committed in tests/fixtures/.

Any change to the outer-optimizer math, pseudogradient computation,
fragment scheduling, or averaging semantics shows up as a fixture diff.

Regenerate (after an *intentional* semantics change) with:
    TORCHFT_TPU_REGEN_FIXTURES=1 python -m pytest tests/test_diloco_regression.py
"""

import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import numpy as np
import optax
import pytest

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.local_sgd import DiLoCo, LocalSGD
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroupTCP

FIXTURES = Path(__file__).parent / "fixtures"
REGEN = os.environ.get("TORCHFT_TPU_REGEN_FIXTURES") == "1"

N_REPLICAS = 2


def _train_replica(
    replica_id: int,
    lighthouse_addr: str,
    variant: dict,
    barrier: threading.Barrier,
) -> list:
    """Deterministic replica: inner delta depends on (replica, key index) so
    the outer average is distinguishable from any single replica's value."""
    params = {
        "layer0": np.zeros(4, dtype=np.float32),
        "layer1": np.zeros(4, dtype=np.float32),
    }
    holder = {"p": params}

    manager = Manager(
        pg=ProcessGroupTCP(timeout=20.0),
        min_replica_size=N_REPLICAS,
        lighthouse_addr=lighthouse_addr,
        replica_id=f"golden_{replica_id}",
        group_rank=0,
        group_world_size=1,
        use_async_quorum=False,
        timeout=30.0,
        quorum_timeout=30.0,
        load_state_dict=lambda sd: holder.__setitem__(
            "p", {k: np.array(v) for k, v in sd.items()}
        ),
        state_dict=lambda: {k: np.array(v) for k, v in holder["p"].items()},
    )
    history = []
    try:
        if variant["algo"] == "local_sgd":
            algo = LocalSGD(
                manager,
                lambda: dict(holder["p"]),
                lambda p: holder.__setitem__("p", dict(p)),
                sync_every=variant["sync_every"],
            )
        else:
            algo = DiLoCo(
                manager,
                variant["fragments"],
                lambda: dict(holder["p"]),
                lambda p: holder.__setitem__("p", dict(p)),
                optax.sgd(0.5, momentum=0.9, nesterov=True),
                sync_every=variant["sync_every"],
                fragment_sync_delay=variant.get("fragment_sync_delay", 0),
                fragment_update_alpha=variant.get("fragment_update_alpha", 0.0),
            )
        barrier.wait(timeout=60)
        last_step = manager.current_step()
        while manager.current_step() < variant["target_steps"]:
            p = dict(holder["p"])
            for i, k in enumerate(sorted(p)):
                p[k] = p[k] - np.float32(0.01 * (1 + i) * (1 + replica_id))
            holder["p"] = p
            algo.step()
            step = manager.current_step()
            if step != last_step:
                last_step = step
                history.append(
                    {
                        "step": step,
                        "params": {
                            k: [float(x) for x in holder["p"][k]]
                            for k in sorted(holder["p"])
                        },
                    }
                )
        return history
    finally:
        manager.shutdown()


VARIANTS = {
    "local_sgd": {"algo": "local_sgd", "sync_every": 3, "target_steps": 4},
    "diloco_1frag": {
        "algo": "diloco",
        "fragments": [["layer0", "layer1"]],
        "sync_every": 2,
        "target_steps": 3,
    },
    "diloco_2frag": {
        "algo": "diloco",
        "fragments": [["layer0"], ["layer1"]],
        "sync_every": 4,
        "target_steps": 6,
    },
    "diloco_2frag_delay1": {
        "algo": "diloco",
        "fragments": [["layer0"], ["layer1"]],
        "sync_every": 4,
        "fragment_sync_delay": 1,
        "target_steps": 6,
    },
    "diloco_2frag_alpha05": {
        "algo": "diloco",
        "fragments": [["layer0"], ["layer1"]],
        "sync_every": 4,
        "fragment_update_alpha": 0.5,
        "target_steps": 6,
    },
}


def _synced_keys(variant: dict, step: int) -> list:
    """Keys that must be bitwise-equal across replicas after commit ``step``.

    In streaming DiLoCo only the just-synced fragment is globally merged;
    the other fragments carry replica-local inner updates until their own
    sync. With ``fragment_update_alpha > 0`` even the synced fragment mixes
    in local params by design, so nothing is cross-replica comparable.
    """
    if variant.get("fragment_update_alpha", 0.0) > 0.0:
        return []
    if variant["algo"] == "local_sgd":
        return ["layer0", "layer1"]
    frags = variant["fragments"]
    return frags[(step - 1) % len(frags)]


def _run_variant(variant: dict) -> list:
    lighthouse = LighthouseServer(min_replicas=N_REPLICAS, join_timeout_ms=30000)
    try:
        barrier = threading.Barrier(N_REPLICAS)
        with ThreadPoolExecutor(max_workers=N_REPLICAS) as ex:
            futures = [
                ex.submit(_train_replica, r, lighthouse.address(), variant, barrier)
                for r in range(N_REPLICAS)
            ]
            histories = [f.result(timeout=180) for f in futures]
    finally:
        lighthouse.shutdown()

    # replicas must agree bitwise on every globally-synced fragment
    assert len(histories[0]) == len(histories[1]), "replicas saw different syncs"
    for rec0, rec1 in zip(histories[0], histories[1]):
        assert rec0["step"] == rec1["step"]
        for key in _synced_keys(variant, rec0["step"]):
            assert rec0["params"][key] == rec1["params"][key], (
                f"replicas diverged on synced fragment {key} at step {rec0['step']}"
            )
    return histories[0]


@pytest.mark.parametrize("name", sorted(VARIANTS))
def test_golden(name):
    history = _run_variant(VARIANTS[name])
    assert history, "no syncs committed"
    path = FIXTURES / f"{name}.json"
    if REGEN or not path.exists():
        FIXTURES.mkdir(exist_ok=True)
        path.write_text(json.dumps(history, indent=1, sort_keys=True) + "\n")
        if REGEN:
            pytest.skip(f"regenerated {path.name}")
    golden = json.loads(path.read_text())
    assert history == golden, (
        f"{name}: parameter history diverged from golden fixture {path.name}. "
        "If this change is intentional, regenerate with "
        "TORCHFT_TPU_REGEN_FIXTURES=1."
    )
