"""OTLP/HTTP exporter behind the events seam, tested against an
in-process fake collector (no egress; reference: torchft/otel.py:42-86
ships Tee(Console + OTLP-HTTP) with batching + resource attrs)."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from torchft_tpu.utils.logging import log_event, unregister_exporter
from torchft_tpu.utils.otel import (
    OTLPHTTPExporter,
    load_resource_attributes,
    maybe_install_from_env,
)


class _FakeCollector:
    """Minimal OTLP/HTTP logs collector: records every POST /v1/logs."""

    def __init__(self, status: int = 200):
        self.requests = []
        self.status = status
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                body = self.rfile.read(int(self.headers["Content-Length"]))
                outer.requests.append(
                    {"path": self.path, "body": json.loads(body)}
                )
                self.send_response(outer.status)
                self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self._srv.server_address[1]}"

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


@pytest.fixture
def collector():
    c = _FakeCollector()
    yield c
    c.close()


class TestOTLPExporter:
    def test_exports_otlp_log_shape(self, collector):
        exp = OTLPHTTPExporter(
            collector.endpoint,
            resource_attributes={"deployment": "test-pod"},
            flush_interval_s=0.1,
        )
        try:
            exp.export(
                {"ts": 1234.5, "kind": "quorum", "message": "joined",
                 "quorum_id": 7, "replica_id": "r0"}
            )
            assert exp.flush(timeout=5.0)
        finally:
            exp.close()
        assert len(collector.requests) == 1
        req = collector.requests[0]
        assert req["path"] == "/v1/logs"
        rl = req["body"]["resourceLogs"][0]
        res_attrs = {
            a["key"]: a["value"] for a in rl["resource"]["attributes"]
        }
        assert res_attrs["service.name"] == {"stringValue": "torchft_tpu"}
        assert res_attrs["deployment"] == {"stringValue": "test-pod"}
        rec = rl["scopeLogs"][0]["logRecords"][0]
        assert rec["timeUnixNano"] == str(int(1234.5 * 1e9))
        assert rec["severityText"] == "INFO"
        assert rec["body"] == {"stringValue": "joined"}
        attrs = {a["key"]: a["value"] for a in rec["attributes"]}
        assert attrs["event.kind"] == {"stringValue": "quorum"}
        assert attrs["quorum_id"] == {"intValue": "7"}
        assert attrs["replica_id"] == {"stringValue": "r0"}
        assert exp.exported == 1 and exp.dropped == 0

    def test_error_severity_and_batching(self, collector):
        exp = OTLPHTTPExporter(
            collector.endpoint, max_batch=2, flush_interval_s=30.0
        )
        try:
            # max_batch=2 triggers a flush without waiting the interval
            exp.export({"ts": 1.0, "kind": "error", "message": "boom"})
            exp.export({"ts": 2.0, "kind": "commit", "message": "ok"})
            assert exp.flush(timeout=5.0)
        finally:
            exp.close()
        recs = collector.requests[0]["body"]["resourceLogs"][0]["scopeLogs"][0][
            "logRecords"
        ]
        assert len(recs) == 2  # one batch, two records
        assert recs[0]["severityText"] == "ERROR"
        assert recs[0]["severityNumber"] == 17
        assert recs[1]["severityText"] == "INFO"

    def test_collector_down_never_raises(self):
        # nothing listens on this port: every batch drops, export/close
        # stay silent (a sink must never take down training)
        exp = OTLPHTTPExporter(
            "http://127.0.0.1:9",  # discard port, connection refused
            flush_interval_s=0.05,
            timeout_s=0.5,
        )
        try:
            exp.export({"ts": 1.0, "kind": "abort", "message": "x"})
            deadline = time.monotonic() + 5.0
            while exp.dropped == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            exp.close()
        assert exp.dropped == 1 and exp.exported == 0

    def test_collector_http_error_counts_dropped(self):
        c = _FakeCollector(status=503)
        exp = OTLPHTTPExporter(c.endpoint, flush_interval_s=0.05)
        try:
            exp.export({"ts": 1.0, "kind": "quorum", "message": "x"})
            deadline = time.monotonic() + 5.0
            while exp.dropped == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            exp.close()
            c.close()
        assert exp.dropped == 1

    def test_wired_through_event_pipeline(self, collector):
        exp = OTLPHTTPExporter(collector.endpoint, flush_interval_s=0.1)
        from torchft_tpu.utils.logging import register_exporter

        register_exporter(exp)
        try:
            log_event("quorum", "pipeline-test", quorum_id=42)
            assert exp.flush(timeout=5.0)
        finally:
            unregister_exporter(exp)
        bodies = [
            r["body"]["stringValue"]
            for req in collector.requests
            for sl in req["body"]["resourceLogs"][0]["scopeLogs"]
            for r in sl["logRecords"]
        ]
        assert "pipeline-test" in bodies

    def test_resource_attributes_file(self, tmp_path, monkeypatch):
        path = tmp_path / "attrs.json"
        path.write_text(
            json.dumps({"torchft_tpu": {"cluster": "c1"}, "other": {"x": 1}})
        )
        monkeypatch.setenv(
            "TORCHFT_OTEL_RESOURCE_ATTRIBUTES_JSON", str(path)
        )
        assert load_resource_attributes("torchft_tpu") == {"cluster": "c1"}
        assert load_resource_attributes("missing") == {}
        monkeypatch.setenv(
            "TORCHFT_OTEL_RESOURCE_ATTRIBUTES_JSON", str(tmp_path / "no.json")
        )
        assert load_resource_attributes() == {}

    def test_env_gate(self, collector, monkeypatch):
        monkeypatch.delenv("TORCHFT_USE_OTEL", raising=False)
        assert maybe_install_from_env() is None
        monkeypatch.setenv("TORCHFT_USE_OTEL", "true")
        monkeypatch.setenv(
            "OTEL_EXPORTER_OTLP_LOGS_ENDPOINT", collector.endpoint
        )
        exp = maybe_install_from_env()
        assert exp is not None
        try:
            log_event("commit", "gated", step=1)
            assert exp.flush(timeout=5.0)
        finally:
            unregister_exporter(exp)
        assert exp.exported >= 1
