"""Golden-file regressions for failure recovery and int8 quantized-sync
numerics (ISSUE 6 satellite; mirrors the reference's
``diloco_mocked_failure_recovery`` fixture scheme and our own
tests/test_diloco_regression.py).

Two fixtures under tests/fixtures/:

- ``failure_recovery.json``: a mocked deterministic optimizer (fixed
  per-step pseudo-gradients, momentum SGD) over 2 thread-replicas with a
  chaos-injected kill of replica 1 at a FIXED step and an immediate
  rejoin+heal.  The committed per-step parameter history of both
  replicas is compared bitwise — any change to heal semantics, the
  zero-contribution allreduce, commit lockstep, or averaging shows up as
  a fixture diff.

- ``quantized_sync_int8.json``: 3 deterministic outer-sync rounds of
  seeded pseudogradients through the REAL int8
  ``allreduce_quantized`` pipeline (2 ranks), applied by a mocked
  deterministic outer optimizer.  Pins the quantized wire numerics
  end to end (quantize -> alltoall -> fma-reduce -> requant ->
  allgather -> dequant -> average).

- ``quantized_sync_int8_hier.json``: the same scheme over the
  HIERARCHICAL reduction plan (4 ranks, topology ``hosts:2``,
  ops/topology.py).  Requantization at hop boundaries makes the
  hierarchical numerics intentionally different from the flat ring's —
  this fixture pins them independently (member quantize -> intra
  reduce -> inter exchange requant -> reduce -> requant -> gather ->
  broadcast -> dequant).

- ``delta_heal.json`` (ISSUE 15): a TRANSIENT crash at a fixed step —
  the replica's training loop dies and restarts but its parameter
  memory survives, with one leaf torn (zeroed) by the crash.  The
  rejoiner heals via the striped DELTA path: it hashes its own state
  into the source's fragment layout and fetches ONLY the fragments
  whose digest moved (the torn leaf + the torchft step counters).
  Pinned bitwise: the per-step per-leaf parameter sums of both
  replicas AND the changed-fragment count.

- ``cold_restore.json`` (ISSUE 17): a WHOLE-FLEET kill at a fixed step
  with parameter memory lost (fresh zeros on restart; only
  ``TORCHFT_STORE_DIR`` disks survive).  The fleet cold-restores the
  newest spilled cut through the striped fragment plane and resumes —
  the committed per-step parameter history, pre-kill AND post-restore,
  is pinned bitwise.  Any drift in spill timing (post-optimizer
  snapshot), cut selection, or the disk-backed striped reassembly moves
  the fixture.

Regenerate (after an *intentional* semantics change) with:
    TORCHFT_TPU_REGEN_FIXTURES=1 python -m pytest tests/test_golden_fixtures.py
"""

import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import numpy as np
import pytest

from tests.test_process_group import make_group, run_parallel, store  # noqa: F401
from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import REDUCE_AVG, ProcessGroupTCP
from torchft_tpu.utils import faults
from torchft_tpu.utils.faults import FaultRule, InjectedFault

FIXTURES = Path(__file__).parent / "fixtures"
REGEN = os.environ.get("TORCHFT_TPU_REGEN_FIXTURES") == "1"

KILL_REPLICA = 1
KILL_STEP = 2
TOTAL_STEPS = 5


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


def _check_or_regen(path: Path, produced) -> None:
    if REGEN or not path.exists():
        path.write_text(json.dumps(produced, indent=1, sort_keys=True) + "\n")
        if REGEN:
            pytest.skip(f"regenerated {path.name}")
    golden = json.loads(path.read_text())
    assert produced == golden, (
        f"{path.name} numerics drifted; if intentional, regenerate with "
        "TORCHFT_TPU_REGEN_FIXTURES=1"
    )


# ---------------------------------------------------------------------------
# failure recovery
# ---------------------------------------------------------------------------


def _recovery_replica(replica_id: int, lighthouse_addr: str) -> "list":
    """Deterministic momentum-SGD replica; kill+rejoin handled by the
    chaos layer + attempt loop, heal by the live checkpoint transport.
    Commits are lockstep (min_replica_size=2), so the committed history
    is value-deterministic regardless of restart timing."""
    history: "list" = []
    for _attempt in range(3):
        params = {"w": np.zeros(4, dtype=np.float32)}
        momentum = {"w": np.zeros(4, dtype=np.float32)}

        def load_state_dict(sd):
            params["w"] = np.array(sd["params"]["w"])
            momentum["w"] = np.array(sd["momentum"]["w"])

        def state_dict():
            return {
                "params": {"w": params["w"].copy()},
                "momentum": {"w": momentum["w"].copy()},
            }

        manager = Manager(
            pg=ProcessGroupTCP(timeout=10.0),
            min_replica_size=2,
            load_state_dict=load_state_dict,
            state_dict=state_dict,
            lighthouse_addr=lighthouse_addr,
            replica_id=f"golden_fr_{replica_id}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=False,
            timeout=20.0,
            quorum_timeout=20.0,
        )
        try:
            while manager.current_step() < TOTAL_STEPS:
                step = manager.current_step()
                faults.check(
                    "train.step", replica=f"golden_fr_{replica_id}", step=step
                )
                manager.start_quorum()
                grads = {
                    "w": np.full(4, float(step + 1), dtype=np.float32)
                    * (1.0 + 0.5 * replica_id)
                }
                avg = manager.allreduce(grads).wait(timeout=30)
                if manager.should_commit():
                    momentum["w"] = 0.9 * momentum["w"] + avg["w"]
                    params["w"] = params["w"] - 0.1 * momentum["w"]
                    history.append(
                        {
                            "step": manager.current_step(),
                            "w": [float(x) for x in params["w"]],
                            "momentum": [float(x) for x in momentum["w"]],
                        }
                    )
            return history
        except InjectedFault:
            continue  # process death: restart as a new incarnation
        finally:
            manager.shutdown()
    raise RuntimeError(f"replica {replica_id} exhausted attempts")


class TestFailureRecoveryGolden:
    def test_kill_and_rejoin_history_matches_fixture(self):
        faults.FAULTS.configure(
            [
                FaultRule(
                    site="train.step",
                    replica=f"golden_fr_{KILL_REPLICA}",
                    step=KILL_STEP,
                )
            ]
        )
        server = LighthouseServer(
            min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        try:
            with ThreadPoolExecutor(max_workers=2) as ex:
                futures = [
                    ex.submit(_recovery_replica, i, server.address())
                    for i in range(2)
                ]
                histories = [f.result(timeout=120) for f in futures]
        finally:
            server.shutdown()
        assert faults.FAULTS.injected() == 1

        produced = {
            "kill_replica": KILL_REPLICA,
            "kill_step": KILL_STEP,
            "total_steps": TOTAL_STEPS,
            "history": {
                f"replica_{i}": h for i, h in enumerate(histories)
            },
        }
        # structural invariants before the golden compare: lockstep
        # commits mean both replicas committed every step once, and the
        # post-heal tail is bitwise-identical across replicas
        for h in histories:
            assert [e["step"] for e in h] == list(range(1, TOTAL_STEPS + 1))
        assert histories[0][-1]["w"] == histories[1][-1]["w"]
        assert histories[0][-1]["momentum"] == histories[1][-1]["momentum"]
        _check_or_regen(FIXTURES / "failure_recovery.json", produced)


# ---------------------------------------------------------------------------
# int8 quantized sync
# ---------------------------------------------------------------------------

SYNC_ROUNDS = 3
QUANT_SHAPE = (6, 256)


class TestQuantizedSyncInt8Golden:
    def test_int8_sync_history_matches_fixture(self, store):  # noqa: F811
        from torchft_tpu.ops.collectives import allreduce_quantized

        world = 2
        pgs = make_group(store, world, prefix="golden_q")
        rng = np.random.default_rng(1234)
        # one deterministic pseudograd stream per (rank, round)
        grads = [
            [
                rng.standard_normal(QUANT_SHAPE).astype(np.float32)
                for _ in range(SYNC_ROUNDS)
            ]
            for _ in range(world)
        ]
        params = [
            np.zeros(QUANT_SHAPE, dtype=np.float32) for _ in range(world)
        ]

        def run(rank, _):
            out = []
            for rnd in range(SYNC_ROUNDS):
                work = allreduce_quantized(
                    [grads[rank][rnd].copy()], REDUCE_AVG, pgs[rank]
                )
                (avg,) = work.wait(timeout=30)
                # mocked deterministic outer optimizer
                params[rank] -= np.float32(0.1) * avg
                out.append(params[rank].copy())
            return out

        results = run_parallel(world, run)
        # both ranks bitwise identical every round
        for rnd in range(SYNC_ROUNDS):
            np.testing.assert_array_equal(results[0][rnd], results[1][rnd])

        produced = {
            "wire": "int8",
            "rounds": SYNC_ROUNDS,
            "shape": list(QUANT_SHAPE),
            "seed": 1234,
            # first row + checksums per round keep the fixture small while
            # still pinning every element (any elementwise drift moves the
            # bit-exact sums)
            "history": [
                {
                    "round": rnd,
                    "first_row": [float(x) for x in results[0][rnd][0]],
                    "sum": float(np.float64(results[0][rnd].sum(dtype=np.float64))),
                    "abs_sum": float(
                        np.float64(np.abs(results[0][rnd]).sum(dtype=np.float64))
                    ),
                }
                for rnd in range(SYNC_ROUNDS)
            ],
        }
        _check_or_regen(FIXTURES / "quantized_sync_int8.json", produced)


# ---------------------------------------------------------------------------
# delta heal (ISSUE 15)
# ---------------------------------------------------------------------------

DH_LEAVES = 6
DH_TORN_LEAF = "w3"
DH_KILL_STEP = 2
DH_TOTAL_STEPS = 5


def _delta_heal_replica(replica_id: int, lighthouse_addr: str):
    """Deterministic SGD over DH_LEAVES separate weight leaves.  A
    transient crash (train.step fault) kills the LOOP but not the
    parameter memory; the restart tears one leaf and rejoins — the delta
    heal must restore exactly the torn leaf + the torchft counters and
    reuse everything else from the rejoiner's own state."""
    rng = np.random.default_rng(99 + replica_id)  # unused: grads are f(step)
    del rng
    params = {
        f"w{i}": np.zeros(16, dtype=np.float32) for i in range(DH_LEAVES)
    }
    history: "list" = []
    for _attempt in range(3):

        def load_state_dict(sd):
            for k in params:
                params[k] = np.array(sd["params"][k])

        def state_dict():
            return {"params": {k: v.copy() for k, v in params.items()}}

        manager = Manager(
            pg=ProcessGroupTCP(timeout=10.0),
            min_replica_size=2,
            load_state_dict=load_state_dict,
            state_dict=state_dict,
            lighthouse_addr=lighthouse_addr,
            replica_id=f"golden_dh_{replica_id}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=False,
            timeout=20.0,
            quorum_timeout=20.0,
        )
        try:
            while manager.current_step() < DH_TOTAL_STEPS:
                step = manager.current_step()
                faults.check(
                    "train.step",
                    replica=f"golden_dh_{replica_id}",
                    step=step,
                )
                manager.start_quorum()
                grads = {
                    k: np.full(16, float(step + 1) * (i + 1),
                               dtype=np.float32)
                    for i, k in enumerate(params)
                }
                avg = manager.allreduce(grads).wait(timeout=30)
                if manager.should_commit():
                    for k in params:
                        params[k] = params[k] - np.float32(0.1) * avg[k]
                    history.append(
                        {
                            "step": manager.current_step(),
                            "sums": {
                                k: float(np.float64(
                                    params[k].sum(dtype=np.float64)
                                ))
                                for k in params
                            },
                        }
                    )
            return history
        except InjectedFault:
            # TRANSIENT crash: the loop dies, the parameter memory
            # survives — except one leaf torn by the crash.  The rejoin
            # must repair exactly that leaf (plus the step counters)
            # over the wire; the rest reuses the local state.
            params[DH_TORN_LEAF] = np.zeros(16, dtype=np.float32)
            continue
        finally:
            manager.shutdown()
    raise RuntimeError(f"replica {replica_id} exhausted attempts")


class TestDeltaHealGolden:
    def test_transient_crash_delta_heal_matches_fixture(self):
        from torchft_tpu.utils import metrics as _metrics

        faults.FAULTS.configure(
            [
                FaultRule(
                    site="train.step",
                    replica="golden_dh_1",
                    step=DH_KILL_STEP,
                )
            ]
        )
        delta_bytes_before = _metrics.HEAL_WIRE_BYTES.labels(
            mode="delta"
        ).get()
        server = LighthouseServer(
            min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        try:
            with ThreadPoolExecutor(max_workers=2) as ex:
                futures = [
                    ex.submit(_delta_heal_replica, i, server.address())
                    for i in range(2)
                ]
                histories = [f.result(timeout=120) for f in futures]
        finally:
            server.shutdown()
        assert faults.FAULTS.injected() == 1

        changed = int(_metrics.HEAL_CHANGED_FRAGMENTS.get())
        delta_bytes = (
            _metrics.HEAL_WIRE_BYTES.labels(mode="delta").get()
            - delta_bytes_before
        )
        # structural invariants first: the rejoin actually took the
        # delta path and its wire scaled with the changed set, not the
        # model — the torn leaf + torchft counters, nowhere near all
        # DH_LEAVES + 2 fragments
        assert 0 < changed <= 3
        full_payload = DH_LEAVES * 16 * 4
        assert 0 < delta_bytes < full_payload + 2048
        for h in histories:
            assert [e["step"] for e in h] == list(
                range(1, DH_TOTAL_STEPS + 1)
            )
        assert histories[0][-1]["sums"] == histories[1][-1]["sums"]

        produced = {
            "kill_step": DH_KILL_STEP,
            "torn_leaf": DH_TORN_LEAF,
            "total_steps": DH_TOTAL_STEPS,
            "leaves": DH_LEAVES,
            "changed_fragments": changed,
            "history": {
                f"replica_{i}": h for i, h in enumerate(histories)
            },
        }
        _check_or_regen(FIXTURES / "delta_heal.json", produced)


# ---------------------------------------------------------------------------
# whole-fleet cold restore (ISSUE 17)
# ---------------------------------------------------------------------------

CR_KILL_STEP = 2
CR_TOTAL_STEPS = 5


def _cold_restore_replica(
    replica_id: int,
    lighthouse_addr: str,
    restart_barrier: "threading.Barrier",
) -> "list":
    """Deterministic momentum-SGD replica for the cold-restore golden.
    A ``train.step`` fault is a process DEATH: parameters restart as
    fresh zeros — only the durable store survives.  The barrier holds
    every replica down until the whole fleet has crashed (and flushed
    its final spill in shutdown), so the restart is a true whole-fleet
    cold start, not a rolling restart that would live-heal."""
    history: "list" = []
    for _attempt in range(3):
        params = {"w": np.zeros(4, dtype=np.float32)}
        momentum = {"w": np.zeros(4, dtype=np.float32)}

        def load_state_dict(sd):
            params["w"] = np.array(sd["params"]["w"])
            momentum["w"] = np.array(sd["momentum"]["w"])

        def state_dict():
            return {
                "params": {"w": params["w"].copy()},
                "momentum": {"w": momentum["w"].copy()},
            }

        manager = Manager(
            pg=ProcessGroupTCP(timeout=10.0),
            min_replica_size=2,
            load_state_dict=load_state_dict,
            state_dict=state_dict,
            lighthouse_addr=lighthouse_addr,
            replica_id=f"golden_cr_{replica_id}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=False,
            timeout=20.0,
            quorum_timeout=20.0,
        )
        try:
            while manager.current_step() < CR_TOTAL_STEPS:
                faults.check(
                    "train.step",
                    replica=f"golden_cr_{replica_id}",
                    step=manager.current_step(),
                )
                manager.start_quorum()
                # post-quorum read: the cold restore advances the step
                # inside start_quorum, and the per-step pseudo-gradient
                # must follow the restored step for the history to align
                # with an uninterrupted run
                step = manager.current_step()
                grads = {
                    "w": np.full(4, float(step + 1), dtype=np.float32)
                    * (1.0 + 0.5 * replica_id)
                }
                avg = manager.allreduce(grads).wait(timeout=30)
                if manager.should_commit():
                    momentum["w"] = 0.9 * momentum["w"] + avg["w"]
                    params["w"] = params["w"] - np.float32(0.1) * momentum["w"]
                    history.append(
                        {
                            "step": manager.current_step(),
                            "w": [float(x) for x in params["w"]],
                            "momentum": [float(x) for x in momentum["w"]],
                        }
                    )
            return history
        except InjectedFault:
            restart_barrier.wait(timeout=60)
            continue  # whole-fleet outage: restart with memory LOST
        finally:
            manager.shutdown()
    raise RuntimeError(f"replica {replica_id} exhausted attempts")


class TestColdRestoreGolden:
    def test_fleet_kill_cold_restore_history_matches_fixture(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TORCHFT_STORE_DIR", str(tmp_path))
        faults.FAULTS.configure(
            [
                FaultRule(
                    site="train.step",
                    replica=f"golden_cr_{i}",
                    step=CR_KILL_STEP,
                )
                for i in range(2)
            ]
        )
        barrier = threading.Barrier(2)
        server = LighthouseServer(
            min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        try:
            with ThreadPoolExecutor(max_workers=2) as ex:
                futures = [
                    ex.submit(
                        _cold_restore_replica, i, server.address(), barrier
                    )
                    for i in range(2)
                ]
                histories = [f.result(timeout=180) for f in futures]
        finally:
            server.shutdown()
        assert faults.FAULTS.injected("train.step") == 2

        # structural invariants first: the fleet resumed at the spilled
        # step (each step committed exactly once — a fresh init would
        # recommit 1..KILL_STEP), and both replicas end bitwise equal
        for h in histories:
            assert [e["step"] for e in h] == list(
                range(1, CR_TOTAL_STEPS + 1)
            )
        assert histories[0][-1]["w"] == histories[1][-1]["w"]
        assert histories[0][-1]["momentum"] == histories[1][-1]["momentum"]

        produced = {
            "kill_step": CR_KILL_STEP,
            "total_steps": CR_TOTAL_STEPS,
            "history": {
                f"replica_{i}": h for i, h in enumerate(histories)
            },
        }
        _check_or_regen(FIXTURES / "cold_restore.json", produced)


HIER_WORLD = 4
HIER_TOPOLOGY = "hosts:2"


class TestHierarchicalSyncInt8Golden:
    def test_hier_int8_sync_history_matches_fixture(self, store):  # noqa: F811
        """Pins the hierarchical-plan numerics end to end: the
        hop-boundary requantization (ops/topology.py module docstring)
        legitimately changes results vs the flat ring, so the
        hierarchical sync gets its own committed golden."""
        from torchft_tpu.ops.collectives import allreduce_quantized

        pgs = make_group(store, HIER_WORLD, prefix="golden_qh")
        rng = np.random.default_rng(4321)
        grads = [
            [
                rng.standard_normal(QUANT_SHAPE).astype(np.float32)
                for _ in range(SYNC_ROUNDS)
            ]
            for _ in range(HIER_WORLD)
        ]
        params = [
            np.zeros(QUANT_SHAPE, dtype=np.float32)
            for _ in range(HIER_WORLD)
        ]

        def run(rank, _):
            out = []
            for rnd in range(SYNC_ROUNDS):
                work = allreduce_quantized(
                    [grads[rank][rnd].copy()], REDUCE_AVG, pgs[rank],
                    topology=HIER_TOPOLOGY,
                )
                (avg,) = work.wait(timeout=30)
                params[rank] -= np.float32(0.1) * avg
                out.append(params[rank].copy())
            return out

        results = run_parallel(HIER_WORLD, run)
        # every rank dequantizes the same reduced-piece bytes: bitwise
        # identical across ALL ranks every round
        for rnd in range(SYNC_ROUNDS):
            for r in range(1, HIER_WORLD):
                np.testing.assert_array_equal(
                    results[0][rnd], results[r][rnd]
                )

        produced = {
            "wire": "int8",
            "topology": HIER_TOPOLOGY,
            "world": HIER_WORLD,
            "rounds": SYNC_ROUNDS,
            "shape": list(QUANT_SHAPE),
            "seed": 4321,
            "history": [
                {
                    "round": rnd,
                    "first_row": [float(x) for x in results[0][rnd][0]],
                    "sum": float(np.float64(results[0][rnd].sum(dtype=np.float64))),
                    "abs_sum": float(
                        np.float64(np.abs(results[0][rnd]).sum(dtype=np.float64))
                    ),
                }
                for rnd in range(SYNC_ROUNDS)
            ],
        }
        _check_or_regen(FIXTURES / "quantized_sync_int8_hier.json", produced)
