"""Topology-aware hierarchical collectives + the WAN (RTT) wire model.

Covers ISSUE 8's tentpole surface:

- ``TORCHFT_TOPOLOGY`` parsing and plan synthesis (ops/topology.py);
- the hierarchical multi-hop quantized allreduce: correctness vs the f32
  truth, bit-identical results across ALL ranks, chunked-vs-monolithic
  bit parity, fp8 wire, device (Pallas interpret) path, env-driven
  topology, pool steady state;
- the RTT wire model: K pacing chunks pay 1x RTT (latency decoupled from
  the bandwidth debt), intra-group messages skip it;
- chaos: an injected ``pg.allreduce.hop`` failure mid-pipeline aborts
  cleanly on every rank and the SAME process groups complete a clean
  collective afterwards.
"""

import time

import numpy as np
import pytest

from tests.test_process_group import make_group, run_parallel, store  # noqa: F401
from torchft_tpu.ops import quantization as q
from torchft_tpu.ops import topology as T
from torchft_tpu.ops.collectives import allreduce_quantized
from torchft_tpu.parallel.process_group import (
    REDUCE_AVG,
    REDUCE_SUM,
    ProcessGroupTCP,
)


class TestTopologyParse:
    def test_flat_spellings(self):
        assert T.parse_topology("", 4) is None
        assert T.parse_topology("flat", 4) is None
        assert T.parse_topology("  Flat ", 4) is None

    def test_hosts_k(self):
        topo = T.parse_topology("hosts:2", 5)
        assert topo.groups == ((0, 1), (2, 3), (4,))
        assert topo.leaders() == [0, 2, 4]
        assert topo.members(0) == [1]
        assert topo.inter(0, 2) and not topo.inter(2, 3)

    def test_hosts_k_adapts_to_world(self):
        # elastic shrink re-ranks; hosts:K must keep partitioning cleanly
        for world in (1, 2, 3, 7):
            topo = T.parse_topology("hosts:4", world)
            if topo is not None:
                assert sorted(r for g in topo.groups for r in g) == list(
                    range(world)
                )

    def test_explicit_groups(self):
        topo = T.parse_topology("0,3;1,2", 4)
        assert topo.groups == ((0, 3), (1, 2))
        assert topo.leader(0) == 0 and topo.leader(1) == 1
        assert topo.group_index(3) == 0

    def test_explicit_world_mismatch_rejected(self):
        with pytest.raises(ValueError, match="lists 4 ranks"):
            T.parse_topology("0,1;2,3", 5)

    def test_malformed_rejected(self):
        with pytest.raises(ValueError):
            T.parse_topology("hosts:zero", 4)
        with pytest.raises(ValueError):
            T.parse_topology("hosts:0", 4)
        with pytest.raises(ValueError):
            T.parse_topology("0,1;1,2", 4)  # duplicate rank
        with pytest.raises(ValueError):
            T.parse_topology("a,b", 2)

    def test_spec_round_trip(self):
        topo = T.parse_topology("0,1;2,3,4", 5)
        assert T.parse_topology(topo.describe(), 5).groups == topo.groups


class TestPlanSynthesis:
    def test_leader_and_member_hops(self):
        topo = T.parse_topology("hosts:2", 4)
        lead = T.synthesize_plan(topo, 2)
        memb = T.synthesize_plan(topo, 3)
        assert lead.is_leader and not memb.is_leader
        names = [h.name for h in lead.hops]
        assert names == [
            "intra.reduce", "inter.exchange", "inter.gather", "intra.bcast"
        ]
        assert lead.hops[0].recvs == (3,)
        assert lead.hops[1].sends == (0,) and lead.hops[1].paired
        assert lead.hops[3].sends == (3,)
        assert memb.hops[0].sends == (2,)
        assert memb.hops[3].recvs == (2,)

    def test_pairwise_offsets_cover_all_leaders(self):
        topo = T.parse_topology("hosts:1", 5)  # every rank its own host
        for r in range(5):
            plan = T.synthesize_plan(topo, r)
            ex = plan.hops[1]
            assert sorted(ex.sends) == sorted(x for x in range(5) if x != r)
            assert sorted(ex.recvs) == sorted(ex.sends)
            # offset schedule: send at +o pairs with recv at -o, so every
            # rank's o-th exchange targets a rank whose o-th exchange
            # targets it back
            for o, (dst, src) in enumerate(zip(ex.sends, ex.recvs)):
                peer = T.synthesize_plan(topo, dst).hops[1]
                assert peer.recvs[o] == r


_SHAPES = ((100, 501), (50_000,))


def _data(world, seed=5):
    rng = np.random.default_rng(seed)
    return [
        [rng.standard_normal(s).astype(np.float32) for s in _SHAPES]
        for _ in range(world)
    ]


def _run_hier(pgs, data, topo, op=REDUCE_AVG, wire_dtype=None, **kw):
    def run(rank, _):
        w = allreduce_quantized(
            data[rank], op, pgs[rank], topology=topo, wire_dtype=wire_dtype,
            **kw,
        )
        out = w.wait(timeout=60)
        return out, dict(w.quant_stats), w.wire_bytes, w.inter_wire_bytes

    return run_parallel(len(pgs), run)


class TestHierarchicalAllreduce:
    def test_correct_and_bitwise_identical_across_ranks(self, store):  # noqa: F811
        world = 4
        pgs = make_group(store, world, prefix="hier4")
        data = _data(world)
        expected = [sum(d[i] for d in data) / world for i in range(len(_SHAPES))]
        results = _run_hier(pgs, data, "hosts:2")
        for out, stats, _, _ in results:
            assert stats["topology"] == "0,1;2,3"
            for got, want in zip(out, expected):
                rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
                assert rel < 0.05, rel
        # per-hop wire telemetry covers this rank's plan hops: leaders
        # run all four; members only touch the wire on the intra hops
        for r, (_, stats, _, _) in enumerate(results):
            want_hops = (
                {"intra.reduce", "inter.exchange", "inter.gather",
                 "intra.bcast"}
                if r in (0, 2)
                else {"intra.reduce", "intra.bcast"}
            )
            assert set(stats["hop_wire_s"]) == want_hops, (r, stats)
        # every rank dequantizes the same reduced-piece bytes
        for i in range(len(_SHAPES)):
            for r in range(1, world):
                np.testing.assert_array_equal(
                    results[0][0][i], results[r][0][i]
                )
        # members pay no inter-host egress; leaders pay both inter hops
        for r, (_, _, wire, inter) in enumerate(results):
            if r in (0, 2):
                assert inter > 0 and wire > inter
            else:
                assert inter == 0 and wire > 0
        for pg in pgs:
            pg.shutdown()

    def test_uneven_groups_and_sum(self, store):  # noqa: F811
        world = 5  # hosts:2 -> {0,1},{2,3},{4}: a solo-leader group
        pgs = make_group(store, world, prefix="hier5")
        data = _data(world, seed=9)
        expected = [sum(d[i] for d in data) for i in range(len(_SHAPES))]
        results = _run_hier(pgs, data, "hosts:2", op=REDUCE_SUM)
        for out, _, _, _ in results:
            for got, want in zip(out, expected):
                rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
                assert rel < 0.05, rel
        for pg in pgs:
            pg.shutdown()

    def test_single_group_topology(self, store):  # noqa: F811
        # one host: no inter hops at all, pure intra reduce + bcast
        world = 3
        pgs = make_group(store, world, prefix="hier1g")
        data = _data(world, seed=3)
        expected = [sum(d[i] for d in data) / world for i in range(len(_SHAPES))]
        results = _run_hier(pgs, data, "0,1,2")
        for out, stats, _, inter in results:
            assert inter == 0
            assert "inter.exchange" not in stats["hop_wire_s"]
            for got, want in zip(out, expected):
                rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
                assert rel < 0.05, rel
        for pg in pgs:
            pg.shutdown()

    @pytest.mark.parametrize("wire_dtype", [q.WIRE_INT8, q.WIRE_FP8])
    def test_chunked_bitwise_parity(
        self, store, monkeypatch, wire_dtype  # noqa: F811
    ):
        """Chunked vs monolithic hierarchical output must be BIT-identical
        for both wire formats (per-row codec + row chunking, same
        argument as the flat pipeline's parity)."""
        world = 4
        data = _data(world, seed=11)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", str(10**9))
        pgs = make_group(store, world, prefix=f"hm{wire_dtype}")
        mono = _run_hier(pgs, data, "hosts:2", wire_dtype=wire_dtype)
        for pg in pgs:
            pg.shutdown()
        assert mono[0][1]["n_chunks"] == 1
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "4")
        pgs = make_group(store, world, prefix=f"hc{wire_dtype}")
        chunked = _run_hier(pgs, data, "hosts:2", wire_dtype=wire_dtype)
        for pg in pgs:
            pg.shutdown()
        assert chunked[0][1]["n_chunks"] > 2
        for (mo, _, _, _), (co, _, _, _) in zip(mono, chunked):
            for a, b in zip(mo, co):
                np.testing.assert_array_equal(a, b)

    def test_env_topology_drives_plan(self, store, monkeypatch):  # noqa: F811
        monkeypatch.setenv("TORCHFT_TOPOLOGY", "hosts:2")
        world = 4
        pgs = make_group(store, world, prefix="hienv")
        data = _data(world, seed=2)
        results = _run_hier(pgs, data, None)  # None -> env default
        assert results[0][1]["topology"] == "0,1;2,3"
        for pg in pgs:
            pg.shutdown()

    def test_device_path_parity(self, store, monkeypatch):  # noqa: F811
        """Pallas (interpret-mode) device quantize through the
        hierarchical chunked pipeline: bit-identical to the monolithic
        device run, ~quantization-error close to the f32 truth."""
        import jax.numpy as jnp

        world = 4
        data = _data(world, seed=13)

        def run_dev(pgs):
            def run(rank, _):
                arrays = [jnp.asarray(a) for a in data[rank]]
                w = allreduce_quantized(
                    arrays, REDUCE_SUM, pgs[rank], device_quantize=True,
                    topology="hosts:2",
                )
                return w.wait(timeout=90), dict(w.quant_stats)

            return run_parallel(world, run)

        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", str(10**9))
        pgs = make_group(store, world, prefix="hdm")
        mono = run_dev(pgs)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "8")
        pgs2 = make_group(store, world, prefix="hdc")
        chunked = run_dev(pgs2)
        for pg in pgs + pgs2:
            pg.shutdown()
        assert chunked[0][1]["n_chunks"] > 1
        for (mo, _), (co, _) in zip(mono, chunked):
            for a, b in zip(mo, co):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        expected = [sum(d[i] for d in data) for i in range(len(_SHAPES))]
        for got, want in zip(mono[0][0], expected):
            rel = np.abs(np.asarray(got) - want).max() / (
                np.abs(want).max() + 1e-9
            )
            assert rel < 0.05, rel

    def test_pool_steady_state(self, store, monkeypatch):  # noqa: F811
        """A repeat hierarchical collective of the same shape takes every
        staging buffer — stage-1 stacks, accumulators, exchange bufs,
        pieces, broadcast bundles, pool-backed receives — from the pool:
        no new allocations in steady state (also catches double-gives,
        which corrupt parity)."""
        from torchft_tpu.utils.bufpool import POOL

        world = 4
        data = _data(world, seed=6)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "8")
        pgs = make_group(store, world, prefix="hpool")
        # two warm rounds: the 4 thread-ranks share ONE process pool, so
        # a run's peak concurrent footprint varies a little with give/take
        # interleaving across ranks — the second round covers the spread
        _run_hier(pgs, data, "hosts:2")
        _run_hier(pgs, data, "hosts:2")
        misses_before = POOL.misses
        results = _run_hier(pgs, data, "hosts:2")
        misses_after = POOL.misses
        for pg in pgs:
            pg.shutdown()
        assert results[0][1]["n_chunks"] > 2
        # a LEAK (buffer never given back) or a double-give would grow
        # misses by O(chunks x ranks) per run; cross-rank timing jitter
        # is at most a couple of takes racing their gives
        assert misses_after - misses_before <= 3, (
            f"steady-state pool misses grew: {misses_before} -> {misses_after}"
        )

    def test_topology_world_mismatch_fails_loudly(self, store):  # noqa: F811
        pgs = make_group(store, 2, prefix="hmis")
        topo = T.parse_topology("0,1;2,3", 4)
        with pytest.raises(ValueError, match="topology"):
            allreduce_quantized(
                [np.ones((8, 8), np.float32)], REDUCE_SUM, pgs[0],
                topology=topo,
            )
        for pg in pgs:
            pg.shutdown()


class TestSendRecv:
    def test_pairwise_exchange(self, store):  # noqa: F811
        world = 3
        pgs = make_group(store, world, prefix="srx")

        def run(rank, _):
            out = []
            for off in range(1, world):
                dst = (rank + off) % world
                src = (rank - off) % world
                got = pgs[rank].sendrecv(
                    np.full(64, float(rank), np.float32), dst, src, tag=off
                ).wait(timeout=20)
                out.append((src, got))
            return out

        for rank, pairs in enumerate(run_parallel(world, run)):
            for src, got in pairs:
                np.testing.assert_array_equal(
                    got, np.full(64, float(src), np.float32)
                )
        for pg in pgs:
            pg.shutdown()


class TestWanWireModel:
    RTT_MS = 120.0

    def test_rtt_and_bandwidth_compose_once_per_message(self, store):  # noqa: F811
        """A 4 MiB message paced in 4 x 1 MiB token-bucket chunks pays
        ONE first-byte RTT plus the serialization time — never K x RTT
        (the decoupling the WAN model promises)."""
        world = 2
        # serialization at 0.2 GB/s for 4 MiB ~ 21 ms << RTT
        pgs = [
            ProcessGroupTCP(
                timeout=20.0, bandwidth_gbps=0.2, rtt_ms=self.RTT_MS
            )
            for _ in range(world)
        ]

        def cfg(rank, _):
            pgs[rank].configure(
                f"{store.address()}/rttc", f"r{rank}", rank, world
            )

        run_parallel(world, cfg)
        payload = np.ones(1 << 20, dtype=np.float32)  # 4 MiB

        def run(rank, _):
            if rank == 0:
                t0 = time.perf_counter()
                pgs[0].send(payload, 1, tag=7).wait(timeout=20)
                return time.perf_counter() - t0
            pgs[1].recv(0, tag=7).wait(timeout=20)
            return 0.0

        wall = max(run_parallel(world, run))
        rtt_s = self.RTT_MS / 1e3
        assert wall >= rtt_s, f"first-byte delay missing: {wall}"
        assert wall < 2.5 * rtt_s, (
            f"pacing chunks multiplied RTT: wall={wall:.3f}s"
        )
        for pg in pgs:
            pg.shutdown()

    def test_intra_group_messages_skip_rtt(self, store, monkeypatch):  # noqa: F811
        monkeypatch.setenv("TORCHFT_TOPOLOGY", "0,1")
        world = 2
        pgs = [
            ProcessGroupTCP(timeout=20.0, rtt_ms=self.RTT_MS)
            for _ in range(world)
        ]

        def cfg(rank, _):
            pgs[rank].configure(
                f"{store.address()}/rtti", f"r{rank}", rank, world
            )

        run_parallel(world, cfg)
        payload = np.ones(1024, dtype=np.float32)

        def run(rank, _):
            if rank == 0:
                t0 = time.perf_counter()
                pgs[0].send(payload, 1, tag=3).wait(timeout=20)
                return time.perf_counter() - t0
            pgs[1].recv(0, tag=3).wait(timeout=20)
            return 0.0

        wall = max(run_parallel(world, run))
        assert wall < self.RTT_MS / 1e3 / 2, (
            f"intra-group message paid the boundary RTT: {wall:.3f}s"
        )
        for pg in pgs:
            pg.shutdown()

    def test_flat_topology_charges_every_peer(self, store):  # noqa: F811
        # no TORCHFT_TOPOLOGY: the multi-region flat premise — every
        # peer is across a boundary
        world = 2
        pgs = [
            ProcessGroupTCP(timeout=20.0, rtt_ms=80.0) for _ in range(world)
        ]

        def cfg(rank, _):
            pgs[rank].configure(
                f"{store.address()}/rttf", f"r{rank}", rank, world
            )

        run_parallel(world, cfg)

        def run(rank, _):
            if rank == 0:
                t0 = time.perf_counter()
                pgs[0].send(
                    np.ones(16, np.float32), 1, tag=1
                ).wait(timeout=20)
                return time.perf_counter() - t0
            pgs[1].recv(0, tag=1).wait(timeout=20)
            return 0.0

        wall = max(run_parallel(world, run))
        assert wall >= 0.08, f"flat-topology RTT not charged: {wall:.3f}s"
        for pg in pgs:
            pg.shutdown()


class TestHopChaos:
    def test_inter_hop_fault_aborts_cleanly_and_pg_reuses(
        self, store, monkeypatch  # noqa: F811
    ):
        """An injected ``pg.allreduce.hop`` failure (step = chunk 1, i.e.
        after chunk 0's inter hops are on the wire) must fail the Work
        promptly on EVERY rank — all drivers stop at the same submission
        point — and the same PGs must complete a clean hierarchical
        collective afterwards (docs/robustness.md)."""
        from torchft_tpu.utils import faults
        from torchft_tpu.utils.faults import FaultRule, InjectedFault

        world = 4
        data = _data(world, seed=8)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "8")
        pgs = make_group(store, world, prefix="hchaos")
        faults.FAULTS.configure(
            [FaultRule(site="pg.allreduce.hop", step=1, times=world)],
            seed=1,
        )

        def run(rank, _):
            w = allreduce_quantized(
                [data[rank][1]], REDUCE_SUM, pgs[rank], topology="hosts:2"
            )
            t0 = time.perf_counter()
            try:
                w.wait(timeout=30)
                return None, 0.0
            except Exception as e:  # noqa: BLE001
                return e, time.perf_counter() - t0

        results = run_parallel(world, run)
        for exc, elapsed in results:
            assert isinstance(exc, InjectedFault), exc
            assert elapsed < 20.0, "mid-pipeline hop abort did not drain"
        assert faults.FAULTS.injected("pg.allreduce.hop") == world

        faults.FAULTS.configure([], seed=0)
        expected = sum(d[1] for d in data)

        def clean(rank, _):
            return allreduce_quantized(
                [data[rank][1]], REDUCE_SUM, pgs[rank], topology="hosts:2"
            ).wait(timeout=30)

        for (out,) in run_parallel(world, clean):
            rel = np.abs(out - expected).max() / (np.abs(expected).max() + 1e-9)
            assert rel < 0.05, rel
        for pg in pgs:
            pg.shutdown()
