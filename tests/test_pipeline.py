"""GPipe pipeline parallelism: schedule correctness vs sequential scan."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from torchft_tpu.parallel.pipeline import pipeline_apply


def _layer(x, p):
    w, b = p
    return jnp.tanh(x @ w + b)


def _stack(n_layers, d, seed=0):
    key = jax.random.PRNGKey(seed)
    ws = jax.random.normal(jax.random.fold_in(key, 0), (n_layers, d, d)) / np.sqrt(d)
    bs = jax.random.normal(jax.random.fold_in(key, 1), (n_layers, d)) * 0.1
    return (ws, bs)


def _sequential(params, x):
    def body(h, p):
        return _layer(h, p), None

    out, _ = jax.lax.scan(body, x, params)
    return out


def _pp_mesh(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("pp",))


class TestPipelineForward:
    @pytest.mark.parametrize("stages", [1, 2, 4])
    @pytest.mark.parametrize("microbatches", [2, 4, 8])
    def test_matches_sequential(self, stages, microbatches):
        if microbatches > 8:
            pytest.skip("batch too small")
        params = _stack(8, 16)
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 16))
        ref = _sequential(params, x)
        out = pipeline_apply(
            params, x, _layer, _pp_mesh(stages), microbatches=microbatches
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_with_dp_axis(self):
        params = _stack(4, 16)
        x = jax.random.normal(jax.random.PRNGKey(3), (8, 16))
        ref = _sequential(params, x)
        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "pp"))
        out = pipeline_apply(
            params, x, _layer, mesh, microbatches=4, batch_axes=("dp",)
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_batch_not_divisible_raises(self):
        params = _stack(4, 8)
        x = jnp.zeros((6, 8))
        with pytest.raises(ValueError, match="divisible"):
            pipeline_apply(params, x, _layer, _pp_mesh(2), microbatches=4)

    def test_tp_sharded_weights_preserved(self):
        # partial-manual mode: fsdp/tp weight shardings must survive inside
        # the pipe (stage weights NOT replicated) and still compute right
        from jax.sharding import NamedSharding, PartitionSpec as P

        params = _stack(4, 16)
        x = jax.random.normal(jax.random.PRNGKey(9), (8, 16))
        ref = _sequential(params, x)
        mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dp", "tp", "pp"))
        sharded = (
            jax.device_put(params[0], NamedSharding(mesh, P("pp", None, "tp"))),
            jax.device_put(params[1], NamedSharding(mesh, P("pp", "tp"))),
        )
        xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        out = jax.jit(
            lambda p, xx: pipeline_apply(p, xx, _layer, mesh, microbatches=4)
        )(sharded, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_validation_errors(self):
        params = _stack(4, 8)
        x = jnp.zeros((8, 8))
        with pytest.raises(ValueError, match="no 'pp' axis"):
            pipeline_apply(
                params, x, _layer,
                Mesh(np.array(jax.devices()[:2]).reshape(2), ("dp",)),
            )
        with pytest.raises(ValueError, match="not divisible by pp"):
            pipeline_apply(params, x, _layer, _pp_mesh(8))

    def test_3d_activations(self):
        # [B, T, E] transformer-shaped activations
        params = _stack(4, 8)
        x = jax.random.normal(jax.random.PRNGKey(5), (4, 6, 8))
        ref = _sequential(params, x)
        out = pipeline_apply(params, x, _layer, _pp_mesh(4), microbatches=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


class TestPipelineBackward:
    def test_grads_match_sequential(self):
        params = _stack(4, 12)
        x = jax.random.normal(jax.random.PRNGKey(7), (8, 12))
        mesh = _pp_mesh(4)

        def pp_loss(p):
            return (pipeline_apply(p, x, _layer, mesh, microbatches=4) ** 2).mean()

        def seq_loss(p):
            return (_sequential(p, x) ** 2).mean()

        g_pp = jax.grad(pp_loss)(params)
        g_seq = jax.grad(seq_loss)(params)
        for gp, gs in zip(jax.tree_util.tree_leaves(g_pp),
                          jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(
                np.asarray(gp), np.asarray(gs), atol=1e-5
            )

    def test_jit_train_step(self):
        params = _stack(4, 12)
        x = jax.random.normal(jax.random.PRNGKey(8), (8, 12))
        mesh = _pp_mesh(4)

        @jax.jit
        def step(p):
            loss, grads = jax.value_and_grad(
                lambda pp: (pipeline_apply(pp, x, _layer, mesh, microbatches=4) ** 2).mean()
            )(p)
            return loss, grads

        loss, grads = step(params)
        assert np.isfinite(float(loss))


class TestPipelinedTransformer:
    def _cfg(self, **kw):
        from torchft_tpu.models import transformer as tfm

        base = dict(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, d_ff=64,
            n_layers=4, max_seq_len=32, dtype=jnp.float32, attn_impl="dense",
        )
        base.update(kw)
        return tfm.TransformerConfig(**base)

    def test_matches_sequential_forward(self):
        from torchft_tpu.models import transformer as tfm

        cfg = self._cfg()
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        ref = tfm.forward(params, tokens, cfg)
        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "pp"))
        out = tfm.forward_pipelined(params, tokens, cfg, mesh, microbatches=2)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
        )

    def test_grads_and_jit(self):
        from torchft_tpu.models import transformer as tfm

        cfg = self._cfg()
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pp",))

        @jax.jit
        def step(p):
            def loss(pp):
                logits = tfm.forward_pipelined(
                    pp, tokens, cfg, mesh, microbatches=4
                )[:, :-1]
                lp = jax.nn.log_softmax(logits, axis=-1)
                return -jnp.take_along_axis(
                    lp, tokens[:, 1:, None], axis=-1
                ).mean()

            return jax.value_and_grad(loss)(p)

        loss, grads = step(params)
        assert np.isfinite(float(loss))
        for leaf in jax.tree_util.tree_leaves(grads):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_with_tp_sharded_weights(self):
        # partial-manual pipeline: tp weight sharding flows automatically
        # through the pipelined transformer
        from torchft_tpu.models import transformer as tfm

        cfg = self._cfg(n_kv_heads=4, max_seq_len=16)
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        ref = tfm.forward(params, tokens, cfg)

        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("tp", "pp"))
        sharded = tfm.shard_params(params, mesh, cfg)
        out = jax.jit(
            lambda p, t: tfm.forward_pipelined(p, t, cfg, mesh, microbatches=2)
        )(sharded, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
        )

    def test_rejects_unknown_attn_impl(self):
        import dataclasses

        from torchft_tpu.models import transformer as tfm

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pp",))
        tokens = jnp.zeros((4, 8), jnp.int32)
        cfg = dataclasses.replace(self._cfg(), attn_impl="bogus")
        params = tfm.init_params(jax.random.PRNGKey(0), self._cfg())
        with pytest.raises(ValueError, match="does not support attn_impl"):
            tfm.forward_pipelined(params, tokens, cfg, mesh)


class TestPipelineWithUlysses:
    def test_pp_ulysses_composition_matches_dense(self):
        # pipeline manual over (pp, cp): each stage runs the local ulysses
        # all-to-all body over its sequence chunk
        import dataclasses

        from torchft_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, d_ff=64,
            n_layers=4, max_seq_len=32, dtype=jnp.float32,
            attn_impl="ulysses",
        )
        cfg_dense = dataclasses.replace(cfg, attn_impl="dense")
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        ref = tfm.forward(params, tokens, cfg_dense)

        # cp=2 divides both head counts (4 q / 2 kv)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("cp", "pp"))
        out = jax.jit(
            lambda p, t: tfm.forward_pipelined(p, t, cfg, mesh, microbatches=2)
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
        )


class TestPipelineWithMoE:
    def _cfg(self, **kw):
        from torchft_tpu.models import transformer as tfm

        base = dict(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=48,
            n_layers=4, max_seq_len=16, dtype=jnp.float32, attn_impl="dense",
            n_experts=4, moe_top_k=2,
            # capacity must fit every routed token: the pipelined path
            # computes capacity per MICROBATCH, the flat path per batch —
            # with no drops both produce identical outputs
            moe_capacity_factor=4.0,
        )
        base.update(kw)
        return tfm.TransformerConfig(**base)

    def test_pp_ep_matches_flat_forward(self):
        from torchft_tpu.models import transformer as tfm

        cfg = self._cfg()
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        ref = tfm.forward(params, tokens, cfg)

        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("ep", "pp"))
        out, aux = jax.jit(
            lambda p, t: tfm.forward_pipelined(
                p, t, cfg, mesh, microbatches=2, return_aux=True
            )
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
        )
        # load-balance aux rode the pipe: positive finite scalar near the
        # flat-forward value (batch stats differ per microbatch)
        aux = float(aux)
        assert np.isfinite(aux) and aux > 0

    def test_pp_ep_grads_finite(self):
        from torchft_tpu.models import transformer as tfm

        cfg = self._cfg(n_layers=2)
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("ep", "pp"))

        @jax.jit
        def step(p):
            def loss(pp):
                logits, aux = tfm.forward_pipelined(
                    pp, tokens, cfg, mesh, microbatches=2, return_aux=True
                )
                logits = logits[:, :-1]
                lp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(
                    lp, tokens[:, 1:, None], axis=-1
                ).mean()
                return nll + cfg.moe_aux_weight * aux

            return jax.value_and_grad(loss)(p)

        loss, grads = step(params)
        assert np.isfinite(float(loss))
        for leaf in jax.tree_util.tree_leaves(grads):
            assert np.isfinite(np.asarray(leaf)).all()





class TestPipelineWithRingAttention:
    def test_pp_cp_composition_matches_dense(self):
        # pipeline manual over (pp, cp): each stage runs local ring
        # attention over its sequence chunk with global rotary positions
        from torchft_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, d_ff=64,
            n_layers=4, max_seq_len=32, dtype=jnp.float32, attn_impl="ring",
        )
        import dataclasses

        cfg_dense = dataclasses.replace(cfg, attn_impl="dense")
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        ref = tfm.forward(params, tokens, cfg_dense)

        mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("cp", "pp"))
        out = jax.jit(
            lambda p, t: tfm.forward_pipelined(p, t, cfg, mesh, microbatches=2)
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
        )

    def test_pp_cp_grads_finite(self):
        from torchft_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=64,
            n_layers=2, max_seq_len=16, dtype=jnp.float32, attn_impl="ring",
        )
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dp", "cp", "pp"))

        @jax.jit
        def step(p):
            def loss(pp):
                logits = tfm.forward_pipelined(
                    pp, tokens, cfg, mesh, microbatches=2
                )[:, :-1]
                lp = jax.nn.log_softmax(logits, axis=-1)
                return -jnp.take_along_axis(
                    lp, tokens[:, 1:, None], axis=-1
                ).mean()

            return jax.value_and_grad(loss)(p)

        loss, grads = step(params)
        assert np.isfinite(float(loss))
        for leaf in jax.tree_util.tree_leaves(grads):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_ring_requires_cp_axis(self):
        from torchft_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=4, d_ff=64,
            n_layers=4, max_seq_len=16, dtype=jnp.float32, attn_impl="ring",
        )
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((4, 16), jnp.int32)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pp",))
        with pytest.raises(ValueError, match="requires a 'cp' mesh axis"):
            tfm.forward_pipelined(params, tokens, cfg, mesh)
