"""BufferPool: the host-collective staging allocator (utils/bufpool.py).

The pool's contract is safety-critical for the quantized collectives:
give() must only ever accept memory the caller exclusively owns, because
a pooled buffer is handed out again to arbitrary concurrent takers."""

import threading

import numpy as np

from torchft_tpu.utils.bufpool import BufferPool


class TestBufferPool:
    def test_take_give_reuse(self):
        pool = BufferPool(max_bytes=1 << 20)
        a = pool.take((16, 32), np.float32)
        assert a.shape == (16, 32) and a.dtype == np.float32
        addr = a.ctypes.data
        pool.give(a)
        b = pool.take((16, 32), np.float32)
        assert b.ctypes.data == addr  # same allocation came back
        c = pool.take((16, 32), np.float32)
        assert c.ctypes.data != addr  # pool was empty again -> fresh

    def test_reshape_views_normalize_to_base(self):
        pool = BufferPool(max_bytes=1 << 20)
        a = pool.take(512, np.uint8)
        pool.give(a)
        # take() reshapes the pooled base; giving the view back must
        # re-pool the WHOLE allocation
        v = pool.take((2, 256), np.uint8)
        assert v.base is not None
        pool.give(v)
        w = pool.take(512, np.uint8)
        assert w.ctypes.data == a.ctypes.data

    def test_rejects_foreign_memory_views(self):
        # arrays over memory numpy does not own (frombuffer, shm-style)
        # must never enter the pool: pooling them would pin their owner's
        # finalizer and alias foreign memory to future takers
        pool = BufferPool(max_bytes=1 << 20)
        raw = bytearray(1024)
        foreign = np.frombuffer(raw, dtype=np.uint8)
        pool.give(foreign)
        assert pool.take(1024, np.uint8).ctypes.data != foreign.ctypes.data

    def test_rejects_slices_and_noncontiguous(self):
        pool = BufferPool(max_bytes=1 << 20)
        owner = np.empty(1024, np.uint8)
        pool.give(owner[100:200])  # partial view: base nbytes differ
        assert pool._held == 0
        mat = np.empty((8, 8), np.float32)
        pool.give(mat[:, ::2])  # non-contiguous
        assert pool._held == 0

    def test_cap_drops_excess(self):
        pool = BufferPool(max_bytes=1000)
        a = np.empty(600, np.uint8)
        b = np.empty(600, np.uint8)
        pool.give(a)
        pool.give(b)  # would exceed the cap -> dropped
        assert pool._held == 600

    def test_zero_byte_noop(self):
        pool = BufferPool(max_bytes=1 << 20)
        pool.give(np.empty(0, np.uint8))
        assert pool._held == 0

    def test_concurrent_take_give(self):
        pool = BufferPool(max_bytes=8 << 20)
        errs = []

        def worker(seed):
            rng = np.random.default_rng(seed)
            for _ in range(200):
                a = pool.take(int(rng.integers(1, 4)) * 1024, np.uint8)
                a[:] = seed  # exclusive ownership: nobody else writes it
                if not np.all(a == seed):
                    errs.append("shared buffer observed")
                    return
                pool.give(a)

        threads = [
            threading.Thread(target=worker, args=(s,)) for s in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
