"""Whole-fleet cold-start restore (ISSUE 17) — chaos + integration layer.

The acceptance property of the durable fragment store: kill EVERY
replica mid-run (RAM gone — live heal has no source), restart the fleet
against the same ``TORCHFT_STORE_DIR``, and training resumes from the
newest complete spilled cut **bitwise** — the restored run's committed
parameter history equals an uninterrupted run's.  Plus the degrade
ladder: a blob torn on one disk fails over to another disk's copy
(per-fragment, via the striped restore), a torn cut degrades to the
newest complete older version, and a restore that fails outright
degrades to fresh init — never a wedge.  Warm restores ride the delta
path: a rejoiner whose local state already matches fetches only the
manifest, not the weights.
"""

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np
import pytest

from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.store import FragmentStore
from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroupTCP
from torchft_tpu.utils import faults
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils.faults import FaultRule, InjectedFault


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


def _replica(
    replica_id: str,
    lighthouse_addr: str,
    total_steps: int,
    min_replica_size: int = 2,
    attempts: int = 2,
    restart_barrier: "Optional[threading.Barrier]" = None,
) -> "List[dict]":
    """Deterministic momentum-SGD replica (the test_manager_integ loop).

    A ``train.step`` fault is a process death: parameter MEMORY is lost
    (fresh zeros on restart — only the disk survives).  With a
    ``restart_barrier`` every replica waits for the whole fleet to be
    down before restarting, which makes the crash a true whole-fleet
    outage instead of a rolling restart that live-heals."""
    history: "List[dict]" = []
    for _attempt in range(attempts):
        params = {"w": np.zeros(4, dtype=np.float32)}
        momentum = {"w": np.zeros(4, dtype=np.float32)}

        def load_state_dict(sd):
            params["w"] = np.array(sd["params"]["w"])
            momentum["w"] = np.array(sd["momentum"]["w"])

        def state_dict():
            return {
                "params": {"w": params["w"].copy()},
                "momentum": {"w": momentum["w"].copy()},
            }

        manager = Manager(
            pg=ProcessGroupTCP(timeout=10.0),
            min_replica_size=min_replica_size,
            load_state_dict=load_state_dict,
            state_dict=state_dict,
            lighthouse_addr=lighthouse_addr,
            replica_id=replica_id,
            group_rank=0,
            group_world_size=1,
            use_async_quorum=False,
            timeout=20.0,
            quorum_timeout=20.0,
        )
        try:
            while manager.current_step() < total_steps:
                faults.check(
                    "train.step",
                    replica=replica_id,
                    step=manager.current_step(),
                )
                manager.start_quorum()
                # read the step AFTER the quorum: a cold restore (or a
                # live heal in sync mode) advances it inside start_quorum,
                # and the deterministic per-step gradients below must use
                # the restored step to be comparable with an
                # uninterrupted run
                step = manager.current_step()
                rep_idx = int(replica_id.rsplit("_", 1)[-1])
                grads = {
                    "w": np.full(4, float(step + 1), dtype=np.float32)
                    * (1.0 + 0.5 * rep_idx)
                }
                avg = manager.allreduce(grads).wait(timeout=30)
                if manager.should_commit():
                    momentum["w"] = 0.9 * momentum["w"] + avg["w"]
                    params["w"] = params["w"] - np.float32(0.1) * momentum["w"]
                    history.append(
                        {
                            "step": manager.current_step(),
                            "w": params["w"].copy(),
                            "momentum": momentum["w"].copy(),
                        }
                    )
            return history
        except InjectedFault:
            # whole-fleet outage: wait until every replica is down (and
            # has flushed its pending spill in shutdown) before restart
            if restart_barrier is not None:
                restart_barrier.wait(timeout=60)
            continue
        finally:
            manager.shutdown()
    raise RuntimeError(f"{replica_id} exhausted attempts")


def _run_fleet(
    prefix: str,
    total_steps: int,
    n: int = 2,
    restart_barrier: "Optional[threading.Barrier]" = None,
    attempts: int = 2,
) -> "List[List[dict]]":
    server = LighthouseServer(
        min_replicas=n, join_timeout_ms=100, heartbeat_timeout_ms=1000
    )
    try:
        with ThreadPoolExecutor(max_workers=n) as ex:
            futures = [
                ex.submit(
                    _replica,
                    f"{prefix}_{i}",
                    server.address(),
                    total_steps,
                    n,
                    attempts,
                    restart_barrier,
                )
                for i in range(n)
            ]
            return [f.result(timeout=180) for f in futures]
    finally:
        server.shutdown()


TOTAL_STEPS = 5
KILL_STEP = 2


class TestWholeFleetColdRestore:
    def test_fleet_kill_cold_restore_resumes_bitwise(
        self, tmp_path, monkeypatch
    ):
        """Both replicas die at the same step with fresh memory on
        restart; the cold restore from TORCHFT_STORE_DIR must make the
        committed history equal an UNINTERRUPTED run's, bitwise."""
        monkeypatch.delenv("TORCHFT_STORE_DIR", raising=False)
        reference = _run_fleet("cr_ref", TOTAL_STEPS)

        monkeypatch.setenv("TORCHFT_STORE_DIR", str(tmp_path))
        faults.FAULTS.configure(
            [
                FaultRule(site="train.step", replica=f"cr_kill_{i}",
                          step=KILL_STEP)
                for i in range(2)
            ]
        )
        restore_bytes = _metrics.STORE_RESTORE_BYTES.get()
        barrier = threading.Barrier(2)
        results = _run_fleet(
            "cr_kill", TOTAL_STEPS, restart_barrier=barrier
        )
        assert faults.FAULTS.injected("train.step") == 2

        for hist in results:
            # resumed at KILL_STEP, not from scratch: each step committed
            # exactly once across both attempts
            assert [e["step"] for e in hist] == list(
                range(1, TOTAL_STEPS + 1)
            )
        # the restore rode the striped store path and counted its wire
        assert _metrics.STORE_RESTORE_BYTES.get() > restore_bytes
        # bitwise: every committed step of every replica matches the
        # uninterrupted fleet (params AND momentum)
        for ref_hist, got_hist in zip(reference, results):
            for ref_e, got_e in zip(ref_hist, got_hist):
                np.testing.assert_array_equal(ref_e["w"], got_e["w"])
                np.testing.assert_array_equal(
                    ref_e["momentum"], got_e["momentum"]
                )

    def test_torn_blob_on_one_disk_fails_over_to_peer_disk(
        self, tmp_path, monkeypatch
    ):
        """Mid-spill SIGKILL leaves a torn blob on one disk: the restore
        detects it by digest at read, treats the fragment as missing on
        that disk, and completes from the other disk's copy — the cut
        survives as long as the UNION of disks covers it."""
        monkeypatch.setenv("TORCHFT_STORE_DIR", str(tmp_path))
        phase1 = _run_fleet("cr_torn", KILL_STEP + 1)
        assert [e["step"] for e in phase1[0]] == [1, 2, 3]

        # tear every blob of replica 0's newest version (worst case for
        # one disk; replica 1's disk still covers the full cut)
        store0 = FragmentStore(
            os.path.join(str(tmp_path), "cr_torn_0"), max_versions=0
        )
        newest = store0.versions()[-1]
        manifest = store0.manifest(newest)
        for digest in manifest["digests"].values():
            with open(store0.blob_path(digest), "r+b") as f:
                f.seek(4)
                f.write(b"\xde\xad\xbe\xef")

        torn_before = _metrics.STORE_TORN_BLOBS.get()
        phase2 = _run_fleet("cr_torn", TOTAL_STEPS, attempts=1)
        # phase 2 committed ONLY the resumed tail: the fleet restored the
        # spilled cut instead of restarting from zero
        for hist in phase2:
            assert [e["step"] for e in hist] == list(
                range(KILL_STEP + 2, TOTAL_STEPS + 1)
            )
        assert _metrics.STORE_TORN_BLOBS.get() > torn_before
        np.testing.assert_array_equal(phase2[0][-1]["w"], phase2[1][-1]["w"])

    def test_restore_failure_degrades_to_fresh_init(
        self, tmp_path, monkeypatch
    ):
        """An injected store.restore failure (site in KNOWN_SITES) must
        degrade to fresh init — training proceeds from step 0, nothing
        wedges, nothing raises into the training loop."""
        monkeypatch.setenv("TORCHFT_STORE_DIR", str(tmp_path))
        phase1 = _run_fleet("cr_deg", 2, n=1)
        assert [e["step"] for e in phase1[0]] == [1, 2]

        faults.FAULTS.configure(
            [FaultRule(site="store.restore", action="raise", times=1)]
        )
        phase2 = _run_fleet("cr_deg", 2, n=1, attempts=1)
        assert faults.FAULTS.injected("store.restore") == 1
        # fresh init: steps 1..2 recommitted from scratch
        assert [e["step"] for e in phase2[0]] == [1, 2]


class TestWarmDeltaRestore:
    def test_matching_local_state_fetches_only_the_manifest(self, tmp_path):
        """Warm restore: a rejoiner whose local state already equals the
        spilled cut (e.g. a transient crash that kept parameter memory)
        diffs digests and fetches ZERO weight fragments off disk."""
        rng = np.random.default_rng(3)
        state = {
            "user": {
                f"w{i}": rng.standard_normal(513).astype(np.float32)
                for i in range(8)
            },
            "torchft": {"step": 9, "batches_committed": 18},
        }
        store = FragmentStore(str(tmp_path), max_versions=0)
        store.put_state(9, state, fragments=4)

        src = HTTPTransport(timeout=5.0)
        src.attach_store(store)
        healer = HTTPTransport(timeout=5.0)
        full_payload = sum(
            v.nbytes for v in state["user"].values()
        )
        try:
            got, info = healer.recv_checkpoint_striped(
                [src.metadata()], 9, timeout=10.0,
                local_state_fn=lambda: {
                    "user": {
                        k: v.copy() for k, v in state["user"].items()
                    },
                    "torchft": dict(state["torchft"]),
                },
                delta=True,
            )
        finally:
            healer.shutdown()
            src.shutdown()
        assert got["torchft"] == state["torchft"]
        for k, v in state["user"].items():
            np.testing.assert_array_equal(got[ "user"][k], v)
        assert info["mode"] == "delta"
        assert info["changed"] == 0
        # only the manifest crossed the wire — nowhere near the weights
        assert info["wire_bytes"] < full_payload / 4
