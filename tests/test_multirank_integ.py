"""Multi-local-rank replica groups: 2 groups x 2 ranks through the full stack.

Reference scenario (manager_integ_test.py multi-rank): each replica group
runs ``group_world_size`` Manager instances (rank 0 hosts the group's
ManagerServer; others discover it via the shared store); the group's ranks
hold different state shards (FSDP-style), each rank allreduces its shard
with same-rank counterparts across groups, and should_commit ANDs the
votes of all local ranks before any of them commits.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from torchft_tpu.coordination import LighthouseServer, StoreServer
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroupTCP

N_GROUPS = 2
GROUP_WORLD = 2
STEPS = 3


class _Kill(Exception):
    pass


def _run_rank(group, rank, lighthouse_addr, store_addr, barrier,
              kill_at=None):
    # per-(group, rank) shard, FSDP-style: ranks hold different state
    state = {"w": np.zeros(64, np.float32)}
    manager = Manager(
        pg=ProcessGroupTCP(timeout=20.0),
        min_replica_size=N_GROUPS,
        lighthouse_addr=lighthouse_addr,
        store_addr=store_addr,
        replica_id=f"mr_{group}",
        group_rank=rank,
        group_world_size=GROUP_WORLD,
        use_async_quorum=False,
        timeout=30.0,
        quorum_timeout=30.0,
        load_state_dict=lambda sd: state.update(
            {k: np.array(v) for k, v in sd.items()}
        ),
        state_dict=lambda: {k: v.copy() for k, v in state.items()},
    )
    try:
        barrier.wait(timeout=60)
        while manager.current_step() < STEPS:
            if kill_at is not None and manager.current_step() == kill_at:
                raise _Kill()
            manager.start_quorum()
            # shard gradient differs per group AND per rank
            grad = np.full(
                64, float(1 + group) * float(10 + rank), np.float32
            )
            avg = manager.allreduce({"w": grad}).wait(timeout=30)
            if manager.should_commit():
                state["w"] = state["w"] - 0.1 * avg["w"]
        return {"group": group, "rank": rank, "w": state["w"].copy(),
                "step": manager.current_step()}
    finally:
        manager.shutdown()


class TestMultiRankGroups:
    def test_two_groups_two_ranks(self):
        lighthouse = LighthouseServer(min_replicas=N_GROUPS, join_timeout_ms=30000)
        stores = [StoreServer() for _ in range(N_GROUPS)]
        try:
            barrier = threading.Barrier(N_GROUPS * GROUP_WORLD)
            with ThreadPoolExecutor(max_workers=N_GROUPS * GROUP_WORLD) as ex:
                futs = {
                    (g, r): ex.submit(
                        _run_rank, g, r, lighthouse.address(),
                        stores[g].address(), barrier,
                    )
                    for g in range(N_GROUPS)
                    for r in range(GROUP_WORLD)
                }
                results = {k: f.result(timeout=240) for k, f in futs.items()}
        finally:
            lighthouse.shutdown()
            for s in stores:
                s.shutdown()

        assert all(res["step"] == STEPS for res in results.values())
        # same-rank shards must be bitwise identical ACROSS groups
        # (they averaged together)...
        for r in range(GROUP_WORLD):
            np.testing.assert_array_equal(
                results[(0, r)]["w"], results[(1, r)]["w"]
            )
        # ...and differ BETWEEN ranks (they held different shards)
        assert not np.array_equal(results[(0, 0)]["w"], results[(0, 1)]["w"])

    def test_group_recovery_multi_rank(self):
        """Group 1 (both ranks) dies mid-run and rejoins: each rank heals
        its own shard from the same-rank counterpart in the healthy group
        (reference multi-rank recovery, manager_integ_test.py)."""
        lighthouse = LighthouseServer(min_replicas=1, join_timeout_ms=5000)
        store0 = StoreServer()
        extra_stores = []
        try:
            # group 0 trains throughout; its 2 ranks never die
            barrier0 = threading.Barrier(GROUP_WORLD)

            def healthy(rank):
                return _run_rank(
                    0, rank, lighthouse.address(), store0.address(), barrier0
                )

            def victim(rank, attempt_state):
                # both ranks die at step 1, then restart with a fresh store
                # (a restarted group gets a fresh rendezvous, as under the
                # launcher); heal brings them back to the healthy group's
                # step
                b = attempt_state["barrier"]
                try:
                    return _run_rank(
                        1, rank, lighthouse.address(),
                        attempt_state["store"].address(), b,
                        kill_at=1 if attempt_state["attempt"] == 0 else None,
                    )
                except _Kill:
                    return None

            with ThreadPoolExecutor(max_workers=2 * GROUP_WORLD) as ex:
                healthy_futs = [ex.submit(healthy, r) for r in range(GROUP_WORLD)]

                attempt_state = {
                    "attempt": 0,
                    "store": StoreServer(),
                    "barrier": threading.Barrier(GROUP_WORLD),
                }
                extra_stores.append(attempt_state["store"])
                victim_futs = [
                    ex.submit(victim, r, dict(attempt_state))
                    for r in range(GROUP_WORLD)
                ]
                first = [f.result(timeout=240) for f in victim_futs]
                assert all(v is None for v in first), "kill did not fire"

                attempt_state = {
                    "attempt": 1,
                    "store": StoreServer(),
                    "barrier": threading.Barrier(GROUP_WORLD),
                }
                extra_stores.append(attempt_state["store"])
                victim_futs = [
                    ex.submit(victim, r, dict(attempt_state))
                    for r in range(GROUP_WORLD)
                ]
                victims = [f.result(timeout=240) for f in victim_futs]
                healthies = [f.result(timeout=240) for f in healthy_futs]
        finally:
            lighthouse.shutdown()
            store0.shutdown()
            for s in extra_stores:
                s.shutdown()

        by_key = {(r["group"], r["rank"]): r for r in victims + healthies}
        assert all(r["step"] == STEPS for r in by_key.values())
        for r in range(GROUP_WORLD):
            np.testing.assert_array_equal(
                by_key[(0, r)]["w"], by_key[(1, r)]["w"]
            )
