"""Tier-1 telemetry smoke check (CI guard).

End-to-end gate on the scrape surface: import the metrics layer, run one
real quorum round through a Manager, scrape the lighthouse's ``/metrics``,
and run every line of the exposition through the strict parser — a
label-escaping or format regression anywhere in the pipeline (Python
renderer, native supplement concatenation, instrument definitions) fails
this test rather than silently corrupting a Prometheus scrape in prod.
"""

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import torchft_tpu.utils.metrics as metrics
import torchft_tpu.utils.tracing as tracing
from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroupTCP


def _run_one_round(lighthouse_addr: str, replica_id: str) -> Manager:
    """One full quorum round (quorum -> allreduce -> commit) on a
    single-replica group; returns the (shut down) Manager."""
    state = {"w": np.zeros(4, dtype=np.float32)}
    manager = Manager(
        pg=ProcessGroupTCP(timeout=10.0),
        min_replica_size=1,
        load_state_dict=lambda sd: state.update(sd),
        state_dict=lambda: state,
        use_async_quorum=False,
        lighthouse_addr=lighthouse_addr,
        replica_id=replica_id,
        group_rank=0,
        group_world_size=1,
        timeout=10.0,
        quorum_timeout=10.0,
    )
    try:
        manager.start_quorum()
        manager.allreduce({"g": np.ones(4, dtype=np.float32)}).wait(timeout=10)
        assert manager.should_commit()
    finally:
        manager.shutdown()
    return manager


def test_metrics_scrape_smoke():
    lighthouse = LighthouseServer(min_replicas=1, join_timeout_ms=100)
    try:
        # one full protocol round so every hot-path instrument fires
        manager = _run_one_round(lighthouse.address(), "smoke")
        body = (
            urllib.request.urlopen(
                f"http://{lighthouse.address()}/metrics", timeout=5
            )
            .read()
            .decode()
        )
    finally:
        lighthouse.shutdown()

    # Strict validation of EVERY line (raises on any malformed exposition).
    fams = metrics.parse_text_exposition(body)

    # The round above must be visible through the scrape: phase histogram
    # observations, a commit, and a PG reconfigure.
    dur = fams["torchft_quorum_duration_seconds"]
    assert dur["type"] == "histogram"
    assert dur["samples"][("torchft_quorum_duration_seconds_count", ())] > 0
    commits = fams["torchft_commits_total"]["samples"]
    assert commits[("torchft_commits_total", ())] >= 1
    reconf = fams["torchft_pg_reconfigures_total"]["samples"]
    assert reconf[("torchft_pg_reconfigures_total", ())] >= 1
    assert ("torchft_pg_aborts_total", ()) in fams["torchft_pg_aborts_total"][
        "samples"
    ]

    # Non-destructive phase view coexists with the scrape (satellite:
    # two consumers must not corrupt each other).
    # NOTE: manager is shut down but the accumulator is plain state.
    snap1 = manager.phase_times()
    snap2 = manager.phase_times()
    assert snap1 == snap2 and "commit" in snap1
    # pop_phase_times (the destructive drain, deprecated in PR 3) is
    # gone: phase_times()/the histogram are the only phase surfaces
    assert not hasattr(manager, "pop_phase_times")


class _FakeOTLPCollector:
    """Records OTLP POSTs by path (/v1/metrics, /v1/traces)."""

    def __init__(self):
        self.by_path = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                body = self.rfile.read(int(self.headers["Content-Length"]))
                outer.by_path.setdefault(self.path, []).append(
                    json.loads(body)
                )
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self._srv.server_address[1]}"

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_otlp_metrics_and_traces_for_full_quorum_round(monkeypatch):
    """Acceptance: with TORCHFT_USE_OTEL=1 a stub collector receives
    well-formed /v1/metrics and /v1/traces OTLP JSON for one full quorum
    round, trace spans correlated via step/quorum_id attributes."""
    collector = _FakeOTLPCollector()
    monkeypatch.setenv("TORCHFT_USE_OTEL", "1")
    monkeypatch.setenv("OTEL_EXPORTER_OTLP_ENDPOINT", collector.endpoint)
    tracer = tracing.maybe_install_from_env()
    assert tracer is not None
    metrics_exp = metrics.OTLPMetricsExporter(
        collector.endpoint, interval_s=3600
    )
    lighthouse = LighthouseServer(min_replicas=1, join_timeout_ms=100)
    try:
        _run_one_round(lighthouse.address(), "otlp")
        assert tracer.exporter.flush(timeout=5.0)
        assert metrics_exp.flush()
    finally:
        lighthouse.shutdown()
        metrics_exp.close()
        tracing.uninstall_tracer()
        collector.close()

    # metrics leg: the quorum round's instruments are in the document
    mdoc = collector.by_path["/v1/metrics"][-1]
    sm = mdoc["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
    by_name = {m["name"]: m for m in sm}
    assert by_name["torchft_commits_total"]["sum"]["isMonotonic"]
    dur = by_name["torchft_quorum_duration_seconds"]["histogram"]
    assert dur["aggregationTemporality"] == 2
    assert any(int(p["count"]) > 0 for p in dur["dataPoints"])

    # traces leg: a root quorum_round span plus phase children sharing its
    # traceId, all carrying the step/quorum_id correlation attributes
    spans = [
        s
        for doc in collector.by_path["/v1/traces"]
        for rs in doc["resourceSpans"]
        for ss in rs["scopeSpans"]
        for s in ss["spans"]
    ]
    roots = [
        s
        for s in spans
        if s["name"] == "quorum_round" and "parentSpanId" not in s
    ]
    assert roots, f"no root span in {[s['name'] for s in spans]}"
    root = roots[-1]
    children = [
        s for s in spans if s.get("parentSpanId") == root["spanId"]
        and s["traceId"] == root["traceId"]
    ]
    names = {s["name"] for s in children}
    assert "quorum_rpc" in names and "commit" in names
    # phase children (and the root) carry the step/quorum_id correlation
    # attributes; native rpc.* server spans are legitimate children too
    # but carry server/method instead (distributed-tracing leg)
    phase_children = [
        s for s in children if not s["name"].startswith("rpc.")
    ]
    for s in phase_children + [root]:
        attrs = {a["key"] for a in s["attributes"]}
        assert {"step", "quorum_id", "replica_id"} <= attrs
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
    for s in children:
        if s["name"].startswith("rpc."):
            attrs = {a["key"] for a in s["attributes"]}
            assert {"server", "method"} <= attrs
