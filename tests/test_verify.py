"""tft-verify tier-1 gate (model-checker leg).

Three proofs, mirroring tests/test_lint.py's trust ladder:

1. the UNMUTATED protocol model explores every bounded scenario clean,
   inside a hard wall-clock budget (the checker stays cheap enough for CI);
2. the mutation gate — each seeded protocol bug (skip the commit-failure
   quorum bump, heal from a stale source, drop the majority guard, ...)
   is provably caught by exactly the invariant that documents it;
3. a counterexample trace round-trips through torchft-diagnose and names
   the violating replica and phase, in the same vocabulary production
   flight dumps use.
"""

import json
import time

import pytest

from torchft_tpu import diagnose
from torchft_tpu.analysis import model_checker as mc
from torchft_tpu.analysis import protocol_model as pm
from torchft_tpu.analysis.verify_cli import main as verify_main
from torchft_tpu.manager import PROTOCOL_PHASES

#: tier-1 wall budget for the FULL clean exploration (ISSUE 7 acceptance:
#: 30 s; observed ~1 s on the dev container, so 30 s is pure headroom).
CLEAN_BUDGET_S = 30.0


class TestCleanExploration:
    def test_all_scenarios_explore_clean_within_budget(self):
        t0 = time.monotonic()
        for name, cfg in mc.SCENARIOS.items():
            r = mc.explore(cfg)
            assert r.ok, (
                f"scenario {name!r} violated {r.violation.invariant}: "
                f"{r.violation.message}\ntrace: {r.trace}"
            )
            assert r.states > 0 and r.transitions >= r.states - 1
        r = mc.explore_votes()
        assert r.ok, f"vote sub-model violated: {r.violation}"
        elapsed = time.monotonic() - t0
        assert elapsed < CLEAN_BUDGET_S, (
            f"clean exploration took {elapsed:.1f}s, budget {CLEAN_BUDGET_S}s"
        )

    def test_scenarios_reach_goals(self):
        """Every scenario that can make progress has goal states — a
        bounded space with zero goals would vacuously 'verify' nothing."""
        for name, cfg in mc.SCENARIOS.items():
            r = mc.explore(cfg)
            if name == "partition":
                # the one deliberately-stuck scenario: the majority guard
                # must HOLD the lone participant at bay, forever
                assert r.goal_states == 0
            else:
                assert r.goal_states > 0, f"{name} never reaches its goal"

    def test_partition_scenario_never_forms_quorum(self):
        """The split-brain guard, positively: with 2 of 3 replicas
        partitioned away (heartbeating, never joining), no quorum ever
        forms — the model has no 'form' transition in its entire space."""
        cfg = mc.SCENARIOS["partition"]
        st = pm.initial_state(cfg)
        assert all(
            t[0] != "form" for t in pm.enabled_transitions(cfg, st)
        )
        r = mc.explore(cfg)
        assert r.ok and r.goal_states == 0

    def test_exploration_is_deterministic(self):
        a = mc.explore(mc.SCENARIOS["churn"])
        b = mc.explore(mc.SCENARIOS["churn"])
        assert (a.states, a.transitions, a.goal_states) == (
            b.states,
            b.transitions,
            b.goal_states,
        )


class TestMutationGate:
    @pytest.mark.parametrize("mutation", pm.MUTATIONS, ids=lambda m: m.name)
    def test_seeded_protocol_bug_is_caught(self, mutation):
        r = mc.check_mutation(mutation.name)
        assert not r.ok, (
            f"mutation {mutation.name} explored clean — the checker "
            f"cannot see the bug class it documents"
        )
        assert r.violation is not None
        assert r.violation.invariant == mutation.catches, (
            f"mutation {mutation.name} caught by {r.violation.invariant}, "
            f"expected {mutation.catches}"
        )
        assert r.trace, "violation must carry a replayable trace"

    def test_every_mutation_has_a_scenario(self):
        assert set(mc.MUTATION_SCENARIOS) == {m.name for m in pm.MUTATIONS}
        for scenario in mc.MUTATION_SCENARIOS.values():
            assert (
                scenario == "votes"
                or scenario in mc.SCENARIOS
                or scenario in mc.RESIZE_SCENARIOS
                or scenario in mc.ELECTION_SCENARIOS
                or scenario in mc.RESTORE_SCENARIOS
            )

    def test_every_invariant_is_exercised_by_a_mutation(self):
        """No dead invariants: each safety predicate must be the catcher
        of record for at least one seeded bug (else we cannot know it can
        fire at all)."""
        caught = {m.catches for m in pm.MUTATIONS}
        assert set(pm.INVARIANTS) <= caught | {"vote-integrity"}
        assert "vote-integrity" in caught


class TestLiveness:
    @pytest.mark.parametrize(
        "schedule", mc.LIVENESS_SCHEDULES, ids=lambda s: s[0]
    )
    def test_fair_schedule_reaches_goal(self, schedule):
        name, scenario, rotation = schedule
        ok, used, trace = mc.run_schedule(mc.SCENARIOS[scenario], rotation)
        assert ok, (
            f"schedule {name} livelocked after {used} transitions; "
            f"tail: {trace[-10:]}"
        )


class TestVoteSubModel:
    def test_clean_barrier_space(self):
        r = mc.explore_votes(world=2, steps=2, drops=1)
        assert r.ok and r.goal_states > 0

    def test_resend_mutation_double_delivers(self):
        r = mc.explore_votes(mutations=frozenset({"resend_vote"}))
        assert not r.ok
        assert r.violation.invariant == "vote-integrity"


class TestResizeSubModel:
    """ISSUE 11: the online-parallelism-switching (resize) scenario —
    layout-epoch-monotone + all-commit-same-epoch proven over churn
    (crash mid-reshard, rejoin, failed transfers) and the two seeded
    switch-protocol bugs provably caught."""

    def test_clean_resize_space_reaches_switches(self):
        r = mc.explore_resize(mc.RESIZE_SCENARIOS["resize"])
        assert r.ok, f"resize scenario violated: {r.violation}"
        # non-vacuous: the bounded space contains completed switches
        assert r.goal_states > 0

    def test_exploration_is_deterministic(self):
        a = mc.explore_resize(mc.RESIZE_SCENARIOS["resize"])
        b = mc.explore_resize(mc.RESIZE_SCENARIOS["resize"])
        assert (a.states, a.transitions, a.goal_states) == (
            b.states, b.transitions, b.goal_states
        )

    def test_mixed_commit_splits_the_fleet(self):
        r = mc.explore_resize(
            mc.RESIZE_SCENARIOS["resize"],
            mutations=frozenset({"commit_mixed_epochs"}),
        )
        assert not r.ok
        assert r.violation.invariant == "all-commit-same-epoch"

    def test_epoch_reuse_after_rollback_is_caught(self):
        r = mc.explore_resize(
            mc.RESIZE_SCENARIOS["resize"],
            mutations=frozenset({"reuse_epoch_after_rollback"}),
        )
        assert not r.ok
        assert r.violation.invariant == "layout-epoch-monotone"

    def test_counterexample_renders_as_flight_dump(self, tmp_path):
        r = mc.check_mutation("commit_mixed_epochs")
        assert not r.ok and r.trace
        path = str(tmp_path / "resize_cex.jsonl")
        mc.write_flight_dump(r, path)
        lines = [json.loads(ln) for ln in open(path) if ln.strip()]
        assert lines[0]["flight"] == "meta"
        errs = [rec for rec in lines[1:] if rec["status"] == "error"]
        assert len(errs) == 1
        # the violating phase renders in the Manager's vocabulary
        assert errs[0]["op"] == "layout_commit"


class TestElectionSubModel:
    """ISSUE 13: the coordination-plane HA (leased leader election)
    scenario — at-most-one-leader-per-term, term monotonicity and
    quorum-id monotonicity across failover proven over candidacies,
    lease grants/expiry and a leader crash, with the two seeded
    election bugs provably caught by their named invariants."""

    def test_clean_election_space_reaches_quorums(self):
        r = mc.explore_election(mc.ELECTION_SCENARIOS["election"])
        assert r.ok, f"election scenario violated: {r.violation}"
        # non-vacuous: the bounded space contains post-takeover quorums
        assert r.goal_states > 0

    def test_exploration_is_deterministic(self):
        a = mc.explore_election(mc.ELECTION_SCENARIOS["election"])
        b = mc.explore_election(mc.ELECTION_SCENARIOS["election"])
        assert (a.states, a.transitions, a.goal_states) == (
            b.states, b.transitions, b.goal_states
        )

    def test_space_contains_takeovers(self):
        """The clean space must actually exercise failover: some path
        establishes two leaderships (else quorum-id-monotone-across-
        failover would be vacuously true)."""
        cfg = mc.ELECTION_SCENARIOS["election"]
        # a crash is enabled somewhere and the expire budget allows the
        # survivors' promises to lapse afterwards
        assert cfg.crash_budget >= 1
        assert cfg.expire_budget >= cfg.n_peers - 1

    def test_two_leaders_same_term_is_caught(self):
        r = mc.explore_election(
            mc.ELECTION_SCENARIOS["election"],
            mutations=frozenset({"two_leaders_same_term"}),
        )
        assert not r.ok
        assert r.violation.invariant == "at-most-one-leader-per-term"

    def test_reuse_quorum_seq_after_takeover_is_caught(self):
        r = mc.explore_election(
            mc.ELECTION_SCENARIOS["election"],
            mutations=frozenset({"reuse_quorum_seq_after_takeover"}),
        )
        assert not r.ok
        assert r.violation.invariant == "quorum-id-monotone-across-failover"

    def test_counterexample_renders_as_flight_dump(self, tmp_path):
        r = mc.check_mutation("two_leaders_same_term")
        assert not r.ok and r.trace
        path = str(tmp_path / "election_cex.jsonl")
        mc.write_flight_dump(r, path)
        lines = [json.loads(ln) for ln in open(path) if ln.strip()]
        assert lines[0]["flight"] == "meta"
        errs = [rec for rec in lines[1:] if rec["status"] == "error"]
        assert len(errs) == 1
        # the violating phase renders in the Manager's vocabulary
        assert errs[0]["op"] == "quorum_rpc"


class TestRestoreSubModel:
    """ISSUE 17: the durable-store cold-restore scenario — the fleet-wide
    cut selection must be complete (digest-valid bytes for every
    fragment), version-consistent (one outer sync, never a cross-version
    splice) and newest-first, proven over every per-disk spill order,
    one bit-rot and the whole-fleet crash, with both seeded restore bugs
    provably caught by their named invariants."""

    def test_clean_restore_space_reaches_restores(self):
        r = mc.explore_restore(mc.RESTORE_SCENARIOS["restore"])
        assert r.ok, f"restore scenario violated: {r.violation}"
        # non-vacuous: the bounded space contains completed restores
        assert r.goal_states > 0

    def test_exploration_is_deterministic(self):
        a = mc.explore_restore(mc.RESTORE_SCENARIOS["restore"])
        b = mc.explore_restore(mc.RESTORE_SCENARIOS["restore"])
        assert (a.states, a.transitions, a.goal_states) == (
            b.states, b.transitions, b.goal_states
        )

    def test_space_contains_torn_blobs_and_partial_spills(self):
        """The clean space must exercise the failure shapes the
        invariants guard against: a rot budget (torn blobs exist) and a
        mid-spill crash (incomplete newest versions exist) — else
        restore-cut-complete/-consistent would be vacuously true."""
        cfg = mc.RESTORE_SCENARIOS["restore"]
        assert cfg.rot_budget >= 1
        assert cfg.n_versions >= 2 and cfg.n_fragments >= 2

    def test_serve_torn_blob_is_caught(self):
        r = mc.explore_restore(
            mc.RESTORE_SCENARIOS["restore"],
            mutations=frozenset({"serve_torn_blob"}),
        )
        assert not r.ok
        assert r.violation.invariant == "restore-cut-complete"

    def test_mix_versions_in_cut_is_caught(self):
        r = mc.explore_restore(
            mc.RESTORE_SCENARIOS["restore"],
            mutations=frozenset({"mix_versions_in_cut"}),
        )
        assert not r.ok
        assert r.violation.invariant == "restore-cut-consistent"

    def test_counterexample_renders_as_flight_dump(self, tmp_path):
        r = mc.check_mutation("serve_torn_blob")
        assert not r.ok and r.trace
        path = str(tmp_path / "restore_cex.jsonl")
        mc.write_flight_dump(r, path)
        lines = [json.loads(ln) for ln in open(path) if ln.strip()]
        assert lines[0]["flight"] == "meta"
        errs = [rec for rec in lines[1:] if rec["status"] == "error"]
        assert len(errs) == 1
        # the violating phase renders in the Manager's vocabulary
        assert errs[0]["op"] == "heal_recv"


class TestDiagnoseRoundTrip:
    """Acceptance: a checker counterexample renders through
    torchft-diagnose and names the violating replica/phase."""

    def test_counterexample_names_replica_and_phase(self, tmp_path):
        r = mc.check_mutation("heal_from_stale")
        assert not r.ok
        path = str(tmp_path / "cex.jsonl")
        mc.write_flight_dump(r, path)
        entries, warnings = diagnose.load_records([path])
        report = diagnose.analyze(entries)
        v = r.violation
        assert report["failure"] is not None
        assert report["failure"]["reported_by"] == v.replica_id
        assert report["failure"]["phase"] == pm.MODEL_PHASE_OPS[v.phase]
        assert v.invariant in report["failure"]["detail"]
        # the culprit signal singles out the same replica with no
        # verify-specific logic in diagnose
        assert report["culprit"] is not None
        assert report["culprit"]["replica_id"] == v.replica_id
        text = diagnose.render_text(entries, report, warnings)
        assert v.replica_id in text and v.invariant in text

    def test_dump_is_valid_flight_dialect(self, tmp_path):
        r = mc.check_mutation("commit_despite_error")
        path = str(tmp_path / "cex.jsonl")
        mc.write_flight_dump(r, path)
        lines = [json.loads(ln) for ln in open(path) if ln.strip()]
        assert lines[0]["flight"] == "meta"
        assert all(rec["flight"] == "rec" for rec in lines[1:])
        # one error record exactly: the violation itself
        errs = [rec for rec in lines[1:] if rec["status"] == "error"]
        assert len(errs) == 1
        assert errs[0]["replica_id"] == r.violation.replica_id


class TestPhaseVocabulary:
    def test_model_ops_render_in_manager_phase_vocabulary(self):
        """Counterexample traces must speak the language operators know
        from production dumps: every model op maps into the Manager's
        canonical phase names ('crash' is the one model-only marker)."""
        allowed = set(PROTOCOL_PHASES) | {"crash"}
        assert set(pm.MODEL_PHASE_OPS.values()) <= allowed

    def test_manager_phase_vocabulary_matches_recorded_phases(self):
        """PROTOCOL_PHASES is the closed set _record_phase is called
        with — scan the source so a new literal cannot drift past it."""
        import ast
        import inspect

        from torchft_tpu import manager as mgr

        recorded = set()
        tree = ast.parse(inspect.getsource(mgr))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_record_phase"
                and node.args
                and isinstance(node.args[0], ast.Constant)
            ):
                recorded.add(node.args[0].value)
        assert recorded == set(PROTOCOL_PHASES)


class TestVerifyCli:
    def test_selftest_exits_zero(self, capsys):
        assert verify_main(["--selftest"]) == 0
        out = capsys.readouterr().out
        assert "caught" in out and "MISSED" not in out

    def test_unknown_scenario_exits_two(self, capsys):
        assert verify_main(["--scenario", "nope"]) == 2

    def test_mutate_dump_cli(self, tmp_path, capsys):
        path = str(tmp_path / "cex.jsonl")
        rc = verify_main(["--mutate", "drop_majority_guard", "--dump", path])
        assert rc == 1  # a violation was (correctly) found
        assert (tmp_path / "cex.jsonl").exists()

    def test_list_cli(self, capsys):
        assert verify_main(["--list"]) == 0
        out = capsys.readouterr().out
        for name in mc.SCENARIOS:
            assert f"scenario {name}" in out
