"""Unit tests for compute_quorum_results (native C++ pure function).

Scenario parity with reference src/manager.rs:626-1218 test list: heal
assignment math, init_sync skip, round-robin source assignment, commit
failure propagation.
"""

import pytest

from torchft_tpu.coordination import (
    Quorum,
    QuorumMember,
    compute_quorum_results,
)


def member(rid: str, step: int = 0, commit_failures: int = 0) -> QuorumMember:
    return QuorumMember(
        replica_id=rid,
        address=f"addr_{rid}",
        store_address=f"store_{rid}",
        step=step,
        world_size=2,
        commit_failures=commit_failures,
    )


def quorum(*members: QuorumMember, quorum_id: int = 1) -> Quorum:
    return Quorum(quorum_id=quorum_id, participants=list(members))


class TestComputeQuorumResults:
    def test_all_up_to_date(self):
        q = quorum(member("a", 5), member("b", 5), member("c", 5))
        r = compute_quorum_results("b", 0, q)
        assert r.quorum_id == 1
        assert r.replica_rank == 1
        assert r.replica_world_size == 3
        assert r.max_step == 5
        assert r.max_world_size == 3
        assert r.max_replica_rank == 1
        assert not r.heal
        assert r.recover_src_replica_rank is None
        assert r.recover_dst_replica_ranks == []
        # primary for group_rank 0 is max_participants[0] == "a"
        assert r.store_address == "store_a"

    def test_sorted_by_replica_id(self):
        q = quorum(member("z", 3), member("a", 3))
        r = compute_quorum_results("z", 0, q)
        assert r.replica_rank == 1
        r = compute_quorum_results("a", 0, q)
        assert r.replica_rank == 0

    def test_behind_replica_heals(self):
        q = quorum(member("a", 5), member("b", 3), member("c", 5))
        rb = compute_quorum_results("b", 0, q)
        assert rb.heal
        assert rb.max_step == 5
        assert rb.max_replica_rank is None
        assert rb.max_world_size == 2
        # src must be an up-to-date rank: a(0) or c(2)
        assert rb.recover_src_replica_rank in (0, 2)
        assert rb.recover_src_manager_address in ("addr_a", "addr_c")
        # and the src's result lists b(1) as a recover destination
        src_id = {0: "a", 2: "c"}[rb.recover_src_replica_rank]
        rsrc = compute_quorum_results(src_id, 0, q)
        assert not rsrc.heal
        assert rsrc.recover_dst_replica_ranks == [1]

    def test_group_rank_offsets_recovery_source(self):
        # Two recovering replicas, two up to date: different group ranks
        # rotate the assignment so transfer load spreads.
        q = quorum(member("a", 5), member("b", 0), member("c", 5), member("d", 0))
        r0 = compute_quorum_results("b", 0, q)
        r1 = compute_quorum_results("b", 1, q)
        assert r0.recover_src_replica_rank != r1.recover_src_replica_rank

    def test_init_sync_at_step_zero(self):
        q = quorum(member("a", 0), member("b", 0), member("c", 0))
        # primary for group_rank 0 is "a": it does not heal, others do.
        ra = compute_quorum_results("a", 0, q, init_sync=True)
        rb = compute_quorum_results("b", 0, q, init_sync=True)
        rc = compute_quorum_results("c", 0, q, init_sync=True)
        assert not ra.heal
        assert rb.heal and rb.recover_src_replica_rank == 0
        assert rc.heal and rc.recover_src_replica_rank == 0
        assert sorted(ra.recover_dst_replica_ranks) == [1, 2]

    def test_init_sync_disabled(self):
        q = quorum(member("a", 0), member("b", 0))
        rb = compute_quorum_results("b", 0, q, init_sync=False)
        assert not rb.heal

    def test_commit_failures_max_propagates(self):
        q = quorum(member("a", 1, commit_failures=0), member("b", 1, commit_failures=3))
        r = compute_quorum_results("a", 0, q)
        assert r.commit_failures == 3

    def test_not_in_quorum_raises(self):
        q = quorum(member("a", 1))
        with pytest.raises(RuntimeError, match="not participating"):
            compute_quorum_results("ghost", 0, q)

    def test_primary_store_rotates_with_group_rank(self):
        q = quorum(member("a", 2), member("b", 2))
        r0 = compute_quorum_results("a", 0, q)
        r1 = compute_quorum_results("a", 1, q)
        assert r0.store_address == "store_a"
        assert r1.store_address == "store_b"
