"""Optimizer wrapper, DDP, and device-mesh unit tests.

Mirrors reference torchft/optim_test.py:19, ddp_test.py:23-39,
device_mesh_test.py.
"""

from unittest.mock import create_autospec

import jax
import numpy as np
import optax
import pytest

from torchft_tpu.ddp import DistributedDataParallel, PureDistributedDataParallel
from torchft_tpu.manager import Manager
from torchft_tpu.optim import OptimizerWrapper
from torchft_tpu.parallel.device_mesh import ft_init_device_mesh
from torchft_tpu.parallel.work import completed_work


def mock_manager():
    manager = create_autospec(Manager, instance=True)
    manager.allreduce.side_effect = lambda v, **kw: completed_work(v)
    return manager


class TestOptimizerWrapper:
    def test_begin_step_starts_quorum(self):
        manager = mock_manager()
        opt = OptimizerWrapper(manager, optax.sgd(0.1))
        opt.begin_step()
        manager.start_quorum.assert_called_once()
        # torch-compatible alias
        opt.zero_grad()
        assert manager.start_quorum.call_count == 2

    def test_step_commits(self):
        manager = mock_manager()
        manager.should_commit.return_value = True
        opt = OptimizerWrapper(manager, optax.sgd(1.0))
        params = {"w": np.full(2, 3.0, dtype=np.float32)}
        state = opt.init(params)
        new_params, state, committed = opt.step(
            params, {"w": np.full(2, 1.0, dtype=np.float32)}, state
        )
        assert committed
        np.testing.assert_allclose(new_params["w"], np.full(2, 2.0))

    def test_step_skipped_on_failed_commit(self):
        manager = mock_manager()
        manager.should_commit.return_value = False
        opt = OptimizerWrapper(manager, optax.sgd(1.0))
        params = {"w": np.full(2, 3.0, dtype=np.float32)}
        state = opt.init(params)
        new_params, new_state, committed = opt.step(
            params, {"w": np.ones(2, dtype=np.float32)}, state
        )
        assert not committed
        np.testing.assert_allclose(new_params["w"], params["w"])
        assert new_state is state


class TestDDP:
    def test_allreduce_gradients(self):
        manager = mock_manager()
        manager.allreduce.side_effect = lambda g, **kw: completed_work(
            jax.tree_util.tree_map(lambda x: x * 0.5, g)
        )
        ddp = DistributedDataParallel(manager)
        grads = {"w": np.full(4, 2.0), "b": np.ones(2)}
        avg = ddp.allreduce_gradients(grads).wait(timeout=5)
        np.testing.assert_allclose(avg["w"], np.full(4, 1.0))

    def test_wrap_grad_fn(self):
        manager = mock_manager()
        ddp = DistributedDataParallel(manager)

        def grad_fn(params, batch):
            return 0.5, {"w": params["w"] * batch}

        wrapped = ddp.wrap_grad_fn(grad_fn)
        loss, grads = wrapped({"w": np.ones(2)}, 3.0)
        assert loss == 0.5
        np.testing.assert_allclose(grads["w"], np.full(2, 3.0))
        manager.allreduce.assert_called_once()

    def test_pure_ddp_per_leaf(self):
        manager = mock_manager()
        ddp = PureDistributedDataParallel(manager)
        grads = {"w": np.ones(2), "b": np.ones(3)}
        out = ddp.allreduce_gradients(grads)
        assert manager.allreduce.call_count == 2
        np.testing.assert_allclose(out["w"], np.ones(2))


class TestManagedDeviceMesh:
    def test_composition(self):
        manager = mock_manager()
        manager.num_participants.return_value = 3
        manager.participating_rank.return_value = 1
        mesh = ft_init_device_mesh(
            manager, {"fsdp": 4, "tp": 2}, devices=jax.devices()
        )
        assert mesh.axis_names == ("dp_replicate", "fsdp", "tp")
        assert mesh.shape() == {"dp_replicate": 3, "fsdp": 4, "tp": 2}
        assert mesh.num_participants() == 3
        # batch slice for replica 1 of 3 on a 12-example global batch
        assert mesh.global_batch_slice(12) == (4, 8)

    def test_zero_participants_reports_one(self):
        manager = mock_manager()
        manager.num_participants.return_value = 0
        manager.participating_rank.return_value = None
        mesh = ft_init_device_mesh(manager, {"fsdp": 8}, devices=jax.devices())
        assert mesh.shape()["dp_replicate"] == 1

    def test_non_participating_gets_empty_batch_slice(self):
        """A healing replica must not silently train on rank 0's data."""
        manager = mock_manager()
        manager.num_participants.return_value = 3
        manager.participating_rank.return_value = None
        manager.is_participating.return_value = False
        mesh = ft_init_device_mesh(manager, {"fsdp": 8}, devices=jax.devices())
        assert mesh.global_batch_slice(12) == (0, 0)

    def test_device_count_mismatch(self):
        manager = mock_manager()
        with pytest.raises(ValueError, match="devices"):
            ft_init_device_mesh(manager, {"fsdp": 3}, devices=jax.devices())

    def test_inner_mesh_usable_by_pjit(self):
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        manager = mock_manager()
        mesh = ft_init_device_mesh(manager, {"fsdp": 8}, devices=jax.devices())
        x = jnp.arange(16.0).reshape(8, 2)
        sharding = NamedSharding(mesh.mesh, P("fsdp", None))
        y = jax.device_put(x, sharding)
        out = jax.jit(lambda a: (a * 2).sum())(y)
        assert float(out) == float((x * 2).sum())
