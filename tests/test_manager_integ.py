"""Integration tests: threads-as-replicas with a real coordination stack.

The reference's central testing trick (reference:
torchft/manager_integ_test.py:179-359): each replica group is a thread with
its own Manager + store + PG; one real LighthouseServer binds port 0.
Fault injection goes through the production chaos layer
(``torchft_tpu.utils.faults`` — the same registry ``TORCHFT_FAULTS``
configures in deployments), NOT a test-local injector: the reference's
EventInjector/FakeProcessGroupWrapper pattern is superseded so integration
tests and production share one injection mechanism.  Recovery must make
state dicts converge **bitwise** across replicas (reference :361-362) —
the zero-contribution allreduce hands the healer the same averaged
gradients the participants applied, so one step after healing everyone is
identical.
"""

import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import pytest

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroupTCP
from torchft_tpu.utils import faults
from torchft_tpu.utils.faults import FaultRule, InjectedFault


@pytest.fixture(autouse=True)
def clean_faults():
    """Every test starts and ends with an empty chaos schedule (the
    registry is process-wide by design)."""
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


def fail_at(replica: int, step: int) -> FaultRule:
    """Replica-crash rule: ``train.step`` raises in the training loop of
    ``replica_<replica>`` at ``step`` — the Runner treats it as a process
    death and restarts (the EventInjector.fail_at analog)."""
    return FaultRule(site="train.step", replica=f"replica_{replica}", step=step)


def fail_allreduce_at(replica: int, step: int) -> FaultRule:
    """Collective-failure rule: ``pg.allreduce`` fails inside
    ``Manager.allreduce`` — latched via report_error, the step aborts
    cleanly and the quorum re-forms (the fail_allreduce_at analog)."""
    return FaultRule(site="pg.allreduce", replica=f"replica_{replica}", step=step)


@dataclass
class Runner:
    """One replica group (single local rank) running a toy DDP loop.

    ``pgs``: optional shared sink every created ProcessGroup is appended
    to — the chaos suite's watchdog aborts them on deadline expiry.
    """

    replica_id: int
    lighthouse_addr: str
    total_steps: int = 5
    min_replica_size: int = 1
    use_async_quorum: bool = True
    attempts: int = 3
    lr: float = 0.1
    state_history: "List[dict]" = field(default_factory=list)
    pgs: "Optional[List[ProcessGroupTCP]]" = None

    def run(self) -> dict:
        last_exc: "Optional[BaseException]" = None
        for attempt in range(self.attempts):
            try:
                return self._train(attempt)
            except InjectedFault as e:
                last_exc = e
                continue
        raise RuntimeError(f"replica {self.replica_id} exhausted attempts") from last_exc

    def _train(self, attempt: int) -> dict:
        # Toy model: params w; deterministic "gradient" = f(step). Fresh
        # params each (re)start — healing must restore them.
        params = {"w": np.zeros(4, dtype=np.float32)}
        momentum = {"w": np.zeros(4, dtype=np.float32)}

        def load_state_dict(sd):
            params["w"] = np.array(sd["params"]["w"])
            momentum["w"] = np.array(sd["momentum"]["w"])

        def state_dict():
            return {
                "params": {"w": params["w"].copy()},
                "momentum": {"w": momentum["w"].copy()},
            }

        pg = ProcessGroupTCP(timeout=10.0)
        if self.pgs is not None:
            self.pgs.append(pg)
        manager = Manager(
            pg=pg,
            min_replica_size=self.min_replica_size,
            load_state_dict=load_state_dict,
            state_dict=state_dict,
            lighthouse_addr=self.lighthouse_addr,
            replica_id=f"replica_{self.replica_id}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=self.use_async_quorum,
            timeout=20.0,
            quorum_timeout=20.0,
        )
        try:
            while manager.current_step() < self.total_steps:
                step = manager.current_step()
                # production injection point for replica-crash chaos: a
                # scheduled train.step fault raises InjectedFault here
                faults.check(
                    "train.step", replica=f"replica_{self.replica_id}", step=step
                )

                manager.start_quorum()
                # deterministic per-step pseudo-gradient, same on every
                # replica so DDP averaging is an identity check
                grads = {
                    "w": np.full(4, float(step + 1), dtype=np.float32)
                    * (1.0 + 0.5 * self.replica_id)
                }
                avg_grads = manager.allreduce(grads).wait(timeout=30)
                if manager.should_commit():
                    momentum["w"] = 0.9 * momentum["w"] + avg_grads["w"]
                    params["w"] = params["w"] - self.lr * momentum["w"]
                    self.state_history.append(
                        {"step": manager.current_step(), "w": params["w"].copy()}
                    )
            return {
                "replica_id": self.replica_id,
                "state_dict": state_dict(),
                "manager_state": manager.state_dict(),
            }
        finally:
            manager.shutdown()


def run_replicas(runners: "List[Runner]") -> "List[dict]":
    with ThreadPoolExecutor(max_workers=len(runners)) as ex:
        futures = [ex.submit(r.run) for r in runners]
        return [f.result(timeout=120) for f in futures]


@pytest.fixture
def lighthouse():
    server = LighthouseServer(
        min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=1000
    )
    yield server
    server.shutdown()


def assert_bitwise_equal(results):
    base = results[0]["state_dict"]
    for other in results[1:]:
        np.testing.assert_array_equal(
            base["params"]["w"], other["state_dict"]["params"]["w"]
        )
        np.testing.assert_array_equal(
            base["momentum"]["w"], other["state_dict"]["momentum"]["w"]
        )


class TestDDPInteg:
    def test_ddp_healthy(self, lighthouse):
        runners = [
            Runner(i, lighthouse.address(), total_steps=4, min_replica_size=2)
            for i in range(2)
        ]
        results = run_replicas(runners)
        assert faults.FAULTS.injected() == 0
        assert all(r["manager_state"]["step"] == 4 for r in results)
        # 2 participants x 4 steps
        assert all(r["manager_state"]["batches_committed"] == 8 for r in results)
        assert_bitwise_equal(results)

    @pytest.mark.parametrize("use_async", [True, False])
    def test_ddp_recovery(self, lighthouse, use_async):
        faults.FAULTS.configure([fail_at(replica=1, step=2)])
        runners = [
            Runner(
                i,
                lighthouse.address(),
                total_steps=5,
                min_replica_size=1,
                use_async_quorum=use_async,
            )
            for i in range(2)
        ]
        results = run_replicas(runners)
        assert faults.FAULTS.injected() == 1
        assert faults.FAULTS.counts() == {("train.step", "raise"): 1}
        assert all(r["manager_state"]["step"] == 5 for r in results)
        assert_bitwise_equal(results)

    def test_ddp_allreduce_failure_recovers(self, lighthouse):
        faults.FAULTS.configure([fail_allreduce_at(replica=1, step=1)])
        runners = [
            Runner(i, lighthouse.address(), total_steps=4, min_replica_size=1)
            for i in range(2)
        ]
        results = run_replicas(runners)
        assert faults.FAULTS.injected() == 1
        assert faults.FAULTS.counts() == {("pg.allreduce", "raise"): 1}
        assert all(r["manager_state"]["step"] == 4 for r in results)
        assert_bitwise_equal(results)

    def test_multi_replica_recovery(self, lighthouse):
        # two different replicas die at different steps
        faults.FAULTS.configure([fail_at(1, 1), fail_at(2, 2)])
        runners = [
            Runner(i, lighthouse.address(), total_steps=5, min_replica_size=1)
            for i in range(3)
        ]
        results = run_replicas(runners)
        assert faults.FAULTS.injected() == 2
        assert all(r["manager_state"]["step"] == 5 for r in results)
        assert_bitwise_equal(results)


class TestEventExport:
    def test_events_file_written_on_replica_kill(self, lighthouse, tmp_path, monkeypatch):
        """The persistent JSONL sink (TORCHFT_EVENTS_FILE) must capture the
        quorum churn, the injected fault, and the post-heal commits of a
        replica-kill run — the crash-durable analog of the reference's OTLP
        exporter (reference torchft/otel.py:42-86)."""
        import json

        events_file = tmp_path / "events.jsonl"
        monkeypatch.setenv("TORCHFT_EVENTS_FILE", str(events_file))

        faults.FAULTS.configure([fail_at(replica=1, step=2)])
        runners = [
            Runner(i, lighthouse.address(), total_steps=5, min_replica_size=1)
            for i in range(2)
        ]
        results = run_replicas(runners)
        assert faults.FAULTS.injected() == 1
        assert_bitwise_equal(results)

        lines = events_file.read_text().strip().splitlines()
        events = [json.loads(line) for line in lines]
        kinds = {e["kind"] for e in events}
        assert "quorum" in kinds and "commit" in kinds
        # the chaos layer writes its injection as a structured event too
        assert any(
            e["kind"] == "fault" and e.get("site") == "train.step" for e in events
        )
        # quorum changed at least twice: initial formation + post-kill rejoin
        assert sum(1 for e in events if e["kind"] == "quorum") >= 2
        # the killed replica's post-heal commits are present
        assert any(
            e["kind"] == "commit" and str(e.get("replica_id", "")).startswith("replica_1")
            for e in events
        )
        # every record carries the structured context fields and a timestamp
        for e in events:
            assert {"ts", "kind", "message", "replica_id", "step"} <= set(e)

    def test_events_file_rotation(self, tmp_path, monkeypatch):
        from torchft_tpu.utils.logging import log_event

        events_file = tmp_path / "ring.jsonl"
        monkeypatch.setenv("TORCHFT_EVENTS_FILE", str(events_file))
        monkeypatch.setenv("TORCHFT_EVENTS_MAX_BYTES", "2000")
        for i in range(100):
            log_event("commit", "x" * 50, replica_id="r", rank=0, step=i)
        assert events_file.exists()
        rotated = events_file.with_name(events_file.name + ".1")
        assert rotated.exists()
        assert events_file.stat().st_size <= 2000 + 200


class TestFixedWithSpares:
    def test_spare_computes_zero_contributes_then_promoted(self, lighthouse):
        """FIXED_WITH_SPARES end to end (reference torchft/manager.py:112-127
        semantics; VERDICT r4 item 4): with 3 replica groups and
        min_replica_size=2, the world is capped at 2 — the 3rd replica is a
        hot spare that computes every step but contributes zeros and holds
        no participating rank; averages divide by 2 and exclude the spare's
        gradients.  When a participant dies, the spare is promoted within
        one quorum, and survivors converge bitwise."""
        from torchft_tpu.manager import WorldSizeMode

        TOTAL, KILL_AT = 10, 5
        results: "Dict[int, dict]" = {}
        errors: "Dict[int, BaseException]" = {}
        # replica_id -> list of (committed_step, participating, num_participants)
        participation: "Dict[int, list]" = {0: [], 1: [], 2: []}
        avg_samples: "Dict[int, dict]" = {0: {}, 1: {}, 2: {}}

        def run(rid: int) -> None:
            params = {"w": np.zeros(4, dtype=np.float32)}

            def load_state_dict(sd):
                params["w"] = np.array(sd["w"])

            def state_dict():
                return {"w": params["w"].copy()}

            manager = Manager(
                pg=ProcessGroupTCP(timeout=10.0),
                min_replica_size=2,
                world_size_mode=WorldSizeMode.FIXED_WITH_SPARES,
                load_state_dict=load_state_dict,
                state_dict=state_dict,
                lighthouse_addr=lighthouse.address(),
                replica_id=f"replica_{rid}",
                group_rank=0,
                group_world_size=1,
                use_async_quorum=False,  # eager heal: spares join in-step
                timeout=20.0,
                quorum_timeout=20.0,
            )
            try:
                while manager.current_step() < TOTAL:
                    step = manager.current_step()
                    if rid == 0 and step == KILL_AT:
                        return  # permanent death: spare must take over
                    manager.start_quorum()
                    grads = {
                        "w": np.full(4, float(step + 1), dtype=np.float32)
                        * (1.0 + 0.5 * rid)
                    }
                    avg = manager.allreduce(grads).wait(timeout=30)
                    if manager.should_commit():
                        params["w"] = params["w"] - 0.1 * avg["w"]
                        participation[rid].append(
                            (
                                manager.current_step(),
                                manager.is_participating(),
                                manager.num_participants(),
                            )
                        )
                        avg_samples[rid][manager.current_step()] = avg["w"].copy()
                results[rid] = state_dict()
            except BaseException as e:  # noqa: BLE001
                errors[rid] = e
            finally:
                manager.shutdown()

        threads = [
            threading.Thread(target=run, args=(r,), daemon=True)
            for r in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not any(t.is_alive() for t in threads), "replica hung"
        assert not errors, errors
        assert set(results) == {1, 2}, results

        # world size stays capped at min_replica_size=2 on EVERY commit
        for rid, hist in participation.items():
            for step, _, nparts in hist:
                assert nparts == 2, (rid, step, nparts)

        # before the kill: replica_2 is the spare (computes, never holds a
        # rank); replicas 0/1 participate
        pre2 = [p for p in participation[2] if p[0] <= KILL_AT]
        assert pre2, "spare committed no steps before the kill"
        assert all(not participating for _, participating, _ in pre2), pre2
        assert all(p for _, p, _ in participation[0]), participation[0]
        pre1 = [p for p in participation[1] if p[0] <= KILL_AT]
        assert all(p for _, p, _ in pre1), pre1

        # spare's zero-contribution is real: phase-1 averages exclude its
        # gradients — avg(step s) = (s+1)*(1.0 + 1.5)/2, not .../3 variants
        for step, avg in avg_samples[1].items():
            if step <= KILL_AT:
                expected = np.full(4, float(step) * 1.25, dtype=np.float32)
                np.testing.assert_allclose(avg, expected, rtol=1e-6)

        # promotion: within one quorum of replica_0's death the spare
        # holds a rank (committed steps after the kill are participating)
        post2 = [p for p in participation[2] if p[0] > KILL_AT + 1]
        assert post2, "spare committed nothing after the kill"
        assert all(p for _, p, _ in post2), post2

        # bitwise convergence of the survivors
        np.testing.assert_array_equal(results[1]["w"], results[2]["w"])
