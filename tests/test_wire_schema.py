"""Wire-schema drift gate (tft-verify leg 2) + conformance tests
generated from the committed protocol.lock.

The drift gate mirrors tests/test_lint.py: the REAL tree yields zero
findings, and a seeded drift on each surface — a Python field rename, a
native field rename, a docs-table omission, a stale lock — is caught.
The conformance tests don't restate the schema by hand: they are
parametrized FROM protocol.lock, so the lock file is executable, not
decorative.
"""

import dataclasses
import json
import os
import threading

import pytest

from torchft_tpu import coordination
from torchft_tpu.analysis import wire_schema as ws
from torchft_tpu.coordination import (
    LighthouseClient,
    LighthouseServer,
    ManagerClient,
    ManagerServer,
    Quorum,
    QuorumMember,
    QuorumResult,
    StoreClient,
    StoreServer,
    compute_quorum_results,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LOCK = ws.load_lock(ws.default_lock_path())
assert LOCK is not None, "torchft_tpu/analysis/protocol.lock must be committed"

_STRUCT_CLASSES = {
    "QuorumMember": QuorumMember,
    "Quorum": Quorum,
    "QuorumResult": QuorumResult,
}

#: sentinel value per canonical wire type (array stays empty: element
#: schemas are struct-typed and covered by their own cases)
_SENTINELS = {
    "string": "sentinel",
    "int": 7,
    "bool": True,
    "double": 1.5,
    "object": {},
    "array": [],
    "any": "opaque",
}


def _tree_inputs():
    return ws.gather_inputs(REPO)


def _findings(py_source, native_sources, docs_text, lock, **kw):
    return list(
        ws.run_checks(py_source, native_sources, docs_text, lock, **kw)
    )


class TestDriftGateClean:
    def test_tree_has_zero_findings(self):
        py, native, nfiles, docs, lock, lockfile = _tree_inputs()
        found = _findings(
            py, native, docs, lock, native_file_of=nfiles, lock_file=lockfile
        )
        assert found == [], "\n".join(f.render() for f in found)

    def test_committed_lock_matches_fresh_build(self):
        py, native, _nf, _docs, lock, _lf = _tree_inputs()
        assert ws.build_lock(py, native) == lock

    def test_lock_dump_is_stable(self):
        """Regenerating an unchanged tree must be byte-stable (sorted
        keys, trailing newline) so the lock never churns in diffs."""
        py, native, _nf, _docs, _lock, _lf = _tree_inputs()
        text = open(ws.default_lock_path(), encoding="utf-8").read()
        assert ws.dump_lock(ws.build_lock(py, native)) == text

    def test_lock_covers_the_expected_surface(self):
        servers = LOCK["servers"]
        assert set(servers) == {"lighthouse", "manager", "store"}
        assert set(servers["lighthouse"]) == {
            "quorum", "heartbeat", "status", "timeline",
            "serving_heartbeat", "serving_plan", "lease", "links",
            "fragments",
        }
        assert set(servers["manager"]) == {
            "quorum", "should_commit", "checkpoint_metadata", "kill",
        }
        assert set(servers["store"]) == {
            "set", "get", "delete_prefix", "num_keys",
        }
        assert set(LOCK["structs"]) == set(_STRUCT_CLASSES)
        # the request envelope (incl. the distributed-tracing field) is
        # part of the locked surface
        assert LOCK["envelope"] == [
            "method", "params", "timeout_ms", "traceparent",
        ]
        assert '"traceparent"?' in LOCK["framing"]


class TestSeededDrift:
    """The gate bites: one seeded drift per surface, against the REAL
    tree sources (not a toy project)."""

    def _codes(self, py=None, native=None, docs=None, lock="keep"):
        tpy, tnative, nfiles, tdocs, tlock, lockfile = _tree_inputs()
        return {
            f.code
            for f in _findings(
                py if py is not None else tpy,
                native if native is not None else tnative,
                docs if docs is not None else tdocs,
                tlock if lock == "keep" else lock,
                native_file_of=nfiles,
                lock_file=lockfile,
            )
        }

    def test_python_field_rename_is_caught(self):
        py, *_ = _tree_inputs()
        drifted = py.replace('"store_address": self.store_address', '"store_addr": self.store_address')
        assert "store_addr" in drifted
        codes = self._codes(py=drifted)
        assert "struct-field-missing" in codes or "lock-drift" in codes

    def test_python_param_rename_is_caught(self):
        py, *_ = _tree_inputs()
        drifted = py.replace('params["inflight_op"] = inflight_op', 'params["inflight"] = inflight_op')
        assert drifted != py
        codes = self._codes(py=drifted)
        assert "param-dead" in codes

    def test_native_field_rename_is_caught(self):
        _py, native, *_ = _tree_inputs()
        lh = native["lighthouse.cc"]
        drifted = dict(native)
        drifted["lighthouse.cc"] = lh.replace(
            'j["world_size"] = world_size;', 'j["worldsize"] = world_size;'
        )
        assert drifted["lighthouse.cc"] != lh
        codes = self._codes(native=drifted)
        assert "struct-field-missing" in codes

    def test_native_param_rename_is_caught(self):
        _py, native, *_ = _tree_inputs()
        mg = native["manager.cc"]
        drifted = dict(native)
        drifted["manager.cc"] = mg.replace(
            'params.get("group_rank")', 'params.get("grp_rank")'
        )
        assert drifted["manager.cc"] != mg
        codes = self._codes(native=drifted)
        assert {"param-dead", "param-missing"} <= codes

    def test_python_serving_param_rename_is_caught(self):
        """Serving-tier surface (ISSUE 12): renaming a serving_heartbeat
        param on the Python side means the native handler reads its wire
        default forever — the gate must bite."""
        py, *_ = _tree_inputs()
        drifted = py.replace('"capacity": int(capacity)', '"cap": int(capacity)')
        assert drifted != py
        codes = self._codes(py=drifted)
        assert {"param-dead", "param-missing"} <= codes

    def test_native_serving_param_rename_is_caught(self):
        _py, native, *_ = _tree_inputs()
        lh = native["lighthouse.cc"]
        drifted = dict(native)
        drifted["lighthouse.cc"] = lh.replace(
            'params.get("version").as_int(0)', 'params.get("ver").as_int(0)'
        )
        assert drifted["lighthouse.cc"] != lh
        codes = self._codes(native=drifted)
        assert {"param-dead", "param-missing"} <= codes

    def test_native_serving_result_rename_is_caught(self):
        """Renaming the plan-epoch reply field natively orphans the
        Python client's result read."""
        _py, native, *_ = _tree_inputs()
        lh = native["lighthouse.cc"]
        drifted = dict(native)
        drifted["lighthouse.cc"] = lh.replace(
            'out["plan_epoch"] = serving_epoch_;',
            'out["planepoch"] = serving_epoch_;',
        )
        assert drifted["lighthouse.cc"] != lh
        codes = self._codes(native=drifted)
        assert "result-missing" in codes or "lock-drift" in codes

    def test_python_lease_param_rename_is_caught(self):
        """Coordination-plane HA surface (ISSUE 13): renaming a lease
        param on the Python side means the native grant rule reads its
        wire default — the gate must bite."""
        py, *_ = _tree_inputs()
        drifted = py.replace('"term": int(term)', '"trm": int(term)')
        assert drifted != py
        codes = self._codes(py=drifted)
        assert {"param-dead", "param-missing"} <= codes

    def test_native_lease_param_rename_is_caught(self):
        _py, native, *_ = _tree_inputs()
        lh = native["lighthouse.cc"]
        drifted = dict(native)
        drifted["lighthouse.cc"] = lh.replace(
            'params.get("candidate").as_string()',
            'params.get("cand").as_string()',
        )
        assert drifted["lighthouse.cc"] != lh
        codes = self._codes(native=drifted)
        assert {"param-dead", "param-missing"} <= codes

    def test_native_lease_result_rename_is_caught(self):
        """Renaming the lease reply's holder field natively orphans the
        Python client's result read."""
        _py, native, *_ = _tree_inputs()
        lh = native["lighthouse.cc"]
        drifted = dict(native)
        drifted["lighthouse.cc"] = lh.replace(
            'out["holder"] = promised_to_;', 'out["holdr"] = promised_to_;'
        )
        assert drifted["lighthouse.cc"] != lh
        codes = self._codes(native=drifted)
        assert "result-missing" in codes or "lock-drift" in codes

    def test_python_links_param_rename_is_caught(self):
        """Link-state surface (ISSUE 16): renaming the heartbeat's links
        piggyback key on the Python side means the native aggregator
        never sees a digest again — the gate must bite."""
        py, *_ = _tree_inputs()
        drifted = py.replace('params["links"] = links', 'params["lnks"] = links')
        assert drifted != py
        codes = self._codes(py=drifted)
        assert {"param-dead", "param-missing"} <= codes

    def test_native_links_result_rename_is_caught(self):
        """Renaming a links-reply field natively drifts the locked
        matrix document out from under /links.json consumers."""
        _py, native, *_ = _tree_inputs()
        lh = native["lighthouse.cc"]
        drifted = dict(native)
        drifted["lighthouse.cc"] = lh.replace(
            'out["reports_total"] = links_reports_total_;',
            'out["reportstotal"] = links_reports_total_;',
        )
        assert drifted["lighthouse.cc"] != lh
        codes = self._codes(native=drifted)
        assert "result-missing" in codes or "lock-drift" in codes

    def test_python_fragments_param_rename_is_caught(self):
        """Fragment provenance surface (ISSUE 18): renaming the
        heartbeat's fragments piggyback key on the Python side means the
        native aggregator never folds a digest again — the gate must
        bite."""
        py, *_ = _tree_inputs()
        drifted = py.replace(
            'params["fragments"] = fragments',
            'params["frgs"] = fragments',
        )
        assert drifted != py
        codes = self._codes(py=drifted)
        assert {"param-dead", "param-missing"} <= codes

    def test_native_fragments_result_rename_is_caught(self):
        """Renaming a fragments-reply field natively drifts the locked
        version-matrix document out from under /fragments.json
        consumers."""
        _py, native, *_ = _tree_inputs()
        lh = native["lighthouse.cc"]
        drifted = dict(native)
        drifted["lighthouse.cc"] = lh.replace(
            'out["reports_total"] = fragments_reports_total_;',
            'out["reportstotal"] = fragments_reports_total_;',
        )
        assert drifted["lighthouse.cc"] != lh
        codes = self._codes(native=drifted)
        assert "result-missing" in codes or "lock-drift" in codes

    def test_doc_omission_is_caught(self):
        _py, _native, _nf, docs, *_ = _tree_inputs()
        drifted = docs.replace("| lighthouse | `timeline` |", "| lighthouse |`timeline-x` |")
        assert drifted != docs
        codes = self._codes(docs=drifted)
        assert "method-undocumented" in codes

    def test_python_traceparent_rename_is_caught(self):
        """The tracing envelope field is machine-checked on the PYTHON
        side: renaming the injected key means the native server never
        sees a context again — the gate must bite."""
        py, *_ = _tree_inputs()
        drifted = py.replace(
            'req["traceparent"] = traceparent',
            'req["trace_parent"] = traceparent',
        )
        assert drifted != py
        codes = self._codes(py=drifted)
        assert {"envelope-field-dead", "envelope-field-missing"} <= codes

    def test_native_traceparent_rename_is_caught(self):
        """...and on the NATIVE side: renaming serve_conn's read breaks
        continuation (and orphans the native client's own write)."""
        _py, native, *_ = _tree_inputs()
        net = native["net.cc"]
        drifted = dict(native)
        drifted["net.cc"] = net.replace(
            'req.get("traceparent")', 'req.get("trace_parent")'
        )
        assert drifted["net.cc"] != net
        codes = self._codes(native=drifted)
        assert {"envelope-field-dead", "envelope-field-missing"} <= codes

    def test_stale_lock_is_caught(self):
        stale = json.loads(json.dumps(LOCK))
        stale["structs"]["QuorumMember"]["vintage"] = "string"
        codes = self._codes(lock=stale)
        assert "lock-drift" in codes

    def test_missing_lock_is_caught(self):
        codes = self._codes(lock=None)
        assert "lock-missing" in codes


# ---------------------------------------------------------------------------
# conformance tests GENERATED from the lock
# ---------------------------------------------------------------------------


class TestStructConformance:
    @pytest.mark.parametrize("struct", sorted(LOCK["structs"]), ids=str)
    def test_dataclass_fields_match_lock(self, struct):
        cls = _STRUCT_CLASSES[struct]
        declared = {f.name for f in dataclasses.fields(cls)}
        assert declared == set(LOCK["structs"][struct])

    @pytest.mark.parametrize("struct", sorted(LOCK["structs"]), ids=str)
    def test_from_dict_round_trips_locked_payload(self, struct):
        """A wire payload carrying exactly the locked fields parses with
        no field falling back to its wire default."""
        cls = _STRUCT_CLASSES[struct]
        payload = {
            k: _SENTINELS[t] for k, t in LOCK["structs"][struct].items()
        }
        obj = cls.from_dict(payload)
        for k, t in LOCK["structs"][struct].items():
            if t == "array":
                continue  # element parsing covered by the member structs
            assert getattr(obj, k) == payload[k], (
                f"{struct}.{k} did not survive from_dict (wire default "
                f"swallowed the payload value — field-name drift)"
            )

    @pytest.mark.parametrize("struct", sorted(LOCK["structs"]), ids=str)
    def test_from_dict_is_total_on_empty_payload(self, struct):
        _STRUCT_CLASSES[struct].from_dict({})

    def test_quorum_member_to_dict_round_trip(self):
        payload = {
            k: _SENTINELS[t]
            for k, t in LOCK["structs"]["QuorumMember"].items()
        }
        assert QuorumMember.from_dict(payload).to_dict() == payload

    def test_native_quorum_math_speaks_the_locked_structs(self):
        """compute_quorum_results: Python Quorum -> native JSON parse ->
        native QuorumResult -> Python from_dict, end to end."""
        members = [
            QuorumMember(replica_id="a:0", address="x:1", store_address="s:1",
                         step=3, world_size=1),
            QuorumMember(replica_id="b:0", address="x:2", store_address="s:2",
                         step=3, world_size=1),
        ]
        q = Quorum(quorum_id=9, participants=members, created_ms=1)
        res = compute_quorum_results("a:0", 0, q)
        assert isinstance(res, QuorumResult)
        assert res.quorum_id == 9
        assert res.max_step == 3
        assert not res.heal


class TestLiveConformance:
    """Every locked method answers on a real server, and its reply's
    top-level keys are a subset of the locked result fields — run
    straight off protocol.lock."""

    @pytest.fixture()
    def stack(self):
        lh = LighthouseServer(min_replicas=1, join_timeout_ms=50)
        store = StoreServer()
        mgr = ManagerServer(
            replica_id="conf_0:a",
            lighthouse_addr=lh.address(),
            store_address=store.address(),
            world_size=1,
        )
        yield lh, store, mgr
        mgr.shutdown()
        store.shutdown()
        lh.shutdown()

    def _check_result(self, server, method, result):
        locked = LOCK["servers"][server][method]
        if isinstance(result, dict) and locked["result_struct"] is None:
            extra = set(result) - set(locked["result"])
            assert not extra, (
                f"{server}.{method} reply carries unlocked field(s) "
                f"{sorted(extra)} — regenerate protocol.lock"
            )

    def test_lighthouse_methods(self):
        # NOT the shared stack: its ManagerServer heartbeats this
        # lighthouse without joining, so the majority-of-heartbeaters
        # guard would (correctly!) hold our lone direct joiner at bay —
        # the exact bystander scenario the tft-verify 'partition' model
        # proves the guard must block.
        lh = LighthouseServer(min_replicas=1, join_timeout_ms=50)
        c = LighthouseClient(lh.address())
        try:
            q = c.quorum("live_0:a", timeout=10.0, step=0)
            assert q.quorum_id >= 1
            hb = c.heartbeat("live_0:a", step=1, last_step_wall_ms=1,
                             inflight_op="test")
            self._check_result("lighthouse", "heartbeat", hb)
            st = c.status()
            self._check_result("lighthouse", "status", st)
            tl = c.timeline()
            self._check_result("lighthouse", "timeline", tl)
            sh = c.serving_heartbeat(
                "live_srv", "http://x:1", role="server", version=2,
                capacity=1,
            )
            self._check_result("lighthouse", "serving_heartbeat", sh)
            sp = c.serving_plan()
            self._check_result("lighthouse", "serving_plan", sp)
            assert [n["replica_id"] for n in sp["nodes"]] == ["live_srv"]
            c.heartbeat(
                "live_0:a",
                links={"host": "h0", "rows": [{
                    "peer": "h1", "plane": "reduction", "local": False,
                    "goodput_bps": 1e8, "rtt_ms": 1.0, "rtt_p99_ms": 2.0,
                    "samples": 9, "bytes": 1024, "age_s": 0.1,
                }]},
            )
            lk = c.links()
            self._check_result("lighthouse", "links", lk)
            assert lk["rows_total"] == 1
            c.heartbeat(
                "live_0:a",
                fragments={"host": "h0", "frags": [{
                    "frag": "weights/0", "version": 3,
                    "digest8": "aabbccdd", "version_ms": 1000,
                    "held_ms": 900, "pub": True,
                }]},
            )
            fr = c.fragments()
            self._check_result("lighthouse", "fragments", fr)
            assert fr["rows_total"] == 1
        finally:
            c.close()
            lh.shutdown()

    def test_manager_methods(self, stack):
        _lh, _store, mgr = stack
        c = ManagerClient(mgr.address())
        try:
            res = c._quorum(
                group_rank=0, step=0, checkpoint_metadata="meta0",
                shrink_only=False, timeout=20.0,
            )
            assert isinstance(res, QuorumResult)
            assert c._checkpoint_metadata(rank=0, timeout=5.0) == "meta0"
            assert c.should_commit(0, step=0, should_commit=True,
                                   timeout=5.0) is True
            # kill is locked but deliberately not exercised live (it
            # makes the remote process exit); its wiring is covered by
            # the chaos-integration suite
        finally:
            c.close()

    def test_store_methods(self, stack):
        _lh, store, _mgr = stack
        c = StoreClient(store.address())
        try:
            c.set("conformance/k", "v")
            assert c.get("conformance/k") == "v"
            assert c.num_keys() >= 1
            assert c.delete_prefix("conformance/") == 1
        finally:
            c.close()
