"""ProcessGroup conformance + resiliency tests.

Mirrors reference torchft/process_group_test.py: per-backend collective
smoke over threads-as-ranks, reconfigure, and the kill-a-rank resiliency
scenario (reference :961-1020) where survivors must error, reconfigure to a
smaller world, and succeed.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from torchft_tpu.coordination import StoreServer
from torchft_tpu.parallel.process_group import (
    REDUCE_AVG,
    REDUCE_MAX,
    REDUCE_SUM,
    ErrorSwallowingProcessGroupWrapper,
    FakeProcessGroupWrapper,
    ProcessGroupDummy,
    ProcessGroupTCP,
    ProcessGroupWrapper,
)


def run_parallel(world, fn, pgs=None):
    """Run fn(rank, pg) on one thread per rank; returns results by rank."""
    if pgs is None:
        pgs = [None] * world
    with ThreadPoolExecutor(max_workers=world) as ex:
        futures = [ex.submit(fn, r, pgs[r]) for r in range(world)]
        return [f.result(timeout=60) for f in futures]


@pytest.fixture
def store():
    server = StoreServer()
    yield server
    server.shutdown()


def make_group(store, world, prefix="test", timeout=20.0):
    """Configure a TCP process group across `world` thread-ranks."""
    pgs = [ProcessGroupTCP(timeout=timeout) for _ in range(world)]

    def configure(rank, _):
        pgs[rank].configure(f"{store.address()}/{prefix}", f"rank{rank}", rank, world)

    run_parallel(world, configure)
    return pgs


class TestProcessGroupTCP:
    @pytest.mark.parametrize("world", [2, 3, 5])
    def test_allreduce_sum(self, store, world):
        pgs = make_group(store, world)
        data = [np.arange(10, dtype=np.float32) + r for r in range(world)]
        expected = sum(data)

        def op(rank, _):
            return pgs[rank].allreduce([data[rank]], REDUCE_SUM).wait()[0]

        for result in run_parallel(world, op):
            np.testing.assert_allclose(result, expected, rtol=1e-6)
        for pg in pgs:
            pg.shutdown()

    def test_allreduce_avg_and_max(self, store):
        world = 3
        pgs = make_group(store, world)
        data = [np.full((4,), float(r + 1), dtype=np.float32) for r in range(world)]

        def op_avg(rank, _):
            return pgs[rank].allreduce([data[rank]], REDUCE_AVG).wait()[0]

        for result in run_parallel(world, op_avg):
            np.testing.assert_allclose(result, np.full((4,), 2.0), rtol=1e-6)

        def op_max(rank, _):
            return pgs[rank].allreduce([data[rank]], REDUCE_MAX).wait()[0]

        for result in run_parallel(world, op_max):
            np.testing.assert_allclose(result, np.full((4,), 3.0))
        for pg in pgs:
            pg.shutdown()

    def test_allreduce_large_buffer(self, store):
        # Bigger than socket buffers: exercises the deadlock-free exchange.
        world = 2
        pgs = make_group(store, world)
        data = [np.random.default_rng(r).standard_normal(1 << 20).astype(np.float32) for r in range(world)]

        def op(rank, _):
            return pgs[rank].allreduce([data[rank]], REDUCE_SUM).wait()[0]

        results = run_parallel(world, op)
        np.testing.assert_allclose(results[0], data[0] + data[1], rtol=1e-5)
        for pg in pgs:
            pg.shutdown()

    def test_allgather(self, store):
        world = 3
        pgs = make_group(store, world)

        def op(rank, _):
            return pgs[rank].allgather(np.array([rank, rank * 10])).wait()

        for result in run_parallel(world, op):
            assert len(result) == world
            for r, piece in enumerate(result):
                np.testing.assert_array_equal(piece, [r, r * 10])
        for pg in pgs:
            pg.shutdown()

    def test_broadcast(self, store):
        world = 3
        pgs = make_group(store, world)

        def op(rank, _):
            arr = np.array([42.0]) if rank == 1 else np.zeros(1)
            return pgs[rank].broadcast(arr, root=1).wait()

        for result in run_parallel(world, op):
            np.testing.assert_array_equal(result, [42.0])
        for pg in pgs:
            pg.shutdown()

    def test_reduce_scatter(self, store):
        world = 2
        pgs = make_group(store, world)
        data = [np.arange(8, dtype=np.float32).reshape(4, 2) * (r + 1) for r in range(world)]
        expected_total = data[0] + data[1]

        def op(rank, _):
            return pgs[rank].reduce_scatter(data[rank], REDUCE_SUM).wait()

        results = run_parallel(world, op)
        np.testing.assert_allclose(results[0], expected_total[:2], rtol=1e-6)
        np.testing.assert_allclose(results[1], expected_total[2:], rtol=1e-6)
        for pg in pgs:
            pg.shutdown()

    def test_alltoall(self, store):
        world = 3
        pgs = make_group(store, world)

        def op(rank, _):
            inputs = [np.array([rank * 10 + dst]) for dst in range(world)]
            return pgs[rank].alltoall(inputs).wait()

        results = run_parallel(world, op)
        for rank, out in enumerate(results):
            for src, piece in enumerate(out):
                np.testing.assert_array_equal(piece, [src * 10 + rank])
        for pg in pgs:
            pg.shutdown()

    def test_send_recv(self, store):
        world = 2
        pgs = make_group(store, world)

        def op(rank, _):
            if rank == 0:
                pgs[0].send(np.array([1.5, 2.5]), dst=1, tag=7).wait()
                return None
            return pgs[1].recv(src=0, tag=7).wait()

        results = run_parallel(world, op)
        np.testing.assert_array_equal(results[1], [1.5, 2.5])
        for pg in pgs:
            pg.shutdown()

    def test_barrier(self, store):
        world = 3
        pgs = make_group(store, world)
        run_parallel(world, lambda r, _: pgs[r].barrier().wait())
        for pg in pgs:
            pg.shutdown()

    def test_world_size_one_local(self, store):
        (pg,) = make_group(store, 1)
        result = pg.allreduce([np.arange(3)], REDUCE_SUM).wait()
        np.testing.assert_array_equal(result[0], [0, 1, 2])
        pg.shutdown()

    def test_abort_latches_error(self, store):
        world = 2
        pgs = make_group(store, world)
        pgs[0].abort()
        assert pgs[0].errored() is not None
        work = pgs[0].allreduce([np.zeros(2)])
        with pytest.raises(RuntimeError):
            work.wait(timeout=5)

    def test_resiliency_kill_rank_then_reconfigure(self, store):
        # reference process_group_test.py:961-1020: kill the last rank,
        # survivors raise, then reconfigure to a smaller world and succeed.
        world = 3
        pgs = make_group(store, world, prefix="r1", timeout=3.0)

        # rank 2 "dies" (abort closes its sockets)
        pgs[2].abort()

        def failing_op(rank, _):
            try:
                pgs[rank].allreduce([np.ones(4)]).wait(timeout=10)
                return None
            except Exception as e:  # noqa: BLE001
                return e

        errors = run_parallel(2, failing_op)
        assert all(e is not None for e in errors), "survivors must observe failure"
        assert all(pgs[r].errored() is not None for r in range(2))

        # survivors reconfigure under a fresh prefix into world=2
        def reconfigure(rank, _):
            pgs[rank].configure(f"{store.address()}/r2", f"rank{rank}", rank, 2)

        run_parallel(2, reconfigure)
        assert all(pgs[r].errored() is None for r in range(2))

        def op(rank, _):
            return pgs[rank].allreduce([np.ones(4)]).wait()[0]

        for result in run_parallel(2, op):
            np.testing.assert_array_equal(result, np.full(4, 2.0))
        for pg in pgs[:2]:
            pg.shutdown()

    def test_timeout_on_missing_peer(self, store):
        # rank 0 configures against a world of 2 but rank 1 never shows up.
        pg = ProcessGroupTCP(timeout=1.0)
        with pytest.raises((TimeoutError, OSError)):
            pg.configure(f"{store.address()}/lonely", "rank0", 1, 2)


class TestWrappers:
    def test_dummy_ops(self):
        pg = ProcessGroupDummy()
        np.testing.assert_array_equal(
            pg.allreduce([np.array([1.0, 2.0])]).wait()[0], [1.0, 2.0]
        )
        assert pg.size() == 1
        pg.configure("", "r", 0, 1)
        assert pg.configure_count == 1

    def test_error_swallowing(self, store):
        inner = ProcessGroupDummy()
        pg = ErrorSwallowingProcessGroupWrapper(inner)
        assert pg.errored() is None
        pg.report_error(RuntimeError("boom"))
        assert pg.errored() is not None
        # ops become pass-through no-ops
        result = pg.allreduce([np.array([3.0])]).wait()
        np.testing.assert_array_equal(result[0], [3.0])
        # configure clears the error
        pg.configure("", "r", 0, 1)
        assert pg.errored() is None

    def test_error_swallowing_catches_op_failure(self):
        inner = ProcessGroupDummy()
        pg = ErrorSwallowingProcessGroupWrapper(inner)
        # recv fails on dummy; wrapper must swallow with a None result
        work = pg.recv(src=0)
        assert work.wait(timeout=5) is None
        assert pg.errored() is not None

    def test_error_swallowing_keeps_result_shapes(self):
        pg = ErrorSwallowingProcessGroupWrapper(ProcessGroupDummy())
        pg.report_error(RuntimeError("down"))
        # single-array ops return a bare array, list ops a list — matching
        # the success path so training code doesn't branch on failure.
        bc = pg.broadcast(np.arange(4.0)).wait(timeout=5)
        assert isinstance(bc, np.ndarray) and bc.shape == (4,)
        ar = pg.allreduce([np.arange(4.0)]).wait(timeout=5)
        assert isinstance(ar, list) and ar[0].shape == (4,)
        rs = pg.reduce_scatter(np.arange(4.0).reshape(4, 1)).wait(timeout=5)
        assert isinstance(rs, np.ndarray)

    def test_fake_injects_future_error(self):
        inner = ProcessGroupDummy()
        pg = FakeProcessGroupWrapper(inner)
        pg.report_future_error(RuntimeError("injected"))
        with pytest.raises(RuntimeError, match="injected"):
            pg.allreduce([np.zeros(1)]).wait(timeout=5)
        # next op is clean
        pg.allreduce([np.zeros(1)]).wait(timeout=5)

    def test_fake_injects_configure_error(self):
        pg = FakeProcessGroupWrapper(ProcessGroupDummy())
        pg.report_configure_error(RuntimeError("cfg boom"))
        with pytest.raises(RuntimeError, match="cfg boom"):
            pg.configure("", "r", 0, 1)
        pg.configure("", "r", 0, 1)  # second attempt clean

    def test_wrapper_forwards(self):
        inner = ProcessGroupDummy()
        pg = ProcessGroupWrapper(inner)
        assert pg.size() == 1
        assert pg.parent is inner

    def test_managed_forwards_allreduce_to_manager(self):
        from unittest.mock import MagicMock

        from torchft_tpu.parallel.process_group import ManagedProcessGroup
        from torchft_tpu.parallel.work import completed_work

        manager = MagicMock()
        manager.num_participants.return_value = 3
        manager.participating_rank.return_value = 1
        manager.errored.return_value = None
        manager.allreduce.return_value = completed_work([np.array([6.0])])

        pg = ManagedProcessGroup(manager)
        assert pg.size() == 3
        assert pg.rank() == 1
        assert pg.errored() is None

        out = pg.allreduce([np.array([2.0])], op="sum").wait(timeout=5)
        np.testing.assert_array_equal(out[0], [6.0])
        manager.allreduce.assert_called_once()
        assert manager.allreduce.call_args.kwargs["reduce_op"] == "sum"

        # non-allreduce collectives are rejected — the Manager owns quorum
        with pytest.raises(RuntimeError):
            pg.broadcast(np.zeros(1)).wait(timeout=5)
        with pytest.raises(RuntimeError):
            pg.configure("", "r", 0, 1)

    def test_managed_rank_when_not_participating(self):
        from unittest.mock import MagicMock

        from torchft_tpu.parallel.process_group import (
            ManagedProcessGroup,
            NotParticipatingError,
        )

        manager = MagicMock()
        manager.participating_rank.return_value = None
        pg = ManagedProcessGroup(manager)
        # a healing replica must NOT silently read rank-0's data shard
        with pytest.raises(NotParticipatingError):
            pg.rank()


class TestBucketing:
    def test_many_mixed_leaves_roundtrip(self, store):
        # mixed dtypes + a leaf above BUCKET_BYTES: bucketing must preserve
        # order, dtypes, shapes, and values
        world = 2
        pgs = make_group(store, world, "bucket")
        rng = np.random.default_rng(0)
        big = ProcessGroupTCP.BUCKET_BYTES // 4 + 100  # f32 elems, solo path
        leaves = [
            rng.standard_normal((5, 3)).astype(np.float32),
            (rng.standard_normal(7) * 10).astype(np.int32),
            rng.standard_normal(big).astype(np.float32),
            rng.standard_normal((2, 2, 2)).astype(np.float64),
            rng.standard_normal(11).astype(np.float32),
            (rng.standard_normal(4) * 10).astype(np.int32),
        ]

        def run(rank, _):
            return pgs[rank].allreduce([l.copy() for l in leaves], REDUCE_SUM).wait(
                timeout=30
            )

        results = run_parallel(world, run)
        for res in results:
            assert len(res) == len(leaves)
            for out, inp in zip(res, leaves):
                assert out.dtype == inp.dtype and out.shape == inp.shape
                np.testing.assert_allclose(
                    out.astype(np.float64), inp.astype(np.float64) * world,
                    rtol=1e-6,
                )
        for pg in pgs:
            pg.shutdown()

    def test_allreduce_reports_ring_wire_bytes(self, store):
        """The unquantized path carries measured wire accounting too
        (parity with the quantized collectives' wire_bytes, so
        bench/diagnose compare f32 vs int8 traffic honestly)."""
        world = 2
        pgs = make_group(store, world, "wirebytes")
        n = 10_000
        data = np.ones(n, dtype=np.float32)

        def run(rank, _):
            w = pgs[rank].allreduce([data.copy()], REDUCE_SUM)
            w.wait(timeout=30)
            return w.wire_bytes, w.unquantized_wire_bytes

        chunk = -(-n // world)
        expected = 2 * (world - 1) * chunk * 4  # ring: rs half + ag half
        for wire, unq in run_parallel(world, run):
            assert wire == expected
            assert unq == expected  # f32 IS the unquantized wire
        # bucketized multi-leaf: accounting follows the same bucket plan
        leaves = [np.ones(100, np.float32), np.ones(7, np.float64)]

        def run_multi(rank, _):
            w = pgs[rank].allreduce([l.copy() for l in leaves], REDUCE_SUM)
            w.wait(timeout=30)
            return w.wire_bytes

        per_bucket = 2 * (world - 1)
        expected_multi = per_bucket * (-(-100 // world)) * 4 + per_bucket * (
            -(-7 // world)
        ) * 8
        for wire in run_parallel(world, run_multi):
            assert wire == expected_multi
        for pg in pgs:
            pg.shutdown()


class TestNumerics:
    def test_bfloat16_allreduce_and_sendrecv(self, store):
        # bf16 is THE TPU training dtype; ml_dtypes arrays have no buffer-
        # protocol format char, so the zero-copy wire path must use uint8
        # views, and accumulation must widen to f32
        import ml_dtypes

        bf16 = np.dtype(ml_dtypes.bfloat16)
        world = 2
        pgs = make_group(store, world, "bf16")

        def ar(rank, _):
            x = np.full((4, 3), 1.5 + rank, dtype=bf16)
            out = pgs[rank].allreduce([x], REDUCE_SUM).wait(timeout=20)
            return out[0]

        results = run_parallel(world, ar)
        for res in results:
            assert res.dtype == bf16
            np.testing.assert_array_equal(
                res.astype(np.float32), np.full((4, 3), 4.0, np.float32)
            )

        def sr(rank, _):
            if rank == 0:
                pgs[0].send(np.arange(6, dtype=bf16), dst=1, tag=9).wait(timeout=20)
                return None
            return pgs[1].recv(src=0, tag=9).wait(timeout=20)

        got = run_parallel(world, sr)[1]
        assert got.dtype == bf16
        np.testing.assert_array_equal(got.astype(np.float32), np.arange(6.0))
        for pg in pgs:
            pg.shutdown()

    def test_accumulation_dtype_widens_ml_floats(self):
        import ml_dtypes

        from torchft_tpu.parallel.process_group import _accumulation_dtype

        assert _accumulation_dtype(np.dtype(ml_dtypes.bfloat16)) == np.float32
        assert _accumulation_dtype(np.dtype(np.float16)) == np.float32
        assert _accumulation_dtype(np.dtype(np.float32)) == np.float32
        assert _accumulation_dtype(np.dtype(np.float64)) == np.float64

    def test_int32_allreduce_no_overflow(self, store):
        # Partial ring sums must widen to i64 (values near 2**30, world 3).
        world = 3
        pgs = make_group(store, world, prefix="ovf")
        data = [np.full(4, 2**30 - 1, dtype=np.int64) for _ in range(world)]

        def op(rank, _):
            return pgs[rank].allreduce([data[rank].astype(np.int64)]).wait()[0]

        for result in run_parallel(world, op):
            np.testing.assert_array_equal(result, np.full(4, 3 * (2**30 - 1)))
        # int32 inputs widen internally and cast back
        data32 = [np.full(4, 1000, dtype=np.int32) for _ in range(world)]

        def op32(rank, _):
            out = pgs[rank].allreduce([data32[rank]]).wait()[0]
            assert out.dtype == np.int32
            return out

        for result in run_parallel(world, op32):
            np.testing.assert_array_equal(result, np.full(4, 3000))
        for pg in pgs:
            pg.shutdown()


class TestFlightRecorder:
    """On abort/deadline of a wedged collective, the in-flight op table
    (op, peer, tag, bytes progressed, deadline, generation) must land in
    the structured event pipeline — reference dumps the NCCL flight
    recorder on abort for the same postmortems
    (torchft/process_group.py:89-108,830-838)."""

    def test_wedged_collective_dumps_flight_record(self, store, tmp_path, monkeypatch):
        import json

        events_file = tmp_path / "events.jsonl"
        monkeypatch.setenv("TORCHFT_EVENTS_FILE", str(events_file))

        world = 2
        pgs = make_group(store, world, prefix="fr", timeout=2.0)
        try:
            # rank 0 submits an allreduce; rank 1 never does -> rank 0's ring
            # exchange wedges on the recv until its deadline fires
            with pytest.raises(Exception):
                pgs[0].allreduce([np.ones(1024, np.float32)]).wait(timeout=10)

            events = [
                json.loads(line)
                for line in events_file.read_text().strip().splitlines()
            ]
            aborts = [e for e in events if e["kind"] == "abort"]
            assert aborts, f"no abort record in {events}"
            rec = aborts[-1]
            assert rec["op"] == "allreduce"
            assert rec["rank"] == 0 and rec["world"] == 2
            assert "generation" in rec and "in_flight_s" in rec
            # it wedged waiting on rank 1 with an expired deadline
            assert rec["recv_peer"] == 1
            assert rec["deadline_remaining_s"] <= 0.1
        finally:
            for pg in pgs:
                pg.shutdown()

    def test_abort_mid_op_dumps_flight_record(self, store, monkeypatch):
        from torchft_tpu.utils.logging import recent_events

        world = 2
        pgs = make_group(store, world, prefix="fr2", timeout=30.0)
        try:
            # wedge rank 0 (long deadline), then abort it from another thread
            work = pgs[0].allreduce([np.ones(8, np.float32)])
            import time as _t

            _t.sleep(0.2)  # let the worker enter the blocked recv
            pgs[0].abort()
            with pytest.raises(Exception):
                work.wait(timeout=10)
            aborts = [e for e in recent_events() if e["kind"] == "abort"]
            assert aborts and aborts[-1]["op"] == "allreduce"
        finally:
            for pg in pgs:
                pg.shutdown()


class TestBandwidthShaper:
    """Egress token-bucket shaping (the measured-DCN bench harness and
    the TORCHFT_WIRE_GBPS knob)."""

    def test_token_bucket_rate(self):
        from torchft_tpu.parallel.process_group import _TokenBucket

        bucket = _TokenBucket(100e6, burst=1 << 20)  # 100 MB/s, 1 MB burst
        t0 = time.monotonic()
        total = 0
        while total < 20 << 20:  # 20 MB
            bucket.consume(1 << 20)
            total += 1 << 20
        elapsed = time.monotonic() - t0
        # fluid-model time for 20 MB minus the 1 MB burst at 100 MB/s is
        # ~0.199 s; allow generous slop above (slow CI) but the floor
        # proves the shaper actually paces
        assert 0.15 <= elapsed <= 1.0, elapsed

    def test_shaped_allreduce_measures_rate(self, store):
        """Asserts on the token bucket's OWN ledger (bytes debited,
        seconds slept serving debt) rather than comparing wall-clock
        legs: a loaded CI box can stretch the unshaped leg past the
        shaped one, but it cannot make the shaper's accounting lie."""
        from torchft_tpu.parallel.process_group import _TokenBucket

        world = 2
        pgs = [ProcessGroupTCP(timeout=60.0) for _ in range(world)]

        def configure(rank, _):
            pgs[rank].configure(
                f"{store.address()}/shaped", f"rank{rank}", rank, world
            )

        run_parallel(world, configure)
        # 50 MB/s with a 1 MB burst: a ring allreduce of 8 MB at w=2
        # moves ~8 MB per rank, so every sender runs well past its burst
        # and MUST serve debt (sleep) in its own bucket
        for pg in pgs:
            pg._bucket = _TokenBucket(50e6, burst=1 << 20)
        data = np.ones(2 << 20, dtype=np.float32)

        def run(rank, _):
            pgs[rank].allreduce([data.copy()], REDUCE_SUM).wait(timeout=60)

        run_parallel(world, run)
        for pg in pgs:
            bucket = pg._bucket
            assert bucket is not None
            # each rank's egress (reduce-scatter + allgather halves) ran
            # through its bucket: at least half the payload was debited
            assert bucket.consumed_bytes >= data.nbytes // 2, (
                bucket.consumed_bytes
            )
            # debt beyond the burst was actually paced off
            assert bucket.slept_s > 0.0
        for pg in pgs:
            pg.set_bandwidth(None)
            assert pg._bucket is None
        # unshaped leg still reduces correctly with shaping removed
        run_parallel(world, run)
        for pg in pgs:
            pg.shutdown()

    def test_env_knob(self, store, monkeypatch):
        monkeypatch.setenv("TORCHFT_WIRE_GBPS", "0.25")
        pg = ProcessGroupTCP(timeout=5.0)
        assert pg._bucket is not None
        assert pg._bucket.rate == 0.25e9
        monkeypatch.delenv("TORCHFT_WIRE_GBPS")
        pg2 = ProcessGroupTCP(timeout=5.0)
        assert pg2._bucket is None
