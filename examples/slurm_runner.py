"""SLURM adapter: submit one job per replica group and keep them alive.

Analog of the reference's torchtitan-on-SLURM runner
(reference: torchft/examples/slurm/runner.py:16-100): each replica group is
its own SLURM job carrying the ``REPLICA_GROUP_ID`` / ``NUM_REPLICA_GROUPS``
/ ``TORCHFT_LIGHTHOUSE`` env, so the cluster scheduler can preempt or kill
any one group while the rest keep training; this runner resubmits dead
jobs, and the quorum protocol heals them back in.

Dry-run (no SLURM needed) prints the exact sbatch command lines:

    python examples/slurm_runner.py --replicas 4 --dry-run -- \
        python examples/train_diloco.py --steps 10000

On a real cluster, point TORCHFT_LIGHTHOUSE at a lighthouse reachable from
the compute nodes (`python -m torchft_tpu.lighthouse --bind :29510`).
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchft_tpu.launcher import replica_app_spec


def sbatch_lines(spec, partition: str, tpus_per_group: int) -> list:
    """One `sbatch --wrap` command per replica-group role."""
    lines = []
    for role in spec["roles"]:
        env = " ".join(f"{k}={shlex.quote(v)}" for k, v in role["env"].items())
        cmd = " ".join(shlex.quote(a) for a in [role["entrypoint"], *role["args"]])
        lines.append(
            f"sbatch --job-name={role['name']} --partition={partition} "
            f"--gres=tpu:{tpus_per_group} --wrap={shlex.quote(f'{env} {cmd}')}"
        )
    return lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--partition", default="tpu")
    p.add_argument("--tpus-per-group", type=int, default=8)
    p.add_argument("--max-restarts", type=int, default=10)
    p.add_argument("--resubmit-interval", type=float, default=30.0)
    p.add_argument("--dry-run", action="store_true")
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        p.error("no command; usage: ... -- python train.py [args]")

    # strip a leading interpreter: roles always launch via sys.executable
    if os.path.basename(cmd[0]).startswith("python"):
        if len(cmd) < 2:
            p.error("interpreter given without a script")
        script, script_args = cmd[1], cmd[2:]
    else:
        script, script_args = cmd[0], cmd[1:]

    spec = replica_app_spec(
        *script_args, replicas=args.replicas, max_restarts=args.max_restarts,
        script=script,
    )
    lines = sbatch_lines(spec, args.partition, args.tpus_per_group)

    if args.dry_run:
        for line in lines:
            print(line)
        return 0

    # submit + babysit: resubmit any group whose job left the queue
    restarts = {i: 0 for i in range(args.replicas)}
    jobs = {}
    for i, line in enumerate(lines):
        out = subprocess.run(line, shell=True, capture_output=True, text=True, check=True)
        jobs[i] = out.stdout.strip().split()[-1]
        print(f"replica_group {i} -> job {jobs[i]}")

    while jobs:
        time.sleep(args.resubmit_interval)
        probe = subprocess.run(
            ["squeue", "-h", "-o", "%i"], capture_output=True, text=True
        )
        if probe.returncode != 0:
            # a flaky slurmctld must not look like "all jobs dead" — that
            # would mass-resubmit duplicates into the same quorum
            print(f"squeue failed ({probe.returncode}); skipping sweep")
            continue
        q = probe.stdout.split()
        for i, jid in list(jobs.items()):
            if jid in q:
                continue
            if restarts[i] >= args.max_restarts:
                print(f"replica_group {i} exhausted restarts; leaving down")
                del jobs[i]
                continue
            restarts[i] += 1
            out = subprocess.run(
                lines[i], shell=True, capture_output=True, text=True, check=True
            )
            jobs[i] = out.stdout.strip().split()[-1]
            print(f"replica_group {i} resubmitted -> job {jobs[i]} "
                  f"({restarts[i]}/{args.max_restarts})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
