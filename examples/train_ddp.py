"""Fault-tolerant DDP training example (reference: train_ddp.py:104-213).

One process = one replica group (TPU slice or CPU worker). Point every
replica at the same Lighthouse and they form an elastic quorum: kill any
replica mid-run and the rest keep training; restart it and it live-heals
its weights from a healthy peer — no full-job restart.

Single-machine demo (threads-as-replicas + in-process Lighthouse):

    python examples/train_ddp.py --local-replicas 2 --steps 50

Note: kill-based chaos testing (dashboard kill button, punisher.py) needs
the one-process-per-replica deployment below — a kill RPC exits the whole
process, so in demo mode it would take down every thread-replica at once.

Real deployment (one process per slice):

    TORCHFT_LIGHTHOUSE=host:port REPLICA_GROUP_ID=0 python examples/train_ddp.py
    TORCHFT_LIGHTHOUSE=host:port REPLICA_GROUP_ID=1 python examples/train_ddp.py

The model is the reference's CIFAR-shaped CNN on synthetic data (this
image has no dataset egress); swap in a real dataloader + the
DistributedSampler shard for production.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=100, help="committed steps to train")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--sync-quorum", action="store_true",
                   help="synchronous quorum (default overlaps with forward)")
    p.add_argument("--local-replicas", type=int, default=0,
                   help="demo mode: run N replica-group threads + a local Lighthouse")
    p.add_argument("--cpu", action="store_true", help="force the CPU backend")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax profiler trace here (Perfetto-compatible)")
    p.add_argument("--save-dir", default=None,
                   help="write durable checkpoints here (cold-start resume)")
    p.add_argument("--save-every", type=int, default=10,
                   help="checkpoint every N committed steps")
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in --save-dir")
    return p.parse_args(argv)


def train(replica_id: str, lighthouse_addr: str, args, log=print) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchft_tpu as ft
    from torchft_tpu.models import cnn

    params = cnn.init_params(jax.random.PRNGKey(0))
    state = {"params": params, "opt_state": None}

    manager = ft.Manager(
        pg=ft.ProcessGroupTCP(timeout=30.0),
        min_replica_size=args.min_replicas,
        load_state_dict=lambda sd: state.update(sd),
        state_dict=lambda: {"params": state["params"],
                            "opt_state": state["opt_state"]},
        replica_id=replica_id,
        lighthouse_addr=lighthouse_addr,
        group_rank=0,
        group_world_size=1,
        use_async_quorum=not args.sync_quorum,
        timeout=30.0,
    )
    ddp = ft.DistributedDataParallel(manager)
    optimizer = ft.Optimizer(manager, optax.adamw(args.lr))
    state["opt_state"] = optimizer.init(params)

    # Durable resume (total-failure case: no live peer to heal from).
    # Restores user state AND the torchft step so the quorum resumes from
    # the checkpointed step (reference: train_ddp.py:201-208).
    if args.resume and args.save_dir:
        from torchft_tpu.checkpointing import latest_checkpoint, load_checkpoint

        path = latest_checkpoint(args.save_dir)
        if path is not None:
            ckpt = load_checkpoint(path)
            state.update(ckpt["user"])
            manager.load_state_dict(ckpt["torchft"])
            log(f"[{replica_id}] resumed from {path} "
                f"at step {manager.current_step()}")

    def loss_fn(params, images, labels):
        logits = cnn.forward(params, images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    rng = np.random.default_rng(hash(replica_id) % 2**31)

    try:
        while manager.current_step() < args.steps:
            # synthetic CIFAR-shaped batch; each replica sees its own data
            images = jnp.asarray(
                rng.standard_normal((args.batch_size, 32, 32, 3), dtype=np.float32)
            )
            labels = jnp.asarray(rng.integers(0, 10, args.batch_size))

            # must be called at the start of each step: triggers the quorum
            # (overlapped with forward unless --sync-quorum)
            optimizer.begin_step()

            loss, grads = grad_fn(state["params"], images, labels)
            # gradient averaging over the live quorum (zero-contribution
            # participation: membership changes never change compiled shapes)
            avg_grads = ddp.allreduce_gradients(grads).wait(timeout=30)

            # applies the update only if the group votes to commit
            state["params"], state["opt_state"], committed = optimizer.step(
                state["params"], avg_grads, state["opt_state"]
            )
            if committed and manager.current_step() % 10 == 0:
                log(f"[{replica_id} step {manager.current_step()}] "
                    f"loss={float(loss):.4f} "
                    f"participants={manager.num_participants()}")
            if (
                committed
                and args.save_dir
                and manager.current_step() % args.save_every == 0
                and manager.participating_rank() == 0
            ):
                # single-writer: the participating-rank-0 replica saves the
                # composite {user, torchft} dict (others would write the
                # same bytes)
                from torchft_tpu.checkpointing import save_checkpoint

                path = save_checkpoint(
                    args.save_dir,
                    manager.current_step(),
                    {
                        "user": {"params": state["params"],
                                 "opt_state": state["opt_state"]},
                        "torchft": manager.state_dict(),
                    },
                )
                log(f"[{replica_id}] saved checkpoint {path}")
        return {"params": state["params"], "step": manager.current_step()}
    finally:
        manager.shutdown()


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import jax

    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)

    try:
        if args.local_replicas:
            from _demo import run_demo

            rc = run_demo(
                train, args.local_replicas, min_replicas=args.min_replicas,
                replica_prefix="train_ddp", extra_args=(args,),
            )
        else:
            from _demo import resolve_lighthouse

            replica_id = f"train_ddp_{os.environ.get('REPLICA_GROUP_ID', 0)}"
            result = train(replica_id, resolve_lighthouse(), args)
            print(f"done: {result['step']} committed steps")
            rc = 0
    finally:
        if args.profile_dir:
            jax.profiler.stop_trace()
            print(f"profiler trace written to {args.profile_dir}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
