"""Actor-style fault-tolerant trainer: supervision trees over the FT stack.

Analog of the reference's Monarch example
(reference: examples/monarch/train_distributed.py): the job is a tree of
actors — a LighthouseActor owning the quorum server, one TrainerActor per
replica group running the real Manager/DDP stack, and a FailureActor
injecting chaos — and a supervisor that restarts dead trainers without
touching the rest of the job (the quorum heals them back in).

Monarch provides proc meshes and typed endpoints; this demo keeps the same
shape with stdlib primitives (threads as actors, queues as mailboxes) so it
runs anywhere. On a real cluster each actor maps to a process/slice via
torchft_tpu.launcher / slurm_runner.

    python examples/actor_trainer.py --replicas 2 --steps 20 --chaos
"""

from __future__ import annotations

import argparse
import os
import queue
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


# ---------------------------------------------------------------------------
# minimal actor runtime (threads + mailboxes)
# ---------------------------------------------------------------------------


@dataclass
class _Call:
    method: str
    args: tuple
    reply: "queue.Queue"


class Actor:
    """A thread with a mailbox; ``endpoint`` methods run in actor context."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._inbox: "queue.Queue[Optional[_Call]]" = queue.Queue()
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    def call(self, method: str, *args: Any, timeout: float = 120.0) -> Any:
        reply: "queue.Queue" = queue.Queue()
        self._inbox.put(_Call(method, args, reply))
        ok, value = reply.get(timeout=timeout)
        if not ok:
            raise value
        return value

    def stop(self) -> None:
        self._inbox.put(None)
        self._thread.join(timeout=30)

    def _loop(self) -> None:
        while True:
            call = self._inbox.get()
            if call is None:
                return
            try:
                call.reply.put((True, getattr(self, call.method)(*call.args)))
            except Exception as e:  # noqa: BLE001 - shipped to caller
                call.reply.put((False, e))


# ---------------------------------------------------------------------------
# actors
# ---------------------------------------------------------------------------


class LighthouseActor(Actor):
    def start_lighthouse(self, min_replicas: int = 1) -> str:
        from torchft_tpu.coordination import LighthouseServer

        self._lighthouse = LighthouseServer(
            min_replicas=min_replicas, join_timeout_ms=10000
        )
        return self._lighthouse.address()

    def shutdown(self) -> None:
        self._lighthouse.shutdown()


class _InjectedCrash(RuntimeError):
    """Raised mid-step by kill(): the step dies uncommitted."""


class TrainerActor(Actor):
    """One replica group: real Manager + FT-DDP loop on a tiny MLP."""

    def start_training(
        self, replica_id: str, lighthouse: str, steps: int, step_time: float = 0.0
    ) -> None:
        self._stop = threading.Event()
        self._result: "Dict[str, Any]" = {}
        self._worker = threading.Thread(
            target=self._train,
            args=(replica_id, lighthouse, steps, step_time),
            daemon=True,
        )
        self._worker.start()

    def _train(
        self, replica_id: str, lighthouse: str, steps: int, step_time: float
    ) -> None:
        import optax

        import torchft_tpu as ft

        state = {"w": np.zeros(1024, np.float32)}
        manager = ft.Manager(
            pg=ft.ProcessGroupTCP(timeout=20.0),
            min_replica_size=1,
            lighthouse_addr=lighthouse,
            replica_id=replica_id,
            group_rank=0,
            group_world_size=1,
            use_async_quorum=False,
            timeout=20.0,
            load_state_dict=lambda sd: state.update(
                {k: np.array(v) for k, v in sd.items()}
            ),
            state_dict=lambda: dict(state),
        )
        optimizer = ft.Optimizer(manager, optax.sgd(0.1))
        opt_state = optimizer.init(state)
        try:
            while manager.current_step() < steps:
                if step_time:
                    time.sleep(step_time)  # simulated compute, keeps the demo's
                    # chaos window open
                optimizer.begin_step()
                grads = {"w": np.ones_like(state["w"])}
                averaged = manager.allreduce(grads).wait(timeout=20)
                if self._stop.is_set():
                    # die mid-step, AFTER the collective and BEFORE the
                    # commit vote — the step aborts uncommitted, like a
                    # crash would leave it
                    raise _InjectedCrash("chaos kill")
                new_state, opt_state, committed = optimizer.step(
                    state, averaged, opt_state
                )
                if committed:
                    state = {k: np.asarray(v) for k, v in new_state.items()}
            self._result = {"w": state["w"].copy(), "step": manager.current_step()}
        except _InjectedCrash:
            self._result = {"step": manager.current_step()}
        finally:
            # thread-actor constraint: the manager must be shut down here or
            # its server/heartbeat threads would leak into the shared
            # process. True kill -9 chaos (no teardown at all) lives in the
            # process-isolated paths: launcher.kill_replica, punisher.py,
            # and bench.py.
            manager.shutdown()

    def status(self) -> "Dict[str, Any]":
        alive = self._worker.is_alive()
        return {"alive": alive, **({} if alive else self._result)}

    def kill(self) -> None:
        """Crash the trainer mid-step: the in-flight step aborts without a
        commit vote (see the _InjectedCrash raise in _train)."""
        self._stop.set()

    def join(self, timeout: float = 120.0) -> "Dict[str, Any]":
        self._worker.join(timeout=timeout)
        return dict(self._result)


class FailureActor(Actor):
    """Chaos: periodically kills one trainer via the supervisor."""

    def start_chaos(self, supervisor: "Supervisor", period: float) -> None:
        self._chaos = threading.Thread(
            target=self._loop_chaos, args=(supervisor, period), daemon=True
        )
        self._chaos.start()

    def _loop_chaos(self, supervisor: "Supervisor", period: float) -> None:
        rng = np.random.default_rng(0)
        time.sleep(period)
        victim = int(rng.integers(supervisor.replicas))
        print(f"[chaos] killing trainer {victim}", flush=True)
        supervisor.kill_trainer(victim)


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


class Supervisor:
    """Restarts dead trainers; the quorum absorbs the membership churn."""

    def __init__(
        self, replicas: int, steps: int, chaos: bool, step_time: float = 0.0
    ) -> None:
        self.replicas = replicas
        self.steps = steps
        self.step_time = step_time
        self.lighthouse = LighthouseActor("lighthouse")
        self.addr = self.lighthouse.call("start_lighthouse")
        self.trainers: "Dict[int, TrainerActor]" = {}
        self.restarts: "Dict[int, int]" = {i: 0 for i in range(replicas)}
        for i in range(replicas):
            self._spawn(i)
        if chaos:
            self.failure = FailureActor("failure")
            self.failure.call("start_chaos", self, 3.0)

    def _spawn(self, i: int) -> None:
        actor = TrainerActor(f"trainer_{i}")
        attempt = self.restarts[i]
        actor.call(
            "start_training",
            f"actor_{i}:a{attempt}",
            self.addr,
            self.steps,
            self.step_time,
        )
        self.trainers[i] = actor

    def kill_trainer(self, i: int) -> None:
        self.trainers[i].call("kill")

    def run(self) -> "Dict[int, Dict[str, Any]]":
        results: "Dict[int, Dict[str, Any]]" = {}
        while len(results) < self.replicas:
            time.sleep(0.5)
            for i, actor in list(self.trainers.items()):
                if i in results:
                    continue
                status = actor.call("status")
                if status["alive"]:
                    continue
                if status.get("step", 0) >= self.steps:
                    results[i] = actor.call("join")
                elif self.restarts[i] < 3:
                    self.restarts[i] += 1
                    print(
                        f"[supervisor] trainer {i} died at step "
                        f"{status.get('step', '?')}; restart "
                        f"{self.restarts[i]}", flush=True,
                    )
                    actor.stop()
                    self._spawn(i)
                else:
                    raise RuntimeError(f"trainer {i} exhausted restarts")
        return results

    def shutdown(self) -> None:
        for actor in self.trainers.values():
            actor.stop()
        self.lighthouse.call("shutdown")
        self.lighthouse.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--chaos", action="store_true")
    p.add_argument("--step-time", type=float, default=0.0,
                   help="simulated per-step compute seconds (keeps the chaos\n"
                        "window open in short demos)")
    args = p.parse_args(argv)

    if args.chaos and args.step_time == 0.0:
        args.step_time = 0.3
    sup = Supervisor(args.replicas, args.steps, args.chaos, args.step_time)
    try:
        results = sup.run()
    finally:
        sup.shutdown()

    ws = [r["w"] for r in results.values()]
    for w in ws[1:]:
        np.testing.assert_array_equal(ws[0], w)
    print(
        f"done: {len(results)} replicas at step {args.steps}, "
        f"weights converged bitwise, restarts={sup.restarts}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
