"""Streaming DiLoCo training example (reference: train_diloco.py:76-238).

Communication-reducing semi-sync data parallelism: each replica group
trains locally for ``--sync-every`` inner steps; parameter fragments are
synchronized round-robin with pseudogradient allreduces overlapped with
compute (``--fragment-sync-delay``), an outer Nesterov-SGD step applied on
commit.  Ideal when replica groups are connected by slow DCN (multi-slice,
multi-region).

Single-machine demo (kill-based chaos testing needs the one-process-per-
replica deployment below; a kill RPC exits the whole process):

    python examples/train_diloco.py --local-replicas 2 --steps 40

Real deployment (one process per slice):

    TORCHFT_LIGHTHOUSE=host:port REPLICA_GROUP_ID=0 python examples/train_diloco.py

Model: MLP fragments (the reference splits an MLP with torch pipelining
SplitPoints; here fragments are pytree key partitions — see
torchft_tpu/local_sgd.py).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=80, help="inner steps to run")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--inner-lr", type=float, default=4e-4)
    p.add_argument("--outer-lr", type=float, default=0.7)
    p.add_argument("--sync-every", type=int, default=20,
                   help="inner steps per full sync round (reference default)")
    p.add_argument("--fragment-sync-delay", type=int, default=1,
                   help="steps between kicking off a fragment allreduce and "
                        "blocking on it")
    p.add_argument("--n-fragments", type=int, default=2)
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--local-replicas", type=int, default=0)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--wire-gbps", type=float, default=None,
                   help="shape the DCN egress to this rate (decimal GB/s, "
                        "token bucket) — demo/validate DiLoCo under a real "
                        "bandwidth constraint; also settable via "
                        "TORCHFT_WIRE_GBPS")
    p.add_argument("--quantize", action="store_true",
                   help="int8-quantize the outer pseudogradient sync "
                        "(TORCHFT_QUANT_WIRE selects int8/fp8_e4m3)")
    return p.parse_args(argv)


def train(replica_id: str, lighthouse_addr: str, args, log=print) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchft_tpu as ft
    from torchft_tpu.models import mlp

    params = mlp.init_params(jax.random.PRNGKey(0), sizes=(64, 128, 128, 128, 10))
    state = {"params": params}

    manager = ft.Manager(
        # --wire-gbps: token-bucket egress shaping (None = unshaped or the
        # TORCHFT_WIRE_GBPS env default) — lets this demo show DiLoCo's
        # sync-every-N advantage under a real DCN bandwidth constraint
        pg=ft.ProcessGroupTCP(timeout=30.0, bandwidth_gbps=args.wire_gbps),
        min_replica_size=args.min_replicas,
        replica_id=replica_id,
        lighthouse_addr=lighthouse_addr,
        group_rank=0,
        group_world_size=1,
        use_async_quorum=False,  # DiLoCo requires a synchronous quorum
        timeout=30.0,
    )

    # fragments = contiguous layer partitions (the reference's
    # pipeline-split analog, mlp.fragment_keys)
    fragments = mlp.fragment_keys(params, args.n_fragments)

    def get_params():
        return dict(state["params"])

    def set_params(flat):
        state["params"] = {**state["params"], **flat}

    inner_opt = optax.adamw(args.inner_lr)
    opt_state = inner_opt.init(params)
    outer_opt = optax.sgd(args.outer_lr, momentum=0.9, nesterov=True)

    def loss_fn(params, x, y):
        return optax.softmax_cross_entropy_with_integer_labels(
            mlp.forward(params, x), y
        ).mean()

    @jax.jit
    def inner_step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = inner_opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(hash(replica_id) % 2**31)
    try:
        with ft.DiLoCo(
            manager,
            fragments,
            get_params,
            set_params,
            outer_opt,
            sync_every=args.sync_every,
            fragment_sync_delay=args.fragment_sync_delay,
            should_quantize=args.quantize,
        ) as diloco:
            for i in range(args.steps):
                x = jnp.asarray(
                    rng.standard_normal((args.batch_size, 64), dtype=np.float32)
                )
                y = jnp.asarray(rng.integers(0, 10, args.batch_size))
                state["params"], opt_state, loss = inner_step(
                    state["params"], opt_state, x, y
                )
                diloco.step()  # counts inner steps; syncs on its schedule
                if i % 10 == 0:
                    log(f"[{replica_id} inner {i} outer "
                        f"{manager.current_step()}] loss={float(loss):.4f}")
        return {"params": state["params"], "outer_steps": manager.current_step()}
    finally:
        manager.shutdown()


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    if args.local_replicas:
        from _demo import run_demo

        return run_demo(
            train, args.local_replicas, min_replicas=args.min_replicas,
            replica_prefix="train_diloco", extra_args=(args,),
        )
    from _demo import resolve_lighthouse

    replica_id = f"train_diloco_{os.environ.get('REPLICA_GROUP_ID', 0)}"
    result = train(replica_id, resolve_lighthouse(), args)
    print(f"done: {result['outer_steps']} outer steps committed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
