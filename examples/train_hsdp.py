"""Fault-tolerant HSDP: inner fsdp/tp sharding x elastic replica groups.

The HSDP composition (reference: torchft README "HSDP" + fsdp_test.py):
each replica group owns a TPU slice and shards the model over its ICI mesh
(fsdp/tp via pjit); the replica dimension across slices is elastic — grads
are averaged through the Manager on host buffers, so slices can die and
rejoin at step granularity while inner sharding stays compiled-once.

Single-machine demo (2 replica-group threads x 4 virtual CPU devices each):

    python examples/train_hsdp.py --local-replicas 2 --steps 20

Real deployment: one process per slice, TORCHFT_LIGHTHOUSE set, and the
inner mesh built over the slice's own devices (jax.local_devices()).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--local-replicas", type=int, default=0,
                   help="demo mode: N replica-group threads + local lighthouse "
                        "(forces the virtual CPU backend)")
    return p.parse_args(argv)


def train(replica_id: str, lighthouse_addr: str, devices, args, log=print) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchft_tpu as ft
    from torchft_tpu.models import transformer as tfm
    from torchft_tpu.parallel.device_mesh import ft_init_device_mesh

    cfg = tfm.TransformerConfig(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=4, d_ff=128,
        n_layers=2, max_seq_len=32, dtype=jnp.float32,
    )
    state = {}

    manager = ft.Manager(
        pg=ft.ProcessGroupTCP(timeout=30.0),
        min_replica_size=args.min_replicas,
        lighthouse_addr=lighthouse_addr,
        replica_id=replica_id,
        group_rank=0,
        group_world_size=1,
        use_async_quorum=False,
        timeout=30.0,
        load_state_dict=lambda sd: state.update(sd),
        state_dict=lambda: {
            "params": jax.tree_util.tree_map(np.asarray, state["params"]),
            "opt_state": jax.tree_util.tree_map(np.asarray, state["opt_state"]),
        },
    )
    try:
        fmesh = ft_init_device_mesh(
            manager, {"fsdp": args.fsdp, "tp": args.tp}, devices=devices
        )
        mesh = fmesh.mesh
        params = tfm.shard_params(
            tfm.init_params(jax.random.PRNGKey(0), cfg), mesh, cfg
        )
        optimizer = ft.Optimizer(manager, optax.adamw(args.lr))
        state["params"] = params
        state["opt_state"] = optimizer.init(params)
        pspecs = tfm.param_specs(cfg, mesh)

        grad_fn = jax.jit(
            lambda p, t: jax.value_and_grad(tfm.loss_fn)(p, t, cfg, mesh=mesh)
        )
        rng = np.random.default_rng(hash(replica_id) % 2**31)

        def reshard_if_healed():
            # a heal delivers host numpy arrays via load_state_dict; they
            # must go back onto the inner mesh BEFORE the jitted grad_fn
            # touches them (else: recompile + fully-replicated weights).
            # Steady-state steps skip the device_put entirely.
            leaves = jax.tree_util.tree_leaves(state["params"])
            if leaves and not isinstance(leaves[0], jax.Array):
                state["params"] = jax.tree_util.tree_map(
                    lambda x, s: jax.device_put(
                        jnp.asarray(x), jax.sharding.NamedSharding(mesh, s)
                    ),
                    state["params"], pspecs,
                )
                state["opt_state"] = jax.tree_util.tree_map(
                    jnp.asarray, state["opt_state"]
                )

        while manager.current_step() < args.steps:
            optimizer.begin_step()  # starts the quorum (sync: heal lands here)
            reshard_if_healed()
            # per-replica batch shape stays FIXED under elastic membership
            # (WorldSizeMode.DYNAMIC semantics): zero-fill + divide-by-live
            # -count absorbs joins/failures without any re-jit
            tokens = jnp.asarray(
                rng.integers(
                    0, cfg.vocab_size, (args.batch_size, cfg.max_seq_len)
                ),
                jnp.int32,
            )
            loss, grads = grad_fn(state["params"], tokens)
            avg = manager.allreduce(
                jax.tree_util.tree_map(np.asarray, grads)
            ).wait(timeout=30)
            new_params, new_opt, committed = optimizer.step(
                state["params"],
                jax.tree_util.tree_map(jnp.asarray, avg),
                state["opt_state"],
            )
            if committed:
                state["params"] = new_params
                state["opt_state"] = new_opt
                step = manager.current_step()
                if step % 5 == 0:
                    log(f"[{replica_id} step {step}] loss={float(loss):.4f} "
                        f"participants={manager.num_participants()}")
        log(f"done: {manager.current_step()} committed steps")
        return {"step": manager.current_step()}
    finally:
        manager.shutdown()


def main(argv=None) -> int:
    args = parse_args(argv)
    import jax

    if args.local_replicas:
        per = args.fsdp * args.tp
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", per * args.local_replicas)
        from _demo import run_demo

        return run_demo(
            train, args.local_replicas, min_replicas=args.min_replicas,
            replica_prefix="hsdp", devices_per_replica=per,
            extra_args=(args,),
        )
    from _demo import resolve_lighthouse

    replica_id = f"hsdp_{os.environ.get('REPLICA_GROUP_ID', 0)}"
    train(replica_id, resolve_lighthouse(), jax.local_devices(), args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
