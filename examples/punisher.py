"""Chaos CLI: kill replicas through the Lighthouse to exercise recovery
(reference: torchft/examples/slurm/punisher.py:15-46).

The reference cancels SLURM jobs through torchx; here replicas are killed
through the Lighthouse's own kill endpoint (``POST /replica/{id}/kill``,
forwarded as a ManagerService.Kill RPC — same path as the dashboard's kill
button), which works for any deployment the Lighthouse can reach.

    python examples/punisher.py --lighthouse host:port kill-one
    python examples/punisher.py --lighthouse host:port kill-all
    python examples/punisher.py --lighthouse host:port kill-loop \
        --num-failures 5 --mtbf-secs 30
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
import urllib.request


def list_replicas(lighthouse: str, max_age_ms: int = 5000) -> "list[str]":
    """Replica ids with a live heartbeat (restarted replicas re-register
    under a fresh uuid suffix, so stale ids must be filtered by age)."""
    with urllib.request.urlopen(
        f"http://{lighthouse}/status.json", timeout=10
    ) as resp:
        status = json.load(resp)
    return [
        m["replica_id"]
        for m in status.get("heartbeats", [])
        if m.get("age_ms", 0) < max_age_ms
    ]


def kill(lighthouse: str, replica_id: str) -> None:
    req = urllib.request.Request(
        f"http://{lighthouse}/replica/{replica_id}/kill", method="POST"
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        print(f"killed {replica_id}: {resp.read().decode().strip()}")


def kill_one(lighthouse: str, spare_first: bool = True) -> None:
    replicas = list_replicas(lighthouse)
    # keep replica 0 alive by convention (reference spares "ft_0") so the
    # job always has a healthy recovery source
    candidates = [r for r in replicas if not spare_first or not r.startswith(
        ("replica_0", "train_ddp_0", "train_diloco_0"))]
    if not candidates:
        sys.exit(f"no killable replicas (live: {replicas})")
    choice = random.choice(candidates)
    print(f"killing {choice!r} of {candidates}")
    kill(lighthouse, choice)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--lighthouse", required=True, help="host:port")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list")
    sub.add_parser("kill-one")
    sub.add_parser("kill-all")
    loop = sub.add_parser("kill-loop")
    loop.add_argument("--num-failures", type=int, default=3)
    loop.add_argument("--mtbf-secs", type=float, default=30.0)
    args = p.parse_args(argv)

    if args.cmd == "list":
        for r in list_replicas(args.lighthouse):
            print(r)
    elif args.cmd == "kill-one":
        kill_one(args.lighthouse)
    elif args.cmd == "kill-all":
        for r in list_replicas(args.lighthouse):
            kill(args.lighthouse, r)
    elif args.cmd == "kill-loop":
        for _ in range(args.num_failures):
            kill_one(args.lighthouse)
            dur = random.random() * (2 * args.mtbf_secs)
            print(f"sleeping {dur:.1f}s (mtbf {args.mtbf_secs}s)")
            time.sleep(dur)


if __name__ == "__main__":
    main()
