"""Multi-host replica groups: FT-DDP across groups, jit mesh within each.

The deployment shape of a real multi-host pod (reference wiring:
torchft/manager.py:277-325 store handoff, torchft/fsdp_test.py:96-120
spawned workers):

- each replica GROUP is ``--procs-per-group`` real OS processes forming one
  jax multi-controller runtime (``jax.distributed.initialize``) — the inner
  data-parallel mean runs as a compiled XLA collective over the group's
  global mesh;
- each process runs one ``Manager`` with ``group_rank = process id``,
  sharing the group's store: rank 0 hosts the ManagerServer, other ranks
  discover it through the store handoff; quorum and commit votes aggregate
  across ranks inside the group's server;
- ACROSS groups, same-rank peers form the elastic ``ProcessGroupTCP`` ring
  that averages gradients — groups can die and rejoin without recompiling
  anything.

Self-launching demo (spawns groups x procs real processes on CPU):

    python examples/train_multihost.py --groups 2 --procs-per-group 2 --steps 4

Streaming DiLoCo across the groups (the BASELINE north-star config),
with optional whole-group kill+rejoin chaos:

    python examples/train_multihost.py --groups 2 --procs-per-group 2 \
        --algo diloco --steps 6 --chaos --step-sleep 0.25

Real deployment: run one process per host with the env/flags below, a
shared Lighthouse, one store + one coordinator per group:

    python examples/train_multihost.py --worker \
        --group-id 0 --process-id $HOST_IDX --procs-per-group 4 \
        --coordinator host0:1234 --store-addr host0:2345 \
        --lighthouse host:port
"""

from __future__ import annotations

import argparse
import hashlib
import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--groups", type=int, default=2)
    p.add_argument("--procs-per-group", type=int, default=2)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--cpu-devices", type=int, default=2,
                   help="virtual CPU devices per process (test mode)")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--algo", choices=["ddp", "diloco"], default="ddp",
                   help="cross-group algorithm: per-step FT-DDP allreduce, "
                        "or Streaming DiLoCo outer syncs every --sync-every "
                        "inner steps (the BASELINE north-star config, over "
                        "real processes)")
    p.add_argument("--sync-every", type=int, default=4,
                   help="diloco: inner steps per outer sync")
    p.add_argument("--quantize", action="store_true",
                   help="int8-quantize the DiLoCo outer pseudograd sync "
                        "across groups (TORCHFT_QUANT_WIRE for fp8)")
    p.add_argument("--chaos", action="store_true",
                   help="kill one whole group's processes mid-run, restart "
                        "them, and require bitwise convergence after the "
                        "supersession rejoin + live heal")
    p.add_argument("--step-sleep", type=float, default=0.0,
                   help="pacing sleep per training step (gives the chaos "
                        "restart a window to overlap the survivors' run)")
    # worker mode (spawned by the launcher above, or run per-host manually)
    p.add_argument("--worker", action="store_true")
    p.add_argument("--group-id", type=int, default=0)
    p.add_argument("--process-id", type=int, default=0)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--store-addr", default=None)
    p.add_argument("--lighthouse", default=None)
    return p.parse_args(argv)


def worker(args) -> int:
    from torchft_tpu.parallel.multihost import (
        host_sharded_array,
        initialize_multihost,
    )

    initialize_multihost(
        coordinator_address=args.coordinator,
        num_processes=args.procs_per_group,
        process_id=args.process_id,
        platform="cpu",
        cpu_devices_per_process=args.cpu_devices,
    )

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import torchft_tpu as ft

    gid, pid = args.group_id, args.process_id
    tag = f"g{gid}p{pid}"

    # ---- inner parallelism: one global mesh over the whole group --------
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    repl = NamedSharding(mesh, P())
    batched = NamedSharding(mesh, P("dp"))

    dim, batch = 8, 4 * len(jax.devices())
    params = {"w": jnp.zeros((dim,), jnp.float32)}
    state = {"params": params}

    # ---- FT layer: one Manager per process, group store shared ---------
    manager = ft.Manager(
        pg=ft.ProcessGroupTCP(timeout=20.0),
        min_replica_size=args.min_replicas,
        load_state_dict=lambda sd: state.update(params=sd["params"]),
        state_dict=lambda: {"params": state["params"]},
        lighthouse_addr=args.lighthouse,
        replica_id=f"mh_group_{gid}",
        group_rank=pid,
        group_world_size=args.procs_per_group,
        store_addr=args.store_addr,
        # DiLoCo requires the synchronous quorum (heal applies eagerly
        # before the inner loop resumes)
        use_async_quorum=args.algo != "diloco",
        timeout=20.0,
        quorum_timeout=20.0,
        init_sync=False,
    )

    def _grad_step(params, xs, ys):
        def loss_fn(p):
            pred = xs @ p["w"]
            return jnp.mean((pred - ys) ** 2)

        return jax.value_and_grad(loss_fn)(params)

    grad_step = jax.jit(
        _grad_step,
        in_shardings=(repl, batched, batched),
        out_shardings=(None, repl),
    )

    import time

    rng = np.random.default_rng(1000 + gid)  # same data on every group rank
    first_commit = None

    def make_batch():
        xs_np = rng.standard_normal((batch, dim)).astype(np.float32)
        ys_np = xs_np @ np.arange(dim, dtype=np.float32)
        # every process contributes only its addressable shards of the
        # group-global batch
        xs = host_sharded_array((batch, dim), batched, lambda idx: xs_np[idx])
        ys = host_sharded_array((batch,), batched, lambda idx: ys_np[idx])
        return xs, ys

    def note_commit():
        # a healed rejoiner's first commit lands at the survivors' step,
        # not 0 — the chaos launcher asserts this to prove the live heal
        # actually ran.  Read the step from the manager (post-commit,
        # minus one): healing updates current_step inside start_quorum.
        nonlocal first_commit
        if first_commit is None:
            first_commit = manager.current_step() - 1

    try:
        if args.algo == "diloco":
            loss = _diloco_loop(
                args, manager, state, grad_step, make_batch, note_commit,
            )
        else:
            while manager.current_step() < args.steps:
                if args.step_sleep:
                    time.sleep(args.step_sleep)
                xs, ys = make_batch()
                manager.start_quorum()
                # loss/grads: dp-mean over the group's mesh (compiled XLA
                # collective spanning the group's processes)
                loss, grads = grad_step(state["params"], xs, ys)
                # cross-group: elastic FT ring between same-rank peers
                avg = manager.allreduce({"w": np.asarray(grads["w"])}).wait(
                    timeout=30
                )
                if manager.should_commit():
                    note_commit()
                    state["params"] = {
                        "w": state["params"]["w"] - 0.1 * jnp.asarray(avg["w"])
                    }
        digest = hashlib.sha256(
            np.asarray(state["params"]["w"]).tobytes()
        ).hexdigest()[:16]
        print(f"[{tag}] done step={manager.current_step()} "
              f"first_commit={first_commit} "
              f"loss={float(loss):.5f} params_sha={digest}", flush=True)
        return 0
    finally:
        manager.shutdown()
        jax.distributed.shutdown()


def _diloco_loop(args, manager, state, grad_step, make_batch, note_commit):
    """Streaming DiLoCo across replica groups over REAL processes: inner
    steps train on the group's own data (dp-mean over the group mesh);
    every ``--sync-every`` inner steps the pseudogradients allreduce
    across groups and the outer Nesterov step applies.  ``--steps`` counts
    OUTER syncs here; the loop exits right after a sync boundary, where
    params are bitwise-identical across groups by construction."""
    import time

    import jax.numpy as jnp

    import torchft_tpu as ft

    def get_params():
        return dict(state["params"])

    def set_params(flat):
        state["params"] = {**state["params"], **flat}

    import optax

    outer_opt = optax.sgd(0.7, momentum=0.9, nesterov=True)
    committed_before = manager.current_step()
    with ft.DiLoCo(
        manager,
        [["w"]],  # one fragment: the whole (tiny) model
        get_params,
        set_params,
        outer_opt,
        sync_every=args.sync_every,
        fragment_sync_delay=0,
        should_quantize=args.quantize,
    ) as diloco:
        while manager.current_step() < args.steps:
            if args.step_sleep:
                time.sleep(args.step_sleep)
            xs, ys = make_batch()
            loss, grads = grad_step(state["params"], xs, ys)
            # inner step: plain SGD on the group-mean gradient
            state["params"] = {
                "w": state["params"]["w"] - 0.05 * jnp.asarray(grads["w"])
            }
            # gate on batches_committed, NOT current_step: a heal jumps
            # current_step inside start_quorum even when that round's
            # commit vote fails, but batches_committed moves only on a
            # real commit — first_commit must prove a commit happened
            before = manager.batches_committed()
            diloco.step()  # counts inner steps; syncs on its schedule
            if manager.batches_committed() > before:
                note_commit()
    assert manager.current_step() > committed_before
    return loss


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def launch(args) -> int:
    """Spawn groups x procs real worker processes against one Lighthouse.

    ``--chaos``: mid-run, one whole group's processes are SIGKILLed (no
    shutdown, no leave RPC — the hard-failure shape) and respawned with a
    fresh jax.distributed coordinator; the new incarnation supersedes the
    dead one at the lighthouse, heals its state live from a surviving
    group, and the run must still end with every process bitwise-equal.
    Reference analog: restart semantics torchft/manager_integ_test.py:
    236-249 over real spawned workers (fsdp_test.py:96-120).
    """
    import time

    from torchft_tpu.coordination import LighthouseServer, StoreServer

    # quorum formation waits for every group — otherwise a fast-starting
    # group trains (and finishes) solo before the others join
    lighthouse = LighthouseServer(
        min_replicas=args.groups, join_timeout_ms=200
    )
    stores = [StoreServer() for _ in range(args.groups)]

    def spawn_group(g: int) -> "list[subprocess.Popen]":
        coord = f"127.0.0.1:{_free_port()}"
        group_procs = []
        for p in range(args.procs_per_group):
            cmd = [
                sys.executable, os.path.abspath(__file__), "--worker",
                "--group-id", str(g), "--process-id", str(p),
                "--procs-per-group", str(args.procs_per_group),
                "--cpu-devices", str(args.cpu_devices),
                "--steps", str(args.steps),
                "--min-replicas", str(args.min_replicas),
                "--algo", args.algo,
                "--sync-every", str(args.sync_every),
                "--step-sleep", str(args.step_sleep),
                "--coordinator", coord,
                "--store-addr", stores[g].address(),
                "--lighthouse", lighthouse.address(),
            ]
            if args.quantize:
                cmd.append("--quantize")
            group_procs.append(subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            ))
        return group_procs

    groups = [spawn_group(g) for g in range(args.groups)]
    killed_out = ""
    try:
        if args.chaos:
            victim = args.groups - 1
            # kill only after real progress: poll the lighthouse (quorum
            # members report their step) until every group has committed a
            # few steps, then hard-kill the victim group's processes
            # (SIGKILL: no Manager.shutdown, no store cleanup, heartbeats
            # just stop)
            from torchft_tpu.coordination import LighthouseClient

            lc = LighthouseClient(lighthouse.address())
            # member steps are per-step commits for ddp, OUTER syncs for
            # diloco — gate on fewer of the latter (each is sync_every
            # inner steps of real progress)
            gate = 2 if args.algo == "diloco" else 3
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                status = lc.status()
                members = (status.get("prev_quorum") or {}).get(
                    "participants", []
                )
                if members and min(m["step"] for m in members) >= gate:
                    break
                time.sleep(0.25)
            else:
                raise RuntimeError("no training progress before chaos kill")
            lc.close()
            for p in groups[victim]:
                p.kill()
            for p in groups[victim]:
                killed_out += p.communicate()[0] or ""
            print(f"[chaos] killed group {victim} "
                  f"({args.procs_per_group} processes)", flush=True)
            # respawn: new incarnation, fresh coordinator, same store
            groups[victim] = spawn_group(victim)
            print(f"[chaos] restarted group {victim}", flush=True)

        procs = [p for grp in groups for p in grp]
        outs = [p.communicate(timeout=240)[0] for p in procs]
        rc = max(p.returncode for p in procs)
        hashes = set()
        for out in outs:
            print(out, end="")
            for line in out.splitlines():
                if "params_sha=" in line:
                    hashes.add(line.rsplit("params_sha=", 1)[1].strip())
        if killed_out:
            print("[chaos] killed incarnation output:")
            print(killed_out, end="")
        if args.chaos and rc == 0:
            # prove the LIVE HEAL ran: the restarted incarnation's first
            # commit must land at the survivors' step, not replay from 0
            victim_firsts = []
            for p_ in groups[args.groups - 1]:
                i = procs.index(p_)
                for line in outs[i].splitlines():
                    if "first_commit=" in line:
                        val = line.split("first_commit=")[1].split()[0]
                        # "None" = the restarted worker healed straight to
                        # the final step and never committed — counts as
                        # heal-not-proven, not a launcher crash
                        victim_firsts.append(-1 if val == "None" else int(val))
            if not victim_firsts or min(victim_firsts) <= 0:
                print(f"ERROR: restarted group did not heal forward "
                      f"(first commits {victim_firsts}) — kill landed "
                      f"before any survivor commit, or heal was skipped")
                rc = 1
            else:
                print(f"[chaos] restarted group healed to step "
                      f"{min(victim_firsts)} before its first commit")
        if rc == 0 and len(hashes) == 1 and outs:
            n = args.groups * args.procs_per_group
            suffix = " after chaos kill+rejoin" if args.chaos else ""
            print(f"params converged bitwise across {n} processes "
                  f"({args.groups} groups x {args.procs_per_group} hosts)"
                  f"{suffix}")
        elif rc == 0:
            print(f"ERROR: divergent params across processes: {hashes}")
            rc = 1
        return rc
    finally:
        for grp in groups:
            for p in grp:
                if p.poll() is None:
                    p.kill()
        for s in stores:
            s.shutdown()
        lighthouse.shutdown()


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.worker:
        return worker(args)
    return launch(args)


if __name__ == "__main__":
    sys.exit(main())
