"""Shared single-machine demo scaffolding for the example trainers.

One process hosts a Lighthouse plus N replica-group threads — the demo
analog of one-process-per-slice deployment. Unlike bare daemon threads, a
replica whose train function raises is surfaced: the demo exits nonzero
with the traceback instead of silently reporting success.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional, Sequence


def run_demo(
    train: "Callable[..., Any]",
    n_replicas: int,
    min_replicas: int = 1,
    replica_prefix: str = "replica",
    devices_per_replica: "Optional[int]" = None,
    extra_args: "Sequence[Any]" = (),
    join_timeout_ms: int = 200,
) -> int:
    """Run ``train(replica_id, lighthouse_addr, [devices,] *extra_args)``
    on one thread per replica group against an in-process Lighthouse.

    ``devices_per_replica``: when set, each replica receives its disjoint
    slice of ``jax.devices()`` as the third argument (the HSDP pattern).
    Returns a process exit code (0 iff every replica finished cleanly).
    """
    from torchft_tpu.coordination import LighthouseServer

    lighthouse = LighthouseServer(
        min_replicas=min_replicas, join_timeout_ms=join_timeout_ms
    )
    print(f"lighthouse dashboard: http://{lighthouse.address()}/")
    try:
        if devices_per_replica is not None:
            import jax

            devices = jax.devices()

            def call(i: int) -> Any:
                dev = devices[
                    i * devices_per_replica : (i + 1) * devices_per_replica
                ]
                return train(
                    f"{replica_prefix}_{i}", lighthouse.address(), dev,
                    *extra_args,
                )
        else:
            def call(i: int) -> Any:
                return train(
                    f"{replica_prefix}_{i}", lighthouse.address(), *extra_args
                )

        failures = 0
        with ThreadPoolExecutor(max_workers=n_replicas) as ex:
            futures = [ex.submit(call, i) for i in range(n_replicas)]
            for i, f in enumerate(futures):
                try:
                    f.result()
                except Exception:  # noqa: BLE001 - surfaced to the operator
                    import traceback

                    traceback.print_exc()
                    print(f"replica {i} FAILED")
                    failures += 1
        return 1 if failures else 0
    finally:
        lighthouse.shutdown()


def resolve_lighthouse() -> str:
    """Deployment mode: the lighthouse address from the environment."""
    addr = os.environ.get("TORCHFT_LIGHTHOUSE")
    if not addr:
        raise SystemExit(
            "set TORCHFT_LIGHTHOUSE=host:port (or use --local-replicas N)"
        )
    return addr
