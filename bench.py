"""Headline benchmark: recovery-to-healthy-step latency after a replica kill.

The BASELINE.json north-star metric: a replica group dies mid-run and must
rejoin with ZERO full-job restart — the survivors keep training, the dead
replica restarts, heals its weights live from a healthy peer, and commits a
healthy step.  This run exercises the entire fault-tolerance stack end to
end on loopback:

  C++ Lighthouse (quorum recompute on membership change) -> C++ Manager
  servers -> quorum-keyed DCN collective reconfigure -> live checkpoint
  heal over the HTTP transport (16 MB state dict) -> zero-contribution
  allreduce -> commit vote.

Two replica groups train a DDP loop; replica 1 is killed at a fixed step;
latency = wall time from the kill to replica 1's next *committed* healthy
step (includes full Manager re-init, quorum join, heal transfer, one
training step, commit).

Prints ONE JSON line:
    {"metric": ..., "value": seconds, "unit": "s", "vs_baseline": r}
``vs_baseline`` = value / 1.0 — a 1-second recovery target we set for
ourselves (the reference publishes no numbers, BASELINE.md; its embedded
join_timeout default alone is 100 ms + 100 ms quorum tick).  Values < 1.0
beat the target; lower is better.  Steady-state throughput and heal
transfer details go to stderr.

Compute is host-side numpy on purpose: under the driver the one real TPU
chip sits behind a tunnel whose 7-17 MB/s host<->device link would make
any device-transfer benchmark a measurement of the tunnel, not the
framework (the driver compile-checks the TPU model path separately via
__graft_entry__).
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import ProcessGroupTCP

PARAM_SIZE = 4 * 1024 * 1024  # 4M fp32 = 16 MB state dict
TOTAL_STEPS = 30
KILL_AT_STEP = 10
KILL_REPLICA = 1


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class _Kill(Exception):
    pass


class Replica:
    def __init__(self, replica_id: int, lighthouse_addr: str, bench: "Bench"):
        self.replica_id = replica_id
        self.lighthouse_addr = lighthouse_addr
        self.bench = bench
        self.step_times: "List[float]" = []

    def run(self) -> dict:
        for attempt in range(3):
            try:
                return self._train(attempt)
            except _Kill:
                log(f"replica {self.replica_id}: killed at step {KILL_AT_STEP}, "
                    "restarting")
                continue
        raise RuntimeError("exhausted attempts")

    def _train(self, attempt: int) -> dict:
        params = np.zeros(PARAM_SIZE, dtype=np.float32)
        state = {"params": params}

        def load_state_dict(sd):
            state["params"] = np.array(sd["params"])

        def state_dict():
            return {"params": state["params"].copy()}

        t_init0 = time.perf_counter()
        manager = Manager(
            pg=ProcessGroupTCP(timeout=30.0),
            min_replica_size=1,
            load_state_dict=load_state_dict,
            state_dict=state_dict,
            lighthouse_addr=self.lighthouse_addr,
            replica_id=f"replica_{self.replica_id}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=True,
            timeout=30.0,
            quorum_timeout=30.0,
        )
        healed = attempt > 0
        if healed and self.bench.t_killed is not None:
            log(f"replica {self.replica_id}: teardown+restart took "
                f"{t_init0 - self.bench.t_killed:.3f}s, manager re-init "
                f"{time.perf_counter() - t_init0:.3f}s")
        try:
            while manager.current_step() < TOTAL_STEPS:
                step = manager.current_step()
                if (
                    self.replica_id == KILL_REPLICA
                    and attempt == 0
                    and step == KILL_AT_STEP
                ):
                    # Stamp at the raise site: Manager teardown in the
                    # finally block is part of real kill-to-healthy time.
                    self.bench.t_killed = time.perf_counter()
                    raise _Kill()

                t0 = time.perf_counter()
                manager.start_quorum()
                grads = np.full(
                    PARAM_SIZE, float(step + 1), dtype=np.float32
                ) * (1.0 + 0.5 * self.replica_id)
                avg = manager.allreduce({"g": grads}).wait(timeout=30)
                if manager.should_commit():
                    state["params"] = state["params"] - 0.1 * avg["g"]
                    self.step_times.append(time.perf_counter() - t0)
                    if healed:
                        self.bench.t_healthy = time.perf_counter()
                        log(f"replica {self.replica_id}: healthy commit at "
                            f"step {manager.current_step()} after heal "
                            f"(quorum+heal+step {time.perf_counter() - t0:.3f}s)")
                        healed = False
            return {
                "replica_id": self.replica_id,
                "params": state["params"],
                "step": manager.current_step(),
            }
        finally:
            manager.shutdown()


class Bench:
    def __init__(self) -> None:
        self.t_killed: "Optional[float]" = None
        self.t_healthy: "Optional[float]" = None

    def run(self) -> float:
        lighthouse = LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        try:
            replicas = [Replica(i, lighthouse.address(), self) for i in range(2)]
            t_start = time.perf_counter()
            with ThreadPoolExecutor(max_workers=2) as ex:
                results = [f.result(timeout=300)
                           for f in [ex.submit(r.run) for r in replicas]]
            wall = time.perf_counter() - t_start
        finally:
            lighthouse.shutdown()

        assert self.t_killed is not None and self.t_healthy is not None
        np.testing.assert_array_equal(results[0]["params"], results[1]["params"])
        log("replicas converged bitwise after recovery")

        all_steps = [t for r in replicas for t in r.step_times]
        log(f"steady-state: median step {statistics.median(all_steps)*1e3:.1f} ms "
            f"({PARAM_SIZE*4/1e6:.0f} MB grads over loopback DCN), "
            f"total wall {wall:.1f}s for {TOTAL_STEPS} steps x 2 replicas")
        return self.t_healthy - self.t_killed


def main() -> None:
    latency = Bench().run()
    print(
        json.dumps(
            {
                "metric": "recovery_to_healthy_step_latency",
                "value": round(latency, 3),
                "unit": "s",
                "vs_baseline": round(latency / 1.0, 3),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
