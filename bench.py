"""Headline benchmark suite: recovery latency, FT overhead, model MFU,
FT-around-model overhead, DiLoCo outer-sync cost.

Measurements, one JSON line:

1. **recovery_to_healthy_step_latency** (primary metric, BASELINE.json
   north star): a replica group dies mid-run and must rejoin with ZERO
   full-job restart — the survivors keep training, the dead replica
   restarts, heals its weights live from a healthy peer, and commits a
   healthy step.  Exercises the whole FT stack end to end on loopback:
   C++ Lighthouse (quorum recompute on membership change) -> C++ Manager
   servers -> quorum-keyed DCN collective reconfigure -> live checkpoint
   heal over the HTTP transport (16 MB state dict) -> zero-contribution
   allreduce -> commit vote.

2. **overhead_pct** (BASELINE.json: "step-time overhead vs non-FT DDP
   <= 5%"): twin 2-replica DDP loops with IDENTICAL compute and the
   IDENTICAL ring allreduce — one driven through the Manager protocol
   (per-step quorum RPC + commit vote + error tracking), one bare
   ProcessGroupTCP configured once.  overhead = ft/bare - 1.  The
   per-phase breakdown comes from ``Manager.phase_times()`` deltas
   (quorum_wait / host_sync / ring / commit).  Harness shape mirrors the
   reference's transport benches (reference:
   torchft/checkpointing/pg_transport_bench.py:24-95).

3. **model.mfu_pct**: the flagship TransformerConfig running
   ``make_train_step`` (fwd+bwd+adamw, one jit) on the real accelerator,
   sized to fill a v5e when one is attached.  Params and batches are
   created ON DEVICE (jitted init) because under the driver the chip sits
   behind a ~10 MB/s tunnel — only scalars cross the wire.  MFU uses
   model FLOPs (6*N*tokens + exact attention term; remat recompute NOT
   counted, per the standard MFU definition), shown in
   ``docs/benchmarks.md``.  Reference-scale intent:
   torchft/examples/slurm/runner.py:16-49.

``vs_baseline`` = median recovery latency / 1.0 — a 1-second recovery
target we set for ourselves (the reference publishes no numbers,
BASELINE.md; its embedded join_timeout default alone is 100 ms + 100 ms
quorum tick).  Values < 1.0 beat the target; lower is better.  The
recovery headline is the MEDIAN of ``RECOVERY_CYCLES`` independent
kill/rejoin cycles, each with a per-phase breakdown (teardown, manager
re-init, quorum RPC, PG reconfigure, heal transfer, ring step, commit)
so a regressed number is attributable to protocol vs host noise.

Recovery/overhead compute is host-side numpy on purpose: those benches
measure the DCN fault-tolerance layer, and routing 16 MB grads through
the tunnel would measure the tunnel.  The model bench is the one that
touches the chip.
"""

from __future__ import annotations

import json
import statistics
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from torchft_tpu.coordination import (
    LighthouseClient,
    LighthouseServer,
    StoreServer,
)
from torchft_tpu.diagnose import dominant_contributor
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.process_group import (
    REDUCE_SUM,
    ProcessGroupTCP,
)

PARAM_SIZE = 4 * 1024 * 1024  # 4M fp32 = 16 MB state dict
TOTAL_STEPS = 20
KILL_AT_STEP = 10
KILL_REPLICA = 1
RECOVERY_CYCLES = 3  # independent kill/rejoin cycles; median is the headline

OVERHEAD_WARMUP = 5
OVERHEAD_STEPS = 30


def _phase_delta(manager, prev: "Dict[str, float]"):
    """Per-step phase delta from the NON-destructive ``phase_times()``
    snapshot (a destructive drain would corrupt any concurrent scraper).
    Returns ``(delta, new_snapshot)``; thread the snapshot through the
    loop."""
    cur = manager.phase_times()
    return {k: v - prev.get(k, 0.0) for k, v in cur.items()}, cur


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# 1. recovery-to-healthy-step latency
# ---------------------------------------------------------------------------


class _Kill(Exception):
    pass


class DivergenceError(AssertionError):
    """Replicas were NOT bitwise-equal after recovery — a protocol
    correctness failure that must fail the whole bench (unlike harness
    asserts or hangs, which only fail their cycle)."""


class Replica:
    def __init__(self, replica_id: int, lighthouse_addr: str, bench: "RecoveryBench"):
        self.replica_id = replica_id
        self.lighthouse_addr = lighthouse_addr
        self.bench = bench
        self.step_times: "List[float]" = []

    def run(self) -> dict:
        for attempt in range(3):
            try:
                return self._train(attempt)
            except _Kill:
                log(f"replica {self.replica_id}: killed at step {KILL_AT_STEP}, "
                    "restarting")
                continue
        raise RuntimeError("exhausted attempts")

    def _train(self, attempt: int) -> dict:
        params = np.zeros(PARAM_SIZE, dtype=np.float32)
        state = {"params": params}

        def load_state_dict(sd):
            state["params"] = np.array(sd["params"])

        def state_dict():
            return {"params": state["params"].copy()}

        t_init0 = time.perf_counter()
        manager = Manager(
            pg=ProcessGroupTCP(timeout=30.0),
            min_replica_size=1,
            load_state_dict=load_state_dict,
            state_dict=state_dict,
            lighthouse_addr=self.lighthouse_addr,
            replica_id=f"replica_{self.replica_id}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=True,
            timeout=30.0,
            quorum_timeout=30.0,
            # a should_commit=False livelock must terminate (an abandoned
            # cycle's thread would otherwise spin on the 1-core host
            # forever — there is no other per-replica wall deadline)
            max_retries=2 * TOTAL_STEPS,
        )
        healed = attempt > 0
        if healed and self.bench.t_killed is not None:
            self.bench.teardown_s = t_init0 - self.bench.t_killed
            self.bench.manager_init_s = time.perf_counter() - t_init0
            log(f"replica {self.replica_id}: teardown+restart took "
                f"{self.bench.teardown_s:.3f}s, manager re-init "
                f"{self.bench.manager_init_s:.3f}s")
        try:
            while manager.current_step() < TOTAL_STEPS:
                step = manager.current_step()
                if (
                    self.replica_id == KILL_REPLICA
                    and attempt == 0
                    and step == KILL_AT_STEP
                ):
                    # Stamp at the raise site: Manager teardown in the
                    # finally block is part of real kill-to-healthy time.
                    self.bench.t_killed = time.perf_counter()
                    raise _Kill()

                t0 = time.perf_counter()
                manager.start_quorum()
                grads = np.full(
                    PARAM_SIZE, float(step + 1), dtype=np.float32
                ) * (1.0 + 0.5 * self.replica_id)
                avg = manager.allreduce({"g": grads}).wait(timeout=30)
                if manager.should_commit():
                    state["params"] = state["params"] - 0.1 * avg["g"]
                    self.step_times.append(time.perf_counter() - t0)
                    if healed:
                        self.bench.t_healthy = time.perf_counter()
                        # phases accumulated since this (fresh) Manager was
                        # built == exactly the recovery step's protocol work
                        self.bench.healed_phases = manager.phase_times()
                        log(f"replica {self.replica_id}: healthy commit at "
                            f"step {manager.current_step()} after heal "
                            f"(quorum+heal+step {time.perf_counter() - t0:.3f}s)")
                        healed = False
            return {
                "replica_id": self.replica_id,
                "params": state["params"],
                "step": manager.current_step(),
            }
        finally:
            manager.shutdown()


class RecoveryBench:
    """One kill/rejoin cycle: 2 replica groups, kill one mid-run, time
    kill→healthy-commit with a per-phase breakdown of where it went."""

    def __init__(self) -> None:
        self.t_killed: "Optional[float]" = None
        self.t_healthy: "Optional[float]" = None
        self.teardown_s: "Optional[float]" = None
        self.manager_init_s: "Optional[float]" = None
        self.healed_phases: "Dict[str, float]" = {}

    def run(self) -> "Dict[str, Any]":
        lighthouse = LighthouseServer(
            min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        try:
            replicas = [Replica(i, lighthouse.address(), self) for i in range(2)]
            t_start = time.perf_counter()
            # daemon threads, not a ThreadPoolExecutor: a hung worker must
            # neither block this cycle past its deadline nor hang process
            # exit via concurrent.futures' atexit join (the worker itself
            # unwedges via its protocol deadlines / max_retries)
            out: "Dict[int, Any]" = {}
            errs: "Dict[int, BaseException]" = {}

            def runner(r: Replica) -> None:
                try:
                    out[r.replica_id] = r.run()
                except BaseException as e:  # noqa: BLE001
                    errs[r.replica_id] = e

            threads = [
                threading.Thread(target=runner, args=(r,), daemon=True)
                for r in replicas
            ]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 300
            for t in threads:
                t.join(timeout=max(deadline - time.monotonic(), 0.001))
            # a still-running worker takes precedence over any error from
            # its peer: the caller keys its unwedge grace on TimeoutError,
            # and a live thread is exactly the condition the grace exists
            # for (it will contend for the single core until its own
            # deadlines fire)
            if any(t.is_alive() for t in threads):
                raise TimeoutError("recovery cycle timed out (worker hung)")
            if errs:
                raise next(iter(errs.values()))
            if len(out) != len(replicas):
                raise TimeoutError("recovery cycle timed out (worker hung)")
            results = [out[r.replica_id] for r in replicas]
            wall = time.perf_counter() - t_start
        finally:
            lighthouse.shutdown()

        assert self.t_killed is not None and self.t_healthy is not None
        try:
            np.testing.assert_array_equal(
                results[0]["params"], results[1]["params"]
            )
        except AssertionError as e:
            raise DivergenceError(str(e)) from None
        log("replicas converged bitwise after recovery")

        all_steps = [t for r in replicas for t in r.step_times]
        log(f"steady-state: median step {statistics.median(all_steps)*1e3:.1f} ms "
            f"({PARAM_SIZE*4/1e6:.0f} MB grads over loopback DCN), "
            f"total wall {wall:.1f}s for {TOTAL_STEPS} steps x 2 replicas")

        # Phase breakdown of kill -> healthy commit.  teardown + manager
        # re-init happen before the healed Manager exists; the rest comes
        # from its phase_times().  quorum_rpc / pg_configure /
        # heal_recv run on the async-quorum thread and are what the
        # caller-side quorum_wait was waiting FOR (they overlap it, not
        # add to it); ring + commit are the healed step's collective and
        # commit barrier.
        phases_ms: "Dict[str, float]" = {
            "teardown": (self.teardown_s or 0.0) * 1e3,
            "manager_init": (self.manager_init_s or 0.0) * 1e3,
        }
        for k in ("quorum_rpc", "pg_configure", "heal_recv",
                  "heal_manifest", "heal_diff", "heal_wire", "heal_decode",
                  "ring", "commit", "quorum_wait", "host_sync"):
            if k in self.healed_phases:
                phases_ms[k] = self.healed_phases[k] * 1e3
        return {
            "latency_s": self.t_healthy - self.t_killed,
            "phases_ms": {k: round(v, 1) for k, v in phases_ms.items()},
            "steady_step_ms": round(statistics.median(all_steps) * 1e3, 1),
            "wall_s": round(wall, 1),
        }


def bench_recovery(cycles: int = RECOVERY_CYCLES) -> "Dict[str, Any]":
    """>= 3 independent kill/rejoin cycles; the MEDIAN is the headline (one
    cycle on a 1-core host is a coin flip — r03's single sample measured
    1.059 s on the driver vs 0.14-0.22 s locally with no way to tell host
    noise from a protocol pathology; the per-cycle phase breakdown now
    says which)."""
    cycle_results = []
    errors = []
    for i in range(cycles):
        # one bad cycle (hung thread, host stall) must not cost the driver
        # the primary metric — the median of the surviving cycles is still
        # a better headline than r03's single-sample coin flip.
        # DivergenceError is NOT survivable: bitwise divergence after
        # recovery is a protocol correctness failure, not host noise.
        try:
            r = RecoveryBench().run()
        except DivergenceError:
            raise
        except Exception as e:  # noqa: BLE001
            log(f"recovery cycle {i} FAILED: {e!r}")
            errors.append(repr(e))
            if isinstance(e, TimeoutError) and i < cycles - 1:
                # let the abandoned cycle's worker threads unwedge via
                # their own protocol deadlines (30 s) before timing the
                # next cycle on this 1-core host; instant failures and the
                # last cycle need no grace
                time.sleep(35.0)
            continue
        log(f"recovery cycle {i}: {r['latency_s']:.3f}s phases {r['phases_ms']}")
        cycle_results.append(r)
    if not cycle_results:
        raise RuntimeError(f"all recovery cycles failed: {errors}")

    latencies = [r["latency_s"] for r in cycle_results]
    median_latency = statistics.median(latencies)
    # median per phase across cycles (phases missing in a cycle count as 0)
    keys = sorted({k for r in cycle_results for k in r["phases_ms"]})
    phase_median = {
        k: round(statistics.median([r["phases_ms"].get(k, 0.0)
                                    for r in cycle_results]), 1)
        for k in keys
    }
    out = {
        "value": round(median_latency, 3),
        "recovery_cycles_s": [round(x, 3) for x in latencies],
        "recovery_min_s": round(min(latencies), 3),
        # seconds, like every sibling top-level metric in this object
        "recovery_phases": {
            k: round(v / 1e3, 4) for k, v in phase_median.items()
        },
        "recovery_phases_ms": phase_median,
        # critical-path ledger vocabulary (torchft_tpu/diagnose.py): which
        # cost category dominated the recovery path this run
        "recovery_dominant": dominant_contributor(phase_median),
        "steady_step_ms": round(
            statistics.median([r["steady_step_ms"] for r in cycle_results]), 1
        ),
    }
    if errors:
        out["recovery_cycle_errors"] = errors
    return out


# ---------------------------------------------------------------------------
# 1b. online-parallelism-switch latency (ISSUE 11)
# ---------------------------------------------------------------------------

SWITCH_GROUPS = 4
SWITCH_KILL_STEP = 3
SWITCH_TOTAL_STEPS = 7
SWITCH_PARAM_ELEMS = 1 << 18  # 1 MB fp32 of layout-sharded state


def bench_switch() -> "Dict[str, Any]":
    """Kill-to-switched latency of online parallelism switching
    (parallel/layout.py): 4 single-rank groups under a memory ceiling
    run layout (2,2,1); killing one shrinks the fleet to 3, which
    re-plans to (1,3,1) and re-shards the 1 MB state live (slice-diff
    fetches from current owners over the HTTP transport).  Measured:
    wall seconds from the kill to the LAST survivor's fleet-synchronous
    layout commit, with the per-phase split (reshard staging wall /
    commit round wall, from ``Manager.phase_times``) and the bytes that
    actually crossed the wire — the price of "the job continuously fits
    the hardware it has", next to the recovery latency it complements."""
    from torchft_tpu.parallel.layout import (
        LayoutConstraints,
        LayoutController,
    )

    lighthouse = LighthouseServer(
        min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=1000
    )
    t_killed: "List[Optional[float]]" = [None]
    commits: "Dict[int, Dict[str, Any]]" = {}
    errs: "Dict[int, BaseException]" = {}

    def worker(gid: int) -> None:
        shard = {"w": np.zeros(SWITCH_PARAM_ELEMS, dtype=np.float32)}
        ctrl = LayoutController(
            LayoutConstraints(
                param_bytes=SWITCH_PARAM_ELEMS * 4,
                shard_memory_bytes=SWITCH_PARAM_ELEMS * 2,
            )
        )
        ctrl.register_sharded_state(
            "model",
            {"w": SWITCH_PARAM_ELEMS},
            lambda: dict(shard),
            lambda new: shard.update(
                {k: np.array(v) for k, v in new.items()}
            ),
        )
        user = {"marker": float(gid)}
        manager = Manager(
            pg=ProcessGroupTCP(timeout=30.0),
            min_replica_size=1,
            load_state_dict=lambda sd: user.update(sd),
            state_dict=lambda: dict(user),
            lighthouse_addr=lighthouse.address(),
            replica_id=f"switch_{gid}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=True,
            init_sync=False,
            timeout=30.0,
            quorum_timeout=30.0,
            max_retries=4 * SWITCH_TOTAL_STEPS,
        )
        manager.attach_layout(ctrl)

        base_phases: "Dict[str, float]" = {}

        def on_commit(layout, info):
            if layout.key() == (2, 2, 1):
                # bootstrap shard-up: snapshot so the shrink switch's
                # phase split below is a delta, not a cumulative sum
                base_phases.update(manager.phase_times())
            elif layout.key() == (1, 3, 1):  # the shrink switch
                cur = manager.phase_times()
                commits[gid] = {
                    "ts": time.perf_counter(),
                    "bytes": info.get("fetched_bytes", 0),
                    "phases": {
                        k: v - base_phases.get(k, 0.0) for k, v in cur.items()
                    },
                }

        ctrl.add_listener(on_commit)
        try:
            while manager.current_step() < SWITCH_TOTAL_STEPS:
                step = manager.current_step()
                if gid == SWITCH_GROUPS - 1 and step == SWITCH_KILL_STEP:
                    t_killed[0] = time.perf_counter()
                    return
                manager.start_quorum()
                g = np.full(
                    SWITCH_PARAM_ELEMS, float(step + 1), dtype=np.float32
                )
                avg = manager.allreduce({"g": g}).wait(timeout=30)
                if manager.should_commit():
                    ctrl.update_sharded(
                        "model",
                        lambda leaf, arr, start: arr.__isub__(
                            np.float32(0.01)
                            * avg["g"][start : start + arr.size]
                        ),
                    )
        finally:
            manager.shutdown()

    try:
        threads = []
        for gid in range(SWITCH_GROUPS):

            def runner(gid=gid):
                try:
                    worker(gid)
                except BaseException as e:  # noqa: BLE001
                    errs[gid] = e

            threads.append(threading.Thread(target=runner, daemon=True))
        for t in threads:
            t.start()
        deadline = time.monotonic() + 180
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.001))
        if any(t.is_alive() for t in threads):
            raise TimeoutError("switch bench wedged (worker hung)")
        if errs:
            raise next(iter(errs.values()))
    finally:
        lighthouse.shutdown()

    survivors = [g for g in range(SWITCH_GROUPS - 1)]
    if t_killed[0] is None or any(g not in commits for g in survivors):
        raise RuntimeError(
            f"shrink switch did not commit on all survivors: {sorted(commits)}"
        )
    latency = max(commits[g]["ts"] for g in survivors) - t_killed[0]
    reshard_s = statistics.median(
        commits[g]["phases"].get("reshard", 0.0) for g in survivors
    )
    commit_s = statistics.median(
        commits[g]["phases"].get("layout_commit", 0.0) for g in survivors
    )
    out = {
        "latency_s": round(latency, 3),
        "reshard_s": round(reshard_s, 4),
        "layout_commit_s": round(commit_s, 4),
        "reshard_bytes": max(commits[g]["bytes"] for g in survivors),
        "layout": "(2,2,1)->(1,3,1)",
        # kill-detection (quorum re-formation, heartbeat expiry) is the
        # remainder — the same protocol cost recovery latency pays
        "detect_s": round(max(latency - reshard_s - commit_s, 0.0), 3),
    }
    # critical-path ledger vocabulary (diagnose.PHASE_CATEGORY): which
    # cost category dominated the switch (detection is quorum protocol)
    out["dominant"] = dominant_contributor(
        {
            "reshard": reshard_s,
            "layout_commit": commit_s,
            "quorum_rpc": out["detect_s"],
        }
    )
    log(f"switch latency: {out}")
    return out


# ---------------------------------------------------------------------------
# 2. FT overhead vs a bare (non-FT) DDP twin
# ---------------------------------------------------------------------------


def _ddp_compute(step: int, rank: int, reps: int = 1) -> np.ndarray:
    """The shared per-step 'gradient computation' of both twins.  ``reps``
    scales the compute (the cross-check mode lengthens steps so the
    twin-ratio estimator's scheduling noise — fixed in ms — shrinks as a
    fraction of the step)."""
    g = np.full(PARAM_SIZE, float(step + 1), dtype=np.float32) * (
        1.0 + 0.5 * rank
    )
    for _ in range(reps - 1):
        g = 0.5 * (g + np.sqrt(np.abs(g) + 1.0))
    return g


def _bare_replica(
    rank: int, world: int, store_addr: str, barrier: "threading.Barrier",
    out: "Dict[int, List[float]]", steps: int = OVERHEAD_STEPS,
    warmup: int = OVERHEAD_WARMUP, reps: int = 1,
) -> None:
    """Non-FT twin: ProcessGroupTCP configured once, no Manager, no quorum,
    no commit vote — plain DDP over the identical ring."""
    pg = ProcessGroupTCP(timeout=30.0)
    pg.configure(f"{store_addr}/bare", f"bare_{rank}", rank, world)
    try:
        params = np.zeros(PARAM_SIZE, dtype=np.float32)
        times: "List[float]" = []
        barrier.wait(timeout=30)
        cpu0 = time.process_time()
        for step in range(warmup + steps):
            if step == warmup and rank == 0:
                # CPU window starts AFTER warmup, matching the wall
                # medians (times[warmup:]) and the phase-sum estimator —
                # else one-time setup CPU biases the ratio
                cpu0 = time.process_time()
            t0 = time.perf_counter()
            grads = _ddp_compute(step, rank, reps)
            (summed,) = pg.allreduce([grads], REDUCE_SUM).wait(timeout=30)
            summed /= world
            params -= 0.1 * summed
            times.append(time.perf_counter() - t0)
        # process-wide CPU per step over the post-warmup window (both
        # ranks read the same counter; rank 0's delta is the total)
        if rank == 0:
            out[-1] = [(time.process_time() - cpu0) / steps]
        out[rank] = times[warmup:]
    finally:
        pg.shutdown()


def _ft_replica(
    rank: int, lighthouse_addr: str, barrier: "threading.Barrier",
    out: "Dict[int, List[float]]", phases: "Dict[int, Dict[str, float]]",
    steps: int = OVERHEAD_STEPS, warmup: int = OVERHEAD_WARMUP,
    reps: int = 1,
) -> None:
    """FT twin: same compute, same ring, driven through the full Manager
    per-step protocol (async quorum + allreduce + commit vote)."""
    params = np.zeros(PARAM_SIZE, dtype=np.float32)
    state = {"params": params}
    manager = Manager(
        pg=ProcessGroupTCP(timeout=30.0),
        min_replica_size=2,
        load_state_dict=lambda sd: state.update(params=np.array(sd["params"])),
        state_dict=lambda: {"params": state["params"].copy()},
        lighthouse_addr=lighthouse_addr,
        replica_id=f"ft_{rank}",
        group_rank=0,
        group_world_size=1,
        use_async_quorum=True,
        timeout=30.0,
        quorum_timeout=30.0,
    )
    try:
        times: "List[float]" = []
        acc: "Dict[str, float]" = {}
        phase_snap: "Dict[str, float]" = {}
        barrier.wait(timeout=30)
        cpu0 = time.process_time()
        cpu_marked = False
        step = 0
        attempts = 0
        while step < warmup + steps:
            if step == warmup and rank == 0 and not cpu_marked:
                # post-warmup CPU window (see _bare_replica): excludes the
                # one-time first-quorum/JIT setup the other estimators
                # also exclude
                cpu0 = time.process_time()
                cpu_marked = True
            attempts += 1
            if attempts > 3 * (warmup + steps):
                raise RuntimeError(
                    f"FT twin stuck: {step} committed after {attempts} attempts"
                )
            t0 = time.perf_counter()
            manager.start_quorum()
            grads = _ddp_compute(step, rank, reps)
            avg = manager.allreduce({"g": grads}).wait(timeout=30)
            if manager.should_commit():
                state["params"] -= 0.1 * avg["g"]
                times.append(time.perf_counter() - t0)
                phase, phase_snap = _phase_delta(manager, phase_snap)
                if step >= warmup:
                    for k, v in phase.items():
                        acc[k] = acc.get(k, 0.0) + v
                step += 1
        if rank == 0:
            # process-wide CPU/step over the post-warmup window: includes
            # the async quorum thread and manager server threads — the
            # background work the caller-side phase sum deliberately
            # excludes
            out[-1] = [(time.process_time() - cpu0) / steps]
        out[rank] = times[warmup:]
        phases[rank] = acc
    finally:
        manager.shutdown()


def _run_bare_twin(
    world: int, steps: int = OVERHEAD_STEPS, warmup: int = OVERHEAD_WARMUP,
    reps: int = 1, cpu_out: "Optional[List[float]]" = None,
) -> float:
    store = StoreServer()
    times: "Dict[int, List[float]]" = {}
    try:
        barrier = threading.Barrier(world)
        threads = [
            threading.Thread(
                target=_bare_replica,
                args=(r, world, store.address(), barrier, times, steps,
                      warmup, reps),
                daemon=True,
            )
            for r in range(world)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
    finally:
        store.shutdown()
    cpu = times.pop(-1, None)
    if cpu_out is not None and cpu:
        cpu_out.append(cpu[0])
    assert len(times) == world, "bare twin failed"
    return statistics.median([t for ts in times.values() for t in ts])


def _run_ft_twin(
    world: int, phase_out: "Dict[str, float]",
    steps: int = OVERHEAD_STEPS, warmup: int = OVERHEAD_WARMUP,
    reps: int = 1, cpu_out: "Optional[List[float]]" = None,
) -> float:
    """Runs the FT twin; merges this run's mean phase ms/step into
    ``phase_out`` (caller divides by number of runs)."""
    lighthouse = LighthouseServer(
        min_replicas=world, join_timeout_ms=100, heartbeat_timeout_ms=1000
    )
    times: "Dict[int, List[float]]" = {}
    phases: "Dict[int, Dict[str, float]]" = {}
    try:
        barrier = threading.Barrier(world)
        threads = [
            threading.Thread(
                target=_ft_replica,
                args=(r, lighthouse.address(), barrier, times, phases, steps,
                      warmup, reps),
                daemon=True,
            )
            for r in range(world)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
    finally:
        lighthouse.shutdown()
    cpu = times.pop(-1, None)
    if cpu_out is not None and cpu:
        cpu_out.append(cpu[0])
    assert len(times) == world, "FT twin failed"
    for acc in phases.values():
        for k, v in acc.items():
            phase_out[k] = phase_out.get(k, 0.0) + v * 1e3 / steps / len(phases)
    return statistics.median([t for ts in times.values() for t in ts])


def bench_overhead(rounds: int = 5) -> "Dict[str, Any]":
    """FT overhead vs the bare twin, phase-sum estimator.

    The two twins run identical numpy compute and the identical ring
    allreduce; the FT twin adds exactly the Manager protocol phases, which
    ``phase_times`` deltas measure per step at perf_counter precision:
    ``quorum_wait`` + ``commit`` + ``host_sync`` (``ring`` is common to
    both twins and excluded).  Headline ``overhead_pct`` = added protocol
    ms / bare step ms.

    The naive estimator — the direct ratio of the two twins' medians — is
    also reported (``twin_ratio_pct``) but is unreliable on this host: the
    bench box has ONE CPU core (nproc=1), so the ~50 ms/step twins are
    thread-scheduling-noise-bound and back-to-back paired runs measured
    ratios swinging 0.89-1.19 around the ~1.03 truth.  The phase-sum is
    immune to that noise because it subtracts within the same process,
    same steps.
    """
    world = 2
    pairs: "List[tuple]" = []
    phase_runs: "List[Dict[str, float]]" = []
    for _ in range(rounds):
        b = _run_bare_twin(world)
        phases: "Dict[str, float]" = {}
        f = _run_ft_twin(world, phases)
        pairs.append((b, f))
        phase_runs.append(phases)

    bare_ms = min(b for b, _ in pairs) * 1e3
    ft_ms = min(f for _, f in pairs) * 1e3
    # quietest-round protocol cost (load inflates RPC latency too)
    protocol_ms = min(
        p.get("quorum_wait", 0.0) + p.get("commit", 0.0) + p.get("host_sync", 0.0)
        for p in phase_runs
    )
    overhead_pct = protocol_ms / bare_ms * 100.0
    twin_ratio_pct = (
        statistics.median([f / b for b, f in pairs]) - 1.0
    ) * 100.0
    n = len(phase_runs)
    phase_ms = {
        k: round(sum(p.get(k, 0.0) for p in phase_runs) / n, 3)
        for k in sorted({k for p in phase_runs for k in p})
    }

    log(
        f"overhead: bare {bare_ms:.2f} ms/step, protocol +{protocol_ms:.3f} ms "
        f"-> {overhead_pct:+.2f}% (twin-ratio cross-check {twin_ratio_pct:+.2f}%) | "
        f"phases ms/step {phase_ms} | pair ratios "
        f"{[round(f / b, 4) for b, f in pairs]}"
    )
    return {
        "overhead_pct": round(overhead_pct, 2),
        "protocol_ms_per_step": round(protocol_ms, 3),
        "ft_step_ms": round(ft_ms, 3),
        "nonft_step_ms": round(bare_ms, 3),
        "twin_ratio_pct": round(twin_ratio_pct, 2),
        "phases_ms_per_step": phase_ms,
        # per-leg dominant-ledger-contributor (diagnose.PHASE_CATEGORY);
        # prefixed because this dict is merged into the top-level result
        "overhead_dominant": dominant_contributor(phase_ms),
    }


def bench_overhead_crosscheck(rounds: int = 4) -> "Dict[str, Any]":
    """Two-estimator convergence check (VERDICT r4 item 7): the headline
    <= 5% claim rests on the phase-sum estimator; this mode de-noises the
    twin-ratio estimator until the two can be compared on a 1-core host.

    De-contenting levers:
    - LONG steps (compute reps stretch ~50 ms steps to ~200+ ms): the
      twin-ratio's scheduling noise is fixed in ms, so its share of the
      ratio shrinks ~4x;
    - alternating windows (bare/FT/bare/FT...) with per-window pairing
      and a median-of-ratios: host drift (page cache, cron, thermal)
      lands on both twins of a pair instead of one side of a long run.

    Convergence = |cpu_ratio_pct - overhead_pct| within ~2 points (the
    CPU-time ratio is the de-contended twin estimator; the wall
    twin_ratio_pct is reported alongside for continuity with r4).  If
    the gap stays larger, the null experiment decides whether that is
    signal: bare-vs-bare CPU ratios (identical twins) measure the
    estimator's own noise floor, and a gap inside the floor means no
    twin comparison on this host can resolve the effect.  Any residual
    beyond the floor would be the ASYNC QUORUM THREAD's CPU steal: on 1
    core the Manager's background quorum thread preempts compute, which
    the caller-thread phase sum deliberately excludes because on a
    deployment host (>= 1 core per replica + servers) it runs on spare
    cores.  The JSON carries all estimators + the null spread so the
    claim is auditable either way.
    """
    world = 2
    # ~4x longer steps; fewer steps/rounds to keep the wall bounded
    reps, steps, warmup = 6, 12, 3
    ratios: "List[float]" = []
    cpu_ratios: "List[float]" = []
    null_ratios: "List[float]" = []
    protocol_ms_runs: "List[float]" = []
    bare_ms_runs: "List[float]" = []
    null_cpu_ratios: "List[float]" = []
    for rnd in range(rounds):
        bare_cpu: "List[float]" = []
        ft_cpu: "List[float]" = []
        null_cpu: "List[float]" = []
        phases: "Dict[str, float]" = {}

        def run_bare(cpu_out):
            return _run_bare_twin(
                world, steps=steps, warmup=warmup, reps=reps, cpu_out=cpu_out
            )

        def run_ft():
            return _run_ft_twin(
                world, phases, steps=steps, warmup=warmup, reps=reps,
                cpu_out=ft_cpu,
            )

        # NULL experiment: bare vs bare — identical twins.  Whatever ratio
        # spread the null shows is the estimator's noise floor; an FT-vs-
        # bare difference smaller than that floor is unmeasurable by ANY
        # twin comparison on this host, de-contended or not.  The floor is
        # computed on the SAME estimator as the gap (CPU ratios).
        #
        # Window order ALTERNATES per round (bare-then-ft / ft-then-bare):
        # later windows in a round run warmer (page cache, pool, branch
        # predictors), and a fixed order turns that warming into a
        # systematic negative "overhead" — alternation cancels it in the
        # across-rounds median.
        b_null = run_bare(null_cpu)
        if rnd % 2 == 0:
            b = run_bare(bare_cpu)
            f = run_ft()
        else:
            f = run_ft()
            b = run_bare(bare_cpu)
        null_ratios.append(b / b_null)
        if bare_cpu and null_cpu:
            null_cpu_ratios.append(bare_cpu[0] / null_cpu[0])
        ratios.append(f / b)
        if bare_cpu and ft_cpu:
            cpu_ratios.append(ft_cpu[0] / bare_cpu[0])
        bare_ms_runs.append(b * 1e3)
        protocol_ms_runs.append(
            phases.get("quorum_wait", 0.0)
            + phases.get("commit", 0.0)
            + phases.get("host_sync", 0.0)
        )
    bare_ms = min(bare_ms_runs)
    protocol_ms = min(protocol_ms_runs)
    overhead_pct = protocol_ms / bare_ms * 100.0
    twin_ratio_pct = (statistics.median(ratios) - 1.0) * 100.0
    # CPU-time ratio: the de-contended estimator.  process_time over the
    # stepping window counts every thread's ACTUAL work (incl. the async
    # quorum/background threads) and excludes idle scheduling gaps — the
    # component of the wall-ratio that made r4's 8.28% unusable.
    cpu_ratio_pct = (
        (statistics.median(cpu_ratios) - 1.0) * 100.0 if cpu_ratios else None
    )
    gap = (cpu_ratio_pct - overhead_pct) if cpu_ratio_pct is not None else None
    # noise floor: half the null twins' CPU-ratio spread, in points —
    # measured on the same estimator the gap uses (the wall null spread
    # is reported too, but excusing a CPU gap with a wall floor would
    # make the falsification unfalsifiable)
    null_spread_pts = (
        (max(null_cpu_ratios) - min(null_cpu_ratios)) / 2.0 * 100.0
        if null_cpu_ratios else None
    )
    null_wall_spread_pts = (
        (max(null_ratios) - min(null_ratios)) / 2.0 * 100.0
        if null_ratios else None
    )
    converged = gap is not None and abs(gap) <= 2.0
    # The estimator's OWN per-pair spread is a second noise floor: when
    # individual FT/bare pairs disagree by more than the median they
    # produce (e.g. pairs 0.83..1.43 around a 1.16 median), the median is
    # statistically indistinguishable from zero effect at this sample
    # size — the claim cannot rest on it.
    pair_spread_pts = (
        (max(cpu_ratios) - min(cpu_ratios)) / 2.0 * 100.0
        if cpu_ratios else None
    )
    floor = max(
        [x for x in (null_spread_pts, pair_spread_pts) if x is not None],
        default=None,
    )
    # falsified = the estimators did NOT converge, but the twin estimator
    # is demonstrably unable to resolve the effect: the gap sits inside
    # the measured noise floor (bare-vs-bare spread OR the pairs' own
    # spread), or the twin ratio reports the FT run as CHEAPER than bare
    # beyond the 2-pt budget — protocol work is strictly additive, so a
    # negative reading is noise by definition (ordering/warming bias).
    falsified = (
        not converged
        and gap is not None
        and (
            (floor is not None and abs(gap) <= floor + 2.0)
            or (cpu_ratio_pct is not None and cpu_ratio_pct < -2.0)
        )
    )
    log(
        f"overhead cross-check (long {bare_ms:.0f} ms steps, alternating "
        f"windows): phase-sum {overhead_pct:+.2f}% vs cpu-ratio "
        f"{cpu_ratio_pct:+.2f}% (gap {gap:+.2f} pts) vs wall twin-ratio "
        f"{twin_ratio_pct:+.2f}%; NULL bare-vs-bare CPU ratios "
        f"{[round(r, 4) for r in null_cpu_ratios]} -> noise floor "
        f"+-{null_spread_pts:.1f} pts (wall null +-{null_wall_spread_pts:.1f}) "
        f"({'converged' if converged else 'estimator noise-floor-bound' if falsified else 'UNEXPLAINED'})"
    )
    return {
        "long_step_ms": round(bare_ms, 1),
        "overhead_pct": round(overhead_pct, 2),
        "cpu_ratio_pct": round(cpu_ratio_pct, 2) if cpu_ratio_pct is not None else None,
        "twin_ratio_pct": round(twin_ratio_pct, 2),
        "gap_pts": round(gap, 2) if gap is not None else None,
        "converged_2pts": converged,
        "null_cpu_spread_pts": (
            round(null_spread_pts, 2) if null_spread_pts is not None else None
        ),
        "pair_spread_pts": (
            round(pair_spread_pts, 2) if pair_spread_pts is not None else None
        ),
        "null_wall_spread_pts": (
            round(null_wall_spread_pts, 2)
            if null_wall_spread_pts is not None else None
        ),
        "noise_floor_bound": falsified,
        "pair_ratios": [round(r, 4) for r in ratios],
        "cpu_pair_ratios": [round(r, 4) for r in cpu_ratios],
        "null_cpu_pair_ratios": [round(r, 4) for r in null_cpu_ratios],
        "null_pair_ratios": [round(r, 4) for r in null_ratios],
    }


# ---------------------------------------------------------------------------
# 3. DiLoCo outer sync at flagship scale (the BASELINE.json north star)
# ---------------------------------------------------------------------------

FLAGSHIP_PARAMS = int(464.4e6)  # matches the bench_model flagship config
DILOCO_FRAGMENTS = 8            # Streaming DiLoCo fragment count
DILOCO_SYNC_EVERY = 20          # inner steps per fragment cycle


def bench_diloco_vs_ddp(
    nonft_ddp_step_ms: float, gbps: "Optional[float]" = None
) -> "Dict[str, Any]":
    """BASELINE.json's own arithmetic, measured: FT Streaming DiLoCo's
    step cost vs the NON-FT DDP twin (the '<= 5% overhead on the
    train_diloco config' target).  Same per-step compute as the DDP
    twins; DiLoCo replaces the per-step 16 MB ring allreduce with one
    pseudograd sync every ``sync_every`` steps.  A fresh bare-DDP twin
    runs back-to-back in this same process so the comparison shares one
    load epoch (still a twin-loop comparison — ±20% noise-bound on the
    1-core host, docs/benchmarks.md §2 — hence the decomposition into
    inner median + per-sync cost, which is the robust part).

    ``gbps``: run BOTH twins under the token-bucket egress shaper (via
    ``TORCHFT_WIRE_GBPS``, which every ProcessGroupTCP in this process
    reads at construction) — the measured version of r4's extrapolated
    "on real DCN the sign flips": DDP pays the shaped wire every step,
    DiLoCo only at the outer sync.
    """
    import os as _os

    import torchft_tpu as ft

    prior = _os.environ.get("TORCHFT_WIRE_GBPS")
    if prior is not None and gbps is None:
        # a pre-set user knob would silently shape the "unshaped" leg
        log(f"note: TORCHFT_WIRE_GBPS={prior} is set — the nominally "
            "unshaped diloco-vs-ddp leg runs SHAPED at that rate")
    if gbps is not None:
        _os.environ["TORCHFT_WIRE_GBPS"] = str(gbps)
    try:
        return _bench_diloco_vs_ddp_body(nonft_ddp_step_ms, gbps, ft)
    finally:
        if gbps is not None:
            if prior is None:
                _os.environ.pop("TORCHFT_WIRE_GBPS", None)
            else:
                _os.environ["TORCHFT_WIRE_GBPS"] = prior


def _bench_diloco_vs_ddp_body(
    nonft_ddp_step_ms: float, gbps: "Optional[float]", ft
) -> "Dict[str, Any]":
    bare = _run_bare_twin(2) * 1e3
    nonft_ddp_step_ms = bare if gbps is not None else min(nonft_ddp_step_ms, bare)
    # warmup past the FIRST sync: it pays the outer-optimizer jit compile,
    # which amortizes to nothing over a real run's thousands of syncs
    world, sync_every, inner_steps, warmup = 2, 20, 100, 25
    lighthouse = LighthouseServer(
        min_replicas=world, join_timeout_ms=100, heartbeat_timeout_ms=1000
    )
    times: "Dict[int, List[float]]" = {}

    def replica(rank: int, barrier: "threading.Barrier") -> None:
        params = {"w": np.zeros(PARAM_SIZE, dtype=np.float32)}
        state = {"params": params}
        manager = Manager(
            pg=ProcessGroupTCP(timeout=30.0),
            min_replica_size=world,
            load_state_dict=lambda sd: state.update(params=dict(sd)),
            state_dict=lambda: dict(state["params"]),
            lighthouse_addr=lighthouse.address(),
            replica_id=f"dl_{rank}",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=False,  # DiLoCo requires sync quorum
            timeout=30.0,
            quorum_timeout=30.0,
        )
        import jax
        import optax

        try:
            # pin the outer optimizer's jax ops to the LOCAL CPU backend:
            # under the driver the default jax device is the tunneled TPU,
            # and routing 16 MB host pseudograds through a ~10 MB/s tunnel
            # would measure the tunnel (bench.py module docstring), not
            # the DCN fault-tolerance layer this bench prices
            with jax.default_device(jax.devices("cpu")[0]), ft.DiLoCo(
                manager,
                [["w"]],
                lambda: dict(state["params"]),
                lambda flat: state["params"].update(flat),
                optax.sgd(0.7, momentum=0.9, nesterov=True),
                sync_every=sync_every,
                fragment_sync_delay=1,  # overlap the sync with compute
            ) as diloco:
                ts: "List[float]" = []
                barrier.wait(timeout=30)
                for step in range(inner_steps):
                    t0 = time.perf_counter()
                    grads = _ddp_compute(step, rank)
                    state["params"]["w"] = state["params"]["w"] - 0.01 * grads
                    diloco.step()
                    ts.append(time.perf_counter() - t0)
                times[rank] = ts[warmup:]
        finally:
            manager.shutdown()

    try:
        barrier = threading.Barrier(world)
        threads = [
            threading.Thread(target=replica, args=(r, barrier), daemon=True)
            for r in range(world)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
    finally:
        lighthouse.shutdown()
    assert len(times) == world, "diloco twin failed"
    # split sync-boundary steps (prepare at count%sync_every==sync_every-1,
    # finish at ==0 with delay=1 -> local indices 18/19 mod 20) from pure
    # inner steps, so the decomposition is explicit
    inner: "List[float]" = []
    boundary: "List[float]" = []
    for ts in times.values():
        for i, t in enumerate(ts):
            step = i + warmup
            (boundary if step % sync_every >= sync_every - 2 else inner).append(t)
    inner_ms = statistics.median(inner) * 1e3
    # 2 boundary steps per sync; subtract their inner-compute share.
    # Clamped: on a noisy host the inner median can exceed the boundary
    # mean, which would read as a nonsensical negative sync cost.
    per_sync_ms = max(
        0.0,
        (sum(boundary) / len(boundary) * 2e3 - 2 * inner_ms)
        if boundary
        else 0.0,
    )
    amortized_ms = inner_ms + per_sync_ms / sync_every
    overhead_pct = (amortized_ms / nonft_ddp_step_ms - 1.0) * 100.0
    inner_vs_ddp_pct = (inner_ms / nonft_ddp_step_ms - 1.0) * 100.0
    wire_note = (
        f"both twins shaped to {gbps} GB/s egress"
        if gbps is not None
        else "loopback makes the per-step allreduce DiLoCo avoids nearly free"
    )
    log(f"diloco-vs-ddp{f' @{gbps} GB/s' if gbps else ''}: FT DiLoCo inner "
        f"step {inner_ms:.1f} ms "
        f"({inner_vs_ddp_pct:+.1f}% vs non-FT DDP {nonft_ddp_step_ms:.1f} ms"
        f" — no per-step allreduce), outer sync {per_sync_ms:.0f} ms every "
        f"{sync_every} steps -> amortized {amortized_ms:.1f} ms = "
        f"{overhead_pct:+.1f}% ({wire_note})")
    return {
        "diloco_inner_step_ms": round(inner_ms, 2),
        "diloco_inner_vs_nonft_ddp_pct": round(inner_vs_ddp_pct, 1),
        "diloco_sync_ms": round(per_sync_ms, 1),
        "diloco_amortized_step_ms": round(amortized_ms, 2),
        "diloco_vs_nonft_ddp_pct": round(overhead_pct, 1),
        "nonft_ddp_step_ms": round(nonft_ddp_step_ms, 2),
    }


def _diloco_sync_leg(
    leg: str, quantize: bool, gbps: "float | None", repeats: int = 2,
    wire_dtype: "Optional[str]" = None,
    world: int = 2,
    rtt_ms: "Optional[float]" = 0.0,
    topology: "Optional[str]" = None,
    n_fragments: int = DILOCO_FRAGMENTS,
    device: bool = False,
) -> "Dict[str, Any]":
    """Flagship-scale outer sync over the TCP ring at a shaped egress
    bandwidth (None = unshaped loopback), best of ``repeats`` runs (the
    shared host shows 2-3x wall spikes from neighbor interference — a
    single sample can turn a 5 s sync into a 15 s headline).  Returns
    wall, wire and codec seconds (codec only on the quantized leg).
    ``wire_dtype``: payload format for the quantized leg (None resolves
    through the collective's default chain: TORCHFT_QUANT_WIRE env, else
    int8 — format-comparison legs pin it explicitly).

    WAN knobs (the RTT-swept legs): ``rtt_ms`` arms the per-message
    boundary latency on every PG; ``topology`` picks the REDUCTION PLAN
    ("flat" or a TORCHFT_TOPOLOGY spec) — the wire model's boundary map
    always comes from the TORCHFT_TOPOLOGY env the caller sets, so flat
    and hierarchical legs price the same physical topology.  ``device``:
    create the fragment on-device and quantize with the Pallas kernel
    (the ``diloco.int8_device`` leg, TPU only)."""
    if repeats > 1:
        runs = [
            _diloco_sync_leg(
                f"{leg}_r{i}", quantize, gbps, repeats=1,
                wire_dtype=wire_dtype, world=world, rtt_ms=rtt_ms,
                topology=topology, n_fragments=n_fragments, device=device,
            )
            for i in range(repeats)
        ]
        return min(runs, key=lambda r: r["sync_s"])
    from torchft_tpu.ops.collectives import allreduce_quantized

    frag_elems = FLAGSHIP_PARAMS // DILOCO_FRAGMENTS
    store = StoreServer()
    barrier = threading.Barrier(world)
    walls: "Dict[int, float]" = {}
    wires: "Dict[int, int]" = {}
    inters: "Dict[int, int]" = {}
    codecs: "Dict[int, float]" = {}
    pipes: "Dict[int, Dict[str, Any]]" = {}

    def worker(rank: int) -> None:
        pg = ProcessGroupTCP(
            timeout=300.0, bandwidth_gbps=gbps, rtt_ms=rtt_ms
        )
        pg.configure(
            f"{store.address()}/diloco_{leg}_{gbps}", f"dl_{rank}", rank, world
        )
        try:
            if device:
                import jax

                # fragment born ON device (only the PRNG key crosses the
                # host link; bench.py module docstring: routing f32 grads
                # through the driver tunnel would measure the tunnel)
                frag = jax.jit(
                    lambda k: jax.random.normal(k, (frag_elems,))
                )(jax.random.PRNGKey(rank))
                frag.block_until_ready()
            else:
                rng = np.random.default_rng(rank)
                frag = rng.standard_normal(frag_elems).astype(np.float32)
            barrier.wait(timeout=60)
            t0 = time.perf_counter()
            wire = 0
            inter = 0
            codec = 0.0
            # per-fragment pipeline accounting (quantized legs): sums of
            # the chunked pipeline's busy walls + the efficiency of the
            # worst fragment (the honest overlap headline)
            pipe: "Dict[str, Any]" = {
                "wire_busy_s": 0.0, "n_chunks": 0, "effs": [], "hops": {},
            }
            for _ in range(n_fragments):
                if quantize:
                    w = allreduce_quantized(
                        [frag], REDUCE_SUM, pg, wire_dtype=wire_dtype,
                        topology=topology,
                        device_quantize=True if device else None,
                    )
                    w.wait(timeout=600)
                    wire += w.wire_bytes
                    inter += getattr(w, "inter_wire_bytes", 0) or 0
                    codec += w.codec_s_box[0]
                    stats = w.quant_stats
                    pipe["wire_busy_s"] += stats["wire_s"]
                    pipe["n_chunks"] = stats["n_chunks"]
                    pipe["effs"].append(stats["overlap_efficiency"])
                    for hop, s in (stats.get("hop_wire_s") or {}).items():
                        pipe["hops"][hop] = pipe["hops"].get(hop, 0.0) + s
                else:
                    aw = pg.allreduce([frag], REDUCE_SUM)
                    aw.wait(timeout=600)
                    # measured per-rank ring egress (reduce-scatter half +
                    # allgather half), reported by the PG itself
                    wire += aw.wire_bytes
            walls[rank] = time.perf_counter() - t0
            wires[rank] = wire
            inters[rank] = inter
            codecs[rank] = codec
            pipes[rank] = pipe
        finally:
            pg.shutdown()

    threads = [
        threading.Thread(target=worker, args=(r,), daemon=True)
        for r in range(world)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=900)
    finally:
        store.shutdown()
    assert len(walls) == world, f"diloco {leg} leg failed (gbps={gbps})"
    out = {
        "sync_s": round(max(walls.values()), 2),
        "wire_gb": round(wires[0] / 1e9, 3),
        "codec_s": round(max(codecs.values()), 2),
    }
    if quantize:
        pipe = pipes[0]
        out["wire_busy_s"] = round(pipe["wire_busy_s"], 2)
        out["chunks_per_fragment"] = pipe["n_chunks"]
        out["overlap_efficiency"] = round(min(pipe["effs"]), 3)
        out["overlap_efficiency_mean"] = round(
            sum(pipe["effs"]) / len(pipe["effs"]), 3
        )
        if pipe["hops"]:
            out["hop_wire_s"] = {
                h: round(s, 2) for h, s in sorted(pipe["hops"].items())
            }
        if any(inters.values()):
            # worst leader's inter-host egress — the bytes the WAN
            # actually carries
            out["inter_wire_gb"] = round(max(inters.values()) / 1e9, 3)
    # per-leg dominant-ledger-contributor: codec vs wire busy time (the
    # unquantized leg has no codec, so its sync wall IS wire)
    wire_est = out.get(
        "wire_busy_s", max(out["sync_s"] - out["codec_s"], 0.0)
    )
    out["dominant"] = "codec" if out["codec_s"] > wire_est else "wire"
    return out


def bench_diloco(model_step_ms: float) -> "Dict[str, Any]":
    """Full outer syncs of flagship-scale pseudogradients over the TCP
    ring, f32 vs int8-quantized — unshaped loopback PLUS token-bucket
    shaped legs at 1 / 0.5 / 0.1 GB/s egress (the DCN bandwidths the
    quantized wire exists for; reference fast path:
    torchft/collectives.py:297-415).  Loopback bandwidth is effectively
    infinite, so only the shaped legs measure the codec-vs-wire tradeoff
    honestly — r4 extrapolated this, r5 measures it.

    Streaming-DiLoCo shape: ~464 M params in 8 fragments, each fragment
    allreduced separately (that IS the streaming schedule — and it caps
    peak memory at one ~232 MB fragment per rank instead of 1.86 GiB).
    Pseudograds are host numpy (the outer sync runs on the DCN host path;
    the device-side Pallas quantize has its own bitwise-equivalence tests
    and here the host codec is the honest leg for host arrays).

    Amortized cost per inner step = sync wall / sync_every; overhead_pct
    prices it against the measured flagship model step.  This is the
    NO-OVERLAP upper bound — the product overlaps fragment syncs with
    inner steps (local_sgd.py fragment_sync_delay), so real overhead is
    lower.  The quantized legs run the chunked software pipeline
    (ops/collectives.py): quantize(chunk i+1) ∥ wire(chunk i) ∥
    reduce(chunk i-1), codec row-blocked across TORCHFT_QUANT_THREADS
    workers — the per-leg ``overlap_efficiency`` / ``chunks_per_fragment``
    / ``wire_busy_s`` fields report how much of the codec actually hid
    behind the wire (docs/benchmarks.md schema notes).
    """
    legs: "Dict[str, Any]" = {}
    # wire_dtype pinned EXPLICITLY on every quantized leg: this bench
    # compares formats by name, so a TORCHFT_QUANT_WIRE env default must
    # not silently swap what the "int8" label measures
    for leg, quantize, wire in (
        ("f32", False, None),
        ("int8", True, "int8"),
        ("fp8_e4m3", True, "fp8_e4m3"),
    ):
        r = _diloco_sync_leg(leg, quantize, None, wire_dtype=wire)
        sync_s = r["sync_s"]
        amortized_ms = sync_s * 1e3 / DILOCO_SYNC_EVERY
        legs[leg] = {
            "sync_s": sync_s,
            "wire_gb": r["wire_gb"],
            "codec_s": r["codec_s"],
            "amortized_ms_per_inner_step": round(amortized_ms, 1),
            "overhead_pct_vs_model_step": round(
                100.0 * amortized_ms / model_step_ms, 1
            ),
        }
        # chunked-pipeline accounting (quantized legs): per-fragment chunk
        # count, summed wire-busy wall, and overlap efficiency (worst +
        # mean fragment) — docs/benchmarks.md schema notes
        for key in (
            "wire_busy_s",
            "chunks_per_fragment",
            "overlap_efficiency",
            "overlap_efficiency_mean",
        ):
            if key in r:
                legs[leg][key] = r[key]
        pipe_note = (
            f", overlap eff {r['overlap_efficiency']:.2f} over "
            f"{r['chunks_per_fragment']} chunks/frag"
            if "overlap_efficiency" in r
            else ""
        )
        log(f"diloco {leg}: one outer sync of {FLAGSHIP_PARAMS/1e6:.0f}M "
            f"params in {sync_s:.2f}s ({r['wire_gb']:.2f} GB wire, "
            f"codec {r['codec_s']:.1f}s{pipe_note}) -> "
            f"{amortized_ms:.0f} ms/inner-step amortized at "
            f"sync_every={DILOCO_SYNC_EVERY} = "
            f"{legs[leg]['overhead_pct_vs_model_step']:.1f}% of a "
            f"{model_step_ms:.0f} ms model step (no-overlap upper bound)")
    # shaped legs: the measured break-even table (VERDICT r4 item 1/2 —
    # every bandwidth-dependent claim measured, none extrapolated)
    shaped: "Dict[str, Any]" = {}
    for gbps in (1.0, 0.5, 0.1):
        f32 = _diloco_sync_leg("f32s", False, gbps)
        i8 = _diloco_sync_leg("int8s", True, gbps, wire_dtype="int8")
        shaped[str(gbps)] = {
            "f32_sync_s": f32["sync_s"],
            "int8_sync_s": i8["sync_s"],
            "int8_codec_s": i8["codec_s"],
            "int8_overlap_efficiency": i8.get("overlap_efficiency"),
            "int8_speedup_x": round(f32["sync_s"] / max(i8["sync_s"], 1e-9), 2),
            "winner": "int8" if i8["sync_s"] < f32["sync_s"] else "f32",
        }
        log(f"diloco shaped @{gbps} GB/s: f32 {f32['sync_s']:.2f}s vs "
            f"int8 {i8['sync_s']:.2f}s (codec {i8['codec_s']:.1f}s) -> "
            f"{shaped[str(gbps)]['winner']} wins "
            f"{shaped[str(gbps)]['int8_speedup_x']:.2f}x")
    legs["shaped"] = shaped
    # diloco.int8_device (ROADMAP item 1): the on-chip Pallas quantize
    # path priced on real hardware — fragment born on device, quantized
    # in one kernel launch, int8 payload + row scales D2H-copied per
    # chunk into the wire pipeline.  TPU only: interpret mode on CPU
    # prices the emulator, not the design point (parity is tested in
    # tier-1 instead).
    import jax as _jax

    if _jax.default_backend() == "tpu":
        try:
            r = _diloco_sync_leg(
                "int8_device", True, None, repeats=1, wire_dtype="int8",
                n_fragments=2, device=True,
            )
            scale = DILOCO_FRAGMENTS / 2
            amortized_ms = r["sync_s"] * scale * 1e3 / DILOCO_SYNC_EVERY
            legs["int8_device"] = {
                **r,
                "fragments_run": 2,
                "amortized_ms_per_inner_step": round(amortized_ms, 1),
                "overhead_pct_vs_model_step": round(
                    100.0 * amortized_ms / model_step_ms, 1
                ),
            }
            log(f"diloco int8_device: {legs['int8_device']}")
        except Exception as e:  # noqa: BLE001 - never cost the host legs
            log(f"diloco int8_device leg failed: {e!r}")
            legs["int8_device"] = {"error": repr(e)}
    else:
        legs["int8_device"] = {"skipped": "no TPU backend"}
    legs["wire_reduction_x"] = round(
        legs["f32"]["wire_gb"] / max(legs["int8"]["wire_gb"], 1e-9), 2
    )
    legs["params_m"] = round(FLAGSHIP_PARAMS / 1e6, 1)
    legs["fragments"] = DILOCO_FRAGMENTS
    legs["sync_every"] = DILOCO_SYNC_EVERY
    return legs


# ---------------------------------------------------------------------------
# 3b. WAN sweep: flat vs hierarchical int8 DiLoCo at simulated RTT
# ---------------------------------------------------------------------------

WAN_WORLD = 4            # 2 hosts x 2 ranks
WAN_TOPOLOGY = "hosts:2"
WAN_GBPS = 0.5           # per-rank shaped egress during the sweep
WAN_FRAGMENTS = 2        # flagship-scale fragments per leg (wall bound)
WAN_RTTS_MS = (0.0, 10.0, 50.0)


def bench_wan(model_step_ms: float) -> "Dict[str, Any]":
    """The WAN-grade leg (ROADMAP item 3): flat-ring vs hierarchical
    int8 DiLoCo outer sync swept over simulated inter-host RTT.

    Both legs run 4 thread-ranks laid out as 2 hosts x 2
    (``TORCHFT_TOPOLOGY=hosts:2`` is set process-wide so the WIRE model
    charges ``rtt_ms`` only on messages crossing the host boundary for
    BOTH schedules — same physical topology, different reduction plan).
    The flat leg pins ``topology="flat"`` (today's alltoall/allgather
    interleave, 2*(w-1) serialized inter-host-bearing ops per chunk);
    the hierarchical leg runs the synthesized plan (2 inter-host
    sendrecv per chunk).  At 0 ms they should be comparable; at WAN RTT
    the flat ring's serialized hops dominate and hierarchical must win
    — the acceptance margin the compact summary carries, next to the
    per-hop wire telemetry and inter-host byte counts.

    Also re-validates the DiLoCo overhead claim at RTT: each leg's sync
    wall scales to a full ``DILOCO_FRAGMENTS``-fragment outer sync and
    amortizes over ``DILOCO_SYNC_EVERY`` inner steps against the
    flagship model step.
    """
    import os as _os

    # the WAN knobs go through the ENV (not ctor args) so every PG a
    # leg constructs — and anything else that resolves the wire model —
    # sees one consistent configuration per sweep point
    prior = {
        k: _os.environ.get(k)
        for k in ("TORCHFT_TOPOLOGY", "TORCHFT_WIRE_GBPS",
                  "TORCHFT_WIRE_RTT_MS")
    }
    _os.environ["TORCHFT_TOPOLOGY"] = WAN_TOPOLOGY
    _os.environ["TORCHFT_WIRE_GBPS"] = str(WAN_GBPS)
    try:
        out: "Dict[str, Any]" = {
            "world": WAN_WORLD,
            "topology": WAN_TOPOLOGY,
            "gbps": WAN_GBPS,
            "fragments_per_leg": WAN_FRAGMENTS,
        }
        scale = DILOCO_FRAGMENTS / WAN_FRAGMENTS
        for rtt in WAN_RTTS_MS:
            _os.environ["TORCHFT_WIRE_RTT_MS"] = str(rtt)
            flat = _diloco_sync_leg(
                "wan_flat", True, None, wire_dtype="int8",
                world=WAN_WORLD, rtt_ms=None, topology="flat",
                n_fragments=WAN_FRAGMENTS,
            )
            hier = _diloco_sync_leg(
                "wan_hier", True, None, wire_dtype="int8",
                world=WAN_WORLD, rtt_ms=None, topology=WAN_TOPOLOGY,
                n_fragments=WAN_FRAGMENTS,
            )
            speedup = flat["sync_s"] / max(hier["sync_s"], 1e-9)
            leg = {
                "flat_sync_s": flat["sync_s"],
                "hier_sync_s": hier["sync_s"],
                "hier_speedup_x": round(speedup, 2),
                "winner": "hier" if hier["sync_s"] < flat["sync_s"] else "flat",
                "flat_inter_wire_gb": flat.get("inter_wire_gb"),
                "hier_inter_wire_gb": hier.get("inter_wire_gb"),
                "hier_hop_wire_s": hier.get("hop_wire_s"),
                "flat_hop_wire_s": flat.get("hop_wire_s"),
                # overhead re-validation at this RTT (no-overlap upper
                # bound, like bench_diloco's table)
                "flat_overhead_pct_vs_model_step": round(
                    100.0 * flat["sync_s"] * scale * 1e3
                    / DILOCO_SYNC_EVERY / model_step_ms, 1
                ),
                "hier_overhead_pct_vs_model_step": round(
                    100.0 * hier["sync_s"] * scale * 1e3
                    / DILOCO_SYNC_EVERY / model_step_ms, 1
                ),
            }
            out[f"rtt_{rtt:g}ms"] = leg
            log(f"wan @rtt={rtt:g}ms {WAN_GBPS}GB/s: flat {flat['sync_s']:.2f}s "
                f"vs hier {hier['sync_s']:.2f}s -> {leg['winner']} wins "
                f"{leg['hier_speedup_x']:.2f}x | hier hops "
                f"{hier.get('hop_wire_s')} | inter GB "
                f"flat={flat.get('inter_wire_gb')} hier={hier.get('inter_wire_gb')}")
        return out
    finally:
        for k, v in prior.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v


# ---------------------------------------------------------------------------
# 4. flagship model MFU on the attached accelerator
# ---------------------------------------------------------------------------

# bf16 peak TFLOP/s per chip by device kind (public spec sheets).
_PEAK_TFLOPS = (
    ("v6", 918.0),       # Trillium
    ("v5p", 459.0),
    ("v5 lite", 197.0),  # v5e device_kind is "TPU v5 lite"
    ("v5e", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def _peak_flops(device_kind: str) -> "Optional[float]":
    kind = device_kind.lower()
    for key, tf in _PEAK_TFLOPS:
        if key in kind:
            return tf * 1e12
    return None


def _model_flops_per_step(cfg, batch: int, seq: int) -> "Dict[str, float]":
    """Model FLOPs (fwd+bwd = 3x fwd) per optimizer step.

    matmul params N: block weights + tied head (embedding gather is not a
    matmul; the tied head IS one).  attention: QK^T and AV are each
    2*B*T^2*d fwd (full causal scores — the kernel does not skip the
    masked half), x3 for bwd.  Remat recompute is deliberately NOT
    counted: MFU is defined over model FLOPs (vs HFU).
    """
    e, f, l = cfg.d_model, cfg.d_ff, cfg.n_layers
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    n_block = l * (e * nh * hd + 2 * e * nkv * hd + nh * hd * e + 3 * e * f)
    n_head = cfg.vocab_size * e
    tokens = batch * seq
    mm = 6 * (n_block + n_head) * tokens
    attn = 3 * (2 * 2 * batch * seq * seq * e) * l
    return {
        "params_matmul": float(n_block + n_head),
        "flops": float(mm + attn),
        "tokens": float(tokens),
    }


def _ft_around_model_step(
    multi_step, state, tokens, step_s: float,
    steps: int = 6, warmup: int = 2,
) -> "Dict[str, Any]":
    """FT overhead around the REAL on-chip model step (VERDICT r03 #2).

    Runs the flagship ``multi_step`` inside the full Manager per-step
    protocol (world-size-1 ring: quorum RPC + managed allreduce of a real
    on-device proxy leaf + commit vote) and prices the protocol against
    the bare fused-dispatch step time measured by the difference method.

    Measurement is the phase-sum estimator (``phase_times`` deltas), not a
    twin wall-clock ratio — the loop's wall time is tunnel-RTT-bound
    (~200 ms/dispatch under the driver) and means nothing.  The headline
    ``model_overhead_pct`` counts quorum_wait + commit + host_sync: the
    phases a real pod pays per step.  ``proxy_ring_ms`` (the managed
    allreduce of a real jax-array leaf, incl. its device→host
    materialisation on the PG worker) is reported separately because on
    the driver it is dominated by the tunnel round trip — on-pod that hop
    is PCIe-microseconds.  The proxy leaf is a real output of the step
    (so the jax-array host path of manager.allreduce is exercised
    end-to-end), sized token-scale rather than full-grad-scale because
    full grads cannot cross the driver tunnel (and the DCN-scale sync
    cost is priced at full scale by bench_diloco).
    """
    import jax

    # a real on-device leaf of the step output as the allreduce proxy:
    # remember its flat index so each iteration reduces the leaf freshly
    # produced by THAT step (not a stale buffer)
    all_leaves = jax.tree_util.tree_leaves(state[0])
    proxy_leaf = min(
        (x for x in all_leaves if x.ndim >= 1),
        key=lambda x: abs(x.size - 2048),
    )
    proxy_idx = next(i for i, x in enumerate(all_leaves) if x is proxy_leaf)

    lighthouse = LighthouseServer(
        min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=1000
    )
    manager = None
    acc: "Dict[str, float]" = {}
    phase_snap: "Dict[str, float]" = {}
    ring_ms: "List[float]" = []
    try:
        manager = Manager(
            pg=ProcessGroupTCP(timeout=30.0),
            min_replica_size=1,
            load_state_dict=lambda sd: None,
            state_dict=lambda: {"ok": np.zeros(1, np.float32)},
            lighthouse_addr=lighthouse.address(),
            replica_id="model_ft",
            group_rank=0,
            group_world_size=1,
            use_async_quorum=True,
            timeout=30.0,
            quorum_timeout=30.0,
        )
        for step in range(steps):
            manager.start_quorum()
            # donation contract: the step consumes state and returns the
            # new buffers; rebind (the bare timing loop does the same)
            p2, s2, loss = multi_step(state[0], state[1], tokens, 1)
            state[0], state[1] = p2, s2
            proxy = jax.tree_util.tree_leaves(p2)[proxy_idx]
            # sync the dispatch the same way the bare measurement does, so
            # the protocol phases below are measured with the device idle
            assert np.isfinite(float(loss))
            work = manager.allreduce({"g": proxy})
            work.wait(timeout=30)
            committed = manager.should_commit()
            assert committed, "world-1 FT step failed to commit"
            phase, phase_snap = _phase_delta(manager, phase_snap)
            if step >= warmup:
                ring_ms.append(phase.get("ring", 0.0) * 1e3)
                for k, v in phase.items():
                    acc[k] = acc.get(k, 0.0) + v
    finally:
        if manager is not None:
            manager.shutdown()
        lighthouse.shutdown()

    n = steps - warmup
    protocol_ms = (
        acc.get("quorum_wait", 0.0) + acc.get("commit", 0.0)
        + acc.get("host_sync", 0.0)
    ) * 1e3 / n
    out = {
        "protocol_ms_per_step": round(protocol_ms, 3),
        "model_overhead_pct": round(100.0 * protocol_ms / (step_s * 1e3), 2),
        "proxy_ring_ms": round(statistics.median(ring_ms), 1),
        "phases_ms_per_step": {
            k: round(v * 1e3 / n, 3) for k, v in sorted(acc.items())
        },
    }
    log(f"model FT overhead: protocol +{protocol_ms:.2f} ms on a "
        f"{step_s*1e3:.0f} ms step -> {out['model_overhead_pct']:.2f}% "
        f"(proxy ring {out['proxy_ring_ms']:.0f} ms, tunnel-RTT-bound "
        f"under the driver)")
    return out


def bench_model() -> "Dict[str, Any]":
    import jax
    import jax.numpy as jnp
    import optax

    from torchft_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        make_train_step,
    )

    dev = jax.devices()[0]
    platform = dev.platform
    on_tpu = platform == "tpu"

    if on_tpu:
        # ~465M params, shaped for the v5e MXU (d_model 1536, head_dim 256
        # — large aligned matmul tiles; hd 64/96 measured 10+ MFU points
        # lower), bf16 compute, Pallas flash attention.
        base = dict(
            vocab_size=32000, d_model=1536, n_heads=6, n_kv_heads=3,
            d_ff=4096, n_layers=16, max_seq_len=1024,
        )
        seq, timed_steps = 1024, 16
        # (attn, remat_policy, batch): flash + dots-policy remat + donated
        # step buffers measured best (57.1% MFU vs 49 for full remat
        # without donation); full-remat and dense fallbacks in case a
        # future driver chip regresses the kernel or the memory headroom.
        attempts = [
            ("flash", "dots", 8), ("flash", "full", 8), ("dense", "full", 8)
        ]
    else:
        base = dict(
            vocab_size=512, d_model=128, n_heads=4, n_kv_heads=2,
            d_ff=384, n_layers=2, max_seq_len=128,
        )
        seq, timed_steps = 128, 5
        attempts = [("flash", "full", 2)]

    def run(attn: str, remat_policy: str, batch: int) -> "Dict[str, Any]":
        import functools

        import jax.numpy as jnp
        from jax import lax

        from torchft_tpu.models.transformer import loss_fn

        cfg = TransformerConfig(
            remat=on_tpu, remat_policy=remat_policy, attn_impl=attn, **base
        )
        optimizer = optax.adamw(3e-4)
        # One dispatch runs n fused train steps (dynamic trip count -> one
        # compile).  Under the driver the chip sits behind a tunnel with
        # ~200 ms RTT per dispatch and no cross-dispatch pipelining
        # (measured; and its block_until_ready returns early), so per-step
        # time comes from the DIFFERENCE between an n-step and a 1-step
        # dispatch, each synced by fetching the scalar loss — the RTT and
        # dispatch cost cancel.
        # donate_argnums: the 5.6 GB params+adamw carry would otherwise be
        # double-buffered across the dispatch (in + out live at once) —
        # donation alone measured +5 MFU points at B8 by relieving that
        # HBM pressure; callers rebind to the returned state each call.
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def multi_step(params, opt_state, tokens, n):
            def body(i, carry):
                params, opt_state, _ = carry
                loss, grads = jax.value_and_grad(loss_fn)(
                    params, tokens, cfg, None
                )
                updates, opt_state = optimizer.update(grads, opt_state, params)
                params = jax.tree_util.tree_map(
                    lambda p, u: p + u, params, updates
                )
                return (params, opt_state, loss)
            init = (params, opt_state, jnp.zeros((), jnp.float32))
            return lax.fori_loop(0, n, body, init)

        # Init params/opt-state/batch ON device: only PRNG seeds cross the
        # host<->device link.
        params = jax.jit(lambda k: init_params(k, cfg))(jax.random.PRNGKey(0))
        opt_state = jax.jit(optimizer.init)(params)
        tokens = jax.jit(
            lambda k: jax.random.randint(k, (batch, seq), 0, cfg.vocab_size)
        )(jax.random.PRNGKey(1))
        state = [params, opt_state]

        def timed(n: int) -> float:
            t0 = time.perf_counter()
            p2, s2, loss = multi_step(state[0], state[1], tokens, n)
            assert np.isfinite(float(loss)), "non-finite loss"
            dt = time.perf_counter() - t0
            state[0], state[1] = p2, s2
            return dt

        t_c0 = time.perf_counter()
        timed(1)  # compile + warm
        compile_s = time.perf_counter() - t_c0
        # best-of-3 for each to cut tunnel-latency variance
        t_one = min(timed(1) for _ in range(3))
        t_many = min(timed(1 + timed_steps) for _ in range(3))
        step_s = (t_many - t_one) / timed_steps

        fl = _model_flops_per_step(cfg, batch, seq)
        peak = _peak_flops(dev.device_kind) if on_tpu else None
        achieved = fl["flops"] / step_s
        try:
            ft = _ft_around_model_step(multi_step, state, tokens, step_s)
        except Exception as e:  # noqa: BLE001 - never cost the MFU number
            log(f"model FT-overhead leg failed: {e!r}")
            ft = {"error": repr(e)}
        out = {
            "platform": platform,
            "device_kind": dev.device_kind,
            "config": (
                f"d{cfg.d_model} L{cfg.n_layers} h{cfg.n_heads}/{cfg.n_kv_heads} "
                f"ff{cfg.d_ff} V{cfg.vocab_size} B{batch} T{seq} "
                f"{attn} remat={remat_policy if cfg.remat else 'off'} donated"
            ),
            "params_matmul_m": round(fl["params_matmul"] / 1e6, 1),
            "step_ms": round(step_s * 1e3, 2),
            "compile_s": round(compile_s, 1),
            "tokens_per_s": round(fl["tokens"] / step_s),
            "tflops_per_s": round(achieved / 1e12, 1),
            "mfu_pct": round(100.0 * achieved / peak, 1) if peak else None,
            "ft": ft,
        }
        log(f"model bench: {out}")
        return out

    import gc

    last_err: "Optional[str]" = None
    for attn, remat, batch in attempts:
        # An OOM crash can wedge the device into FAILED_PRECONDITION for a
        # little while (measured under the driver tunnel); give each config
        # a settle-and-retry before moving to the next.
        for retry in range(3):
            try:
                return run(attn, remat, batch)
            except Exception as e:  # noqa: BLE001 - OOM etc: try next config
                log(f"model bench {attn} remat={remat} B{batch} failed: {e!r}")
                last_err = repr(e)
                retryable = "FAILED_PRECONDITION" in repr(e)
            # The raised exception's traceback pins the failed attempt's
            # device buffers via frame refs; collect before the next try.
            gc.collect()
            if not retryable:
                break
            time.sleep(15)
    raise RuntimeError(f"model bench failed in all configs: {last_err}")


# ---------------------------------------------------------------------------
# compact tail summary
# ---------------------------------------------------------------------------

# The driver keeps only the LAST 2000 bytes of stdout; the full result
# line alone is several KB, so its head (with the primary metric) was
# truncated out of r5's capture.  The compact summary printed after it
# must always fit the tail window with room for the trailing newline.
# ---------------------------------------------------------------------------
# serving: fan-out weight distribution under churn (ISSUE 12)
# ---------------------------------------------------------------------------

SERVING_SERVERS = 4
SERVING_CLIENTS = 8
SERVING_RUN_S = 12.0
SERVING_LEAVES = 8
SERVING_LEAF_ELEMS = 64 * 1024  # 8 x 64k fp32 = 2 MB payload


def bench_serving() -> "Dict[str, Any]":
    """Weight-serving tier under churn: a publisher streams versioned
    int8 payloads through a lighthouse-synthesized fan-out tree of
    ``SERVING_SERVERS`` relays while ``SERVING_CLIENTS`` stub clients
    fetch the latest version in a loop; mid-run the chaos kill takes a
    TREE NODE down while fetches are in flight.  Headlines: sustained
    published+delivered checkpoints/sec, client fetch p50/p99, failover
    count, and the bitwise-identity check after failover (a client's
    post-kill fetch must decode byte-identical to the published
    payload).  docs/architecture.md "Weight-serving tier"."""
    from torchft_tpu.ops import quantization as q
    from torchft_tpu.serving import (
        ServingClient,
        ServingReplica,
        WeightPublisher,
    )

    rng = np.random.RandomState(7)
    base = {
        f"layer{i}": rng.randn(SERVING_LEAF_ELEMS).astype(np.float32)
        for i in range(SERVING_LEAVES)
    }
    payload_bytes = sum(a.nbytes for a in base.values())

    lh = LighthouseServer(
        min_replicas=1, heartbeat_timeout_ms=1000, quorum_tick_ms=50,
        serving_fanout=2,
    )
    pub = WeightPublisher(
        lh.address(), wire="int8", fragments=2, heartbeat_interval=0.1
    )
    reps = [
        ServingReplica(
            lh.address(), replica_id=f"bench{i}", poll_interval=0.05,
            fetch_timeout=10.0,
        )
        for i in range(SERVING_SERVERS)
    ]
    stop = threading.Event()
    lat: "List[float]" = []
    errors: "List[str]" = []
    lock = threading.Lock()
    published_states: "Dict[int, Dict[str, np.ndarray]]" = {}

    def _publish(vi: int) -> int:
        state = {k: a + np.float32(vi) for k, a in base.items()}
        v = pub.publish(state)
        with lock:
            published_states[v] = state
            while len(published_states) > 8:
                published_states.pop(min(published_states))
        return v

    def _client_loop(i: int) -> None:
        c = ServingClient(lh.address(), plan_ttl=0.2, client_id=str(i))
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                c.fetch(timeout=15)
                with lock:
                    lat.append(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001 - tallied
                with lock:
                    errors.append(repr(e))
            time.sleep(0.01)
        c.close()

    from torchft_tpu.utils import metrics as _m

    def _failover_count() -> float:
        return (
            _m.SERVING_FAILOVERS.labels(role="client").get()
            + _m.SERVING_FAILOVERS.labels(role="relay").get()
        )

    failovers0 = _failover_count()
    kill_info: "Dict[str, Any]" = {}
    bitwise_ok = False
    try:
        t_pub0 = time.perf_counter()
        vi = _publish(0)
        threads = [
            threading.Thread(target=_client_loop, args=(i,), daemon=True)
            for i in range(SERVING_CLIENTS)
        ]
        for t in threads:
            t.start()
        t_end = time.monotonic() + SERVING_RUN_S
        killed = False
        while time.monotonic() < t_end:
            vi = _publish(vi)
            if not killed and time.monotonic() > t_end - SERVING_RUN_S / 2:
                # chaos: kill a live TREE NODE mid-run, fetches in flight
                cl = ServingClient(lh.address(), plan_ttl=0.0)
                plan = cl.plan(refresh=True)
                cl.close()
                interior = [
                    n for n in plan["nodes"] if n["children"] > 0
                ] or plan["nodes"]
                victim_id = interior[0]["replica_id"]
                victim = next(
                    r for r in reps if r.replica_id() == victim_id
                )
                t_kill = time.perf_counter()
                victim.shutdown()
                killed = True
                kill_info = {
                    "victim": victim_id,
                    "victim_children": interior[0]["children"],
                    "at_version": vi,
                }
            time.sleep(0.1)
        publish_wall = time.perf_counter() - t_pub0
        published = pub.latest_version()

        # post-kill bitwise check: fetch the latest version through the
        # surviving tree and compare against the int8 round trip of the
        # exact published state
        vc = ServingClient(lh.address(), plan_ttl=0.0, client_id="verify")
        state, got = vc.fetch(timeout=30)
        vc.close()
        with lock:
            src = published_states.get(got)
        if src is not None:
            bitwise_ok = all(
                np.array_equal(
                    state[k],
                    q.dequantize(
                        *q.quantize(a.reshape(1, -1), q.WIRE_INT8),
                        a.shape,
                        np.dtype(np.float32),
                    ),
                )
                for k, a in src.items()
            )
        stop.set()
        for t in threads:
            t.join(timeout=30)
    finally:
        stop.set()
        for r in reps:
            try:
                r.shutdown()
            except Exception:  # noqa: BLE001 - victim already down
                pass
        pub.shutdown()
        lh.shutdown()

    failovers = _failover_count() - failovers0
    lat.sort()

    def _pct(p: float) -> "Optional[float]":
        if not lat:
            return None
        return round(lat[min(int(len(lat) * p), len(lat) - 1)] * 1000, 1)

    return {
        "servers": SERVING_SERVERS,
        "clients": SERVING_CLIENTS,
        "payload_mb": round(payload_bytes / 2**20, 2),
        "wire": "int8",
        "published_cps": round(published / publish_wall, 2),
        "delivered_total": len(lat),
        "delivered_cps": round(len(lat) / publish_wall, 2),
        "fetch_p50_ms": _pct(0.50),
        "fetch_p99_ms": _pct(0.99),
        "failed_fetches": len(errors),
        "failovers": int(failovers),
        "kill": kill_info,
        "bitwise_identical_after_failover": bitwise_ok,
    }


# ---------------------------------------------------------------------------
# serving depth axis (ISSUE 14): publish->leaf latency, flat vs streaming
# ---------------------------------------------------------------------------

SERVING_DEPTHS = (1, 2, 3)
SERVING_DEPTH_RTTS_MS = (0.0, 10.0, 50.0)
SERVING_DEPTH_GBPS = 0.02       # per-SOURCE uplink (serving/wire.py)
SERVING_DEPTH_BURST_MB = 0.25
SERVING_DEPTH_LEAVES = 8        # == fragments: one leaf per fragment
SERVING_DEPTH_LEAF_ELEMS = 128 * 1024  # 8 x 512 KB fp32 = 4 MB payload
SERVING_DEPTH_PUBLISHES = 4     # measured publishes per config (+1 warm)
SERVING_DEPTH_PARALLEL = 8      # in-flight frag window: overlap all RTTs


def _staged_raw_frags(transport, step: int) -> "Dict[str, bytes]":
    """Raw wire bytes of every ``frag:*`` payload staged at ``step`` —
    the bitwise ground truth both data planes must serve verbatim."""
    from torchft_tpu.checkpointing import serialization as _ser

    out: "Dict[str, bytes]" = {}
    with transport._staged_lock.r_lock(timeout=10.0):
        rec = transport._staged.get(step)
        sd = dict(rec.sd) if rec is not None else {}
    for k, v in sd.items():
        if isinstance(k, str) and k.startswith("frag:"):
            mv = _ser.raw_view(v)
            if mv is not None:
                out[k] = bytes(mv)
    return out


def _serving_depth_trial(
    base: "Dict[str, np.ndarray]", depth: int, stream: bool,
    plane_info: "Optional[Dict[str, Any]]" = None,
    warm_publishes: int = 1,
) -> "Tuple[List[float], List[float]]":
    """One (depth, mode) config: a fanout-1 CHAIN of ``depth`` relays;
    returns (full-change publish->leaf latencies, single-fragment delta
    latencies, publish-stamp staleness at leaf convergence) in seconds.
    publish->leaf = publish() call to the LEAF relay holding the
    version complete.

    When ``plane_info`` is a dict (the native data-plane comparison,
    ISSUE 20), it is filled with acceptance evidence before teardown:
    ``bitwise_payload`` (the leaf's staged fragment bytes == the
    publisher's, byte for byte), ``digest_rejects`` (provenance
    ``mismatch`` hops — a failed fetch the chain had to heal around),
    ``native_fallbacks`` (raw fetches that fell off the native plane
    mid-trial), and the chain-wide native ``serves``/``serve_copies``
    counters proving which plane actually moved the bytes."""
    from torchft_tpu.checkpointing import provenance as _prov
    from torchft_tpu.serving import ServingReplica, WeightPublisher
    from torchft_tpu.utils import flightrecorder as _flightrec

    _prov.PROV.reset()  # per-trial hop ring: versions restart at 1
    fallbacks0 = sum(
        1
        for r in _flightrec.snapshot()
        if r.get("op") == "fragment.native_fallback"
    )
    lh = LighthouseServer(
        min_replicas=1, heartbeat_timeout_ms=3000, quorum_tick_ms=50,
        serving_fanout=1,
    )
    pub = WeightPublisher(
        lh.address(), wire="f32", fragments=len(base),
        heartbeat_interval=0.05,
    )
    reps = [
        ServingReplica(
            lh.address(), replica_id=f"depth{i:02d}", poll_interval=0.02,
            fetch_timeout=60.0, stream=stream,
        )
        for i in range(depth)
    ]
    leaf = reps[-1]
    full: "List[float]" = []
    delta: "List[float]" = []
    stale: "List[float]" = []
    frag_stale: "List[float]" = []
    try:
        # wait for the full chain to form before measuring — and fail
        # LOUDLY if it never does: measuring a shallower tree would
        # silently mislabel the depth axis the headline is judged on
        cl = LighthouseClient(lh.address())
        t_end = time.monotonic() + 20
        while True:
            plan = cl.serving_plan()
            if sorted(n["depth"] for n in plan["nodes"]) == list(
                range(depth)
            ):
                break
            if time.monotonic() > t_end:
                cl.close()
                raise TimeoutError(
                    f"serving depth bench: chain of depth {depth} never "
                    f"formed (plan depths: "
                    f"{sorted(n['depth'] for n in plan['nodes'])})"
                )
            time.sleep(0.05)
        cl.close()

        def _publish_and_wait(state: "Dict[str, np.ndarray]") -> float:
            t0 = time.perf_counter()
            v = pub.publish(state)
            t_dead = time.monotonic() + 120
            while leaf.version() < v:
                if time.monotonic() > t_dead:
                    raise TimeoutError(
                        f"leaf never converged to v{v} "
                        f"(depth={depth} stream={stream})"
                    )
                time.sleep(0.005)
            dt = time.perf_counter() - t0
            # staleness-ledger cell: wall at leaf convergence minus the
            # manifest publish stamp — the publish->leaf measurement the
            # lighthouse's /serving.json staleness_ms rows report live
            v_ms = pub.latest_version_ms()
            if v_ms > 0:
                stale.append(max(time.time() - v_ms / 1e3, 0.0))
            # per-FRAGMENT staleness spread (ISSUE 18): the LAST relay
            # hold per frag id for this version is the deepest node to
            # stage it; its ring stamp minus the manifest publish stamp
            # is that fragment's individual publish->stage staleness
            last_hold: "Dict[str, Dict[str, Any]]" = {}
            for r in _prov.PROV.hop_records():
                if (
                    r.get("op") == "fragment.hold"
                    and r.get("version") == v
                    and r.get("role") == "relay"
                ):
                    last_hold[str(r.get("frag"))] = r
            for r in last_hold.values():
                if int(r.get("version_ms") or 0) > 0:
                    frag_stale.append(
                        max(
                            r["end_ns"] / 1e6 - r["version_ms"], 0.0
                        )
                        / 1e3
                    )
            return dt

        for t in range(SERVING_DEPTH_PUBLISHES + warm_publishes):
            # every leaf changes: the full payload moves each publish
            state = {k: a + np.float32(t + 1) for k, a in base.items()}
            dt = _publish_and_wait(state)
            # warm publishes prime the chain/tree; callers measuring
            # steady-state serving (the native data-plane comparison)
            # warm a full version window so the one-time window-fill
            # transient — fresh buffer allocation + first-touch page
            # faults on every node, in BOTH planes — is excluded
            if t >= warm_publishes:
                full.append(dt)
        for t in range(2):
            # one leaf changes: the delta path moves ~1 fragment/hop
            state["layer0"] = base["layer0"] + np.float32(100 + t)
            delta.append(_publish_and_wait(dict(state)))
        if plane_info is not None:
            # acceptance evidence (ISSUE 20): compare the LEAF's staged
            # fragment bytes against the publisher's for the final
            # version — the relay chain re-serves wire bytes verbatim,
            # so any divergence is a data-plane corruption
            v = leaf.version()
            want = _staged_raw_frags(pub._transport, v)
            got = _staged_raw_frags(leaf._transport, v)
            common = sorted(set(want) & set(got))
            plane_info["bitwise_payload"] = bool(
                len(common) >= len(base)
                and set(want) == set(got)
                and all(want[k] == got[k] for k in common)
            )
            plane_info["digest_rejects"] = sum(
                1
                for r in _prov.PROV.hop_records()
                if r.get("verdict") == "mismatch"
            )
            plane_info["native_fallbacks"] = (
                sum(
                    1
                    for r in _flightrec.snapshot()
                    if r.get("op") == "fragment.native_fallback"
                )
                - fallbacks0
            )
            serves = copies = 0
            for tr in [pub._transport] + [r._transport for r in reps]:
                srv = getattr(tr, "_frag_native", None)
                if srv is not None:
                    c = srv.counters()
                    serves += int(c.get("serves", 0))
                    copies += int(c.get("serve_copies", 0))
            plane_info["native_serves"] = serves
            plane_info["native_serve_copies"] = copies
    finally:
        for r in reps:
            try:
                r.shutdown()
            except Exception:  # noqa: BLE001
                pass
        pub.shutdown()
        lh.shutdown()
    return full, delta, stale, frag_stale


def bench_serving_depth() -> "Dict[str, Any]":
    """The streaming-relay acceptance leg (ISSUE 14): publish->leaf
    propagation latency over a fanout-1 relay CHAIN at depth {1,2,3} x
    simulated WAN RTT {0,10,50} ms, whole-payload store-and-forward
    (``flat``) vs cut-through fragment streaming (``stream``).  Every
    measured publish changes EVERY leaf, so the full payload moves; the
    ``delta`` rows change one leaf, so streaming relays move ~one
    fragment per hop.  Headline: the depth-3 / 50 ms speedup (flat
    store-and-forward costs ~depth x T_payload; cut-through costs
    ~T_payload + depth x T_frag)."""
    import os as _os

    rng = np.random.RandomState(11)
    base = {
        f"layer{i}": rng.randn(SERVING_DEPTH_LEAF_ELEMS).astype(np.float32)
        for i in range(SERVING_DEPTH_LEAVES)
    }
    payload_bytes = sum(a.nbytes for a in base.values())
    prior = {
        k: _os.environ.get(k)
        for k in ("TORCHFT_WIRE_RTT_MS", "TORCHFT_WIRE_GBPS",
                  "TORCHFT_WIRE_BURST_MB", "TORCHFT_TOPOLOGY",
                  "TORCHFT_SERVING_PARALLEL")
    }
    # flat/unset topology: every fetch crosses the WAN boundary; each
    # serving node's uplink is its own token bucket (per-source model)
    _os.environ.pop("TORCHFT_TOPOLOGY", None)
    _os.environ["TORCHFT_WIRE_GBPS"] = str(SERVING_DEPTH_GBPS)
    _os.environ["TORCHFT_WIRE_BURST_MB"] = str(SERVING_DEPTH_BURST_MB)
    # one in-flight slot per fragment: the per-message RTTs of a hop
    # overlap into ~one RTT instead of ceil(F/K) batches
    _os.environ["TORCHFT_SERVING_PARALLEL"] = str(SERVING_DEPTH_PARALLEL)

    def _pcts(lat: "List[float]") -> "Tuple[float, float]":
        lat = sorted(lat)
        p50 = lat[len(lat) // 2]
        return round(p50 * 1e3, 1), round(lat[-1] * 1e3, 1)

    out: "Dict[str, Any]" = {
        "payload_mb": round(payload_bytes / 2**20, 2),
        "fragments": SERVING_DEPTH_LEAVES,
        "gbps_per_uplink": SERVING_DEPTH_GBPS,
        "publishes": SERVING_DEPTH_PUBLISHES,
    }
    try:
        for rtt in SERVING_DEPTH_RTTS_MS:
            _os.environ["TORCHFT_WIRE_RTT_MS"] = str(rtt)
            leg: "Dict[str, Any]" = {}
            for depth in SERVING_DEPTHS:
                flat_full, _, _, _ = _serving_depth_trial(
                    base, depth, False
                )
                stream_full, stream_delta, stream_stale, stream_fstale = (
                    _serving_depth_trial(base, depth, True)
                )
                f50, f99 = _pcts(flat_full)
                s50, s99 = _pcts(stream_full)
                d50, _d99 = _pcts(stream_delta)
                leg[f"d{depth}"] = {
                    "flat_p50_ms": f50, "flat_p99_ms": f99,
                    "stream_p50_ms": s50, "stream_p99_ms": s99,
                    "stream_delta_p50_ms": d50,
                    "stream_speedup_x": round(f50 / max(s50, 1e-9), 2),
                }
                if stream_stale:
                    leg[f"d{depth}"]["stream_staleness_p50_ms"] = _pcts(
                        stream_stale
                    )[0]
                if stream_fstale:
                    # per-fragment staleness spread (ISSUE 18): the
                    # provenance vector's per-frag publish->stage stamps
                    fp50, fmax = _pcts(stream_fstale)
                    leg[f"d{depth}"]["frag_staleness_p50_ms"] = fp50
                    leg[f"d{depth}"]["frag_staleness_max_ms"] = fmax
                log(
                    f"serving depth d={depth} rtt={rtt}ms: flat p50 "
                    f"{f50}ms stream p50 {s50}ms delta p50 {d50}ms"
                )
            out[f"rtt_{int(rtt)}ms"] = leg
        d3 = out.get("rtt_50ms", {}).get("d3", {})
        out["d3_rtt50_speedup_x"] = d3.get("stream_speedup_x")
        out["d3_rtt50_flat_p50_ms"] = d3.get("flat_p50_ms")
        out["d3_rtt50_stream_p50_ms"] = d3.get("stream_p50_ms")
        out["d3_rtt50_delta_p50_ms"] = d3.get("stream_delta_p50_ms")
        out["d3_rtt50_staleness_p50_ms"] = d3.get("stream_staleness_p50_ms")
        out["d3_rtt50_frag_staleness_p50_ms"] = d3.get(
            "frag_staleness_p50_ms"
        )
        out["d3_rtt50_frag_staleness_max_ms"] = d3.get(
            "frag_staleness_max_ms"
        )
        out["winner"] = (
            "stream"
            if (d3.get("stream_speedup_x") or 0) > 1.0
            else "flat"
        )
    finally:
        for k, v in prior.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
    return out


# ---------------------------------------------------------------------------
# native zero-copy fragment data plane (ISSUE 20): native vs python serve
# ---------------------------------------------------------------------------

SERVING_NATIVE_DEPTHS = (3, 4)
SERVING_NATIVE_RTTS_MS = (0.0, 10.0)  # 0 ms = the headline cell; 10 ms
#                                       shows where the WAN re-dominates
SERVING_NATIVE_GBPS = 1.25      # 10 GbE-class uplink: the simulated wire
#                                 is cheap+identical for both planes, so
#                                 the real serve/receive cost shows
SERVING_NATIVE_BURST_MB = 4.0
SERVING_NATIVE_LEAVES = 128     # many small fragments: the per-request
#                                 interpreter overhead the native plane
#                                 eliminates dominates the payload move
SERVING_NATIVE_LEAF_ELEMS = 64 * 1024  # 128 x 256 KB fp32 = 32 MB


def bench_serving_native() -> "Dict[str, Any]":
    """Native zero-copy fragment data plane vs pure-Python serving
    (ISSUE 20): the SAME fanout-1 relay chain as the depth bench, every
    fetch cut-through streamed, run twice per cell — once with
    ``TORCHFT_FRAG_NATIVE=0`` (Python ``BaseHTTPRequestHandler`` serve +
    ``urllib`` receive) and once armed (native writev serve out of
    pooled buffers, GIL-free receive+sha256).  Uplinks are shaped at
    10 GbE class so the (identical) simulated wire charge stays small
    and the measured difference is the data plane itself.  Headline:
    native publish->leaf p99 speedup at depth 3/4, 0 ms RTT — with
    bitwise payload verification and zero failed fetches as hard
    evidence rows, and a striped-heal leg on the same footing."""
    import os as _os

    from torchft_tpu.checkpointing import fragdata as _fragdata

    rng = np.random.RandomState(31)
    base = {
        f"layer{i}": rng.randn(SERVING_NATIVE_LEAF_ELEMS).astype(np.float32)
        for i in range(SERVING_NATIVE_LEAVES)
    }
    payload_bytes = sum(a.nbytes for a in base.values())
    prior = {
        k: _os.environ.get(k)
        for k in ("TORCHFT_WIRE_RTT_MS", "TORCHFT_WIRE_GBPS",
                  "TORCHFT_WIRE_BURST_MB", "TORCHFT_TOPOLOGY",
                  "TORCHFT_SERVING_PARALLEL", "TORCHFT_HEAL_PARALLEL",
                  "TORCHFT_FRAG_NATIVE")
    }
    _os.environ.pop("TORCHFT_TOPOLOGY", None)
    _os.environ["TORCHFT_WIRE_GBPS"] = str(SERVING_NATIVE_GBPS)
    _os.environ["TORCHFT_WIRE_BURST_MB"] = str(SERVING_NATIVE_BURST_MB)
    _os.environ["TORCHFT_SERVING_PARALLEL"] = str(SERVING_DEPTH_PARALLEL)
    _os.environ["TORCHFT_HEAL_PARALLEL"] = str(HEAL_PARALLEL)

    def _pcts(lat: "List[float]") -> "Tuple[float, float]":
        lat = sorted(lat)
        return round(lat[len(lat) // 2] * 1e3, 1), round(lat[-1] * 1e3, 1)

    out: "Dict[str, Any]" = {
        "native_available": _fragdata.available(),
        "payload_mb": round(payload_bytes / 2**20, 2),
        "fragments": SERVING_NATIVE_LEAVES,
        "gbps_per_uplink": SERVING_NATIVE_GBPS,
        "publishes": SERVING_DEPTH_PUBLISHES,
        "warm_publishes": 5,
    }
    if not _fragdata.available():
        out["error"] = "native library unavailable: nothing to compare"
        for k, v in prior.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
        return out
    try:
        for rtt in SERVING_NATIVE_RTTS_MS:
            _os.environ["TORCHFT_WIRE_RTT_MS"] = str(rtt)
            leg: "Dict[str, Any]" = {}
            for depth in SERVING_NATIVE_DEPTHS:
                cell: "Dict[str, Any]" = {}
                for plane in ("python", "native"):
                    _os.environ["TORCHFT_FRAG_NATIVE"] = (
                        "1" if plane == "native" else "0"
                    )
                    _fragdata.reset_port_cache()
                    info: "Dict[str, Any]" = {}
                    # warm a full staged-version window (4) + 1: the
                    # window-fill transient (fresh buffer allocation +
                    # first-touch faults on every node, both planes)
                    # is a one-time cost, not the steady-state serving
                    # regime this cell compares
                    full, _, _, _ = _serving_depth_trial(
                        base, depth, True, plane_info=info,
                        warm_publishes=5,
                    )
                    p50, p99 = _pcts(full)
                    cell[f"{plane}_p50_ms"] = p50
                    cell[f"{plane}_p99_ms"] = p99
                    cell[f"{plane}_bitwise_payload"] = info.get(
                        "bitwise_payload"
                    )
                    # a failed fetch = a digest reject the chain healed
                    # around; leaf convergence itself is the
                    # zero-timeout proof (the trial raises otherwise)
                    cell[f"{plane}_failed_fetches"] = info.get(
                        "digest_rejects"
                    )
                    if plane == "native":
                        cell["native_serves"] = info.get("native_serves")
                        cell["native_serve_copies"] = info.get(
                            "native_serve_copies"
                        )
                        cell["native_fallbacks"] = info.get(
                            "native_fallbacks"
                        )
                cell["native_speedup_p99_x"] = round(
                    cell["python_p99_ms"] / max(cell["native_p99_ms"], 1e-9),
                    2,
                )
                cell["native_speedup_p50_x"] = round(
                    cell["python_p50_ms"] / max(cell["native_p50_ms"], 1e-9),
                    2,
                )
                leg[f"d{depth}"] = cell
                log(
                    f"serving native d={depth} rtt={int(rtt)}ms: python "
                    f"p99 {cell['python_p99_ms']}ms native p99 "
                    f"{cell['native_p99_ms']}ms "
                    f"({cell['native_speedup_p99_x']}x, serves="
                    f"{cell['native_serves']}, copies="
                    f"{cell['native_serve_copies']})"
                )
            out[f"rtt_{int(rtt)}ms"] = leg

        # striped-heal leg on the same footing: one healer pulls the
        # 8 MB heal state striped across 4 sources at 0 ms / 10 GbE,
        # python vs native receive path
        _os.environ["TORCHFT_WIRE_RTT_MS"] = "0"
        rng2 = np.random.RandomState(37)
        heal_state = {
            "user": {
                f"w{i}": rng2.randn(HEAL_LEAF_ELEMS).astype(np.float32)
                for i in range(HEAL_STATE_LEAVES)
            },
            "torchft": {"step": 5, "batches_committed": 10},
        }
        heal_leg: "Dict[str, Any]" = {}
        for plane in ("python", "native"):
            _os.environ["TORCHFT_FRAG_NATIVE"] = (
                "1" if plane == "native" else "0"
            )
            _fragdata.reset_port_cache()
            walls: "List[float]" = []
            for _t in range(HEAL_TRIALS):
                wall, _info = _heal_trial(heal_state, max(HEAL_SOURCES))
                walls.append(wall)
            walls.sort()
            heal_leg[f"{plane}_wall_p50_s"] = round(
                walls[len(walls) // 2], 3
            )
        heal_leg["native_speedup_x"] = round(
            heal_leg["python_wall_p50_s"]
            / max(heal_leg["native_wall_p50_s"], 1e-9),
            2,
        )
        out["heal_stripe"] = heal_leg
        log(
            f"serving native heal stripe: python p50 "
            f"{heal_leg['python_wall_p50_s']}s native p50 "
            f"{heal_leg['native_wall_p50_s']}s "
            f"({heal_leg['native_speedup_x']}x)"
        )

        # headline: the 0 ms cells the acceptance judges
        r0 = out.get("rtt_0ms", {})
        for depth in SERVING_NATIVE_DEPTHS:
            d = r0.get(f"d{depth}", {})
            out[f"d{depth}_rtt0_speedup_p99_x"] = d.get(
                "native_speedup_p99_x"
            )
        d3 = r0.get("d3", {})
        out["bitwise"] = bool(
            d3.get("native_bitwise_payload")
            and d3.get("python_bitwise_payload")
        )
        out["failed_fetches"] = (
            (d3.get("native_failed_fetches") or 0)
            + (d3.get("python_failed_fetches") or 0)
        )
        out["heal_speedup_x"] = heal_leg.get("native_speedup_x")
        out["winner"] = (
            "native"
            if (out.get("d3_rtt0_speedup_p99_x") or 0) > 1.0
            else "python"
        )
    finally:
        for k, v in prior.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
    return out


# ---------------------------------------------------------------------------
# striped multi-source heal (ISSUE 15)
# ---------------------------------------------------------------------------

HEAL_STATE_LEAVES = 16
HEAL_LEAF_ELEMS = 1 << 17  # 16 x 512 KB = 8 MB f32 heal state
HEAL_FRAGMENTS = 16
HEAL_SOURCES = (1, 2, 4)
HEAL_RTTS_MS = (0.0, 10.0, 50.0)
HEAL_GBPS = 0.02  # per-SOURCE uplink: striping aggregates them
HEAL_BURST_MB = 0.25
HEAL_PARALLEL = 4
HEAL_TRIALS = 3


def _heal_trial(
    state: "Dict[str, Any]", n_sources: int,
    local: "Optional[Dict[str, Any]]" = None,
) -> "Tuple[float, Dict[str, Any]]":
    """One striped heal against ``n_sources`` freshly stream-staging
    transports (staging runs CONCURRENTLY with the healer's fetch — the
    cut-through overlap the design claims); returns ``(wall_s, info)``."""
    from torchft_tpu.checkpointing.http_transport import HTTPTransport

    srcs = [HTTPTransport(timeout=60.0) for _ in range(n_sources)]
    healer = HTTPTransport(timeout=60.0)
    threads = [
        threading.Thread(
            target=t.send_checkpoint_streamed,
            args=([1], 5, state, 60.0, HEAL_FRAGMENTS),
            daemon=True,
        )
        for t in srcs
    ]
    try:
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        _got, info = healer.recv_checkpoint_striped(
            [t.metadata() for t in srcs], 5, timeout=120.0,
            local_state_fn=(lambda: local) if local is not None else None,
            delta=local is not None,
        )
        wall = time.perf_counter() - t0
    finally:
        for t in threads:
            t.join(timeout=10)
        healer.shutdown()
        for t in srcs:
            t.shutdown()
    return wall, info


def bench_heal() -> "Dict[str, Any]":
    """Striped multi-source delta heal (ISSUE 15): recovery over the
    fragment plane, measured on shaped links.  One healer pulls an
    8 MB heal state striped across {1, 2, 4} sources at WAN RTT
    {0, 10, 50} ms, each source's uplink its own HEAL_GBPS token bucket
    (Prime CCL's premise: striping aggregates source uplinks).  The
    acceptance row is the 4-source wire-time speedup over single-source
    (>= 1.5x on bandwidth-bound links).  The ``delta`` row rejoins with
    a state differing in ONE leaf: wire bytes must scale with the
    changed-fragment count, not the model."""
    import os as _os

    rng = np.random.RandomState(23)
    state = {
        "user": {
            f"w{i}": rng.randn(HEAL_LEAF_ELEMS).astype(np.float32)
            for i in range(HEAL_STATE_LEAVES)
        },
        "torchft": {"step": 5, "batches_committed": 10},
    }
    payload_bytes = sum(a.nbytes for a in state["user"].values())
    prior = {
        k: _os.environ.get(k)
        for k in ("TORCHFT_WIRE_RTT_MS", "TORCHFT_WIRE_GBPS",
                  "TORCHFT_WIRE_BURST_MB", "TORCHFT_TOPOLOGY",
                  "TORCHFT_HEAL_PARALLEL")
    }
    _os.environ.pop("TORCHFT_TOPOLOGY", None)  # flat: every fetch is WAN
    _os.environ["TORCHFT_WIRE_GBPS"] = str(HEAL_GBPS)
    _os.environ["TORCHFT_WIRE_BURST_MB"] = str(HEAL_BURST_MB)
    _os.environ["TORCHFT_HEAL_PARALLEL"] = str(HEAL_PARALLEL)

    out: "Dict[str, Any]" = {
        "state_mb": round(payload_bytes / 2**20, 2),
        "fragments": HEAL_FRAGMENTS,
        "gbps_per_uplink": HEAL_GBPS,
        "trials": HEAL_TRIALS,
    }
    try:
        for rtt in HEAL_RTTS_MS:
            _os.environ["TORCHFT_WIRE_RTT_MS"] = str(rtt)
            leg: "Dict[str, Any]" = {}
            for n in HEAL_SOURCES:
                walls: "List[float]" = []
                wires: "List[float]" = []
                for _t in range(HEAL_TRIALS):
                    wall, info = _heal_trial(state, n)
                    walls.append(wall)
                    wires.append(info["phases"]["heal_wire"])
                walls.sort()
                wires.sort()
                leg[f"s{n}"] = {
                    "wall_p50_s": round(walls[len(walls) // 2], 3),
                    "wire_p50_s": round(wires[len(wires) // 2], 3),
                }
            for n in HEAL_SOURCES[1:]:
                leg[f"s{n}"]["wire_speedup_x"] = round(
                    leg["s1"]["wire_p50_s"]
                    / max(leg[f"s{n}"]["wire_p50_s"], 1e-9),
                    2,
                )
            out[f"rtt_{int(rtt)}ms"] = leg
            log(
                f"heal rtt={rtt}ms: wire p50 "
                + " ".join(
                    f"s{n}={leg[f's{n}']['wire_p50_s']}s" for n in HEAL_SOURCES
                )
                + f" (s4 speedup {leg['s4'].get('wire_speedup_x')}x)"
            )
        # delta-rejoin row (unshaped RTT, max sources): one changed leaf
        _os.environ["TORCHFT_WIRE_RTT_MS"] = "0"
        local = {
            "user": {k: v.copy() for k, v in state["user"].items()},
            "torchft": {"step": 3, "batches_committed": 6},
        }
        local["user"]["w7"] = local["user"]["w7"] + np.float32(1.0)
        wall, info = _heal_trial(state, max(HEAL_SOURCES), local=local)
        out["delta"] = {
            "wall_s": round(wall, 3),
            "changed_fragments": info["changed"],
            "total_fragments": info["fragments"],
            "wire_bytes": info["wire_bytes"],
            "full_bytes": payload_bytes,
            "bytes_ratio": round(info["wire_bytes"] / payload_bytes, 4),
        }
        log(
            f"heal delta rejoin: {info['changed']}/{info['fragments']} "
            f"fragments, {info['wire_bytes']} B "
            f"({out['delta']['bytes_ratio']:.1%} of full)"
        )
        s4_0 = out.get("rtt_0ms", {}).get("s4", {})
        s4_50 = out.get("rtt_50ms", {}).get("s4", {})
        out["s4_rtt0_speedup_x"] = s4_0.get("wire_speedup_x")
        out["s4_rtt50_speedup_x"] = s4_50.get("wire_speedup_x")
        out["winner"] = (
            "striped" if (s4_0.get("wire_speedup_x") or 0) > 1.0 else "single"
        )
    finally:
        for k, v in prior.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
    return out


# ---------------------------------------------------------------------------
# durable cold restore (ISSUE 17)
# ---------------------------------------------------------------------------

CR_STATE_LEAVES = 16
CR_LEAF_ELEMS = 1 << 17  # 16 x 512 KB = 8 MB f32 restore state
CR_FRAGMENTS = 16
CR_TRIALS = 3
CR_DISKS = (1, 2)


def _dir_bytes(path: str) -> int:
    import os

    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def _cold_restore_trial(
    stores: "List[Any]", version: int,
    local: "Optional[Dict[str, Any]]" = None,
) -> "Tuple[float, Dict[str, Any]]":
    """One cold restore against ``stores`` as stripe sources: transports
    with NO RAM staging (the fleet is dead — every ``frag_<name>`` fetch
    is served straight off the attached disk store), reassembled by the
    PR 15 striped fetch path; returns ``(wall_s, info)``."""
    from torchft_tpu.checkpointing.http_transport import HTTPTransport

    srcs = [HTTPTransport(timeout=60.0) for _ in stores]
    for t, s in zip(srcs, stores):
        t.attach_store(s)
    healer = HTTPTransport(timeout=60.0)
    try:
        t0 = time.perf_counter()
        _got, info = healer.recv_checkpoint_striped(
            [t.metadata() for t in srcs], version, timeout=120.0,
            local_state_fn=(lambda: local) if local is not None else None,
            delta=local is not None,
        )
        wall = time.perf_counter() - t0
    finally:
        healer.shutdown()
        for t in srcs:
            t.shutdown()
    return wall, info


def bench_cold_restore() -> "Dict[str, Any]":
    """Durable fragment store (ISSUE 17): spill + whole-fleet cold
    restore off disk.  An 8 MB state is spilled to 2 rank-local stores;
    the headline is the cold-restore wall (disk -> reassembled state)
    striped over {1, 2} disks, plus the spill-side rows the design
    claims: content-addressed DEDUP (respilling an unchanged state
    writes ~0 new blob bytes) and the WARM delta restore (a rejoiner
    whose memory survived fetches only the manifest)."""
    import os
    import shutil
    import tempfile

    from torchft_tpu.checkpointing.store import FragmentStore

    rng = np.random.RandomState(41)
    state = {
        "user": {
            f"w{i}": rng.randn(CR_LEAF_ELEMS).astype(np.float32)
            for i in range(CR_STATE_LEAVES)
        },
        "torchft": {"step": 7, "batches_committed": 14},
    }
    payload_bytes = sum(a.nbytes for a in state["user"].values())
    root = tempfile.mkdtemp(prefix="tft_bench_store_")
    out: "Dict[str, Any]" = {
        "state_mb": round(payload_bytes / 2**20, 2),
        "fragments": CR_FRAGMENTS,
        "trials": CR_TRIALS,
    }
    try:
        stores = [
            FragmentStore(os.path.join(root, f"rank{i}"), max_versions=0)
            for i in range(max(CR_DISKS))
        ]
        # spill row: wall to durably persist one full version per disk
        spill_walls: "List[float]" = []
        for s in stores:
            t0 = time.perf_counter()
            s.put_state(7, state, fragments=CR_FRAGMENTS)
            spill_walls.append(time.perf_counter() - t0)
        spill_walls.sort()
        out["spill"] = {
            "wall_p50_s": round(spill_walls[len(spill_walls) // 2], 3),
            "disk_bytes": _dir_bytes(stores[0].directory),
        }
        # dedup row: respill the SAME state as a newer version — blobs
        # are content-addressed, so only the manifest should hit disk
        before = _dir_bytes(stores[0].directory)
        t0 = time.perf_counter()
        stores[0].put_state(8, state, fragments=CR_FRAGMENTS)
        dedup_wall = time.perf_counter() - t0
        out["dedup"] = {
            "wall_s": round(dedup_wall, 3),
            "new_bytes": _dir_bytes(stores[0].directory) - before,
            "payload_bytes": payload_bytes,
        }
        stores[1].put_state(8, state, fragments=CR_FRAGMENTS)
        # cold-restore rows: striped reassembly with disks as sources
        for n in CR_DISKS:
            walls: "List[float]" = []
            for _t in range(CR_TRIALS):
                wall, info = _cold_restore_trial(stores[:n], 8)
                walls.append(wall)
            walls.sort()
            out[f"d{n}"] = {
                "wall_p50_s": round(walls[len(walls) // 2], 3),
                "sources": n,
            }
            log(
                f"cold restore d{n}: wall p50 "
                f"{out[f'd{n}']['wall_p50_s']}s"
            )
        # warm delta row: local memory survived — only the manifest moves
        local = {
            "user": {k: v.copy() for k, v in state["user"].items()},
            "torchft": dict(state["torchft"]),
        }
        wall, info = _cold_restore_trial(stores[:2], 8, local=local)
        out["warm_delta"] = {
            "wall_s": round(wall, 3),
            "changed_fragments": info["changed"],
            "wire_bytes": info["wire_bytes"],
            "bytes_ratio": round(info["wire_bytes"] / payload_bytes, 4),
        }
        log(
            f"cold restore warm delta: {info['changed']} changed, "
            f"{info['wire_bytes']} B "
            f"({out['warm_delta']['bytes_ratio']:.1%} of full)"
        )
        out["restore_wall_p50_s"] = out["d2"]["wall_p50_s"]
        out["dedup_new_bytes"] = out["dedup"]["new_bytes"]
        out["winner"] = (
            "dedup"
            if out["dedup"]["new_bytes"] < payload_bytes / 10
            else "rewrite"
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


COMPACT_SUMMARY_MAX_BYTES = 1500


HA_PEERS = 3
HA_TRIALS = 3
HA_LEASE_MS = 500


HA_RTTS_MS = (0.0, 50.0)


def _ha_failover_trials(n_trials: int, tag: str) -> "Dict[str, Any]":
    """``n_trials`` leader-kill -> next-quorum measurements (one fleet
    per trial); the per-leg body of :func:`bench_ha`."""
    from torchft_tpu.ha import LighthouseFleet

    trials: "List[float]" = []
    monotone = True
    term_advanced = True
    takeover_terms: "List[int]" = []
    for t in range(n_trials):
        fleet = LighthouseFleet(
            n=HA_PEERS, min_replicas=1, lease_timeout_ms=HA_LEASE_MS,
            quorum_tick_ms=50,
        )
        try:
            fleet.wait_for_leader(20)
            cli = LighthouseClient(fleet.addresses(), connect_timeout=5.0)
            try:
                q1 = cli.quorum(f"bench_ha:{tag}{t}a", timeout=15.0)
                t0 = time.monotonic()
                fleet.kill_leader()
                q2 = cli.quorum(f"bench_ha:{tag}{t}b", timeout=30.0)
                trials.append(time.monotonic() - t0)
                monotone = monotone and q2.quorum_id > q1.quorum_id
                term_advanced = term_advanced and (
                    (q2.quorum_id >> 32) > (q1.quorum_id >> 32)
                )
                takeover_terms.append(q2.quorum_id >> 32)
            finally:
                cli.close()
        finally:
            fleet.shutdown()
    trials.sort()
    return {
        "trials": len(trials),
        "kill_to_quorum_p50_s": round(trials[len(trials) // 2], 3),
        "kill_to_quorum_max_s": round(trials[-1], 3),
        "kill_to_quorum_s": [round(x, 3) for x in trials],
        "quorum_id_monotone": monotone,
        "term_advanced": term_advanced,
        "takeover_terms": takeover_terms,
    }


def bench_ha() -> "Dict[str, Any]":
    """Coordination-plane HA failover: HA_PEERS in-process lighthouse
    peers with leased leadership; a replica-group stub quorums through
    the endpoint-list client, the LEADER is killed, and the headline is
    leader-kill -> next formed quorum latency (the coordination-plane
    twin of the recovery metric).  Also asserts what the chaos tests
    assert: quorum_id strictly monotone with an advancing term word.

    WAN-shaped legs (ISSUE 14 satellite, the PR 13 carry-over): the
    sweep re-runs the measurement with ``TORCHFT_WIRE_RTT_MS`` in
    HA_RTTS_MS and ``TORCHFT_WIRE_RPC=1``, pricing one first-byte RTT on
    every Python coordination RPC round trip — the client-visible share
    of lease/election cost under WAN (the native peers' own lease
    exchanges are in-process and unshaped; docs/observability.md
    ``TORCHFT_WIRE_RPC``).  docs/architecture.md "Coordination-plane
    HA"."""
    import os as _os

    prior = {
        k: _os.environ.get(k)
        for k in ("TORCHFT_WIRE_RTT_MS", "TORCHFT_WIRE_RPC",
                  "TORCHFT_TOPOLOGY")
    }
    _os.environ.pop("TORCHFT_TOPOLOGY", None)  # flat: every RPC is WAN
    _os.environ["TORCHFT_WIRE_RPC"] = "1"
    wan: "Dict[str, Any]" = {}
    try:
        for rtt in HA_RTTS_MS:
            _os.environ["TORCHFT_WIRE_RTT_MS"] = str(rtt)
            n = HA_TRIALS if rtt == 0.0 else max(HA_TRIALS - 1, 1)
            wan[f"rtt_{int(rtt)}ms"] = _ha_failover_trials(
                n, f"r{int(rtt)}_"
            )
            log(
                f"ha failover rtt={rtt}ms: p50 "
                f"{wan[f'rtt_{int(rtt)}ms']['kill_to_quorum_p50_s']}s"
            )
    finally:
        for k, v in prior.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
    base = wan.get("rtt_0ms", {})
    return {
        "peers": HA_PEERS,
        "lease_ms": HA_LEASE_MS,
        **base,
        "wan": {
            leg: {
                "kill_to_quorum_p50_s": d.get("kill_to_quorum_p50_s"),
                "kill_to_quorum_max_s": d.get("kill_to_quorum_max_s"),
            }
            for leg, d in sorted(wan.items())
        },
    }


def links_summary() -> "Optional[Dict[str, Any]]":
    """Distill this process's passive link-state registry (ISSUE 16)
    into a handful of fleet-health cells: tracked pair count, matrix
    version, the worst WAN link by goodput, and the worst observed RTT
    tail.  The registry fills as a side effect of the shaped legs (WAN
    sweep, striped heal, relay depth) — no probe traffic of its own.
    Returns None when nothing was recorded (e.g. a CPU-only quick leg)."""
    from torchft_tpu.utils import linkstats

    matrix = linkstats.LINKS.snapshot()
    if not matrix.entries:
        return None
    out: "Dict[str, Any]" = {
        "pairs": len(matrix.entries),
        "version": matrix.version,
    }
    wan = [
        s for s in matrix.entries
        if not s.local and s.goodput_bps > 0
    ]
    if wan:
        worst = min(wan, key=lambda s: s.goodput_bps)
        out["worst_wan_goodput_bps"] = round(worst.goodput_bps)
        out["worst_wan_link"] = f"{worst.peer}/{worst.plane}"
    tails = [s.rtt_p99_ms for s in matrix.entries if s.rtt_p99_ms > 0]
    if tails:
        out["rtt_p99_max_ms"] = round(max(tails), 3)
    return out


def compact_summary(result: "Dict[str, Any]") -> "Dict[str, Any]":
    """Distill the full bench result into one < 1.5 KB JSON line: the
    primary recovery metric + cycle medians, overhead + cross-check
    verdict, MFU, and the DiLoCo winners table.  Degrades field by field
    (never errors) so a partially failed run still tails its primary
    metric."""
    model = result.get("model") or {}
    diloco = result.get("diloco") or {}
    crosscheck = result.get("crosscheck") or {}
    phases = result.get("recovery_phases_ms") or {}
    top_phases = dict(
        sorted(phases.items(), key=lambda kv: -abs(kv[1]))[:4]
    )
    winners = {
        gbps: {
            "winner": leg.get("winner"),
            "int8_speedup_x": leg.get("int8_speedup_x"),
        }
        for gbps, leg in sorted((diloco.get("shaped") or {}).items())
        if isinstance(leg, dict)
    }
    wan = result.get("wan") or {}
    wan_winners = {
        key: {
            "winner": leg.get("winner"),
            "hier_speedup_x": leg.get("hier_speedup_x"),
        }
        for key, leg in sorted(wan.items())
        if isinstance(leg, dict) and key.startswith("rtt_")
    }
    # per-hop wire telemetry of the highest-RTT hierarchical leg — the
    # acceptance surface (hier must beat flat at 50 ms, hops visible)
    wan_hops = (
        (wan.get("rtt_50ms") or {}).get("hier_hop_wire_s")
        if isinstance(wan.get("rtt_50ms"), dict)
        else None
    )
    switch = result.get("switch") or {}
    serving = result.get("serving") or {}
    ha = result.get("ha") or {}
    ha_compact = {
        k: ha.get(k)
        for k in (
            "kill_to_quorum_p50_s",
            "kill_to_quorum_max_s",
            "lease_ms",
            "quorum_id_monotone",
            "term_advanced",
        )
        if ha.get(k) is not None
    } or None
    # WAN-shaped HA legs (ISSUE 14 satellite): kill->quorum p50 per RTT
    ha_wan = {
        leg: d.get("kill_to_quorum_p50_s")
        for leg, d in sorted((ha.get("wan") or {}).items())
        if isinstance(d, dict)
    }
    if ha_compact is not None and ha_wan:
        ha_compact["wan_p50_s"] = ha_wan
    heal = result.get("heal") or {}
    heal_compact = {
        k: heal.get(k)
        for k in ("s4_rtt0_speedup_x", "s4_rtt50_speedup_x", "winner")
        if heal.get(k) is not None
    }
    if isinstance(heal.get("delta"), dict):
        heal_compact["delta_changed"] = heal["delta"].get(
            "changed_fragments"
        )
        heal_compact["delta_bytes_ratio"] = heal["delta"].get("bytes_ratio")
    heal_compact = heal_compact or None
    cr = result.get("cold_restore") or {}
    cold_restore_compact = {
        k: cr.get(k)
        for k in ("restore_wall_p50_s", "dedup_new_bytes", "winner")
        if cr.get(k) is not None
    }
    if isinstance(cr.get("warm_delta"), dict):
        cold_restore_compact["warm_bytes_ratio"] = cr["warm_delta"].get(
            "bytes_ratio"
        )
    cold_restore_compact = cold_restore_compact or None
    sdepth = result.get("serving_depth") or {}
    serving_depth_compact = {
        k: sdepth.get(k)
        for k in (
            "d3_rtt50_speedup_x",
            "d3_rtt50_flat_p50_ms",
            "d3_rtt50_stream_p50_ms",
            "d3_rtt50_delta_p50_ms",
            "winner",
        )
        if sdepth.get(k) is not None
    } or None
    # native data-plane headline (ISSUE 20): native-vs-python p99
    # speedup at the 0 ms cells + the bitwise / failed-fetch evidence
    snative = result.get("serving_native") or {}
    native_compact = {
        k: snative.get(k)
        for k in (
            "d3_rtt0_speedup_p99_x",
            "d4_rtt0_speedup_p99_x",
            "heal_speedup_x",
            "bitwise",
            "failed_fetches",
            "winner",
        )
        if snative.get(k) is not None
    } or None
    # fragment-provenance headline (ISSUE 18): per-fragment staleness
    # spread at the deepest WAN leg of the streaming-relay bench
    fragments_compact = {
        key: sdepth.get(src)
        for key, src in (
            ("stale_p50_ms", "d3_rtt50_frag_staleness_p50_ms"),
            ("stale_max_ms", "d3_rtt50_frag_staleness_max_ms"),
        )
        if sdepth.get(src) is not None
    } or None
    serving_compact = {
        k: serving.get(k)
        for k in (
            "published_cps",
            "delivered_cps",
            "fetch_p50_ms",
            "fetch_p99_ms",
            "failovers",
            "failed_fetches",
            "bitwise_identical_after_failover",
        )
        if serving.get(k) is not None
    } or None
    out: "Dict[str, Any]" = {
        "compact": True,
        "metric": result.get("metric", "recovery_to_healthy_step_latency"),
        "unit": result.get("unit", "s"),
        "value": result.get("value"),
        "vs_baseline": result.get("vs_baseline"),
        # online-parallelism-switch latency (kill -> fleet-synchronous
        # layout commit) next to the recovery headline it complements
        "switch_latency_s": switch.get("latency_s"),
        "switch": {
            k: switch.get(k)
            for k in ("reshard_s", "layout_commit_s", "detect_s",
                      "reshard_bytes", "layout")
            if switch.get(k) is not None
        } or None,
        "recovery_cycles_s": result.get("recovery_cycles_s"),
        "recovery_phases_ms_top": top_phases,
        "overhead_pct": result.get("overhead_pct"),
        "model_overhead_pct": result.get("model_overhead_pct"),
        "crosscheck": {
            "converged_2pts": crosscheck.get("converged_2pts"),
            "gap_pts": crosscheck.get("gap_pts"),
            "noise_floor_bound": crosscheck.get("noise_floor_bound"),
        },
        "mfu_pct": model.get("mfu_pct"),
        "step_ms": model.get("step_ms"),
        "diloco_winners": winners,
        "diloco_wire_reduction_x": diloco.get("wire_reduction_x"),
        # serving-tier headline (ISSUE 12): sustained checkpoints/sec +
        # p99 fetch under churn + the post-failover bitwise verdict
        "serving": serving_compact,
        # streaming-relay headline (ISSUE 14): publish->leaf at depth 3 /
        # 50 ms RTT, cut-through vs store-and-forward + the delta row
        "serving_depth": serving_depth_compact,
        # native data-plane headline (ISSUE 20): zero-copy serve +
        # GIL-free receive vs the pure-Python path on the same chain
        "native": native_compact,
        # coordination-plane HA headline (ISSUE 13): leader-kill -> next
        # formed quorum latency + the monotonicity verdicts
        "ha": ha_compact,
        # striped-heal headline (ISSUE 15): 4-source wire-time speedup
        # over single-source on shaped links + the delta-rejoin row
        "heal": heal_compact,
        # durable-store headline (ISSUE 17): cold-restore wall off 2
        # disks + the content-addressed dedup and warm-delta verdicts
        "cold_restore": cold_restore_compact,
        # link-state headline (ISSUE 16): pairs the passive registry
        # tracked + the worst WAN link it singled out
        "links": result.get("links"),
        # staleness-ledger headline (ISSUE 16): publish->leaf staleness
        # at depth 3 / 50 ms RTT from the streaming-relay leg
        "staleness": sdepth.get("d3_rtt50_staleness_p50_ms"),
        # fragment-provenance headline (ISSUE 18): per-fragment
        # staleness spread (p50/max) on the same leg
        "fragments": fragments_compact,
        "wan": wan_winners,
        "wan_hops_50ms": wan_hops,
        # per-leg dominant-ledger-contributor (torchft_tpu/diagnose.py
        # PHASE_CATEGORY vocabulary): which cost category ate each leg
        "dominant": {
            k: v
            for k, v in {
                "recovery": result.get("recovery_dominant"),
                "overhead": result.get("overhead_dominant"),
                "switch": switch.get("dominant"),
                **{
                    f"diloco.{leg}": legd.get("dominant")
                    for leg, legd in sorted(diloco.items())
                    if isinstance(legd, dict) and legd.get("dominant")
                },
            }.items()
            if v
        },
    }
    if "error" in result:
        out["error"] = str(result["error"])[:200]
    # Enforce the byte budget structurally: drop the least essential
    # fields first rather than shipping an unparseable truncation.
    droppable = [
        "diloco_wire_reduction_x", "step_ms", "wan_hops_50ms",
        "switch", "diloco_winners", "dominant", "crosscheck",
        "recovery_phases_ms_top", "recovery_cycles_s", "wan",
        "links", "staleness", "fragments", "ha", "serving",
        "serving_depth", "native", "heal", "cold_restore",
    ]
    while (
        len(json.dumps(out).encode()) > COMPACT_SUMMARY_MAX_BYTES and droppable
    ):
        out.pop(droppable.pop(0), None)
    return out


def last_json_line(text: str) -> "Dict[str, Any]":
    """Parse the last complete JSON line of a captured emission tail —
    exactly what the driver's 2000-byte tail parser needs to do.  A
    truncated first line (the tail window cutting into the full result
    line) is skipped, not fatal."""
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            return obj
    raise ValueError("no parseable JSON line in tail")


# ---------------------------------------------------------------------------


def main() -> None:
    # Opt-in live scrape surface for long runs: TORCHFT_METRICS_PORT serves
    # the telemetry registry (phase histograms, abort/heal counters) this
    # bench's Managers populate — watchable mid-run alongside the
    # non-destructive phase_times() snapshots the estimators diff.
    from torchft_tpu.utils import metrics as _metrics

    _metrics.maybe_serve_from_env()
    if "--serving" in sys.argv:
        # `make bench-serving`: the weight-serving churn leg alone, with
        # the compact tail (same last-line contract as the full run)
        serving = bench_serving()
        result = {"metric": "serving_fanout_under_churn", "serving": serving}
        print(json.dumps(result), flush=True)
        print(json.dumps(compact_summary(result)), flush=True)
        return
    if "--serving-depth" in sys.argv:
        # `make bench-serving-depth`: the streaming-relay depth axis
        # alone (flat vs cut-through publish->leaf at depth x RTT), with
        # the compact tail (same last-line contract as the full run)
        sdepth = bench_serving_depth()
        result = {
            "metric": "serving_publish_to_leaf_latency",
            "serving_depth": sdepth,
            "links": links_summary(),
        }
        print(json.dumps(result), flush=True)
        print(json.dumps(compact_summary(result)), flush=True)
        return
    if "--serving-native" in sys.argv:
        # `make bench-serving-native`: the native-vs-python fragment
        # data-plane comparison alone (zero-copy serve + GIL-free
        # receive vs pure Python on the same cut-through chain, plus
        # the striped-heal leg), with the compact tail (same last-line
        # contract as the full run)
        snative = bench_serving_native()
        result = {
            "metric": "native_data_plane_speedup",
            "serving_native": snative,
            "links": links_summary(),
        }
        print(json.dumps(result), flush=True)
        print(json.dumps(compact_summary(result)), flush=True)
        return
    if "--heal" in sys.argv:
        # `make bench-heal`: the striped multi-source heal leg alone
        # (stripe sources x RTT on shaped per-source uplinks + the
        # delta-rejoin row), with the compact tail (same last-line
        # contract as the full run)
        heal = bench_heal()
        result = {
            "metric": "striped_heal_wire_time",
            "heal": heal,
            "links": links_summary(),
        }
        print(json.dumps(result), flush=True)
        print(json.dumps(compact_summary(result)), flush=True)
        return
    if "--cold-restore" in sys.argv:
        # `make bench-cold-restore`: the durable-store leg alone (spill,
        # dedup, disk-striped cold restore, warm delta), with the
        # compact tail (same last-line contract as the full run)
        cr = bench_cold_restore()
        result = {
            "metric": "cold_restore_wall_time",
            "cold_restore": cr,
        }
        print(json.dumps(result), flush=True)
        print(json.dumps(compact_summary(result)), flush=True)
        return
    if "--ha-failover" in sys.argv:
        # `make bench-ha`: the coordination-plane failover leg alone
        # (incl. the WAN-shaped RTT legs), with the compact tail (same
        # last-line contract as the full run)
        ha = bench_ha()
        result = {"metric": "ha_leader_failover", "ha": ha}
        print(json.dumps(result), flush=True)
        print(json.dumps(compact_summary(result)), flush=True)
        return
    if "--wan" in sys.argv:
        # `make bench-wan`: the RTT sweep alone, with the compact tail
        # (same last-line contract as the full run)
        wan = bench_wan(262.0)
        result = {
            "metric": "wan_rtt_sweep",
            "wan": wan,
            "links": links_summary(),
        }
        print(json.dumps(result), flush=True)
        print(json.dumps(compact_summary(result)), flush=True)
        return
    recovery = bench_recovery()
    # switch latency (ISSUE 11): the membership-change twin of recovery
    # latency — a shrink triggers a live re-shard instead of a restart.
    # Degrades to an error field like every secondary bench.
    try:
        switch = bench_switch()
    except Exception as e:  # noqa: BLE001
        log(f"switch bench failed: {e!r}")
        switch = {"error": repr(e)}
    # Insurance against an external wall-cap killing the process mid-run:
    # emit a parseable JSON line with the PRIMARY metric as soon as it
    # exists.  A completed run prints the full line at the end (later on
    # stdout, so a tail-parser picks it up); a killed run still leaves
    # this one.
    print(
        json.dumps(
            {
                "metric": "recovery_to_healthy_step_latency",
                "unit": "s",
                "vs_baseline": round(recovery["value"] / 1.0, 3),
                **recovery,
                "preliminary": True,
            }
        ),
        flush=True,
    )
    # The secondary benches must never cost the driver the primary metric:
    # degrade to an "error" field instead of dying without the JSON line.
    try:
        overhead = bench_overhead()
    except Exception as e:  # noqa: BLE001
        log(f"overhead bench failed: {e!r}")
        overhead = {"overhead_error": repr(e)}
    try:
        overhead["crosscheck"] = bench_overhead_crosscheck()
    except Exception as e:  # noqa: BLE001
        log(f"overhead cross-check failed: {e!r}")
        overhead["crosscheck"] = {"error": repr(e)}
    try:
        model: "Dict[str, Any]" = bench_model()
    except Exception as e:  # noqa: BLE001
        log(f"model bench failed: {e!r}")
        model = {"error": repr(e)}
    try:
        diloco = bench_diloco(model.get("step_ms") or 262.0)
    except Exception as e:  # noqa: BLE001
        log(f"diloco bench failed: {e!r}")
        diloco = {"error": repr(e)}
    try:
        diloco.update(
            bench_diloco_vs_ddp(overhead.get("nonft_step_ms") or 50.0)
        )
    except Exception as e:  # noqa: BLE001
        log(f"diloco-vs-ddp bench failed: {e!r}")
        diloco["vs_ddp_error"] = repr(e)
    try:
        # the measured version of "on real DCN the sign flips": both twins
        # under the 0.5 GB/s egress shaper — DDP pays the wire every step
        diloco["vs_ddp_shaped_0p5gbps"] = bench_diloco_vs_ddp(
            1e9, gbps=0.5
        )
    except Exception as e:  # noqa: BLE001
        log(f"shaped diloco-vs-ddp bench failed: {e!r}")
        diloco["vs_ddp_shaped_0p5gbps"] = {"error": repr(e)}
    try:
        wan = bench_wan(model.get("step_ms") or 262.0)
    except Exception as e:  # noqa: BLE001
        log(f"wan bench failed: {e!r}")
        wan = {"error": repr(e)}
    try:
        # the "millions of users" axis: fan-out weight serving under
        # churn (chaos kills a tree node mid-fetch)
        serving = bench_serving()
    except Exception as e:  # noqa: BLE001
        log(f"serving bench failed: {e!r}")
        serving = {"error": repr(e)}
    try:
        # streaming-relay depth axis (ISSUE 14): publish->leaf flat vs
        # cut-through at depth {1,2,3} x RTT {0,10,50} ms
        serving_depth = bench_serving_depth()
    except Exception as e:  # noqa: BLE001
        log(f"serving depth bench failed: {e!r}")
        serving_depth = {"error": repr(e)}
    try:
        # native data-plane comparison (ISSUE 20): zero-copy serve +
        # GIL-free receive vs the pure-Python path on the same chain
        serving_native = bench_serving_native()
    except Exception as e:  # noqa: BLE001
        log(f"serving native bench failed: {e!r}")
        serving_native = {"error": repr(e)}
    try:
        # coordination-plane HA: leader-kill -> next-quorum latency over
        # a replicated lighthouse (ISSUE 13)
        ha = bench_ha()
    except Exception as e:  # noqa: BLE001
        log(f"ha bench failed: {e!r}")
        ha = {"error": repr(e)}
    try:
        # striped multi-source heal (ISSUE 15): recovery over the
        # fragment plane — stripe sources x RTT + the delta-rejoin row
        heal = bench_heal()
    except Exception as e:  # noqa: BLE001
        log(f"heal bench failed: {e!r}")
        heal = {"error": repr(e)}
    result = {
        "metric": "recovery_to_healthy_step_latency",
        "unit": "s",
        "vs_baseline": round(recovery["value"] / 1.0, 3),
        **recovery,
        **overhead,
        "model_overhead_pct": (model.get("ft") or {}).get("model_overhead_pct"),
        "model": model,
        "diloco": diloco,
        "wan": wan,
        "switch": switch,
        "serving": serving,
        "serving_depth": serving_depth,
        "serving_native": serving_native,
        "ha": ha,
        "heal": heal,
        # passive link-state registry distilled (ISSUE 16): fills as a
        # side effect of the shaped legs above, no probe traffic
        "links": links_summary(),
    }
    print(json.dumps(result), flush=True)
    # LAST line, always < 1500 bytes: the driver's 2000-byte stdout tail
    # must carry the primary metric no matter how large the full result
    # line grew (VERDICT r5 #2 — r5's number was truncated out).
    print(json.dumps(compact_summary(result)), flush=True)


if __name__ == "__main__":
    main()
