# Developer entry points.  The native core builds via native/Makefile
# (wheels trigger it from setup.py); this file wires the repo-level
# verification gates CI and humans share.

PYTHON ?= python

.PHONY: native verify lint typecheck plan-verify test tier1 bench-wan trace-smoke reshard-smoke serve-smoke bench-serving bench-serving-depth bench-serving-native serve-soak ha-smoke bench-ha heal-smoke bench-heal links-smoke cold-restore-smoke bench-cold-restore fragments-smoke

native:
	$(MAKE) -C native

# The correctness gate: project-invariant lint (tft-lint), the protocol
# model checker's self-consistency (mutation gate + clean steady space +
# wire extractor selftest), then the full bounded exploration + liveness
# + wire-schema drift pass.  Exit code != 0 on any finding/violation.
verify:
	$(PYTHON) -m torchft_tpu.analysis torchft_tpu/
	$(PYTHON) -m torchft_tpu.analysis.verify_cli --selftest
	$(PYTHON) -m torchft_tpu.analysis.verify_cli

lint:
	$(PYTHON) -m torchft_tpu.analysis torchft_tpu/

# The tft-plan gate alone (ISSUE 19): exhaustive small-world plan
# enumeration on the reduction/serving/stripe planes + the seeded
# plan-mutation catalog, each caught by its named invariant.  Also part
# of the default `tft-verify` full gate (and therefore `make verify`).
plan-verify:
	$(PYTHON) -m torchft_tpu.analysis.verify_cli --scenario plan

# mypy strict over the analysis + utils layers (mirrors the slow-marked
# tests/test_typecheck.py gate); requires mypy on PATH.
typecheck:
	$(PYTHON) -m mypy --config-file mypy.ini torchft_tpu/analysis torchft_tpu/utils torchft_tpu/ops/topology.py

# tier-1: the default CI selection (ROADMAP.md).
tier1:
	$(PYTHON) -m pytest tests/ -m "not slow" -q

test: tier1

# Distributed-tracing round trip alone: live 2-replica + lighthouse run
# with a forced heal against the TORCHFT_TRACE_FILE span sink, ONE trace
# id per step across the fleet, and the diagnose critical-path ledger
# (docs/observability.md "Distributed tracing").
trace-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_tracing_integ.py -q -m "not slow"

# Online-parallelism-switching round trip alone: the live shrink/grow
# reshard integration incl. the tier-1 mid-reshard chaos tests (kill a
# replica between stage and commit -> completed switch without the
# victim or clean rollback, never a wedge; docs/architecture.md
# "Online parallelism switching").
reshard-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_reshard_integ.py -q -m "not slow"

# Weight-serving tier round trip alone: tree synthesis, payload codec,
# fan-out round trips, and the tier-1 chaos smoke — kill a tree node
# mid-fetch, clients complete from a failover source with
# bitwise-identical weights (docs/architecture.md "Weight-serving tier").
serve-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_serving.py -q -m "not slow"

# The slow serving soak: 32 stub clients against a churning tree with
# staggered server kills; asserts the p99 fetch bound and zero failed
# fetches after failover settles.
serve-soak:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_serving.py -q -m "slow"

# Serving bench alone: sustained checkpoints/sec + client fetch p50/p99
# at stub-client load with a chaos kill of a tree node mid-fetch; ends
# with the same < 1.5 KB compact-summary JSON line as the full bench.
bench-serving:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --serving

# Streaming-relay depth axis alone (ISSUE 14): publish->leaf latency
# over a fanout-1 relay chain at depth {1,2,3} x simulated RTT
# {0,10,50} ms, whole-payload store-and-forward vs cut-through fragment
# streaming + the single-fragment delta rows (docs/benchmarks.md);
# ends with the same < 1.5 KB compact-summary JSON line as the full
# bench.
bench-serving-depth:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --serving-depth

# Native-vs-python fragment data plane (ISSUE 20): same shaped relay
# chain as bench-serving-depth at depth {3,4} x RTT {0,10} ms, each
# cell run once with TORCHFT_FRAG_NATIVE=0 (pure Python HTTP plane)
# and once =1 (C++ writev serve / GIL-free receive), plus a striped
# heal leg; reports per-plane publish->leaf p50/p99, bitwise payload
# equality, native serve/fallback counters, and the p99 speedup
# headline recorded in docs/benchmarks.md §9.
bench-serving-native:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --serving-native

# Coordination-plane HA round trip alone: 3 lighthouse subprocesses,
# SIGKILL the active leader mid-quorum-round and mid-serving-fetch —
# the fleet re-quorums with monotone term-prefixed quorum ids, serving
# clients complete bitwise-identical, never a wedge
# (docs/architecture.md "Coordination-plane HA").
ha-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_ha.py tests/test_ha_integ.py -q -m "not slow"

# HA failover bench alone: leader-kill -> next-quorum latency over an
# in-process 3-peer fleet; ends with the same < 1.5 KB compact-summary
# JSON line as the full bench.
bench-ha:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --ha-failover

# Striped-heal round trip alone (ISSUE 15): streamed fragment staging,
# multi-source striping with per-fragment failover (kill a stripe source
# mid-heal, poisoned-fragment rejection), delta rejoins, the delta-heal
# golden fixture, and the fleet-level striped recovery chaos test
# (docs/architecture.md "Striped heal").
heal-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_heal_striped.py tests/test_golden_fixtures.py -q -m "not slow"

# Striped-heal bench alone: heal wire time striped across {1,2,4}
# sources x RTT {0,10,50} ms on shaped per-source uplinks + the
# delta-rejoin row (docs/benchmarks.md §8); ends with the same < 1.5 KB
# compact-summary JSON line as the full bench.
bench-heal:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --heal

# Durable-store round trip alone (ISSUE 17): store unit surface (dedup,
# torn-blob digest verify, cut selection, spiller, durable.py on the
# store), whole-fleet SIGKILL cold restore with bitwise resume, the
# torn-disk failover and degrade-to-fresh chaos legs, and the
# cold-restore golden fixture (docs/architecture.md "Durable fragment
# store").
cold-restore-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_store.py tests/test_cold_restore.py tests/test_golden_fixtures.py -q -m "not slow"

# Durable-store bench alone: spill wall, content-addressed dedup bytes,
# cold-restore wall striped over {1,2} disks + the warm delta row
# (docs/benchmarks.md); ends with the same < 1.5 KB compact-summary
# JSON line as the full bench.
bench-cold-restore:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --cold-restore

# Fleet link-state plane round trip alone: passive estimator accuracy
# on a shaped topology (closed-loop vs the declared RTT/Gbps), the
# record() hot-path budget, heartbeat digest -> lighthouse matrix ->
# /links.json aggregation, the serving staleness ledger, and the
# dropped-link-report chaos degradation (docs/observability.md
# "Link-state plane").
links-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_linkstats.py -q -m "not slow"

# Fragment provenance plane round trip alone (ISSUE 18): the version
# vector's semantics, the hop-audit ring + crash-durable .prov
# companion dumps, heartbeat digest -> lighthouse per-(host, frag_id)
# matrix -> /fragments.json (incl. the 64-node 16 KB byte budget and
# per-fragment staleness consistency), and torchft-diagnose --fragment
# naming a poisoned hop from dumps alone (docs/observability.md
# "Fragment provenance plane").
fragments-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_provenance.py -q -m "not slow"

# WAN sweep alone: flat vs hierarchical int8 DiLoCo at simulated
# 0/10/50 ms inter-host RTT (docs/benchmarks.md §WAN); ends with the
# same < 1.5 KB compact-summary JSON line as the full bench.
bench-wan:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --wan
